// znicz_infer: C++ forward-inference engine for exported znicz-tpu models.
//
// Capability parity with the reference's libVeles/libZnicz (SURVEY.md 2.1,
// 2.3, 2.4): load a trained snapshot, run forward passes without Python.
// Reads the ZNICZT01 format written by znicz_tpu/export.py and executes the
// layer list on CPU (NHWC layouts matching the Python ops).
//
// Usage:
//   znicz_infer MODEL.znicz INPUT.f32 OUTPUT.f32 [batch]
//     INPUT.f32: raw little-endian float32, batch x input_shape
//     OUTPUT.f32: raw float32 written back, batch x output_shape
//   znicz_infer MODEL.znicz --describe

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <iostream>
#include <limits>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Minimal JSON parser (objects, arrays, strings, numbers, bools) — the
// header is machine-generated so this only needs to be correct, not lenient.
// ---------------------------------------------------------------------------
struct Json {
  enum Kind { OBJECT, ARRAY, STRING, NUMBER, BOOL, NUL } kind = NUL;
  std::map<std::string, Json> object;
  std::vector<Json> array;
  std::string str;
  double number = 0;
  bool boolean = false;

  const Json& at(const std::string& key) const {
    auto it = object.find(key);
    if (it == object.end()) {
      throw std::runtime_error("missing JSON key: " + key);
    }
    return it->second;
  }
  bool has(const std::string& key) const { return object.count(key) > 0; }
  int as_int() const { return static_cast<int>(number); }
  float as_float() const { return static_cast<float>(number); }
  std::vector<int> as_int_array() const {
    std::vector<int> out;
    for (const auto& v : array) out.push_back(v.as_int());
    return out;
  }
};

struct JsonParser {
  const char* p;
  const char* end;

  explicit JsonParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r' || *p == ',' || *p == ':')) ++p;
  }
  Json parse() {
    skip_ws();
    if (p >= end) throw std::runtime_error("unexpected end of JSON");
    switch (*p) {
      case '{': return parse_object();
      case '[': return parse_array();
      case '"': return parse_string();
      case 't': case 'f': return parse_bool();
      case 'n': p += 4; return Json{};
      default: return parse_number();
    }
  }
  Json parse_object() {
    Json j; j.kind = Json::OBJECT;
    ++p;  // {
    skip_ws();
    while (p < end && *p != '}') {
      Json key = parse_string();
      skip_ws();
      j.object[key.str] = parse();
      skip_ws();
    }
    ++p;  // }
    return j;
  }
  Json parse_array() {
    Json j; j.kind = Json::ARRAY;
    ++p;  // [
    skip_ws();
    while (p < end && *p != ']') {
      j.array.push_back(parse());
      skip_ws();
    }
    ++p;  // ]
    return j;
  }
  Json parse_string() {
    Json j; j.kind = Json::STRING;
    ++p;  // "
    while (p < end && *p != '"') {
      if (*p == '\\' && p + 1 < end) ++p;
      j.str += *p++;
    }
    ++p;  // "
    return j;
  }
  Json parse_bool() {
    Json j; j.kind = Json::BOOL;
    if (*p == 't') { j.boolean = true; p += 4; } else { j.boolean = false; p += 5; }
    return j;
  }
  Json parse_number() {
    Json j; j.kind = Json::NUMBER;
    char* next = nullptr;
    j.number = std::strtod(p, &next);
    p = next;
    return j;
  }
};

// ---------------------------------------------------------------------------
// Tensor: NHWC float32 on the heap
// ---------------------------------------------------------------------------
struct Tensor {
  std::vector<int> shape;  // [N, ...]
  std::vector<float> data;

  int64_t size() const {
    int64_t s = 1;
    for (int d : shape) s *= d;
    return s;
  }
  int dim(int i) const { return shape[i]; }
};

struct Padding { int left = 0, top = 0, right = 0, bottom = 0; };

Padding read_padding(const Json& cfg) {
  Padding p;
  if (!cfg.has("padding")) return p;
  const Json& pj = cfg.at("padding");
  if (pj.kind != Json::ARRAY)
    throw std::runtime_error(
        "unsupported padding encoding (expected [l,t,r,b]); re-export with "
        "explicit padding");
  auto v = pj.as_int_array();
  if (v.size() == 2) { p.left = v[0]; p.top = v[1]; p.right = v[0]; p.bottom = v[1]; }
  else if (v.size() == 4) { p.left = v[0]; p.top = v[1]; p.right = v[2]; p.bottom = v[3]; }
  else throw std::runtime_error("padding must have 2 or 4 entries");
  return p;
}

void read_sliding(const Json& cfg, int* sx, int* sy, int def_x, int def_y) {
  *sx = def_x; *sy = def_y;
  if (cfg.has("sliding")) {
    auto v = cfg.at("sliding").as_int_array();
    if (v.size() == 2) { *sx = v[0]; *sy = v[1]; }
  }
}

// ---------------------------------------------------------------------------
// Ops (match znicz_tpu/ops/*.py semantics)
// ---------------------------------------------------------------------------
void apply_activation(const std::string& type, Tensor* t) {
  // semantics match znicz_tpu/ops/activation.py (reference znicz):
  // "tanh" is the scaled 1.7159*tanh(0.6666x); "relu" is smooth softplus;
  // "strict_relu"/"str" is max(0, x).
  if (type.find("_tanh") != std::string::npos) {
    for (auto& v : t->data) v = 1.7159f * std::tanh(0.6666f * v);
  } else if (type.find("_str") != std::string::npos) {
    for (auto& v : t->data) v = v > 0 ? v : 0;
  } else if (type.find("_relu") != std::string::npos) {
    for (auto& v : t->data)
      v = v > 0 ? v + std::log1p(std::exp(-v)) : std::log1p(std::exp(v));
  } else if (type.find("_sigmoid") != std::string::npos) {
    for (auto& v : t->data) v = 1.0f / (1.0f + std::exp(-v));
  } else if (type.find("_log") != std::string::npos) {
    for (auto& v : t->data) v = std::asinh(v);
  }
}

// FC: x [N, F] @ w [F, O] + b
Tensor all2all(const Tensor& x, const float* w, const float* b,
               int n_in, int n_out, bool include_bias) {
  int n = x.dim(0);
  Tensor y;
  y.shape = {n, n_out};
  y.data.assign(static_cast<size_t>(n) * n_out, 0.0f);
  for (int i = 0; i < n; ++i) {
    const float* xi = x.data.data() + static_cast<int64_t>(i) * n_in;
    float* yi = y.data.data() + static_cast<int64_t>(i) * n_out;
    for (int f = 0; f < n_in; ++f) {
      float xv = xi[f];
      if (xv == 0.0f) continue;
      const float* wf = w + static_cast<int64_t>(f) * n_out;
      for (int o = 0; o < n_out; ++o) yi[o] += xv * wf[o];
    }
    if (include_bias && b) {
      for (int o = 0; o < n_out; ++o) yi[o] += b[o];
    }
  }
  return y;
}

// Conv: x [N,H,W,C], w [ky,kx,C,K] (HWIO), NHWC out
Tensor conv2d(const Tensor& x, const float* w, const float* b,
              int kx, int ky, int n_kernels, int sx, int sy, Padding pad) {
  int n = x.dim(0), h = x.dim(1), wd = x.dim(2), c = x.dim(3);
  int oh = (h + pad.top + pad.bottom - ky) / sy + 1;
  int ow = (wd + pad.left + pad.right - kx) / sx + 1;
  Tensor y;
  y.shape = {n, oh, ow, n_kernels};
  y.data.assign(static_cast<size_t>(n) * oh * ow * n_kernels, 0.0f);
  for (int ni = 0; ni < n; ++ni) {
    for (int oy = 0; oy < oh; ++oy) {
      for (int ox = 0; ox < ow; ++ox) {
        float* out = y.data.data() +
            ((static_cast<int64_t>(ni) * oh + oy) * ow + ox) * n_kernels;
        for (int dy = 0; dy < ky; ++dy) {
          int iy = oy * sy + dy - pad.top;
          if (iy < 0 || iy >= h) continue;
          for (int dx = 0; dx < kx; ++dx) {
            int ix = ox * sx + dx - pad.left;
            if (ix < 0 || ix >= wd) continue;
            const float* in = x.data.data() +
                ((static_cast<int64_t>(ni) * h + iy) * wd + ix) * c;
            const float* wk = w +
                (static_cast<int64_t>(dy) * kx + dx) * c * n_kernels;
            for (int ci = 0; ci < c; ++ci) {
              float xv = in[ci];
              const float* wc = wk + static_cast<int64_t>(ci) * n_kernels;
              for (int k = 0; k < n_kernels; ++k) out[k] += xv * wc[k];
            }
          }
        }
        if (b) for (int k = 0; k < n_kernels; ++k) out[k] += b[k];
      }
    }
  }
  return y;
}

// Stochastic pooling at inference: probability-weighted expectation over the
// positive mass (matches ops/pooling.py stochastic_pool(train=False)).
Tensor stochastic_pool_eval(const Tensor& x, int kx, int ky, int sx, int sy) {
  int n = x.dim(0), h = x.dim(1), w = x.dim(2), c = x.dim(3);
  int oh = (h - ky) / sy + 1;
  int ow = (w - kx) / sx + 1;
  Tensor y;
  y.shape = {n, oh, ow, c};
  y.data.assign(static_cast<size_t>(n) * oh * ow * c, 0.0f);
  for (int ni = 0; ni < n; ++ni)
    for (int oy = 0; oy < oh; ++oy)
      for (int ox = 0; ox < ow; ++ox)
        for (int ci = 0; ci < c; ++ci) {
          float total = 0.0f, acc = 0.0f;
          for (int dy = 0; dy < ky; ++dy)
            for (int dx = 0; dx < kx; ++dx) {
              int iy = oy * sy + dy, ix = ox * sx + dx;
              float v = x.data[((static_cast<int64_t>(ni) * h + iy) * w + ix) * c + ci];
              float pos = v > 0 ? v : 0.0f;
              total += pos;
              acc += pos * v;
            }
          y.data[((static_cast<int64_t>(ni) * oh + oy) * ow + ox) * c + ci] =
              total > 0 ? acc / total : 0.0f;
        }
  return y;
}

Tensor pool2d(const Tensor& x, int kx, int ky, int sx, int sy, bool is_max,
              bool max_abs = false) {
  int n = x.dim(0), h = x.dim(1), w = x.dim(2), c = x.dim(3);
  int oh = (h - ky) / sy + 1;
  int ow = (w - kx) / sx + 1;
  Tensor y;
  y.shape = {n, oh, ow, c};
  y.data.assign(static_cast<size_t>(n) * oh * ow * c, 0.0f);
  for (int ni = 0; ni < n; ++ni) {
    for (int oy = 0; oy < oh; ++oy) {
      for (int ox = 0; ox < ow; ++ox) {
        for (int ci = 0; ci < c; ++ci) {
          float best = is_max ? -1e30f : 0.0f;
          float best_abs = -1.0f;
          float acc = 0.0f;
          for (int dy = 0; dy < ky; ++dy) {
            for (int dx = 0; dx < kx; ++dx) {
              int iy = oy * sy + dy, ix = ox * sx + dx;
              float v = x.data[((static_cast<int64_t>(ni) * h + iy) * w + ix) * c + ci];
              if (is_max) {
                if (max_abs) {
                  if (std::fabs(v) > best_abs) { best_abs = std::fabs(v); best = v; }
                } else if (v > best) {
                  best = v;
                }
              } else {
                acc += v;
              }
            }
          }
          y.data[((static_cast<int64_t>(ni) * oh + oy) * ow + ox) * c + ci] =
              is_max ? best : acc / (kx * ky);
        }
      }
    }
  }
  return y;
}

// Deconv (transposed conv): the exact adjoint of conv2d with the same
// geometry — matches znicz_tpu/ops/deconv.py (minimal-inverse output size).
// x [N, OH, OW, K]; w [ky, kx, C, K]; out [N, H, W, C] with
// H = (OH-1)*sy + ky - top - bottom (scatter-add formulation).
Tensor deconv2d(const Tensor& x, const float* w, int kx, int ky,
                int n_channels, int sx, int sy, Padding pad) {
  int n = x.dim(0), oh = x.dim(1), ow = x.dim(2), k = x.dim(3);
  int h = (oh - 1) * sy + ky - pad.top - pad.bottom;
  int wd = (ow - 1) * sx + kx - pad.left - pad.right;
  if (h <= 0 || wd <= 0)
    throw std::runtime_error("deconv: padding exceeds reconstructed size");
  Tensor y;
  y.shape = {n, h, wd, n_channels};
  y.data.assign(static_cast<size_t>(n) * h * wd * n_channels, 0.0f);
  for (int ni = 0; ni < n; ++ni) {
    for (int oy = 0; oy < oh; ++oy) {
      for (int ox = 0; ox < ow; ++ox) {
        const float* in = x.data.data() +
            ((static_cast<int64_t>(ni) * oh + oy) * ow + ox) * k;
        for (int dy = 0; dy < ky; ++dy) {
          int iy = oy * sy + dy - pad.top;
          if (iy < 0 || iy >= h) continue;
          for (int dx = 0; dx < kx; ++dx) {
            int ix = ox * sx + dx - pad.left;
            if (ix < 0 || ix >= wd) continue;
            float* out = y.data.data() +
                ((static_cast<int64_t>(ni) * h + iy) * wd + ix) * n_channels;
            const float* wk = w +
                (static_cast<int64_t>(dy) * kx + dx) * n_channels * k;
            for (int ci = 0; ci < n_channels; ++ci) {
              const float* wc = wk + static_cast<int64_t>(ci) * k;
              float acc = 0.0f;
              for (int ki = 0; ki < k; ++ki) acc += in[ki] * wc[ki];
              out[ci] += acc;
            }
          }
        }
      }
    }
  }
  return y;
}

// Cutter: crop (left, top, right, bottom) — matches znicz_tpu/ops/cutter.py
Tensor cut(const Tensor& x, Padding pad) {
  int n = x.dim(0), h = x.dim(1), w = x.dim(2), c = x.dim(3);
  int oh = h - pad.top - pad.bottom;
  int ow = w - pad.left - pad.right;
  if (oh <= 0 || ow <= 0)
    throw std::runtime_error("cutter: padding exceeds input size");
  Tensor y;
  y.shape = {n, oh, ow, c};
  y.data.resize(static_cast<size_t>(n) * oh * ow * c);
  for (int ni = 0; ni < n; ++ni)
    for (int oy = 0; oy < oh; ++oy)
      for (int ox = 0; ox < ow; ++ox) {
        const float* in = x.data.data() +
            ((static_cast<int64_t>(ni) * h + oy + pad.top) * w + ox +
             pad.left) * c;
        float* out = y.data.data() +
            ((static_cast<int64_t>(ni) * oh + oy) * ow + ox) * c;
        std::memcpy(out, in, sizeof(float) * c);
      }
  return y;
}

// Cross-channel LRN, SAME window (matches ops/normalization.py)
Tensor lrn(const Tensor& x, float alpha, float beta, float k, int n_window) {
  Tensor y = x;
  int c = x.shape.back();
  int64_t rows = x.size() / c;
  int half = n_window / 2;
  for (int64_t r = 0; r < rows; ++r) {
    const float* in = x.data.data() + r * c;
    float* out = y.data.data() + r * c;
    for (int ci = 0; ci < c; ++ci) {
      float s = 0.0f;
      int lo = ci - half, hi = ci + (n_window - 1 - half);
      if (lo < 0) lo = 0;
      if (hi >= c) hi = c - 1;
      for (int j = lo; j <= hi; ++j) s += in[j] * in[j];
      out[ci] = in[ci] * std::pow(k + alpha * s, -beta);
    }
  }
  return y;
}

// ---------------------------------------------------------------------------
// Transformer LM ops (match znicz_tpu/workflow/transformer.py lm_apply /
// _block_forward and znicz_tpu/ops/attention.py mha semantics)
// ---------------------------------------------------------------------------

// LayerNorm over the last dim (ops/normalization.py layer_norm, eps 1e-5)
void layer_norm_rows(Tensor* t, const float* scale, const float* bias) {
  int d = t->shape.back();
  int64_t rows = t->size() / d;
  for (int64_t r = 0; r < rows; ++r) {
    float* row = t->data.data() + r * d;
    double mean = 0.0;
    for (int i = 0; i < d; ++i) mean += row[i];
    mean /= d;
    double var = 0.0;
    for (int i = 0; i < d; ++i) {
      double c = row[i] - mean;
      var += c * c;
    }
    var /= d;
    float inv = 1.0f / std::sqrt(static_cast<float>(var) + 1e-5f);
    for (int i = 0; i < d; ++i)
      row[i] = (row[i] - static_cast<float>(mean)) * inv * scale[i] + bias[i];
  }
}

// x [..., n_in] @ w [n_in, n_out] (+ optional bias) -> [..., n_out]
Tensor matmul_rows(const Tensor& x, const float* w, const float* b,
                   int n_in, int n_out) {
  Tensor y;
  y.shape = x.shape;
  y.shape.back() = n_out;
  int64_t rows = x.size() / n_in;
  y.data.assign(rows * n_out, 0.0f);
  for (int64_t r = 0; r < rows; ++r) {
    const float* xi = x.data.data() + r * n_in;
    float* yi = y.data.data() + r * n_out;
    for (int f = 0; f < n_in; ++f) {
      float xv = xi[f];
      if (xv == 0.0f) continue;
      const float* wf = w + static_cast<int64_t>(f) * n_out;
      for (int o = 0; o < n_out; ++o) yi[o] += xv * wf[o];
    }
    if (b)
      for (int o = 0; o < n_out; ++o) yi[o] += b[o];
  }
  return y;
}

// token ids (rounded from f32 input) [N, T] -> embed[id] + pos[t], [N, T, D]
Tensor lm_embed(const Tensor& x, const float* embed, int vocab,
                const float* pos, int max_seq, int d, int offset = 0) {
  int n = x.dim(0), t = x.dim(1);
  if (offset + t > max_seq)
    throw std::runtime_error("lm_embed: sequence longer than max_seq");
  Tensor y;
  y.shape = {n, t, d};
  y.data.resize(static_cast<size_t>(n) * t * d);
  for (int ni = 0; ni < n; ++ni)
    for (int ti = 0; ti < t; ++ti) {
      long id = std::lround(x.data[static_cast<int64_t>(ni) * t + ti]);
      if (id < 0 || id >= vocab)
        throw std::runtime_error("lm_embed: token id out of vocabulary");
      const float* e = embed + static_cast<int64_t>(id) * d;
      const float* p = pos + static_cast<int64_t>(offset + ti) * d;
      float* out = y.data.data() + (static_cast<int64_t>(ni) * t + ti) * d;
      for (int i = 0; i < d; ++i) out[i] = e[i] + p[i];
    }
  return y;
}

// Per-block K/V cache for incremental decoding: [n, t_max, inner] rows,
// written as positions are consumed (the deployment-side twin of
// znicz_tpu/workflow/generate.py's init_kv_cache).
struct KVCache {
  int t_max = 0;
  std::vector<float> k, v;
};

void softmax_rows(Tensor* t) {
  int c = t->shape.back();
  int64_t rows = t->size() / c;
  for (int64_t r = 0; r < rows; ++r) {
    float* row = t->data.data() + r * c;
    float mx = row[0];
    for (int i = 1; i < c; ++i) mx = std::max(mx, row[i]);
    float sum = 0;
    for (int i = 0; i < c; ++i) { row[i] = std::exp(row[i] - mx); sum += row[i]; }
    for (int i = 0; i < c; ++i) row[i] /= sum;
  }
}

// ---------------------------------------------------------------------------
// Model
// ---------------------------------------------------------------------------
struct Layer {
  std::string type;
  Json config;
  std::map<std::string, std::pair<std::vector<int>, const float*>> params;
};

// Gated mixture-of-experts FFN (ops/moe.py dense-dispatch semantics):
// router softmax over top-k logits (renormalized), every selected expert
// runs x @ w1 + b1 -> tanh -> @ w2 + b2, gate-weighted combine.
// h [R, d] flattened tokens; params carry a leading expert dim.
Tensor moe_ffn(const Tensor& h, const Layer& layer, int top_k) {
  for (const char* name : {"moe_router", "moe_w_up", "moe_up_bias",
                           "moe_w_down", "moe_down_bias"}) {
    if (!layer.params.count(name))
      throw std::runtime_error("moe: missing param '" + std::string(name) +
                               "' (corrupt artifact?)");
  }
  const auto& router = layer.params.at("moe_router");  // [d, E]
  const auto& w1 = layer.params.at("moe_w_up");        // [E, d, dff]
  const auto& b1 = layer.params.at("moe_up_bias");     // [E, dff]
  const auto& w2 = layer.params.at("moe_w_down");      // [E, dff, d]
  const auto& b2 = layer.params.at("moe_down_bias");   // [E, d]
  int d = h.shape.back();
  if (router.first.size() != 2 || router.first[0] != d)
    throw std::runtime_error("moe: router must be [d_model, E]");
  int e = router.first[1];
  int dff = w1.first.size() == 3 ? w1.first[2] : -1;
  if (w1.first != std::vector<int>{e, d, dff} ||
      b1.first != std::vector<int>{e, dff} ||
      w2.first != std::vector<int>{e, dff, d} ||
      b2.first != std::vector<int>{e, d} || dff <= 0)
    throw std::runtime_error("moe: expert param shape mismatch");
  if (top_k < 1) top_k = 1;
  if (top_k > e) top_k = e;
  int64_t rows = h.size() / d;
  Tensor y;
  y.shape = h.shape;
  y.data.assign(h.data.size(), 0.0f);
  std::vector<float> logits(e), hid(dff), gate(top_k);
  std::vector<int> idx(e);
  for (int64_t r = 0; r < rows; ++r) {
    const float* xr = h.data.data() + r * d;
    for (int j = 0; j < e; ++j) {
      float s = 0.0f;
      for (int i = 0; i < d; ++i) s += xr[i] * router.second[
          static_cast<int64_t>(i) * e + j];
      logits[j] = s;
      idx[j] = j;
    }
    // top-k expert ids by logit (ties: lower id first — matches
    // jax.lax.top_k's stable ordering)
    std::partial_sort(idx.begin(), idx.begin() + top_k, idx.end(),
                      [&](int a, int b) {
                        return logits[a] != logits[b] ? logits[a] > logits[b]
                                                      : a < b;
                      });
    float mx = logits[idx[0]], sum = 0.0f;
    for (int k = 0; k < top_k; ++k) {
      gate[k] = std::exp(logits[idx[k]] - mx);
      sum += gate[k];
    }
    float* yr = y.data.data() + r * d;
    for (int k = 0; k < top_k; ++k) {
      int ex = idx[k];
      float g = gate[k] / sum;
      const float* w1e = w1.second + static_cast<int64_t>(ex) * d * dff;
      const float* b1e = b1.second + static_cast<int64_t>(ex) * dff;
      for (int j = 0; j < dff; ++j) {
        float s = b1e[j];
        for (int i = 0; i < d; ++i)
          s += xr[i] * w1e[static_cast<int64_t>(i) * dff + j];
        hid[j] = std::tanh(s);
      }
      const float* w2e = w2.second + static_cast<int64_t>(ex) * dff * d;
      const float* b2e = b2.second + static_cast<int64_t>(ex) * d;
      for (int i = 0; i < d; ++i) {
        float s = b2e[i];
        for (int j = 0; j < dff; ++j)
          s += hid[j] * w2e[static_cast<int64_t>(j) * d + i];
        yr[i] += g * s;
      }
    }
  }
  return y;
}

// One pre-LN transformer block: x + causalMHA(ln1(x)), then
// x + tanh(ln2(x) @ w_up + up_bias) @ w_down + down_bias (or the MoE
// FFN when the block carries expert params).
// Plain tanh — NOT the scaled 1.7159 activation of the conv/FC stack.
// With ``cache`` set, the block runs INCREMENTALLY: x_in holds positions
// ``offset .. offset+t-1``, the new K/V rows append into the cache, and
// attention reads the cache prefix (<= absolute query position) instead of
// recomputing the full [T x T] score matrix per forward.  cache == nullptr
// is the original full-sequence forward, bit-for-bit unchanged.
Tensor lm_block(const Tensor& x_in, const Layer& layer,
                KVCache* cache = nullptr, int offset = 0) {
  int n_heads = layer.config.at("n_heads").as_int();
  int n = x_in.dim(0), t = x_in.dim(1), d = x_in.dim(2);
  // Validate EVERY param's shape against the activation dims before any
  // pointer walks: a corrupt/inconsistent artifact must fail cleanly,
  // never read past the weight blob (the Model::load invariant).
  auto check = [&](const char* name, std::vector<int> want) {
    const auto& got = layer.params.at(name).first;
    if (got != want) {
      std::string msg = "lm_block: param '" + std::string(name) +
                        "' shape mismatch (corrupt artifact?)";
      throw std::runtime_error(msg);
    }
  };
  const auto& wq = layer.params.at("wq");
  if (wq.first.size() != 2 || wq.first[0] != d)
    throw std::runtime_error("lm_block: wq must be [d_model, inner]");
  int inner = wq.first[1];
  if (n_heads <= 0 || inner % n_heads != 0)
    throw std::runtime_error("lm_block: inner dim not divisible by heads");
  bool is_moe = layer.params.count("moe_router") > 0;
  if (!is_moe) {
    const auto& wup = layer.params.at("w_up");
    if (wup.first.size() != 2 || wup.first[0] != d)
      throw std::runtime_error("lm_block: w_up must be [d_model, d_ff]");
    int dff = wup.first[1];
    check("up_bias", {dff});
    check("w_down", {dff, d});
    check("down_bias", {d});
  }
  check("ln1_scale", {d});
  check("ln1_bias", {d});
  check("ln2_scale", {d});
  check("ln2_bias", {d});
  check("wk", {d, inner});
  check("wv", {d, inner});
  check("wo", {inner, d});
  int hd = inner / n_heads;
  float scale = 1.0f / std::sqrt(static_cast<float>(hd));

  Tensor h = x_in;
  layer_norm_rows(&h, layer.params.at("ln1_scale").second,
                  layer.params.at("ln1_bias").second);
  Tensor q = matmul_rows(h, wq.second, nullptr, d, inner);
  Tensor k = matmul_rows(h, layer.params.at("wk").second, nullptr, d, inner);
  Tensor v = matmul_rows(h, layer.params.at("wv").second, nullptr, d, inner);

  // key/value source: the fresh projections (full forward) or the cache
  // with this call's rows appended (incremental decode)
  const float* ksrc = k.data.data();
  const float* vsrc = v.data.data();
  int kv_stride = t;  // row stride per sample in the K/V source
  int kv_offset = 0;  // absolute position of x_in's first row
  if (cache) {
    if (offset + t > cache->t_max)
      throw std::runtime_error("lm_block: decode past the cache capacity");
    for (int ni = 0; ni < n; ++ni)
      for (int ti = 0; ti < t; ++ti) {
        int64_t src = (static_cast<int64_t>(ni) * t + ti) * inner;
        int64_t dst =
            (static_cast<int64_t>(ni) * cache->t_max + offset + ti) * inner;
        std::memcpy(cache->k.data() + dst, k.data.data() + src,
                    inner * sizeof(float));
        std::memcpy(cache->v.data() + dst, v.data.data() + src,
                    inner * sizeof(float));
      }
    ksrc = cache->k.data();
    vsrc = cache->v.data();
    kv_stride = cache->t_max;
    kv_offset = offset;
  }

  // causal softmax attention per (batch, head); layouts are head-major
  // within the inner dim (mha's reshape(b, t, heads, hd)); key positions
  // run to the ABSOLUTE query position (== tq for the full forward)
  Tensor att;
  att.shape = {n, t, inner};
  att.data.assign(static_cast<size_t>(n) * t * inner, 0.0f);
  std::vector<float> p(kv_offset + t);
  for (int ni = 0; ni < n; ++ni) {
    for (int hh = 0; hh < n_heads; ++hh) {
      for (int tq = 0; tq < t; ++tq) {
        const float* qrow =
            q.data.data() + (static_cast<int64_t>(ni) * t + tq) * inner +
            static_cast<int64_t>(hh) * hd;
        int t_keys = kv_offset + tq;  // inclusive causal bound
        float mx = -1e30f;
        for (int tk = 0; tk <= t_keys; ++tk) {
          const float* krow =
              ksrc + (static_cast<int64_t>(ni) * kv_stride + tk) * inner +
              static_cast<int64_t>(hh) * hd;
          float s = 0.0f;
          for (int i = 0; i < hd; ++i) s += qrow[i] * krow[i];
          p[tk] = s * scale;
          mx = std::max(mx, p[tk]);
        }
        float sum = 0.0f;
        for (int tk = 0; tk <= t_keys; ++tk) {
          p[tk] = std::exp(p[tk] - mx);
          sum += p[tk];
        }
        float* out =
            att.data.data() + (static_cast<int64_t>(ni) * t + tq) * inner +
            static_cast<int64_t>(hh) * hd;
        for (int tk = 0; tk <= t_keys; ++tk) {
          float w = p[tk] / sum;
          const float* vrow =
              vsrc + (static_cast<int64_t>(ni) * kv_stride + tk) * inner +
              static_cast<int64_t>(hh) * hd;
          for (int i = 0; i < hd; ++i) out[i] += w * vrow[i];
        }
      }
    }
  }
  Tensor o = matmul_rows(att, layer.params.at("wo").second, nullptr,
                         inner, d);
  Tensor x = x_in;
  for (int64_t i = 0; i < x.size(); ++i) x.data[i] += o.data[i];

  Tensor h2 = x;
  layer_norm_rows(&h2, layer.params.at("ln2_scale").second,
                  layer.params.at("ln2_bias").second);
  Tensor dn;
  if (is_moe) {
    int top_k = layer.config.has("top_k")
                    ? layer.config.at("top_k").as_int()
                    : 1;
    dn = moe_ffn(h2, layer, top_k);
  } else {
    const auto& wup = layer.params.at("w_up");
    int dff = wup.first[1];
    Tensor u = matmul_rows(h2, wup.second,
                           layer.params.at("up_bias").second, d, dff);
    for (auto& uv : u.data) uv = std::tanh(uv);
    dn = matmul_rows(u, layer.params.at("w_down").second,
                     layer.params.at("down_bias").second, dff, d);
  }
  for (int64_t i = 0; i < x.size(); ++i) x.data[i] += dn.data[i];
  return x;
}

struct Model {
  Json header;
  std::vector<char> blob;
  std::vector<Layer> layers;
  std::vector<int> input_shape;
  std::string output_kind = "raw";

  static Model load(const std::string& path) {
    std::ifstream f(path, std::ios::binary);
    if (!f) throw std::runtime_error("cannot open " + path);
    char magic[8];
    f.read(magic, 8);
    if (std::memcmp(magic, "ZNICZT01", 8) != 0)
      throw std::runtime_error("bad magic in " + path);
    uint32_t hlen = 0;
    f.read(reinterpret_cast<char*>(&hlen), 4);
    std::string hjson(hlen, '\0');
    f.read(hjson.data(), hlen);
    Model m;
    m.header = JsonParser(hjson).parse();
    m.blob.assign(std::istreambuf_iterator<char>(f),
                  std::istreambuf_iterator<char>());
    m.input_shape = m.header.at("input_shape").as_int_array();
    if (m.header.has("output_kind"))
      m.output_kind = m.header.at("output_kind").str;
    for (const auto& lj : m.header.at("layers").array) {
      Layer layer;
      layer.type = lj.at("type").str;
      layer.config = lj.at("config");
      for (const auto& [name, pj] : lj.at("params").object) {
        int64_t offset = static_cast<int64_t>(pj.at("offset").number);
        std::vector<int> shape = pj.at("shape").as_int_array();
        // Never trust header-declared offsets/shapes: a truncated or
        // inconsistent file must fail cleanly, not read out of bounds.
        // Guard the product against int64 overflow by bailing as soon as
        // it exceeds the number of floats the blob could possibly hold.
        const int64_t blob_floats =
            static_cast<int64_t>(m.blob.size() / sizeof(float));
        int64_t numel = 1;
        bool shape_ok = true;
        for (int d : shape) {
          if (d <= 0 || numel > blob_floats) { shape_ok = false; break; }
          numel *= d;
        }
        if (!shape_ok || offset < 0 || offset % 4 != 0 ||
            offset / static_cast<int64_t>(sizeof(float)) >
                blob_floats - numel) {
          throw std::runtime_error(
              "param '" + name + "' of layer '" + layer.type +
              "' exceeds weight blob (truncated or corrupt file): " + path);
        }
        layer.params[name] = {
            std::move(shape),
            reinterpret_cast<const float*>(m.blob.data() + offset)};
      }
      m.layers.push_back(std::move(layer));
    }
    return m;
  }

  Tensor forward(Tensor x) const {
    for (const auto& layer : layers) {
      const std::string& t = layer.type;
      const Json& cfg = layer.config;
      if (t.rfind("all2all", 0) == 0 || t == "softmax") {
        const auto& wp = layer.params.at("weights");
        if (wp.first.size() != 2)
          throw std::runtime_error(
              "layer '" + t + "': weights must be rank 2");
        int n_in = wp.first[0], n_out = wp.first[1];
        // flatten trailing dims
        x.shape = {x.dim(0), static_cast<int>(x.size() / x.dim(0))};
        if (x.dim(1) != n_in)
          throw std::runtime_error(
              "layer '" + t + "': input has " + std::to_string(x.dim(1)) +
              " features per sample, weights expect " + std::to_string(n_in));
        bool include_bias = !cfg.has("include_bias") ||
                            cfg.at("include_bias").boolean;
        const float* b = layer.params.count("bias")
                             ? layer.params.at("bias").second
                             : nullptr;
        x = all2all(x, wp.second, b, n_in, n_out, include_bias);
        apply_activation(t, &x);
        if (t == "softmax") softmax_rows(&x);
      } else if (t.rfind("conv", 0) == 0) {
        const auto& wp = layer.params.at("weights");
        if (wp.first.size() != 4)
          throw std::runtime_error(
              "layer '" + t + "': weights must be rank 4 (HWIO)");
        int ky = wp.first[0], kx = wp.first[1], k = wp.first[3];
        if (x.shape.size() != 4 || x.dim(3) != wp.first[2])
          throw std::runtime_error(
              "layer '" + t + "': input channels do not match weights");
        int sx, sy;
        read_sliding(cfg, &sx, &sy, 1, 1);
        const float* b = layer.params.count("bias")
                             ? layer.params.at("bias").second
                             : nullptr;
        x = conv2d(x, wp.second, b, kx, ky, k, sx, sy, read_padding(cfg));
        apply_activation(t, &x);
      } else if (t == "max_pooling" || t == "avg_pooling" ||
                 t == "maxabs_pooling" || t == "stochastic_pooling") {
        int kx = cfg.at("kx").as_int(), ky = cfg.at("ky").as_int();
        int sx, sy;
        read_sliding(cfg, &sx, &sy, kx, ky);
        if (t == "stochastic_pooling") {
          x = stochastic_pool_eval(x, kx, ky, sx, sy);
        } else {
          bool is_max = (t == "max_pooling" || t == "maxabs_pooling");
          x = pool2d(x, kx, ky, sx, sy, is_max, t == "maxabs_pooling");
        }
      } else if (t == "deconv") {
        const auto& wp = layer.params.at("weights");
        if (wp.first.size() != 4)
          throw std::runtime_error(
              "layer 'deconv': weights must be rank 4 [ky,kx,C,K]");
        int ky = wp.first[0], kx = wp.first[1], n_channels = wp.first[2];
        if (x.shape.size() != 4 || x.dim(3) != wp.first[3])
          throw std::runtime_error(
              "layer 'deconv': input channels do not match weights");
        int sx, sy;
        read_sliding(cfg, &sx, &sy, 1, 1);
        x = deconv2d(x, wp.second, kx, ky, n_channels, sx, sy,
                     read_padding(cfg));
      } else if (t == "cutter") {
        if (x.shape.size() != 4)
          throw std::runtime_error("layer 'cutter': input must be NHWC");
        x = cut(x, read_padding(cfg));
      } else if (t == "norm") {
        float alpha = cfg.has("alpha") ? cfg.at("alpha").as_float() : 1e-4f;
        float beta = cfg.has("beta") ? cfg.at("beta").as_float() : 0.75f;
        float k = cfg.has("k") ? cfg.at("k").as_float() : 2.0f;
        int n = cfg.has("n") ? cfg.at("n").as_int() : 5;
        x = lrn(x, alpha, beta, k, n);
      } else if (t == "lm_embed") {
        const auto& ep = layer.params.at("embed");  // [vocab, d]
        const auto& pp = layer.params.at("pos");    // [max_seq, d]
        if (x.shape.size() != 2)
          throw std::runtime_error("lm_embed: input must be [N, T] tokens");
        if (ep.first.size() != 2 || pp.first.size() != 2 ||
            pp.first[1] != ep.first[1])
          throw std::runtime_error(
              "lm_embed: embed/pos tables disagree on d_model "
              "(corrupt artifact?)");
        x = lm_embed(x, ep.second, ep.first[0], pp.second, pp.first[0],
                     ep.first[1]);
      } else if (t == "lm_block") {
        if (x.shape.size() != 3)
          throw std::runtime_error("lm_block: input must be [N, T, D]");
        x = lm_block(x, layer);
      } else if (t == "lm_head") {
        const auto& hp = layer.params.at("head");  // [d, vocab]
        if (hp.first.size() != 2)
          throw std::runtime_error("lm_head: head param must be rank-2");
        if (x.shape.size() != 3 || x.dim(2) != hp.first[0])
          throw std::runtime_error("lm_head: input dim mismatch");
        x = matmul_rows(x, hp.second, nullptr, hp.first[0], hp.first[1]);
      } else if (t == "dropout") {
        // inference no-op (inverted dropout)
      } else if (t.rfind("activation_", 0) == 0) {
        std::string suffix = "_" + t.substr(11);
        apply_activation(suffix, &x);
      } else {
        throw std::runtime_error("znicz_infer: unsupported layer type " + t);
      }
    }
    return x;
  }

  // Greedy KV-cache decoding: prompt [n, tp] token ids -> [n, tp + n_new]
  // (prompt included).  Prefill runs the prompt once, filling each block's
  // cache; every further token is ONE cached block-tower step — the
  // deployment twin of workflow/generate.py's generate(temperature=0).
  Tensor generate(const Tensor& prompt, int n_new) const {
    if (layers.size() < 3 || layers.front().type != "lm_embed" ||
        layers.back().type != "lm_head")
      throw std::runtime_error(
          "generate: artifact is not an LM (want lm_embed .. lm_head)");
    for (size_t i = 1; i + 1 < layers.size(); ++i)
      if (layers[i].type != "lm_block")
        throw std::runtime_error(
            "generate: non-lm_block layer inside the tower");
    if (n_new < 1)
      throw std::runtime_error("generate: need n_new >= 1");
    int n = prompt.dim(0), tp = prompt.dim(1);
    int t_max = tp + n_new;
    const auto& ep = layers.front().params.at("embed");  // [vocab, d]
    const auto& pp = layers.front().params.at("pos");    // [max_seq, d]
    if (ep.first.size() != 2 || pp.first.size() != 2 ||
        pp.first[1] != ep.first[1])
      throw std::runtime_error(
          "lm_embed: embed/pos tables disagree on d_model "
          "(corrupt artifact?)");
    int vocab = ep.first[0], d = ep.first[1];
    if (t_max > pp.first[0])
      throw std::runtime_error(
          "generate: prompt + n_new exceeds the positional table (" +
          std::to_string(pp.first[0]) + ")");
    int n_blocks = static_cast<int>(layers.size()) - 2;
    std::vector<KVCache> caches(n_blocks);
    for (int i = 0; i < n_blocks; ++i) {
      const auto& wq = layers[1 + i].params.at("wq");
      if (wq.first.size() != 2)
        throw std::runtime_error("lm_block: wq must be [d_model, inner]");
      int inner = wq.first[1];
      caches[i].t_max = t_max;
      caches[i].k.assign(
          static_cast<size_t>(n) * t_max * inner, 0.0f);
      caches[i].v.assign(
          static_cast<size_t>(n) * t_max * inner, 0.0f);
    }
    Tensor out;
    out.shape = {n, t_max};
    out.data.resize(static_cast<size_t>(n) * t_max);
    for (int ni = 0; ni < n; ++ni)
      std::memcpy(out.data.data() + static_cast<int64_t>(ni) * t_max,
                  prompt.data.data() + static_cast<int64_t>(ni) * tp,
                  tp * sizeof(float));

    const auto& hp = layers.back().params.at("head");  // [d, vocab]
    if (hp.first.size() != 2 || hp.first[0] != d || hp.first[1] != vocab)
      throw std::runtime_error("lm_head: head param shape mismatch");
    auto greedy_from_last = [&](const Tensor& x, std::vector<float>* tok) {
      // logits for the LAST position only: row-major [n,1,d]x[d,vocab]
      // through matmul_rows (contiguous weight reads — this runs once per
      // generated token), then argmax
      int t = x.dim(1);
      Tensor last;
      last.shape = {n, 1, d};
      last.data.resize(static_cast<size_t>(n) * d);
      for (int ni = 0; ni < n; ++ni)
        std::memcpy(
            last.data.data() + static_cast<int64_t>(ni) * d,
            x.data.data() + (static_cast<int64_t>(ni) * t + t - 1) * d,
            d * sizeof(float));
      Tensor logits = matmul_rows(last, hp.second, nullptr, d, vocab);
      tok->resize(n);
      for (int ni = 0; ni < n; ++ni) {
        const float* lr =
            logits.data.data() + static_cast<int64_t>(ni) * vocab;
        int best = 0;
        float best_v = -std::numeric_limits<float>::infinity();
        for (int vv = 0; vv < vocab; ++vv)
          if (lr[vv] > best_v) { best_v = lr[vv]; best = vv; }
        (*tok)[ni] = static_cast<float>(best);
      }
    };

    // prefill
    Tensor x = lm_embed(prompt, ep.second, vocab, pp.second, pp.first[0],
                        d, 0);
    for (int i = 0; i < n_blocks; ++i)
      x = lm_block(x, layers[1 + i], &caches[i], 0);
    std::vector<float> tok;
    greedy_from_last(x, &tok);
    for (int ni = 0; ni < n; ++ni)
      out.data[static_cast<int64_t>(ni) * t_max + tp] = tok[ni];

    // decode: one position per step through the cached tower
    Tensor step_in;
    step_in.shape = {n, 1};
    step_in.data.resize(n);
    for (int s = 1; s < n_new; ++s) {
      int pos = tp + s - 1;  // position of the token being consumed
      for (int ni = 0; ni < n; ++ni)
        step_in.data[ni] = out.data[static_cast<int64_t>(ni) * t_max + pos];
      Tensor xs = lm_embed(step_in, ep.second, vocab, pp.second,
                           pp.first[0], d, pos);
      for (int i = 0; i < n_blocks; ++i)
        xs = lm_block(xs, layers[1 + i], &caches[i], pos);
      greedy_from_last(xs, &tok);
      for (int ni = 0; ni < n; ++ni)
        out.data[static_cast<int64_t>(ni) * t_max + pos + 1] = tok[ni];
    }
    return out;
  }
};

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::cerr << "usage: " << argv[0]
              << " MODEL.znicz (INPUT.f32 OUTPUT.f32 [batch"
                 " [--generate N]] | --describe)\n"
              << "  --generate N: greedy KV-cache decode of N new tokens"
                 " from the [batch, Tp] prompt in INPUT.f32 (LM artifacts"
                 " only); OUTPUT.f32 gets [batch, Tp+N] token ids\n";
    return 2;
  }
  try {
    Model model = Model::load(argv[1]);
    if (std::string(argv[2]) == "--describe") {
      std::cout << "input_shape:";
      for (int d : model.input_shape) std::cout << " " << d;
      std::cout << "\noutput_kind: " << model.output_kind;
      std::cout << "\nlayers:";
      for (const auto& l : model.layers) std::cout << " " << l.type;
      std::cout << "\n";
      return 0;
    }
    if (argc < 4) {
      std::cerr << "missing OUTPUT.f32\n";
      return 2;
    }
    int batch = 1, n_generate = 0;
    for (int a = 4; a < argc; ++a) {
      std::string arg = argv[a];
      if (arg == "--generate") {
        if (a + 1 >= argc)
          throw std::runtime_error("--generate needs a count");
        n_generate = std::atoi(argv[++a]);
        if (n_generate < 1)
          throw std::runtime_error("--generate wants N >= 1");
      } else if (a == 4) {
        batch = std::atoi(arg.c_str());
        if (batch < 1)  // also catches a mistyped flag landing here
          throw std::runtime_error(
              "batch must be a positive integer, got '" + arg + "'");
      } else {
        throw std::runtime_error("unrecognized argument: " + arg);
      }
    }
    std::ifstream in(argv[2], std::ios::binary);
    if (!in) throw std::runtime_error(std::string("cannot open ") + argv[2]);
    Tensor x;
    if (n_generate) {
      // prompt length is whatever the file holds: [batch, Tp] token ids
      in.seekg(0, std::ios::end);
      int64_t bytes = in.tellg();
      in.seekg(0, std::ios::beg);
      int64_t floats = bytes / static_cast<int64_t>(sizeof(float));
      if (floats <= 0 || floats % batch)
        throw std::runtime_error(
            "prompt file size not divisible by batch");
      x.shape = {batch, static_cast<int>(floats / batch)};
      x.data.resize(floats);
    } else {
      int64_t per_sample = 1;
      for (int d : model.input_shape) per_sample *= d;
      x.shape = {batch};
      for (int d : model.input_shape) x.shape.push_back(d);
      x.data.resize(batch * per_sample);
    }
    in.read(reinterpret_cast<char*>(x.data.data()),
            x.data.size() * sizeof(float));
    if (in.gcount() != static_cast<std::streamsize>(x.data.size() * sizeof(float)))
      throw std::runtime_error("input file too small for batch");
    Tensor y = n_generate ? model.generate(x, n_generate)
                          : model.forward(std::move(x));
    std::ofstream out(argv[3], std::ios::binary);
    out.write(reinterpret_cast<const char*>(y.data.data()),
              y.data.size() * sizeof(float));
    std::cerr << "ok: wrote " << y.size() << " floats\n";
    return 0;
  } catch (const std::exception& e) {
    std::cerr << "error: " << e.what() << "\n";
    return 1;
  }
}
