// batch_assembler: native minibatch assembly for the loader hot path.
//
// The reference's data-plane hot paths are native (CL/CUDA kernels fed by
// C-backed numpy ops); this keeps the rebuilt loader's per-step work native
// too (SURVEY.md 2.4 rebuild mapping).  Exposed as a plain C ABI for ctypes
// (the environment has no pybind11).  All functions are thread-parallel.
//
// Build:  g++ -O3 -march=native -shared -fPIC -o libbatch_assembler.so \
//             batch_assembler.cc -pthread

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// run fn(begin, end) over [0, n) split across hardware threads
template <typename Fn>
void parallel_for(int64_t n, Fn fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t n_threads = hw ? static_cast<int64_t>(hw) : 4;
  if (n_threads > n) n_threads = n > 0 ? n : 1;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  std::vector<std::thread> threads;
  for (int64_t t = 0; t < n_threads; ++t) {
    int64_t begin = t * chunk;
    int64_t end = begin + chunk < n ? begin + chunk : n;
    if (begin >= end) break;
    threads.emplace_back([=] { fn(begin, end); });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// Gather rows: out[i, :] = data[indices[i], :].  f32, row-major.
void gather_rows_f32(const float* data, int64_t feat, const int64_t* indices,
                     int64_t batch, float* out) {
  parallel_for(batch, [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      std::memcpy(out + i * feat, data + indices[i] * feat,
                  static_cast<size_t>(feat) * sizeof(float));
    }
  });
}

// Gather rows from uint8 storage with affine normalize:
// out[i, j] = data[indices[i], j] / scale + shift.
// Keeps the dataset in u8 (4x less host RAM) and converts per batch.
void gather_rows_u8_normalize(const uint8_t* data, int64_t feat,
                              const int64_t* indices, int64_t batch,
                              float scale, float shift, float* out) {
  float inv = 1.0f / scale;
  parallel_for(batch, [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      const uint8_t* src = data + indices[i] * feat;
      float* dst = out + i * feat;
      for (int64_t j = 0; j < feat; ++j) dst[j] = src[j] * inv + shift;
    }
  });
}

// Gather random/center crops (optionally h-flipped) from packed u8 images.
// data: [n_imgs, H, W, C] u8; per sample i: copy the window
// data[indices[i], oy[i]:oy[i]+out_h, ox[i]:ox[i]+out_w, :] into
// out[i, :, :, :], reversing the W axis when flip[i] != 0.  Output stays u8 —
// the affine normalize runs on-device (fused into the XLA step), so the
// host->device transfer is 4x smaller than f32.
void crop_gather_u8(const uint8_t* data, int64_t h, int64_t w, int64_t c,
                    const int64_t* indices, const int64_t* oy,
                    const int64_t* ox, const uint8_t* flip, int64_t batch,
                    int64_t out_h, int64_t out_w, uint8_t* out) {
  const int64_t img = h * w * c;
  const int64_t out_img = out_h * out_w * c;
  parallel_for(batch, [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      const uint8_t* src = data + indices[i] * img + (oy[i] * w + ox[i]) * c;
      uint8_t* dst = out + i * out_img;
      if (!flip[i]) {
        for (int64_t r = 0; r < out_h; ++r)
          std::memcpy(dst + r * out_w * c, src + r * w * c,
                      static_cast<size_t>(out_w) * c);
      } else {
        for (int64_t r = 0; r < out_h; ++r) {
          const uint8_t* srow = src + r * w * c;
          uint8_t* drow = dst + r * out_w * c;
          for (int64_t col = 0; col < out_w; ++col)
            std::memcpy(drow + col * c, srow + (out_w - 1 - col) * c,
                        static_cast<size_t>(c));
        }
      }
    }
  });
}

// Plain u8 row gather (no conversion): feeds the u8->device path where the
// normalize happens on-device instead of on-host.
void gather_rows_u8_raw(const uint8_t* data, int64_t feat,
                        const int64_t* indices, int64_t batch, uint8_t* out) {
  parallel_for(batch, [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      std::memcpy(out + i * feat, data + indices[i] * feat,
                  static_cast<size_t>(feat));
    }
  });
}

// In-place affine normalize of an f32 block (mean/disp style per-feature).
// out[i, j] = (out[i, j] - mean[j]) * inv_disp[j]
void normalize_rows_f32(float* data, int64_t rows, int64_t feat,
                        const float* mean, const float* inv_disp) {
  parallel_for(rows, [&](int64_t begin, int64_t end) {
    for (int64_t i = begin; i < end; ++i) {
      float* row = data + i * feat;
      for (int64_t j = 0; j < feat; ++j)
        row[j] = (row[j] - mean[j]) * inv_disp[j];
    }
  });
}

}  // extern "C"
