"""Headline benchmark: AlexNet training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "alexnet_images_per_sec", "value": N, "unit": "images/sec",
   "vs_baseline": mfu/0.35, ...}

``vs_baseline`` is measured model-FLOPs-utilization relative to the
BASELINE.json north-star gate of 35% MFU (the reference itself has no
published numbers to compare against — see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _model_flops_per_image(layers, input_shape) -> float:
    """Analytic fwd FLOPs (2*MACs) through the declarative layer list."""
    import numpy as np

    from znicz_tpu.ops import conv as conv_op, pooling as pool_op

    shape = (1,) + tuple(input_shape)
    total = 0.0
    for spec in layers:
        kind = spec["type"]
        fwd = spec.get("->", {})
        if kind.startswith("conv"):
            out = conv_op.output_shape(
                shape, fwd["n_kernels"], fwd["kx"], fwd["ky"],
                fwd.get("sliding", (1, 1)), fwd.get("padding", (0, 0, 0, 0)),
            )
            total += (
                2.0 * out[1] * out[2] * out[3]
                * fwd["kx"] * fwd["ky"] * shape[3]
            )
            shape = out
        elif kind.endswith("pooling"):
            shape = pool_op.output_shape(
                shape, fwd["kx"], fwd["ky"], fwd.get("sliding")
            )
        elif kind.startswith("all2all") or kind == "softmax":
            n_in = int(np.prod(shape[1:]))
            n_out = int(np.prod(fwd["output_sample_shape"]))
            total += 2.0 * n_in * n_out
            shape = (1, n_out)
    return total


def _metrics_snapshot() -> dict:
    """The process-wide telemetry registry, attached to every bench
    record (success or error) so each number carries the serve/train
    counters and latency histograms behind it."""
    try:
        from znicz_tpu.observability import get_registry

        return get_registry().snapshot()
    except Exception as e:
        # the record must still print even if telemetry import breaks
        print(f"metrics snapshot failed: {e!r}", file=sys.stderr)
        return {}


def main() -> None:
    """Run the bench; on ANY failure (backend init included — e.g. the
    relay TPU being unavailable) print ONE parseable JSON error line
    instead of a traceback, so the bench trajectory records WHY a round
    has no number."""
    try:
        _bench()
    except Exception as e:
        print(
            json.dumps(
                {
                    "error": type(e).__name__,
                    "detail": str(e)[:500],
                    "metrics_snapshot": _metrics_snapshot(),
                }
            )
        )
        print(f"bench failed: {type(e).__name__}: {e}", file=sys.stderr)
        raise SystemExit(1)


def _bench() -> None:
    t_setup = time.time()
    import jax

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.models import alexnet

    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    root.alexnet.loader.update(
        {"minibatch_size": batch, "n_train": batch, "n_valid": 0}
    )
    prng.seed_all(1234)
    wf = alexnet.build_workflow()
    wf.initialize(seed=1234)

    import jax.numpy as jnp

    mb = next(iter(wf.loader.batches("train")))
    x = jnp.asarray(mb.data)
    y = jnp.asarray(mb.labels)
    mask = jnp.asarray(mb.mask)

    # compile + warmup (steps carry the on-device metric accumulator)
    state, acc = wf._train_step(
        wf.state, x, y, mask, 1.0, wf._acc_init(), wf._ctx
    )
    state, acc = wf._train_step(state, x, y, mask, 1.0, acc, wf._ctx)
    jax.block_until_ready(acc)
    print(f"setup+compile {time.time()-t_setup:.1f}s", file=sys.stderr)

    # Remote-relay transports add a large fixed sync overhead per fetch;
    # difference two run lengths so the fixed cost cancels and only true
    # per-step device time remains.
    def timed(n):
        nonlocal state, acc
        t0 = time.time()
        for _ in range(n):
            state, acc = wf._train_step(state, x, y, mask, 1.0, acc, wf._ctx)
        # A value fetch (not just block_until_ready) is the only reliable
        # full-pipeline sync under remote-relay transports.
        float(jax.device_get(acc)[0])
        return time.time() - t0

    timed(2)  # absorb the donated-buffer-layout recompile
    timed(2)
    # relay noise is additive-positive and large (±20% on single shots):
    # min over repeats per run length is the robust estimator, and the
    # 3N-vs-N difference cancels the fixed sync cost
    t_short = min(timed(steps) for _ in range(3))
    t_long = min(timed(3 * steps) for _ in range(3))
    print(
        f"t_short({steps})={t_short:.3f}s t_long({3*steps})={t_long:.3f}s",
        file=sys.stderr,
    )
    dt = (t_long - t_short) / (2 * steps)  # seconds per step
    if dt <= 0:  # fell into noise; use the long run directly
        dt = t_long / (3 * steps)

    images_per_sec = batch / dt

    # ---- end-to-end epoch throughput: the production run_epoch path with
    # the loader IN the loop (shuffle, index gather, prefetch thread,
    # on-device normalize, per-epoch metric sync).  Two modes:
    #   device_resident — dataset pool in HBM, per batch only the index
    #     vector crosses host->device (the TPU-first mode for datasets that
    #     fit on-chip); this is the headline epoch number.
    #   streaming — u8 minibatches cross host->device each step (the
    #     ImageNet-at-scale mode).  Through this harness's remote relay the
    #     link runs at tens of MB/s (measured + reported below) vs multi-
    #     GB/s host DMA on co-located hardware, so the number is reported
    #     alongside the measured link bandwidth rather than as a framework
    #     property.
    import numpy as np

    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.workflow import StandardWorkflow

    n_epoch_imgs = int(os.environ.get("BENCH_EPOCH_IMAGES", str(8 * batch)))
    gen = np.random.default_rng(0)
    # dtype=uint8 up front: the default int64 would transiently be 8x the
    # final array (~GBs at default sizes)
    images_u8 = gen.integers(
        0, 256, (n_epoch_imgs, 227, 227, 3), dtype=np.uint8
    )
    labels = gen.integers(0, 1000, n_epoch_imgs).astype(np.int32)

    def epoch_rate(device_resident: bool, n_epochs: int):
        e_loader = FullBatchLoader(
            {"train": images_u8},
            {"train": labels},
            minibatch_size=batch,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_convert=not device_resident,
            device_resident=device_resident,
        )
        ewf = StandardWorkflow(
            e_loader,
            root.alexnet.get("layers"),
            decision_config={"max_epochs": 10000},
            compute_dtype="bfloat16",
            # deferred epoch sync: the metric fetch of epoch N rides
            # behind epoch N+1's dispatch, so the per-epoch transport
            # round trip overlaps compute (VERDICT r3 #4)
            epoch_sync="deferred",
            name="AlexNetEpochBench",
        )
        ewf.initialize(seed=7)
        ewf.run_epoch()  # compile + warmup
        ewf.sync_epoch()
        ewf.timer.reset()
        t0 = time.time()
        for _ in range(n_epochs):
            ewf.run_epoch()
        ewf.sync_epoch()  # observe the final epoch (timed: honest wall)
        wall = time.time() - t0
        # per-phase breakdown (VERDICT r3 gate: explain the epoch-vs-
        # compute-only gap): host stack+put, async scan dispatch, and the
        # blocking metric fetch — whatever wall time none of them covers
        # is untimed host work (shuffle, python loop)
        phases = {
            k: round(v["total_s"] / n_epochs, 4)
            for k, v in ewf.timer.summary().items()
        }
        phases["wall_per_epoch"] = round(wall / n_epochs, 4)
        return n_epoch_imgs * n_epochs / wall, phases

    # 15 epochs: the one blocking round trip left (the FINAL epoch's
    # deferred fetch) amortizes to ~1/15 of an epoch, and the longer run
    # averages over relay-latency jitter (the ratio wobbles ~+-0.01)
    epoch_images_per_sec, epoch_phases = epoch_rate(True, 15)
    print(
        f"epoch bench (device-resident): {epoch_images_per_sec:.0f} img/s "
        f"breakdown={epoch_phases}",
        file=sys.stderr,
    )
    streaming_images_per_sec, _ = epoch_rate(False, 1)

    # measured host->device link bandwidth: difference two chunk sizes so
    # the fixed per-round-trip sync cost cancels (same methodology as the
    # step timing above)
    def put_time(rows):
        chunk = images_u8[:rows]
        dev = jax.device_put(chunk)
        float(jnp.sum(dev.astype(jnp.float32))[None][0])  # force arrival
        t0 = time.time()
        dev = jax.device_put(chunk)
        float(jnp.sum(dev.astype(jnp.float32))[None][0])
        return chunk.nbytes, time.time() - t0

    put_time(64)  # warm both program shapes
    b_small, t_small = put_time(64)
    b_large, t_large = put_time(512)
    dt_put = t_large - t_small  # NOT `dt` — that is the step time above
    put_mbps = (
        (b_large - b_small) / dt_put / 1e6
        if dt_put > 0
        else b_large / max(t_large, 1e-9) / 1e6
    )
    print(
        f"epoch bench (streaming): {streaming_images_per_sec:.0f} img/s; "
        f"host->device link ~{put_mbps:.0f} MB/s",
        file=sys.stderr,
    )

    # ---- HBM-resident ImageNet pipeline (VERDICT r3 #5): the packed 256^2
    # pool ships ONCE; per step only [B, 4] int32 (row, oy, ox, flip)
    # crosses the link and random-crop+flip+normalize run inside the jitted
    # step.  This is the TPU-first answer to a slow host link for datasets
    # that fit HBM — steady-state behaves like device-resident, with real
    # reference augmentation semantics.
    import tempfile

    from znicz_tpu.loader.imagenet import ImageNetLoader

    n_imnet = int(os.environ.get("BENCH_IMAGENET_IMAGES", "4096"))
    pack_dir = tempfile.mkdtemp(prefix="bench_imnet_")
    pool = gen.integers(0, 256, (n_imnet, 256, 256, 3), dtype=np.uint8)
    np.save(os.path.join(pack_dir, "train_images.npy"), pool)
    np.save(
        os.path.join(pack_dir, "train_labels.npy"),
        gen.integers(0, 1000, n_imnet).astype(np.int32),
    )
    with open(os.path.join(pack_dir, "mean_rgb.json"), "w") as f:
        json.dump([0.485, 0.456, 0.406], f)
    del pool

    im_loader = ImageNetLoader(
        pack_dir, crop_size=227, minibatch_size=batch,
        device_resident=True,
    )
    iwf = StandardWorkflow(
        im_loader,
        root.alexnet.get("layers"),
        decision_config={"max_epochs": 10000},
        compute_dtype="bfloat16",
        # same deferred harness as the device-resident epoch bench: at
        # 4 steps/epoch a synchronous per-epoch fetch costs ~1/3 of the
        # epoch through the relay (r4 probe: the crop itself is ~0.8 ms)
        epoch_sync="deferred",
        name="ImageNetResidentBench",
    )
    iwf.initialize(seed=11)  # ships the 256^2 pool to HBM once
    iwf.run_epoch()  # compile + warmup
    iwf.sync_epoch()
    t0 = time.time()
    n_im_epochs = 12
    for _ in range(n_im_epochs):
        iwf.run_epoch()
    iwf.sync_epoch()
    imagenet_resident_images_per_sec = (
        n_imnet * n_im_epochs / (time.time() - t0)
    )
    print(
        f"epoch bench (HBM-resident imagenet, on-device crops): "
        f"{imagenet_resident_images_per_sec:.0f} img/s",
        file=sys.stderr,
    )
    import shutil

    shutil.rmtree(pack_dir, ignore_errors=True)

    # secondary metric (BASELINE.json): MNIST MLP step latency
    from znicz_tpu.models import mnist as mnist_model

    root.mnist.loader.update(
        {"minibatch_size": 100, "n_train": 100, "n_test": 0,
         "validation_ratio": 0.0}
    )
    mwf = mnist_model.build_workflow()
    mwf.initialize(seed=1234)
    mmb = next(iter(mwf.loader.batches("train")))
    mx, my, mmask = (
        jnp.asarray(mmb.data), jnp.asarray(mmb.labels), jnp.asarray(mmb.mask)
    )

    # Device-side measurement: N steps inside ONE compiled lax.fori_loop, so
    # per-step host dispatch and relay sync overhead amortize to zero and the
    # quotient is pure device step time (sub-ms steps would otherwise drown
    # in transport noise).
    from jax import lax

    step_fn = mwf.train_step_fn
    N_INNER = 1000

    @jax.jit
    def mnist_many_steps(state):
        def body(_, s):
            s2, _m = step_fn(s, mx, my, mmask, 1.0, mwf._ctx)
            return s2
        return lax.fori_loop(0, N_INNER, body, state)

    def _sync(arr):
        # a VALUE fetch is the only reliable full-pipeline sync through
        # remote-relay transports (block_until_ready returns early there)
        float(jnp.sum(arr)[None][0])

    mstate = mnist_many_steps(mwf.state)  # compile + warmup
    _sync(mstate.params[0]["weights"])

    def mnist_timed():
        nonlocal mstate
        t0 = time.time()
        mstate = mnist_many_steps(mstate)
        _sync(mstate.params[0]["weights"])
        return time.time() - t0

    # relay noise is additive-positive: discard the first post-warmup rep
    # (it absorbs still-queued async work) and min over the rest — the r3
    # 2x swing (0.058 -> 0.112 ms) came from a single-shot measurement
    mnist_timed()
    mnist_step_ms = min(mnist_timed() for _ in range(4)) / N_INNER * 1000

    # dispatch-bound regime: a small-model PRODUCTION epoch (run_epoch, 100
    # steps).  The scanned dispatch (one lax.scan per split) removes the
    # per-step host round trip that dominates sub-ms steps; the stepwise
    # number is reported alongside as the contrast.
    gen2 = np.random.default_rng(1)
    m_imgs = gen2.integers(0, 256, (12800, 28, 28, 1), dtype=np.uint8)
    m_labels = gen2.integers(0, 10, 12800).astype(np.int32)

    def mnist_epoch_rate(dispatch: str) -> float:
        ld = FullBatchLoader(
            {"train": m_imgs}, {"train": m_labels}, minibatch_size=128,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_resident=True,
        )
        ewf = StandardWorkflow(
            ld,
            [{"type": "all2all_tanh", "->": {"output_sample_shape": 256}},
             {"type": "softmax", "->": {"output_sample_shape": 10}}],
            decision_config={"max_epochs": 10000},
            default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
            epoch_dispatch=dispatch,
        )
        ewf.initialize(seed=3)
        ewf.run_epoch()  # compile + warmup
        t0 = time.time()
        for _ in range(3):
            ewf.run_epoch()
        return 3 * len(m_imgs) / (time.time() - t0)

    mnist_epoch_scan = mnist_epoch_rate("scan")
    mnist_epoch_step = mnist_epoch_rate("step")
    print(
        f"mnist epoch (100 steps): scan {mnist_epoch_scan:.0f} img/s vs "
        f"stepwise {mnist_epoch_step:.0f} img/s",
        file=sys.stderr,
    )

    # ---- SOM on the device-resident scan path (VERDICT r3 #1: the wiring
    # of device_preproc through every workflow family makes the
    # HBM-resident epoch available to non-backprop trainers too)
    from znicz_tpu.workflow import KohonenWorkflow

    som_loader = FullBatchLoader(
        {"train": m_imgs}, minibatch_size=128,
        normalization="range",
        normalization_kwargs={"scale": 255.0, "shift": -0.5},
        device_resident=True,
    )
    som_wf = KohonenWorkflow(
        som_loader, sx=8, sy=8, total_epochs=10000,
        epoch_sync="deferred",
    )
    som_wf.initialize(seed=5)
    assert som_wf._use_epoch_scan()
    som_wf.run_epoch()  # compile + warmup
    som_wf.sync_epoch()
    t0 = time.time()
    for _ in range(3):
        som_wf.run_epoch()
    som_wf.sync_epoch()
    som_epoch_images_per_sec = 3 * len(m_imgs) / (time.time() - t0)
    print(
        f"SOM epoch (device-resident scan): "
        f"{som_epoch_images_per_sec:.0f} img/s",
        file=sys.stderr,
    )

    # peak: TPU v5e bf16 ~197 TFLOP/s per chip (override for other chips)
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))

    # free EVERYTHING the earlier benches put in HBM before the LM
    # section — the AlexNet step bench alone pins ~1.4 GB (the [1024,
    # 227, 227, 3] f32 batch is 633 MB; params+momentum+pool the rest),
    # and with 9 LM variants the tail rows (MoE/decode/long) OOMed in
    # r5 trials while each passed in isolation.  fwd_flops only needs
    # the sample shape — capture it, then drop the objects.
    alex_sample_shape = wf.loader.sample_shape
    del iwf, im_loader, som_wf, som_loader, mstate, mwf
    del wf, state, acc, x, y, mask, mb
    import gc

    gc.collect()
    jax.clear_caches()

    # ---- transformer LM: the flagship beyond-parity model needs a
    # driver-visible number (VERDICT r3 #2).  Fixed ~11M-param GPT-small,
    # T=2048, bf16-on-MXU (jax default matmul precision), single chip.
    # Measured exactly like the MNIST step: N steps inside ONE compiled
    # fori_loop, min over repeats, value-fetch sync.
    from znicz_tpu.workflow.transformer import TransformerLMWorkflow

    LM_T = 2048
    LM = dict(vocab=8192, d_model=256, n_layers=8, n_heads=8)
    LM_B = 8
    # mid config (~50M matmul params): shows MFU scaling with model size
    # — d=256 matmuls are too small to tile the v5e MXU well; tokens/s is
    # FLAT from B=8 to B=32 (step time scales with B — every extra row
    # costs proportional time), so the small model is geometry/utilization
    # -bound, not framework-bound
    LM_MID = dict(vocab=8192, d_model=512, n_layers=12, n_heads=8)
    LM_MID_B = 16
    lm_tokens = np.random.default_rng(6).integers(
        0, 8192, (2 * max(LM_B, LM_MID_B), LM_T)
    ).astype(np.int32)

    def lm_train_flops_per_token(cfg) -> float:
        # matmul params (QKV+O, FFN, head — embed/pos are gathers/adds)
        # x 2, plus CAUSAL attention scores+weighted-sum 2*T*D per layer
        # per token (avg attended length T/2; the flash kernel skips the
        # entirely-masked blocks, so counting the full bidirectional
        # 4*T*D would inflate MFU ~1.2x at the mid config — the r4
        # numbers did).  Training ~ 3x forward (fwd + input-grad +
        # weight-grad); remat recomputes fwd (~4x) but MFU uses the
        # remat-off run.  Convention reported as lm_flops_convention.
        d, L, v = cfg["d_model"], cfg["n_layers"], cfg["vocab"]
        d_ff = cfg.get("d_ff") or 4 * d
        p_mat = L * (4 * d * d + 2 * d * d_ff) + d * v
        return 3.0 * (2.0 * p_mat + 2.0 * L * LM_T * d)

    def lm_rate(
        cfg, b, attention: str, remat: bool, tokens=None, extra=None
    ) -> float:
        tokens = lm_tokens if tokens is None else tokens
        t_len = tokens.shape[1]
        prng.seed_all(99)
        ld = FullBatchLoader(
            {"train": tokens[: 2 * b].copy()}, minibatch_size=b
        )
        lwf = TransformerLMWorkflow(
            ld, max_epochs=1, attention=attention, remat=remat,
            **cfg, **(extra or {}),
        )
        lwf.initialize(seed=99)
        lx = jnp.asarray(tokens[:b])
        ly = jnp.zeros((b,), jnp.int32)
        lmask = jnp.ones((b,), jnp.float32)
        lstep = lwf.train_step_fn
        n_inner = 20

        @jax.jit
        def lm_many(state):
            def body(_, s):
                s2, _m = lstep(s, lx, ly, lmask, 1.0, lwf._ctx)
                return s2
            return lax.fori_loop(0, n_inner, body, state)

        st = lm_many(lwf.state)  # compile + warmup
        _sync(st.params[0]["embed"])

        def timed():
            nonlocal st
            t0 = time.time()
            st = lm_many(st)
            _sync(st.params[0]["embed"])
            return time.time() - t0

        dt = min(timed() for _ in range(3)) / n_inner
        return b * t_len / dt

    def lm_rate_safe(
        cfg, b, attention, remat, tokens=None, extra=None
    ) -> float:
        # HBM headroom through the relay varies run to run — a failed LM
        # variant must degrade to 0.0, never kill the whole bench
        try:
            return lm_rate(cfg, b, attention, remat, tokens=tokens,
                           extra=extra)
        except Exception as e:
            print(
                f"lm config d={cfg['d_model']} B={b} {attention} "
                f"remat={remat} failed: {type(e).__name__}",
                file=sys.stderr,
            )
            return 0.0
        finally:
            # compiled executables pin HBM; with 9+ LM variants in one
            # process the accumulation OOMed the tail rows (r5 trial 1:
            # MoE/decode/long all JaxRuntimeError, each fine in isolation)
            jax.clear_caches()
            gc.collect()

    lm_flash = lm_rate_safe(LM, LM_B, "flash", remat=False)
    lm_dense = lm_rate_safe(LM, LM_B, "dot", remat=False)
    lm_flash_remat = lm_rate_safe(LM, LM_B, "flash", remat=True)
    lm_mfu = lm_flash * lm_train_flops_per_token(LM) / peak
    lm_mid = lm_rate_safe(LM_MID, LM_MID_B, "flash", remat=False)
    if not lm_mid:
        LM_MID_B = 8
        lm_mid = lm_rate_safe(LM_MID, LM_MID_B, "flash", remat=False)
    lm_mid_mfu = lm_mid * lm_train_flops_per_token(LM_MID) / peak

    # hd=128 variant (same d=512 tower, 4 heads x 128): tests the r4
    # hypothesis that QK^T at head_dim 64 half-fills the MXU's 128-lane
    # contraction dim.  Same matmul params, same counted FLOPs.
    LM_HD128 = dict(LM_MID, n_heads=4)
    lm_hd128 = lm_rate_safe(LM_HD128, LM_MID_B, "flash", remat=False)
    lm_hd128_mfu = lm_hd128 * lm_train_flops_per_token(LM_HD128) / peak

    # bf16 attention (q/k/v on the MXU in bf16, f32 accumulation): the r5
    # kernel keeps input dtype — standalone fwd+full-bwd 12.7 -> 10.7 ms
    # (hd64) / 6.0 -> 4.3 ms (hd128)
    bf16 = dict(attention_dtype="bf16")
    lm_mid_bf16 = lm_rate_safe(
        LM_MID, LM_MID_B, "flash", remat=False, extra=bf16
    )
    lm_hd128_bf16 = lm_rate_safe(
        LM_HD128, LM_MID_B, "flash", remat=False, extra=bf16
    )
    lm_hd128_bf16_mfu = (
        lm_hd128_bf16 * lm_train_flops_per_token(LM_HD128) / peak
    )

    # MoE perf at matched ACTIVE FLOPs (VERDICT r4 weak #3): E=8 experts
    # of d_ff=1024 at top_k=2 activate exactly the dense tower's
    # d_ff=2048-worth of FFN FLOPs per token, so tokens/s is directly
    # comparable to lm_mid.  Dense dispatch runs all 8 experts (4x the
    # active FFN FLOPs — the "trades k/E of the FLOPs" cost made
    # visible); capacity dispatch computes only the routed tokens.
    LM_MOE = dict(LM_MID, d_ff=1024)
    moe_kw = dict(moe_experts=8, moe_top_k=2)
    lm_moe_dense = lm_rate_safe(
        LM_MOE, LM_MID_B, "flash", remat=False,
        extra=dict(moe_kw, moe_dispatch="dense"),
    )
    lm_moe_capacity = lm_rate_safe(
        LM_MOE, LM_MID_B, "flash", remat=False,
        extra=dict(moe_kw, moe_dispatch="capacity"),
    )

    # KV-cache decode (VERDICT r4 weak #2): greedy generation on the mid
    # config — prefill 64-token prompts, decode 256 new tokens/row in ONE
    # compiled lax.scan; rate counts generated tokens only.
    from znicz_tpu.workflow.generate import generate as lm_generate

    def lm_decode_rate(cfg, b, prompt_len, new_tokens) -> float:
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(97)
        params = init_lm_params(
            cfg["vocab"], cfg["d_model"], cfg["n_layers"], cfg["n_heads"],
            max_seq=prompt_len + new_tokens,
        )
        prompt = jnp.asarray(
            lm_tokens[:b, :prompt_len] % cfg["vocab"], jnp.int32
        )
        kw = dict(n_heads=cfg["n_heads"], max_new_tokens=new_tokens)
        out = lm_generate(params, prompt, **kw)  # compile + warmup
        _sync(out.astype(jnp.float32))

        def timed():
            t0 = time.time()
            o = lm_generate(params, prompt, **kw)
            _sync(o.astype(jnp.float32))
            return time.time() - t0

        dt = min(timed() for _ in range(3))
        return b * new_tokens / dt

    try:
        lm_decode = lm_decode_rate(LM_MID, LM_MID_B, 64, 256)
    except Exception as e:
        print(f"lm decode failed: {type(e).__name__}", file=sys.stderr)
        lm_decode = 0.0
    finally:
        jax.clear_caches()
        gc.collect()

    # ---- decode SERVING (ISSUE 2): continuous batching over a mixed-
    # prompt-length request stream.  The engine coalesces ragged prompts
    # into a fixed-slot batch over static KV buffers: admit programs
    # compile once per prompt-length bucket, the chunked per-row decode
    # program compiles ONCE, and rows retire/admit independently — so
    # the whole stream runs recompile-free (lm_serve_compiles is the
    # total distinct-program count, reported to catch regressions).
    LM_SERVE_LENS = (16, 40, 64, 120)  # buckets 16 / 64 / 64 / 128
    LM_SERVE_NEW = 64

    def lm_serve_stats(cfg, b):
        from znicz_tpu.services.engine import DecodeEngine
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(95)
        params = init_lm_params(
            cfg["vocab"], cfg["d_model"], cfg["n_layers"], cfg["n_heads"],
            max_seq=256,
        )
        reqs = np.random.default_rng(12)

        def make_engine():
            return DecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0, batch_size=b,
                admit_every=8, max_seq=256,
            )

        def stream(eng, n):
            for j in range(n):
                length = LM_SERVE_LENS[j % len(LM_SERVE_LENS)]
                eng.submit(
                    reqs.integers(1, cfg["vocab"], (length,)).astype(
                        np.int32
                    ),
                    max_new_tokens=LM_SERVE_NEW,
                )
            return eng.run()

        stream(make_engine(), len(LM_SERVE_LENS))  # compile every bucket
        eng = make_engine()  # fresh engine rides the warm jit cache
        t0 = time.time()
        comps = stream(eng, 4 * b)
        wall = time.time() - t0
        toks = sum(c.n_new for c in comps)
        return toks / wall, eng.stats()

    try:
        lm_serve, lm_serve_st = lm_serve_stats(LM_MID, LM_MID_B)
    except Exception as e:
        print(f"lm serve failed: {type(e).__name__}", file=sys.stderr)
        lm_serve, lm_serve_st = 0.0, {}
    finally:
        jax.clear_caches()
        gc.collect()
    print(
        f"LM serving (continuous batching, mixed prompts "
        f"{LM_SERVE_LENS}): {lm_serve:.0f} tok/s, "
        f"{lm_serve_st.get('n_programs', 0)} compiled programs, "
        f"latency {lm_serve_st.get('latency', {})}",
        file=sys.stderr,
    )

    # ---- PAGED serving (ISSUE 4): the same mixed stream through the
    # block-pool engine, pool sized to the dense engine's EXACT KV
    # footprint (B slots x t_max tokens) so tokens/s is an apples-to-
    # apples layout comparison, plus a max-sustained-concurrency probe:
    # 2x the slots against that same pool with short requests — the
    # dense layout caps at B rows in this memory; the paged pool packs
    # them by blocks actually used (peak_active is the measured answer,
    # preemptions how often pressure forced an eviction).
    # block 32: at the mid config the fatter prefill chunk/window halves
    # host dispatches for the same pool memory (32-multiple padding on
    # this stream matches the dense bucket ladder's anyway)
    LM_SERVE_PAGED_BLOCK = 32

    def lm_serve_paged_stats(cfg, b):
        from znicz_tpu.services.engine import PagedDecodeEngine
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(95)
        params = init_lm_params(
            cfg["vocab"], cfg["d_model"], cfg["n_layers"], cfg["n_heads"],
            max_seq=256,
        )
        reqs = np.random.default_rng(12)
        block = LM_SERVE_PAGED_BLOCK
        n_blocks = b * (256 // block) + 1  # dense footprint + null block

        def make_engine(slots):
            return PagedDecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0,
                batch_size=slots, admit_every=8, max_seq=256,
                block_size=block, n_blocks=n_blocks,
            )

        def stream(eng, n):
            for j in range(n):
                length = LM_SERVE_LENS[j % len(LM_SERVE_LENS)]
                eng.submit(
                    reqs.integers(1, cfg["vocab"], (length,)).astype(
                        np.int32
                    ),
                    max_new_tokens=LM_SERVE_NEW,
                )
            return eng.run()

        stream(make_engine(b), len(LM_SERVE_LENS))  # warm both programs
        eng = make_engine(b)  # fresh engine rides the warm jit cache
        t0 = time.time()
        comps = stream(eng, 4 * b)
        wall = time.time() - t0
        toks = sum(c.n_new for c in comps)
        # concurrency probe: short requests (16-token prompts, 16-token
        # budgets = 2 blocks each) through 2x slots over the same pool
        probe = make_engine(2 * b)
        for _ in range(4 * b):
            probe.submit(
                reqs.integers(1, cfg["vocab"], (16,)).astype(np.int32),
                max_new_tokens=16,
            )
        probe.run()
        return toks / wall, eng.stats(), probe.stats()

    try:
        lm_serve_paged, lm_paged_st, lm_paged_probe = lm_serve_paged_stats(
            LM_MID, LM_MID_B
        )
    except Exception as e:
        print(f"lm serve paged failed: {type(e).__name__}", file=sys.stderr)
        lm_serve_paged, lm_paged_st, lm_paged_probe = 0.0, {}, {}
    finally:
        jax.clear_caches()
        gc.collect()
    print(
        f"LM serving PAGED (block {LM_SERVE_PAGED_BLOCK}, mixed prompts "
        f"{LM_SERVE_LENS}): {lm_serve_paged:.0f} tok/s "
        f"({lm_paged_st.get('n_programs', 0)} programs, "
        f"{lm_paged_st.get('preemptions', 0)} preemptions); "
        f"concurrency probe peak {lm_paged_probe.get('peak_active', 0)} "
        f"rows (dense layout caps at {LM_MID_B} in the same memory)",
        file=sys.stderr,
    )

    # long context: flash (O(T*D) memory) + remat train the mid model at
    # 8x the headline sequence length on ONE chip — dense attention OOMs
    # at T=2048 already.  T=16384, B=2 (32k tokens/step, same as mid).
    LM_LONG_T, LM_LONG_B = 16384, 2
    lm_long_tokens = np.random.default_rng(8).integers(
        0, 8192, (2 * LM_LONG_B, LM_LONG_T)
    ).astype(np.int32)
    lm_long = lm_rate_safe(
        LM_MID, LM_LONG_B, "flash", remat=True, tokens=lm_long_tokens
    )
    print(
        f"LM GPT-small T={LM_T}: flash {lm_flash:.0f} tok/s "
        f"(causal MFU {lm_mfu:.3f}), dense {lm_dense:.0f}, "
        f"flash+remat {lm_flash_remat:.0f}; "
        f"mid 512dx12L: {lm_mid:.0f} tok/s (MFU {lm_mid_mfu:.3f}); "
        f"hd128 4Hx128: {lm_hd128:.0f} tok/s (MFU {lm_hd128_mfu:.3f}); "
        f"bf16-attn mid {lm_mid_bf16:.0f} / hd128 {lm_hd128_bf16:.0f} "
        f"tok/s (MFU {lm_hd128_bf16_mfu:.3f}); "
        f"moe E=8 k=2 dense {lm_moe_dense:.0f} / capacity "
        f"{lm_moe_capacity:.0f} tok/s; decode {lm_decode:.0f} tok/s; "
        f"long T={LM_LONG_T}: {lm_long:.0f} tok/s",
        file=sys.stderr,
    )
    fwd_flops = _model_flops_per_image(
        root.alexnet.get("layers"), alex_sample_shape
    )
    train_flops = 3.0 * fwd_flops  # fwd + input-grad + weight-grad
    mfu = images_per_sec * train_flops / peak
    print(
        json.dumps(
            {
                "metric": "alexnet_images_per_sec",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(mfu / 0.35, 4),
                "mfu": round(mfu, 4),
                "batch": batch,
                "step_ms": round(1000 * dt, 2),
                "epoch_images_per_sec": round(epoch_images_per_sec, 2),
                "epoch_vs_compute_only": round(
                    epoch_images_per_sec / images_per_sec, 4
                ),
                "epoch_streaming_images_per_sec": round(
                    streaming_images_per_sec, 2
                ),
                "imagenet_resident_images_per_sec": round(
                    imagenet_resident_images_per_sec, 2
                ),
                "imagenet_resident_vs_device_resident": round(
                    imagenet_resident_images_per_sec / epoch_images_per_sec,
                    4,
                ),
                "epoch_breakdown_s": epoch_phases,
                # the epoch-vs-compute gap, explained (VERDICT r3 #4): the
                # scanned epoch is ONE async dispatch; all wall time sits
                # in the blocking metric fetch = device compute (epoch
                # images / compute-only rate) + ONE transport round trip.
                # The residual below is that round trip — µs on co-located
                # hosts, ~0.1-0.2 s through this harness's remote relay.
                "epoch_sync_residual_s": round(
                    epoch_phases.get("metrics_sync", 0.0)
                    - n_epoch_imgs / images_per_sec,
                    4,
                ),
                "host_to_device_MBps": round(put_mbps, 1),
                "mnist_mlp_step_ms": round(mnist_step_ms, 3),
                # min-of-4 after a discarded rep since r4: the r3 0.112 ms
                # was a single-shot reading through the relay whose first
                # measurement absorbs queued async work — measurement
                # noise, not a regression (min-of-reps reproduces ~0.07-0.08)
                "mnist_step_method": "fori_loop_1000_min4_discard1",
                "mnist_epoch_scan_images_per_sec": round(
                    mnist_epoch_scan, 1
                ),
                "mnist_epoch_step_images_per_sec": round(
                    mnist_epoch_step, 1
                ),
                "som_epoch_images_per_sec": round(
                    som_epoch_images_per_sec, 1
                ),
                "lm_config": (
                    f"GPT-small {LM['d_model']}d x {LM['n_layers']}L x "
                    f"{LM['n_heads']}H, vocab {LM['vocab']}, T={LM_T}, "
                    f"B={LM_B}, bf16-on-MXU"
                ),
                "lm_tokens_per_sec": round(lm_flash, 1),
                "lm_mfu": round(lm_mfu, 4),
                "lm_flash_vs_dense": round(
                    lm_flash / lm_dense if lm_dense else 0.0, 4
                ),
                "lm_remat_vs_no_remat": round(
                    lm_flash_remat / lm_flash if lm_flash else 0.0, 4
                ),
                "lm_mid_config": (
                    f"{LM_MID['d_model']}d x {LM_MID['n_layers']}L x "
                    f"{LM_MID['n_heads']}H, vocab {LM_MID['vocab']}, "
                    f"T={LM_T}, B={LM_MID_B}"
                ),
                "lm_mid_tokens_per_sec": round(lm_mid, 1),
                "lm_mid_mfu": round(lm_mid_mfu, 4),
                # MFU accounting counts CAUSAL attention (2*L*T*D per
                # token — avg attended length T/2, matching what the
                # flash kernel actually computes), not bidirectional
                "lm_flops_convention": "causal_attention_2LTD",
                "lm_hd128_config": (
                    f"{LM_HD128['d_model']}d x {LM_HD128['n_layers']}L x "
                    f"4H(hd=128), T={LM_T}, B={LM_MID_B}"
                ),
                "lm_hd128_tokens_per_sec": round(lm_hd128, 1),
                "lm_hd128_mfu": round(lm_hd128_mfu, 4),
                "lm_hd128_vs_mid": round(
                    lm_hd128 / lm_mid if lm_mid else 0.0, 4
                ),
                "lm_mid_bf16_attn_tokens_per_sec": round(lm_mid_bf16, 1),
                "lm_hd128_bf16_attn_tokens_per_sec": round(
                    lm_hd128_bf16, 1
                ),
                "lm_hd128_bf16_attn_mfu": round(lm_hd128_bf16_mfu, 4),
                "lm_best_vs_r4_mid": round(
                    max(lm_hd128_bf16, lm_hd128, lm_mid_bf16, lm_mid)
                    / 134730.3,
                    4,
                ),
                "lm_moe_config": (
                    "mid tower, E=8 experts d_ff=1024 top_k=2 "
                    "(active FFN FLOPs == dense d_ff=2048)"
                ),
                "lm_moe_dense_tokens_per_sec": round(lm_moe_dense, 1),
                "lm_moe_capacity_tokens_per_sec": round(lm_moe_capacity, 1),
                "lm_moe_dense_vs_dense_ffn": round(
                    lm_moe_dense / lm_mid if lm_mid else 0.0, 4
                ),
                "lm_moe_capacity_vs_dense_ffn": round(
                    lm_moe_capacity / lm_mid if lm_mid else 0.0, 4
                ),
                "lm_decode_config": (
                    "mid config, greedy KV-cache decode: prompt 64, "
                    f"256 new tokens, B={LM_MID_B}, one lax.scan"
                ),
                "lm_decode_tokens_per_sec": round(lm_decode, 1),
                "lm_serve_config": (
                    f"mid config engine: B={LM_MID_B} slots, mixed "
                    f"prompts {LM_SERVE_LENS}, budget {LM_SERVE_NEW}, "
                    "admit_every 8, eos 0, greedy"
                ),
                "lm_serve_tokens_per_sec": round(lm_serve, 1),
                "lm_serve_compiles": lm_serve_st.get("n_programs", 0),
                "lm_serve_requests": lm_serve_st.get("completed", 0),
                "lm_serve_latency_ms": {
                    k: round(v, 1)
                    for k, v in lm_serve_st.get("latency", {}).items()
                },
                "lm_serve_paged_config": (
                    f"mid config paged engine: B={LM_MID_B} slots, "
                    f"block {LM_SERVE_PAGED_BLOCK}, pool == dense "
                    f"footprint ({LM_MID_B}x256 tokens), mixed prompts "
                    f"{LM_SERVE_LENS}, budget {LM_SERVE_NEW}; probe: "
                    f"2x slots, 16+16-token requests, same pool"
                ),
                "lm_serve_paged_tokens_per_sec": round(lm_serve_paged, 1),
                "lm_serve_paged_vs_dense": round(
                    lm_serve_paged / lm_serve if lm_serve else 0.0, 4
                ),
                "lm_serve_paged_compiles": lm_paged_st.get(
                    "n_programs", 0
                ),
                "lm_serve_paged_preemptions": lm_paged_st.get(
                    "preemptions", 0
                ),
                "lm_serve_paged_max_concurrency": lm_paged_probe.get(
                    "peak_active", 0
                ),
                "lm_serve_paged_latency_ms": {
                    k: round(v, 1)
                    for k, v in lm_paged_st.get("latency", {}).items()
                },
                "lm_long_context": (
                    f"mid config at T={LM_LONG_T}, B={LM_LONG_B}, "
                    "flash+remat (dense OOMs at T=2048 already)"
                ),
                "lm_long_tokens_per_sec": round(lm_long, 1),
                "device": str(jax.devices()[0].device_kind),
                # full telemetry registry behind this run's numbers:
                # phase histograms, serve counters/latency, cache stats
                "metrics_snapshot": _metrics_snapshot(),
            }
        )
    )


if __name__ == "__main__":
    main()
