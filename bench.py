"""Headline benchmarks: training + serving throughput on one TPU chip.

Prints one JSON line PER SECTION:
  {"metric": "alexnet_images_per_sec", "value": N, "unit": "images/sec",
   "vs_baseline": mfu/0.35, ...}
  {"metric": "lm_tokens_per_sec", ...}
  ...
  {"metric": "bench_sections_failed", "value": K, "failed_sections": []}

Each section runs in its own try/except and emits its own
``{"metric": ...}`` or ``{"error": ..., "section": ...}`` record, so one
section's failure (or one backend hiccup mid-run) can never zero out the
whole round — BENCH_r05 lost every number to a single init flake.
Backend bring-up itself retries with backoff before anything runs.

``--only <prefix>`` re-runs just the sections whose name starts with
the prefix (cheap re-runs: ``python bench.py --only lm_serve``).

``vs_baseline`` on the AlexNet record is measured
model-FLOPs-utilization relative to the BASELINE.json north-star gate
of 35% MFU (the reference itself has no published numbers to compare
against — see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

# ---------------------------------------------------------------------------
# shared helpers

_SECTIONS = []


def _section(name):
    """Register a bench section: ``fn(ctx) -> list-of-records``."""

    def deco(fn):
        _SECTIONS.append((name, fn))
        return fn

    return deco


def emit(rec) -> None:
    """One record, one parseable line."""
    print(json.dumps(rec), flush=True)


def _metrics_snapshot() -> dict:
    """The process-wide telemetry registry, attached to error records
    and the final summary so every round carries the serve/train
    counters and latency histograms behind it.  A ``"slo"`` entry
    (``{"type": "slo", ...}`` — self-describing next to the metric
    families) carries the lifetime SLO judgment over the same registry:
    per-target percentiles, burn rates and the breach flag that
    ``tools/znicz-slo`` gates on.  A ``"programs"`` entry (same
    self-describing shape) carries the device/compile ledger headline —
    every round records how many programs the run compiled, their total
    compile wall seconds and the per-kind split, so a compile-count
    regression is diffable round-over-round via znicz-bench-diff."""
    try:
        from znicz_tpu.observability import device, get_registry
        from znicz_tpu.observability import slo as slo_mod

        from znicz_tpu.observability.pipeline import PipelineAttribution

        snap = get_registry().snapshot()
        snap["slo"] = slo_mod.lifetime_snapshot()
        # the input-pipeline attribution verdict over the whole round
        # ({"type": "pipeline"} — self-describing like "slo", skipped
        # by the aggregator's family merge)
        snap["pipeline"] = PipelineAttribution.from_registry().attribution()
        ledger = device.ledger_snapshot()
        snap["programs"] = {
            "type": "programs",
            "count": ledger["count"],
            "engine_count": ledger["engine_count"],
            "by_kind": ledger["by_kind"],
            "compile_seconds_total": ledger["compile_seconds_total"],
        }
        return snap
    except Exception as e:
        # the record must still print even if telemetry import breaks
        print(f"metrics snapshot failed: {e!r}", file=sys.stderr)
        return {}


def _program_headline() -> dict:
    """Top-level numeric compile-ledger fields for the summary record
    (``programs_compiled`` is lower-better under znicz-bench-diff's
    name heuristic — a compile-count regression across rounds fails
    the gate)."""
    try:
        from znicz_tpu.observability import device

        # the two scalars only — ledger_snapshot() would copy every
        # entry and poll per-device memory_stats a second time per
        # record (metrics_snapshot already does that once)
        return {
            "programs_compiled": device.program_count(),
            "programs_compile_seconds": device.compile_seconds_total(),
        }
    except Exception as e:
        print(f"program headline failed: {e!r}", file=sys.stderr)
        return {}


def _init_backend(retries: int = 3, delay: float = 2.0, probe=None):
    """Bounded-retry backend bring-up with exponential backoff.

    BENCH_r05 lost the whole round to one transient ``Unable to
    initialize backend 'axon': UNAVAILABLE`` — a relay-side flake, not
    a code failure.  Between attempts the cached backend state is
    dropped (best-effort) so the retry actually re-probes the device.
    ``probe`` is injectable for the tier-1 schema test."""
    last = None
    for i in range(retries):
        try:
            if probe is not None:
                return probe()
            import jax

            devs = jax.devices()
            print(
                f"backend up: {devs[0].device_kind} x{len(devs)}",
                file=sys.stderr,
            )
            return devs
        except Exception as e:
            last = e
            print(
                f"backend init attempt {i + 1}/{retries} failed: "
                f"{type(e).__name__}: {e}",
                file=sys.stderr,
            )
            if i + 1 < retries:
                try:  # drop any cached failed-backend state before retrying
                    import jax

                    jax.clear_caches()
                    from jax.extend import backend as _jeb

                    _jeb.clear_backends()
                except Exception as clear_err:
                    # retry proceeds anyway, but say WHY the re-probe may
                    # still see the cached dead backend
                    print(
                        f"backend cache clear failed: {clear_err!r}",
                        file=sys.stderr,
                    )
                time.sleep(delay * (2 ** i))
    raise last


def run_sections(sections=None, only=None, emit_record=emit,
                 budget_s=None):
    """Run bench sections under per-section isolation AND a per-section
    wall-clock budget; returns the list of failed section names.
    Records flow through ``emit_record`` (one call per record) —
    injectable for the tier-1 schema test.

    Each section runs on a worker thread joined with ``budget_s``
    (default ``BENCH_SECTION_BUDGET_S`` env, 900 s): a HUNG section —
    a wedged device call, a deadlocked engine — emits its own
    ``{"error": "timeout", "section": ...}`` record and the round moves
    on instead of stalling forever.  The abandoned worker is daemonic;
    it may keep contending for the device until the process exits, so
    a timeout can degrade (not zero) the sections after it — the
    timeout record names the culprit."""
    import threading

    if budget_s is None:
        budget_s = float(os.environ.get("BENCH_SECTION_BUDGET_S", "900"))
    ctx: dict = {}
    failed = []
    for name, fn in (_SECTIONS if sections is None else sections):
        if only and not name.startswith(only):
            continue
        t0 = time.time()
        print(f"=== section {name}", file=sys.stderr)
        holder: dict = {}

        def _worker(fn=fn):
            try:
                holder["records"] = list(fn(ctx) or [])
            except Exception as e:  # reported by the join below
                holder["exc"] = e

        worker = threading.Thread(
            target=_worker, name=f"bench-{name}", daemon=True
        )
        worker.start()
        worker.join(timeout=budget_s if budget_s > 0 else None)
        if worker.is_alive():
            failed.append(name)
            emit_record(
                {
                    "error": "timeout",
                    "section": name,
                    "budget_s": budget_s,
                }
            )
            print(
                f"=== section {name} TIMED OUT after {budget_s:.0f}s "
                "(worker abandoned)",
                file=sys.stderr,
            )
            continue
        if "exc" in holder:
            e = holder["exc"]
            failed.append(name)
            traceback.print_exception(
                type(e), e, e.__traceback__, file=sys.stderr
            )
            emit_record(
                {
                    "error": type(e).__name__,
                    "section": name,
                    "detail": str(e)[:500],
                }
            )
        else:
            for rec in holder.get("records", []):
                emit_record(rec)
        print(
            f"=== section {name} done in {time.time() - t0:.1f}s",
            file=sys.stderr,
        )
    return failed


def _peak_flops() -> float:
    # peak: TPU v5e bf16 ~197 TFLOP/s per chip (override for other chips)
    return float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))


def _sync(arr):
    """A VALUE fetch is the only reliable full-pipeline sync through
    remote-relay transports (block_until_ready returns early there)."""
    import jax.numpy as jnp

    float(jnp.sum(arr)[None][0])


def _model_flops_per_image(layers, input_shape) -> float:
    """Analytic fwd FLOPs (2*MACs) through the declarative layer list."""
    import numpy as np

    from znicz_tpu.ops import conv as conv_op, pooling as pool_op

    shape = (1,) + tuple(input_shape)
    total = 0.0
    for spec in layers:
        kind = spec["type"]
        fwd = spec.get("->", {})
        if kind.startswith("conv"):
            out = conv_op.output_shape(
                shape, fwd["n_kernels"], fwd["kx"], fwd["ky"],
                fwd.get("sliding", (1, 1)), fwd.get("padding", (0, 0, 0, 0)),
            )
            total += (
                2.0 * out[1] * out[2] * out[3]
                * fwd["kx"] * fwd["ky"] * shape[3]
            )
            shape = out
        elif kind.endswith("pooling"):
            shape = pool_op.output_shape(
                shape, fwd["kx"], fwd["ky"], fwd.get("sliding")
            )
        elif kind.startswith("all2all") or kind == "softmax":
            n_in = int(np.prod(shape[1:]))
            n_out = int(np.prod(fwd["output_sample_shape"]))
            total += 2.0 * n_in * n_out
            shape = (1, n_out)
    return total


# ---------------------------------------------------------------------------
# training sections


@_section("alexnet_step")
def _sec_alexnet(ctx):
    t_setup = time.time()
    import jax
    import jax.numpy as jnp

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.models import alexnet

    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    ctx["batch"] = batch
    root.alexnet.loader.update(
        {"minibatch_size": batch, "n_train": batch, "n_valid": 0}
    )
    prng.seed_all(1234)
    wf = alexnet.build_workflow()
    wf.initialize(seed=1234)
    ctx["alex_sample_shape"] = wf.loader.sample_shape
    ctx["alex_layers"] = root.alexnet.get("layers")

    mb = next(iter(wf.loader.batches("train")))
    x = jnp.asarray(mb.data)
    y = jnp.asarray(mb.labels)
    mask = jnp.asarray(mb.mask)

    # compile + warmup (steps carry the on-device metric accumulator)
    state, acc, _w = wf._train_step(
        wf.state, x, y, mask, 1.0, wf._acc_init(), wf._ctx
    )
    state, acc, _w = wf._train_step(state, x, y, mask, 1.0, acc, wf._ctx)
    jax.block_until_ready(acc)
    print(f"setup+compile {time.time()-t_setup:.1f}s", file=sys.stderr)

    # Remote-relay transports add a large fixed sync overhead per fetch;
    # difference two run lengths so the fixed cost cancels and only true
    # per-step device time remains.
    def timed(n):
        nonlocal state, acc
        t0 = time.time()
        for _ in range(n):
            state, acc, _w = wf._train_step(state, x, y, mask, 1.0, acc, wf._ctx)
        # A value fetch (not just block_until_ready) is the only reliable
        # full-pipeline sync under remote-relay transports.
        float(jax.device_get(acc)[0])
        return time.time() - t0

    timed(2)  # absorb the donated-buffer-layout recompile
    timed(2)
    # relay noise is additive-positive and large (±20% on single shots):
    # min over repeats per run length is the robust estimator, and the
    # 3N-vs-N difference cancels the fixed sync cost
    t_short = min(timed(steps) for _ in range(3))
    t_long = min(timed(3 * steps) for _ in range(3))
    print(
        f"t_short({steps})={t_short:.3f}s t_long({3*steps})={t_long:.3f}s",
        file=sys.stderr,
    )
    dt = (t_long - t_short) / (2 * steps)  # seconds per step
    if dt <= 0:  # fell into noise; use the long run directly
        dt = t_long / (3 * steps)

    images_per_sec = batch / dt
    ctx["alexnet_images_per_sec"] = images_per_sec

    fwd_flops = _model_flops_per_image(
        ctx["alex_layers"], ctx["alex_sample_shape"]
    )
    train_flops = 3.0 * fwd_flops  # fwd + input-grad + weight-grad
    mfu = images_per_sec * train_flops / _peak_flops()
    return [
        {
            "metric": "alexnet_images_per_sec",
            "value": round(images_per_sec, 2),
            "unit": "images/sec",
            "vs_baseline": round(mfu / 0.35, 4),
            "mfu": round(mfu, 4),
            "batch": batch,
            "step_ms": round(1000 * dt, 2),
            "device": str(jax.devices()[0].device_kind),
        }
    ]


@_section("alexnet_epoch")
def _sec_epoch(ctx):
    # end-to-end epoch throughput: the production run_epoch path with
    # the loader IN the loop (shuffle, index gather, prefetch thread,
    # on-device normalize, per-epoch metric sync).  Two modes:
    #   device_resident — dataset pool in HBM, per batch only the index
    #     vector crosses host->device (the TPU-first mode for datasets
    #     that fit on-chip); this is the headline epoch number.
    #   streaming — u8 minibatches cross host->device each step (the
    #     ImageNet-at-scale mode).  Through this harness's remote relay
    #     the link runs at tens of MB/s (measured + reported below) vs
    #     multi-GB/s host DMA on co-located hardware, so the number is
    #     reported alongside the measured link bandwidth rather than as
    #     a framework property.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from znicz_tpu.core.config import root
    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.workflow import StandardWorkflow

    batch = ctx.get("batch") or int(os.environ.get("BENCH_BATCH", "1024"))
    n_epoch_imgs = int(os.environ.get("BENCH_EPOCH_IMAGES", str(8 * batch)))
    gen = np.random.default_rng(0)
    # dtype=uint8 up front: the default int64 would transiently be 8x the
    # final array (~GBs at default sizes)
    images_u8 = gen.integers(
        0, 256, (n_epoch_imgs, 227, 227, 3), dtype=np.uint8
    )
    labels = gen.integers(0, 1000, n_epoch_imgs).astype(np.int32)

    def epoch_rate(device_resident: bool, n_epochs: int):
        e_loader = FullBatchLoader(
            {"train": images_u8},
            {"train": labels},
            minibatch_size=batch,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_convert=not device_resident,
            device_resident=device_resident,
        )
        ewf = StandardWorkflow(
            e_loader,
            root.alexnet.get("layers"),
            decision_config={"max_epochs": 10000},
            compute_dtype="bfloat16",
            # deferred epoch sync: the metric fetch of epoch N rides
            # behind epoch N+1's dispatch, so the per-epoch transport
            # round trip overlaps compute (VERDICT r3 #4)
            epoch_sync="deferred",
            name="AlexNetEpochBench",
        )
        ewf.initialize(seed=7)
        ewf.run_epoch()  # compile + warmup
        ewf.sync_epoch()
        ewf.timer.reset()
        t0 = time.time()
        for _ in range(n_epochs):
            ewf.run_epoch()
        ewf.sync_epoch()  # observe the final epoch (timed: honest wall)
        wall = time.time() - t0
        # per-phase breakdown (VERDICT r3 gate: explain the epoch-vs-
        # compute-only gap): host stack+put, async scan dispatch, and the
        # blocking metric fetch — whatever wall time none of them covers
        # is untimed host work (shuffle, python loop)
        phases = {
            k: round(v["total_s"] / n_epochs, 4)
            for k, v in ewf.timer.summary().items()
        }
        phases["wall_per_epoch"] = round(wall / n_epochs, 4)
        return n_epoch_imgs * n_epochs / wall, phases

    # 15 epochs: the one blocking round trip left (the FINAL epoch's
    # deferred fetch) amortizes to ~1/15 of an epoch, and the longer run
    # averages over relay-latency jitter (the ratio wobbles ~+-0.01)
    epoch_images_per_sec, epoch_phases = epoch_rate(True, 15)
    ctx["epoch_images_per_sec"] = epoch_images_per_sec
    print(
        f"epoch bench (device-resident): {epoch_images_per_sec:.0f} img/s "
        f"breakdown={epoch_phases}",
        file=sys.stderr,
    )
    streaming_images_per_sec, _ = epoch_rate(False, 1)

    # measured host->device link bandwidth: difference two chunk sizes so
    # the fixed per-round-trip sync cost cancels (same methodology as the
    # step timing above)
    def put_time(rows):
        chunk = images_u8[:rows]
        dev = jax.device_put(chunk)
        float(jnp.sum(dev.astype(jnp.float32))[None][0])  # force arrival
        t0 = time.time()
        dev = jax.device_put(chunk)
        float(jnp.sum(dev.astype(jnp.float32))[None][0])
        return chunk.nbytes, time.time() - t0

    put_time(64)  # warm both program shapes
    b_small, t_small = put_time(64)
    b_large, t_large = put_time(512)
    dt_put = t_large - t_small
    put_mbps = (
        (b_large - b_small) / dt_put / 1e6
        if dt_put > 0
        else b_large / max(t_large, 1e-9) / 1e6
    )
    print(
        f"epoch bench (streaming): {streaming_images_per_sec:.0f} img/s; "
        f"host->device link ~{put_mbps:.0f} MB/s",
        file=sys.stderr,
    )
    images_per_sec = ctx.get("alexnet_images_per_sec", 0.0)
    return [
        {
            "metric": "epoch_images_per_sec",
            "value": round(epoch_images_per_sec, 2),
            "unit": "images/sec",
            "epoch_vs_compute_only": round(
                epoch_images_per_sec / images_per_sec, 4
            ) if images_per_sec else 0.0,
            "epoch_streaming_images_per_sec": round(
                streaming_images_per_sec, 2
            ),
            "epoch_breakdown_s": epoch_phases,
            # the epoch-vs-compute gap, explained (VERDICT r3 #4): the
            # scanned epoch is ONE async dispatch; all wall time sits in
            # the blocking metric fetch = device compute (epoch images /
            # compute-only rate) + ONE transport round trip.  The
            # residual below is that round trip — µs on co-located
            # hosts, ~0.1-0.2 s through this harness's remote relay.
            "epoch_sync_residual_s": round(
                epoch_phases.get("metrics_sync", 0.0)
                - n_epoch_imgs / images_per_sec,
                4,
            ) if images_per_sec else 0.0,
            "host_to_device_MBps": round(put_mbps, 1),
        }
    ]


@_section("imagenet_resident")
def _sec_imagenet(ctx):
    # HBM-resident ImageNet pipeline (VERDICT r3 #5): the packed 256^2
    # pool ships ONCE; per step only [B, 4] int32 (row, oy, ox, flip)
    # crosses the link and random-crop+flip+normalize run inside the
    # jitted step.  This is the TPU-first answer to a slow host link for
    # datasets that fit HBM — steady-state behaves like device-resident,
    # with real reference augmentation semantics.
    import shutil
    import tempfile

    import numpy as np

    from znicz_tpu.core.config import root
    from znicz_tpu.loader.imagenet import ImageNetLoader
    from znicz_tpu.workflow import StandardWorkflow

    batch = ctx.get("batch") or int(os.environ.get("BENCH_BATCH", "1024"))
    gen = np.random.default_rng(0)
    n_imnet = int(os.environ.get("BENCH_IMAGENET_IMAGES", "4096"))
    pack_dir = tempfile.mkdtemp(prefix="bench_imnet_")
    try:
        pool = gen.integers(0, 256, (n_imnet, 256, 256, 3), dtype=np.uint8)
        np.save(os.path.join(pack_dir, "train_images.npy"), pool)
        np.save(
            os.path.join(pack_dir, "train_labels.npy"),
            gen.integers(0, 1000, n_imnet).astype(np.int32),
        )
        with open(os.path.join(pack_dir, "mean_rgb.json"), "w") as f:
            json.dump([0.485, 0.456, 0.406], f)
        del pool

        im_loader = ImageNetLoader(
            pack_dir, crop_size=227, minibatch_size=batch,
            device_resident=True,
        )
        iwf = StandardWorkflow(
            im_loader,
            root.alexnet.get("layers"),
            decision_config={"max_epochs": 10000},
            compute_dtype="bfloat16",
            # same deferred harness as the device-resident epoch bench:
            # at 4 steps/epoch a synchronous per-epoch fetch costs ~1/3
            # of the epoch through the relay (r4: the crop is ~0.8 ms)
            epoch_sync="deferred",
            name="ImageNetResidentBench",
        )
        iwf.initialize(seed=11)  # ships the 256^2 pool to HBM once
        iwf.run_epoch()  # compile + warmup
        iwf.sync_epoch()
        t0 = time.time()
        n_im_epochs = 12
        for _ in range(n_im_epochs):
            iwf.run_epoch()
        iwf.sync_epoch()
        rate = n_imnet * n_im_epochs / (time.time() - t0)
    finally:
        shutil.rmtree(pack_dir, ignore_errors=True)
    print(
        f"epoch bench (HBM-resident imagenet, on-device crops): "
        f"{rate:.0f} img/s",
        file=sys.stderr,
    )
    epoch_rate = ctx.get("epoch_images_per_sec", 0.0)
    return [
        {
            "metric": "imagenet_resident_images_per_sec",
            "value": round(rate, 2),
            "unit": "images/sec",
            "imagenet_resident_vs_device_resident": round(
                rate / epoch_rate, 4
            ) if epoch_rate else 0.0,
        }
    ]


@_section("mnist")
def _sec_mnist(ctx):
    # secondary metric (BASELINE.json): MNIST MLP step latency, plus the
    # dispatch-bound production epoch in scan vs stepwise dispatch
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from znicz_tpu.core.config import root
    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.models import mnist as mnist_model
    from znicz_tpu.workflow import StandardWorkflow

    root.mnist.loader.update(
        {"minibatch_size": 100, "n_train": 100, "n_test": 0,
         "validation_ratio": 0.0}
    )
    mwf = mnist_model.build_workflow()
    mwf.initialize(seed=1234)
    mmb = next(iter(mwf.loader.batches("train")))
    mx, my, mmask = (
        jnp.asarray(mmb.data), jnp.asarray(mmb.labels), jnp.asarray(mmb.mask)
    )

    # Device-side measurement: N steps inside ONE compiled lax.fori_loop,
    # so per-step host dispatch and relay sync overhead amortize to zero
    # and the quotient is pure device step time (sub-ms steps would
    # otherwise drown in transport noise).
    step_fn = mwf.train_step_fn
    N_INNER = 1000

    @jax.jit
    def mnist_many_steps(state):
        def body(_, s):
            s2, _m = step_fn(s, mx, my, mmask, 1.0, mwf._ctx)
            return s2
        return lax.fori_loop(0, N_INNER, body, state)

    mstate = mnist_many_steps(mwf.state)  # compile + warmup
    _sync(mstate.params[0]["weights"])

    def mnist_timed():
        nonlocal mstate
        t0 = time.time()
        mstate = mnist_many_steps(mstate)
        _sync(mstate.params[0]["weights"])
        return time.time() - t0

    # relay noise is additive-positive: discard the first post-warmup rep
    # (it absorbs still-queued async work) and min over the rest — the r3
    # 2x swing (0.058 -> 0.112 ms) came from a single-shot measurement
    mnist_timed()
    mnist_step_ms = min(mnist_timed() for _ in range(4)) / N_INNER * 1000

    # dispatch-bound regime: a small-model PRODUCTION epoch (run_epoch,
    # 100 steps).  The scanned dispatch (one lax.scan per split) removes
    # the per-step host round trip that dominates sub-ms steps; the
    # stepwise number is reported alongside as the contrast.
    gen2 = np.random.default_rng(1)
    m_imgs = gen2.integers(0, 256, (12800, 28, 28, 1), dtype=np.uint8)
    m_labels = gen2.integers(0, 10, 12800).astype(np.int32)
    ctx["mnist_imgs"] = m_imgs

    def mnist_epoch_rate(dispatch: str) -> float:
        ld = FullBatchLoader(
            {"train": m_imgs}, {"train": m_labels}, minibatch_size=128,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_resident=True,
        )
        ewf = StandardWorkflow(
            ld,
            [{"type": "all2all_tanh", "->": {"output_sample_shape": 256}},
             {"type": "softmax", "->": {"output_sample_shape": 10}}],
            decision_config={"max_epochs": 10000},
            default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
            epoch_dispatch=dispatch,
        )
        ewf.initialize(seed=3)
        ewf.run_epoch()  # compile + warmup
        t0 = time.time()
        for _ in range(3):
            ewf.run_epoch()
        return 3 * len(m_imgs) / (time.time() - t0)

    mnist_epoch_scan = mnist_epoch_rate("scan")
    mnist_epoch_step = mnist_epoch_rate("step")
    print(
        f"mnist epoch (100 steps): scan {mnist_epoch_scan:.0f} img/s vs "
        f"stepwise {mnist_epoch_step:.0f} img/s",
        file=sys.stderr,
    )
    return [
        {
            "metric": "mnist_mlp_step_ms",
            "value": round(mnist_step_ms, 3),
            "unit": "ms",
            # min-of-4 after a discarded rep since r4: the r3 0.112 ms
            # was a single-shot reading through the relay whose first
            # measurement absorbs queued async work — measurement noise,
            # not a regression (min-of-reps reproduces ~0.07-0.08)
            "mnist_step_method": "fori_loop_1000_min4_discard1",
            "mnist_epoch_scan_images_per_sec": round(mnist_epoch_scan, 1),
            "mnist_epoch_step_images_per_sec": round(mnist_epoch_step, 1),
        }
    ]


@_section("mnist_stream")
def _sec_mnist_stream(ctx):
    # streaming-input training: u8 minibatches cross host->device every
    # step (stepwise dispatch + the prefetch thread) — the regime of
    # ROADMAP's 100x gap.  Beyond the throughput number, this section
    # carries the PIPELINE ATTRIBUTION verdict (where each step's wall
    # went: compute / prefetch-wait / H2D / other) — the measurement the
    # streaming-rebuild rung is judged with, identical to what
    # tools/znicz-doctor prints from this run's metrics.prom.
    import numpy as np

    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.observability import PipelineAttribution
    from znicz_tpu.observability import pipeline as pipeline_obs
    from znicz_tpu.workflow import StandardWorkflow

    m_imgs = ctx.get("mnist_imgs")
    if m_imgs is None:
        gen = np.random.default_rng(1)
        m_imgs = gen.integers(0, 256, (12800, 28, 28, 1), dtype=np.uint8)
    m_labels = (
        np.random.default_rng(2).integers(0, 10, len(m_imgs)).astype(np.int32)
    )
    ld = FullBatchLoader(
        {"train": m_imgs},
        {"train": m_labels},
        minibatch_size=128,
        normalization="range",
        normalization_kwargs={"scale": 255.0, "shift": -0.5},
        device_convert=True,
        device_resident=False,
    )
    swf = StandardWorkflow(
        ld,
        [{"type": "all2all_tanh", "->": {"output_sample_shape": 256}},
         {"type": "softmax", "->": {"output_sample_shape": 10}}],
        decision_config={"max_epochs": 10000},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
        epoch_dispatch="step",
    )
    swf.initialize(seed=3)
    swf.run_epoch()  # compile + warmup
    # steady-state attribution window: exclude the compile epoch's
    # stall from the fractions the record reports
    pipeline_obs.reset_window()
    n_ep = 2
    t0 = time.time()
    for _ in range(n_ep):
        swf.run_epoch()
    stream_rate = n_ep * len(m_imgs) / (time.time() - t0)
    att = PipelineAttribution.from_registry().attribution()
    fr = att.get("fractions", {})
    print(
        f"mnist stream: {stream_rate:.0f} img/s; {att.get('verdict')} "
        f"(compute {fr.get('compute', 0):.2f}, prefetch-wait "
        f"{fr.get('prefetch_wait', 0):.2f}, h2d {fr.get('h2d', 0):.2f}, "
        f"other {fr.get('other', 0):.2f}); "
        f"H2D {(att.get('h2d_bytes_per_second') or 0) / 1e6:.1f} MB/s",
        file=sys.stderr,
    )
    return [
        {
            "metric": "mnist_stream_images_per_sec",
            "value": round(stream_rate, 1),
            "unit": "images/sec",
            # top-level numerics: znicz-bench-diff lifts these into the
            # round diff (*_bound_frac lower-better, *_bytes_per_second
            # higher-better)
            "train_input_bound_frac": float(
                att.get("input_bound_frac", 0.0)
            ),
            "train_h2d_bytes_per_second": float(
                att.get("h2d_bytes_per_second") or 0.0
            ),
            # the full self-describing attribution record ({"type":
            # "pipeline"} — skipped by metric-family walkers, safe
            # through the aggregator round trip like the programs entry)
            "pipeline": att,
        }
    ]


@_section("som")
def _sec_som(ctx):
    # SOM on the device-resident scan path (VERDICT r3 #1: the wiring of
    # device_preproc through every workflow family makes the
    # HBM-resident epoch available to non-backprop trainers too)
    import numpy as np

    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.workflow import KohonenWorkflow

    m_imgs = ctx.get("mnist_imgs")
    if m_imgs is None:
        m_imgs = np.random.default_rng(1).integers(
            0, 256, (12800, 28, 28, 1), dtype=np.uint8
        )
    som_loader = FullBatchLoader(
        {"train": m_imgs}, minibatch_size=128,
        normalization="range",
        normalization_kwargs={"scale": 255.0, "shift": -0.5},
        device_resident=True,
    )
    som_wf = KohonenWorkflow(
        som_loader, sx=8, sy=8, total_epochs=10000,
        epoch_sync="deferred",
    )
    som_wf.initialize(seed=5)
    assert som_wf._use_epoch_scan()
    som_wf.run_epoch()  # compile + warmup
    som_wf.sync_epoch()
    t0 = time.time()
    for _ in range(3):
        som_wf.run_epoch()
    som_wf.sync_epoch()
    rate = 3 * len(m_imgs) / (time.time() - t0)
    print(
        f"SOM epoch (device-resident scan): {rate:.0f} img/s",
        file=sys.stderr,
    )
    return [
        {
            "metric": "som_epoch_images_per_sec",
            "value": round(rate, 1),
            "unit": "images/sec",
        }
    ]


# ---------------------------------------------------------------------------
# transformer LM sections.  Fixed configs shared across them:

LM_T = 2048
LM = dict(vocab=8192, d_model=256, n_layers=8, n_heads=8)
LM_B = 8
# mid config (~50M matmul params): shows MFU scaling with model size —
# d=256 matmuls are too small to tile the v5e MXU well; tokens/s is FLAT
# from B=8 to B=32 (step time scales with B — every extra row costs
# proportional time), so the small model is geometry/utilization-bound,
# not framework-bound
LM_MID = dict(vocab=8192, d_model=512, n_layers=12, n_heads=8)
LM_MID_B = 16
LM_SERVE_LENS = (16, 40, 64, 120)  # buckets 16 / 64 / 64 / 128
LM_SERVE_NEW = 64
# block 32: at the mid config the fatter prefill chunk/window halves
# host dispatches for the same pool memory (32-multiple padding on this
# stream matches the dense bucket ladder's anyway)
LM_SERVE_PAGED_BLOCK = 32
# shared-system-prompt stream for the prefix-cache bench: 160 tokens =
# 5 full blocks of 32, cached once and mapped by every later request
LM_PREFIX_SYS = 160
# repeat-heavy mixed stream for the speculative-decoding bench: tiled
# motifs whose GREEDY CONTINUATIONS this seed's mid-config LM locks
# into near-periodic runs (measured offline — prompt repetition alone
# is not enough, the drafter must predict what the model actually
# emits).  Mixed prompt lengths 16/40/64/120 like the other serve
# streams, weighted toward the long prompts that anchor the attractor.
LM_SPEC_STREAM = (
    ((2765, 2796, 6653, 2317), 120),
    ((3347, 4349, 4741), 120),
    ((4069, 5480, 3836), 120),
    ((123, 1175, 3860), 16),
    ((1359, 63), 40),
    ((1805, 2090, 1511, 2733), 16),
    ((4069, 5480, 3836), 64),
    ((2765, 2796, 6653, 2317), 64),
)
LM_SPEC_B = 8  # decode-bound regime: spec trades FLOPs for steps
LM_SPEC_K = 7  # up to 7 drafts/row/tick -> verify widths 2/4/8


def _lm_cleanup():
    import gc

    import jax

    # compiled executables pin HBM; with many LM variants in one process
    # the accumulation OOMed tail sections in r5 trials (each fine in
    # isolation) — every LM section drops its caches on the way out
    jax.clear_caches()
    gc.collect()


def _lm_train_flops_per_token(cfg) -> float:
    # matmul params (QKV+O, FFN, head — embed/pos are gathers/adds) x 2,
    # plus CAUSAL attention scores+weighted-sum 2*T*D per layer per
    # token (avg attended length T/2; the flash kernel skips the
    # entirely-masked blocks, so counting the full bidirectional 4*T*D
    # would inflate MFU ~1.2x at the mid config — the r4 numbers did).
    # Training ~ 3x forward (fwd + input-grad + weight-grad); remat
    # recomputes fwd (~4x) but MFU uses the remat-off run.  Convention
    # reported as lm_flops_convention.
    d, L, v = cfg["d_model"], cfg["n_layers"], cfg["vocab"]
    d_ff = cfg.get("d_ff") or 4 * d
    p_mat = L * (4 * d * d + 2 * d * d_ff) + d * v
    return 3.0 * (2.0 * p_mat + 2.0 * L * LM_T * d)


def _lm_tokens(rows):
    import numpy as np

    return np.random.default_rng(6).integers(
        0, 8192, (rows, LM_T)
    ).astype(np.int32)


def _lm_rate(cfg, b, attention, remat, tokens=None, extra=None) -> float:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from znicz_tpu.core import prng
    from znicz_tpu.loader.fullbatch import FullBatchLoader
    from znicz_tpu.workflow.transformer import TransformerLMWorkflow

    tokens = _lm_tokens(2 * b) if tokens is None else tokens
    t_len = tokens.shape[1]
    prng.seed_all(99)
    ld = FullBatchLoader(
        {"train": tokens[: 2 * b].copy()}, minibatch_size=b
    )
    lwf = TransformerLMWorkflow(
        ld, max_epochs=1, attention=attention, remat=remat,
        **cfg, **(extra or {}),
    )
    lwf.initialize(seed=99)
    lx = jnp.asarray(tokens[:b])
    ly = jnp.zeros((b,), jnp.int32)
    lmask = jnp.ones((b,), jnp.float32)
    lstep = lwf.train_step_fn
    n_inner = 20

    @jax.jit
    def lm_many(state):
        def body(_, s):
            s2, _m = lstep(s, lx, ly, lmask, 1.0, lwf._ctx)
            return s2
        return lax.fori_loop(0, n_inner, body, state)

    st = lm_many(lwf.state)  # compile + warmup
    _sync(st.params[0]["embed"])

    def timed():
        nonlocal st
        t0 = time.time()
        st = lm_many(st)
        _sync(st.params[0]["embed"])
        return time.time() - t0

    dt = min(timed() for _ in range(3)) / n_inner
    return b * t_len / dt


def _lm_rate_safe(cfg, b, attention, remat, tokens=None, extra=None) -> float:
    # HBM headroom through the relay varies run to run — a failed LM
    # variant must degrade to 0.0, never kill the whole section
    try:
        return _lm_rate(cfg, b, attention, remat, tokens=tokens,
                        extra=extra)
    except Exception as e:
        print(
            f"lm config d={cfg['d_model']} B={b} {attention} "
            f"remat={remat} failed: {type(e).__name__}",
            file=sys.stderr,
        )
        return 0.0
    finally:
        _lm_cleanup()


@_section("lm_train")
def _sec_lm_train(ctx):
    # the flagship beyond-parity model needs a driver-visible number
    # (VERDICT r3 #2).  Fixed ~11M-param GPT-small, T=2048, bf16-on-MXU
    # (jax default matmul precision), single chip.  Measured exactly
    # like the MNIST step: N steps inside ONE compiled fori_loop, min
    # over repeats, value-fetch sync.
    import numpy as np

    peak = _peak_flops()
    lm_flash = _lm_rate_safe(LM, LM_B, "flash", remat=False)
    lm_dense = _lm_rate_safe(LM, LM_B, "dot", remat=False)
    lm_flash_remat = _lm_rate_safe(LM, LM_B, "flash", remat=True)
    lm_mfu = lm_flash * _lm_train_flops_per_token(LM) / peak
    mid_b = LM_MID_B
    lm_mid = _lm_rate_safe(LM_MID, mid_b, "flash", remat=False)
    if not lm_mid:
        mid_b = 8
        lm_mid = _lm_rate_safe(LM_MID, mid_b, "flash", remat=False)
    lm_mid_mfu = lm_mid * _lm_train_flops_per_token(LM_MID) / peak
    ctx["lm_mid_tokens_per_sec"] = lm_mid

    # hd=128 variant (same d=512 tower, 4 heads x 128): tests the r4
    # hypothesis that QK^T at head_dim 64 half-fills the MXU's 128-lane
    # contraction dim.  Same matmul params, same counted FLOPs.
    LM_HD128 = dict(LM_MID, n_heads=4)
    lm_hd128 = _lm_rate_safe(LM_HD128, mid_b, "flash", remat=False)
    lm_hd128_mfu = lm_hd128 * _lm_train_flops_per_token(LM_HD128) / peak

    # bf16 attention (q/k/v on the MXU in bf16, f32 accumulation): the
    # r5 kernel keeps input dtype — standalone fwd+full-bwd 12.7 -> 10.7
    # ms (hd64) / 6.0 -> 4.3 ms (hd128)
    bf16 = dict(attention_dtype="bf16")
    lm_mid_bf16 = _lm_rate_safe(
        LM_MID, mid_b, "flash", remat=False, extra=bf16
    )
    lm_hd128_bf16 = _lm_rate_safe(
        LM_HD128, mid_b, "flash", remat=False, extra=bf16
    )
    lm_hd128_bf16_mfu = (
        lm_hd128_bf16 * _lm_train_flops_per_token(LM_HD128) / peak
    )

    # MoE perf at matched ACTIVE FLOPs (VERDICT r4 weak #3): E=8 experts
    # of d_ff=1024 at top_k=2 activate exactly the dense tower's
    # d_ff=2048-worth of FFN FLOPs per token, so tokens/s is directly
    # comparable to lm_mid.  Dense dispatch runs all 8 experts (4x the
    # active FFN FLOPs — the "trades k/E of the FLOPs" cost made
    # visible); capacity dispatch computes only the routed tokens.
    LM_MOE = dict(LM_MID, d_ff=1024)
    moe_kw = dict(moe_experts=8, moe_top_k=2)
    lm_moe_dense = _lm_rate_safe(
        LM_MOE, mid_b, "flash", remat=False,
        extra=dict(moe_kw, moe_dispatch="dense"),
    )
    lm_moe_capacity = _lm_rate_safe(
        LM_MOE, mid_b, "flash", remat=False,
        extra=dict(moe_kw, moe_dispatch="capacity"),
    )

    # long context: flash (O(T*D) memory) + remat train the mid model at
    # 8x the headline sequence length on ONE chip — dense attention OOMs
    # at T=2048 already.  T=16384, B=2 (32k tokens/step, same as mid).
    LM_LONG_T, LM_LONG_B = 16384, 2
    lm_long_tokens = np.random.default_rng(8).integers(
        0, 8192, (2 * LM_LONG_B, LM_LONG_T)
    ).astype(np.int32)
    lm_long = _lm_rate_safe(
        LM_MID, LM_LONG_B, "flash", remat=True, tokens=lm_long_tokens
    )
    print(
        f"LM GPT-small T={LM_T}: flash {lm_flash:.0f} tok/s "
        f"(causal MFU {lm_mfu:.3f}), dense {lm_dense:.0f}, "
        f"flash+remat {lm_flash_remat:.0f}; "
        f"mid 512dx12L: {lm_mid:.0f} tok/s (MFU {lm_mid_mfu:.3f}); "
        f"hd128 4Hx128: {lm_hd128:.0f} tok/s (MFU {lm_hd128_mfu:.3f}); "
        f"bf16-attn mid {lm_mid_bf16:.0f} / hd128 {lm_hd128_bf16:.0f} "
        f"tok/s (MFU {lm_hd128_bf16_mfu:.3f}); "
        f"moe E=8 k=2 dense {lm_moe_dense:.0f} / capacity "
        f"{lm_moe_capacity:.0f} tok/s; long T={LM_LONG_T}: "
        f"{lm_long:.0f} tok/s",
        file=sys.stderr,
    )
    return [
        {
            "metric": "lm_tokens_per_sec",
            "value": round(lm_flash, 1),
            "unit": "tokens/sec",
            "lm_config": (
                f"GPT-small {LM['d_model']}d x {LM['n_layers']}L x "
                f"{LM['n_heads']}H, vocab {LM['vocab']}, T={LM_T}, "
                f"B={LM_B}, bf16-on-MXU"
            ),
            "lm_mfu": round(lm_mfu, 4),
            "lm_flash_vs_dense": round(
                lm_flash / lm_dense if lm_dense else 0.0, 4
            ),
            "lm_remat_vs_no_remat": round(
                lm_flash_remat / lm_flash if lm_flash else 0.0, 4
            ),
            "lm_mid_config": (
                f"{LM_MID['d_model']}d x {LM_MID['n_layers']}L x "
                f"{LM_MID['n_heads']}H, vocab {LM_MID['vocab']}, "
                f"T={LM_T}, B={mid_b}"
            ),
            "lm_mid_tokens_per_sec": round(lm_mid, 1),
            "lm_mid_mfu": round(lm_mid_mfu, 4),
            # MFU accounting counts CAUSAL attention (2*L*T*D per token
            # — avg attended length T/2, matching what the flash kernel
            # actually computes), not bidirectional
            "lm_flops_convention": "causal_attention_2LTD",
            "lm_hd128_config": (
                f"{LM_HD128['d_model']}d x {LM_HD128['n_layers']}L x "
                f"4H(hd=128), T={LM_T}, B={mid_b}"
            ),
            "lm_hd128_tokens_per_sec": round(lm_hd128, 1),
            "lm_hd128_mfu": round(lm_hd128_mfu, 4),
            "lm_hd128_vs_mid": round(
                lm_hd128 / lm_mid if lm_mid else 0.0, 4
            ),
            "lm_mid_bf16_attn_tokens_per_sec": round(lm_mid_bf16, 1),
            "lm_hd128_bf16_attn_tokens_per_sec": round(lm_hd128_bf16, 1),
            "lm_hd128_bf16_attn_mfu": round(lm_hd128_bf16_mfu, 4),
            "lm_best_vs_r4_mid": round(
                max(lm_hd128_bf16, lm_hd128, lm_mid_bf16, lm_mid)
                / 134730.3,
                4,
            ),
            "lm_moe_config": (
                "mid tower, E=8 experts d_ff=1024 top_k=2 "
                "(active FFN FLOPs == dense d_ff=2048)"
            ),
            "lm_moe_dense_tokens_per_sec": round(lm_moe_dense, 1),
            "lm_moe_capacity_tokens_per_sec": round(lm_moe_capacity, 1),
            "lm_moe_dense_vs_dense_ffn": round(
                lm_moe_dense / lm_mid if lm_mid else 0.0, 4
            ),
            "lm_moe_capacity_vs_dense_ffn": round(
                lm_moe_capacity / lm_mid if lm_mid else 0.0, 4
            ),
            "lm_long_context": (
                f"mid config at T={LM_LONG_T}, B={LM_LONG_B}, "
                "flash+remat (dense OOMs at T=2048 already)"
            ),
            "lm_long_tokens_per_sec": round(lm_long, 1),
        }
    ]


@_section("lm_decode")
def _sec_lm_decode(ctx):
    # KV-cache decode (VERDICT r4 weak #2): greedy generation on the mid
    # config — prefill 64-token prompts, decode 256 new tokens/row in
    # ONE compiled program; rate counts generated tokens only.
    import jax.numpy as jnp

    from znicz_tpu.core import prng
    from znicz_tpu.workflow.generate import generate as lm_generate
    from znicz_tpu.workflow.transformer import init_lm_params

    cfg, b, prompt_len, new_tokens = LM_MID, LM_MID_B, 64, 256
    try:
        prng.seed_all(97)
        params = init_lm_params(
            cfg["vocab"], cfg["d_model"], cfg["n_layers"], cfg["n_heads"],
            max_seq=prompt_len + new_tokens,
        )
        prompt = jnp.asarray(
            _lm_tokens(b)[:, :prompt_len] % cfg["vocab"], jnp.int32
        )
        kw = dict(n_heads=cfg["n_heads"], max_new_tokens=new_tokens)
        out = lm_generate(params, prompt, **kw)  # compile + warmup
        _sync(out.astype(jnp.float32))

        def timed():
            t0 = time.time()
            o = lm_generate(params, prompt, **kw)
            _sync(o.astype(jnp.float32))
            return time.time() - t0

        dt = min(timed() for _ in range(3))
        rate = b * new_tokens / dt
    finally:
        _lm_cleanup()
    return [
        {
            "metric": "lm_decode_tokens_per_sec",
            "value": round(rate, 1),
            "unit": "tokens/sec",
            "lm_decode_config": (
                "mid config, greedy KV-cache decode: prompt 64, "
                f"256 new tokens, B={b}, one lax.scan"
            ),
        }
    ]


def _lm_serve_params():
    from znicz_tpu.core import prng
    from znicz_tpu.workflow.transformer import init_lm_params

    cfg = LM_MID
    prng.seed_all(95)
    return init_lm_params(
        cfg["vocab"], cfg["d_model"], cfg["n_layers"], cfg["n_heads"],
        max_seq=256,
    )


@_section("lm_serve")
def _sec_lm_serve(ctx):
    # decode SERVING (ISSUE 2): continuous batching over a mixed-
    # prompt-length request stream.  The engine coalesces ragged prompts
    # into a fixed-slot batch over static KV buffers: admit programs
    # compile once per prompt-length bucket, the chunked per-row decode
    # program compiles ONCE, and rows retire/admit independently — so
    # the whole stream runs recompile-free (lm_serve_compiles is the
    # total distinct-program count, reported to catch regressions).
    import numpy as np

    from znicz_tpu.services.engine import DecodeEngine

    cfg, b = LM_MID, LM_MID_B
    try:
        params = _lm_serve_params()
        reqs = np.random.default_rng(12)

        def make_engine():
            return DecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0, batch_size=b,
                admit_every=8, max_seq=256,
            )

        def stream(eng, n):
            for j in range(n):
                length = LM_SERVE_LENS[j % len(LM_SERVE_LENS)]
                eng.submit(
                    reqs.integers(1, cfg["vocab"], (length,)).astype(
                        np.int32
                    ),
                    max_new_tokens=LM_SERVE_NEW,
                )
            return eng.run()

        stream(make_engine(), len(LM_SERVE_LENS))  # compile every bucket
        eng = make_engine()  # fresh engine rides the warm jit cache
        t0 = time.time()
        comps = stream(eng, 4 * b)
        wall = time.time() - t0
        toks = sum(c.n_new for c in comps)
        rate, st = toks / wall, eng.stats()
        ctx["lm_serve_tokens_per_sec"] = rate
    finally:
        _lm_cleanup()
    print(
        f"LM serving (continuous batching, mixed prompts "
        f"{LM_SERVE_LENS}): {rate:.0f} tok/s, "
        f"{st.get('n_programs', 0)} compiled programs, "
        f"latency {st.get('latency', {})}",
        file=sys.stderr,
    )
    return [
        {
            "metric": "lm_serve_tokens_per_sec",
            "value": round(rate, 1),
            "unit": "tokens/sec",
            "lm_serve_config": (
                f"mid config engine: B={b} slots, mixed "
                f"prompts {LM_SERVE_LENS}, budget {LM_SERVE_NEW}, "
                "admit_every 8, eos 0, greedy"
            ),
            "lm_serve_compiles": st.get("n_programs", 0),
            "lm_serve_requests": st.get("completed", 0),
            "lm_serve_latency_ms": {
                k: round(v, 1)
                for k, v in st.get("latency", {}).items()
            },
        }
    ]


@_section("lm_serve_paged")
def _sec_lm_serve_paged(ctx):
    # PAGED serving (ISSUE 4): the same mixed stream through the
    # block-pool engine, pool sized to the dense engine's EXACT KV
    # footprint (B slots x t_max tokens) so tokens/s is an apples-to-
    # apples layout comparison, plus a max-sustained-concurrency probe:
    # 2x the slots against that same pool with short requests — the
    # dense layout caps at B rows in this memory; the paged pool packs
    # them by blocks actually used (peak_active is the measured answer,
    # preemptions how often pressure forced an eviction).  Prefix cache
    # OFF here: the stream shares no prefixes, and the layout comparison
    # must not pay (or gain) anything cache-related.
    import numpy as np

    from znicz_tpu.services.engine import PagedDecodeEngine

    cfg, b = LM_MID, LM_MID_B
    try:
        params = _lm_serve_params()
        reqs = np.random.default_rng(12)
        block = LM_SERVE_PAGED_BLOCK
        n_blocks = b * (256 // block) + 1  # dense footprint + null block

        def make_engine(slots):
            return PagedDecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0,
                batch_size=slots, admit_every=8, max_seq=256,
                block_size=block, n_blocks=n_blocks, prefix_cache=False,
            )

        def stream(eng, n):
            for j in range(n):
                length = LM_SERVE_LENS[j % len(LM_SERVE_LENS)]
                eng.submit(
                    reqs.integers(1, cfg["vocab"], (length,)).astype(
                        np.int32
                    ),
                    max_new_tokens=LM_SERVE_NEW,
                )
            return eng.run()

        stream(make_engine(b), len(LM_SERVE_LENS))  # warm both programs
        eng = make_engine(b)  # fresh engine rides the warm jit cache
        t0 = time.time()
        comps = stream(eng, 4 * b)
        wall = time.time() - t0
        toks = sum(c.n_new for c in comps)
        rate, st = toks / wall, eng.stats()
        # concurrency probe: short requests (16-token prompts, 16-token
        # budgets = 2 blocks each) through 2x slots over the same pool
        probe = make_engine(2 * b)
        for _ in range(4 * b):
            probe.submit(
                reqs.integers(1, cfg["vocab"], (16,)).astype(np.int32),
                max_new_tokens=16,
            )
        probe.run()
        probe_st = probe.stats()
    finally:
        _lm_cleanup()
    print(
        f"LM serving PAGED (block {LM_SERVE_PAGED_BLOCK}, mixed prompts "
        f"{LM_SERVE_LENS}): {rate:.0f} tok/s "
        f"({st.get('n_programs', 0)} programs, "
        f"{st.get('preemptions', 0)} preemptions); "
        f"concurrency probe peak {probe_st.get('peak_active', 0)} "
        f"rows (dense layout caps at {b} in the same memory)",
        file=sys.stderr,
    )
    dense_rate = ctx.get("lm_serve_tokens_per_sec", 0.0)
    return [
        {
            "metric": "lm_serve_paged_tokens_per_sec",
            "value": round(rate, 1),
            "unit": "tokens/sec",
            "lm_serve_paged_config": (
                f"mid config paged engine: B={b} slots, "
                f"block {LM_SERVE_PAGED_BLOCK}, pool == dense "
                f"footprint ({b}x256 tokens), mixed prompts "
                f"{LM_SERVE_LENS}, budget {LM_SERVE_NEW}; probe: "
                f"2x slots, 16+16-token requests, same pool"
            ),
            "lm_serve_paged_vs_dense": round(
                rate / dense_rate if dense_rate else 0.0, 4
            ),
            "lm_serve_paged_compiles": st.get("n_programs", 0),
            "lm_serve_paged_preemptions": st.get("preemptions", 0),
            "lm_serve_paged_max_concurrency": probe_st.get(
                "peak_active", 0
            ),
            "lm_serve_paged_latency_ms": {
                k: round(v, 1)
                for k, v in st.get("latency", {}).items()
            },
        }
    ]


@_section("lm_serve_prefix")
def _sec_lm_serve_prefix(ctx):
    # PREFIX-CACHE serving (ISSUE 5): a shared-system-prompt stream
    # (the production-dominant shape: one 160-token system prefix, a
    # short per-user tail) through the paged engine with the prefix
    # cache ON vs the identical engine with it OFF.  The warm engine
    # maps the system prompt's 5 blocks out of cache at every
    # admission and chunk-prefills only the tail, so TTFT collapses to
    # the tail — lm_serve_prefix_ttft_vs_cold is the measured ratio
    # (lower is better; <1 means the cache pays).
    import numpy as np

    from znicz_tpu.services.engine import PagedDecodeEngine

    cfg, b = LM_MID, LM_MID_B
    try:
        from znicz_tpu.core import prng
        from znicz_tpu.workflow.transformer import init_lm_params

        t_max = 384  # 160-token system prompt + tail + budget
        prng.seed_all(95)
        params = init_lm_params(
            cfg["vocab"], cfg["d_model"], cfg["n_layers"],
            cfg["n_heads"], max_seq=t_max,
        )
        block = LM_SERVE_PAGED_BLOCK
        n_blocks = b * (t_max // block) + 1
        gen = np.random.default_rng(14)
        sys_prompt = gen.integers(
            1, cfg["vocab"], (LM_PREFIX_SYS,)
        ).astype(np.int32)

        def make_engine(prefix):
            return PagedDecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0,
                batch_size=b, admit_every=8, max_seq=t_max,
                block_size=block, n_blocks=n_blocks,
                prefix_cache=prefix,
            )

        def stream(eng, n, seed=15):
            r = np.random.default_rng(seed)
            for j in range(n):
                tail = r.integers(
                    1, cfg["vocab"], (16 + 8 * (j % 3),)
                ).astype(np.int32)
                eng.submit(
                    np.concatenate([sys_prompt, tail]),
                    max_new_tokens=LM_SERVE_NEW,
                )
            return eng.run()

        def mean_ttft(comps):
            ts = [c.ttft_s for c in comps if c.ttft_s is not None]
            return sum(ts) / max(len(ts), 1)

        stream(make_engine(True), 4)  # warm every program shape
        # WARM: seed the cache with the bare system prompt, then time
        warm = make_engine(True)
        warm.submit(sys_prompt, 1)
        warm.run()
        t0 = time.time()
        warm_comps = stream(warm, 4 * b)
        warm_wall = time.time() - t0
        warm_rate = sum(c.n_new for c in warm_comps) / warm_wall
        warm_st = warm.stats()
        # COLD: identical engine + stream, cache disabled
        cold = make_engine(False)
        cold.submit(sys_prompt, 1)
        cold.run()
        t0 = time.time()
        cold_comps = stream(cold, 4 * b)
        cold_wall = time.time() - t0
        cold_rate = sum(c.n_new for c in cold_comps) / cold_wall
        ttft_vs_cold = (
            mean_ttft(warm_comps) / mean_ttft(cold_comps)
            if mean_ttft(cold_comps)
            else 0.0
        )
    finally:
        _lm_cleanup()
    pstats = warm_st.get("prefix_cache", {})
    print(
        f"LM serving PREFIX (system prompt {LM_PREFIX_SYS} tokens, "
        f"block {LM_SERVE_PAGED_BLOCK}): warm {warm_rate:.0f} vs cold "
        f"{cold_rate:.0f} tok/s; TTFT warm/cold {ttft_vs_cold:.3f}; "
        f"{pstats.get('hits', 0)} block hits, "
        f"{pstats.get('cached_tokens', 0)} cached tokens",
        file=sys.stderr,
    )
    return [
        {
            "metric": "lm_serve_prefix_tokens_per_sec",
            "value": round(warm_rate, 1),
            "unit": "tokens/sec",
            "lm_serve_prefix_config": (
                f"mid config paged engine + prefix cache: B={b} slots, "
                f"block {LM_SERVE_PAGED_BLOCK}, shared "
                f"{LM_PREFIX_SYS}-token system prompt + 16/24/32-token "
                f"tails, budget {LM_SERVE_NEW}; cold twin runs the "
                "same stream with prefix_cache=False"
            ),
            "lm_serve_prefix_ttft_vs_cold": round(ttft_vs_cold, 4),
            "lm_serve_prefix_vs_cold_tokens_per_sec": round(
                warm_rate / cold_rate if cold_rate else 0.0, 4
            ),
            "lm_serve_prefix_block_hits": pstats.get("hits", 0),
            "lm_serve_prefix_cached_tokens": pstats.get(
                "cached_tokens", 0
            ),
            "lm_serve_prefix_evictions": pstats.get("evictions", 0),
            "lm_serve_prefix_cow_splits": pstats.get("cow_splits", 0),
            "lm_serve_prefix_compiles": warm_st.get("n_programs", 0),
        }
    ]


@_section("lm_serve_spec")
def _sec_lm_serve_spec(ctx):
    # SPECULATIVE serving (ISSUE 12): the repeat-heavy mixed stream
    # through a warm paged engine with prompt-lookup drafting + bucketed
    # parallel verify, against the IDENTICAL engine with spec off.
    # Decode is step-bound: the baseline pays one tower pass per token
    # per chunk iteration; the spec engine verifies up to LM_SPEC_K
    # drafts per row in ONE bucketed pass and keeps the longest agreeing
    # prefix (greedy, so token-identical to the baseline — the twin
    # comparison is apples-to-apples by construction).  Prefix cache OFF
    # on both twins so the speedup is speculation's alone.
    # lm_serve_spec_vs_baseline >= 1.0 is the acceptance bar;
    # _acceptance_rate says why (drafts the verifier kept / proposed).
    import numpy as np

    from znicz_tpu.services.engine import PagedDecodeEngine

    cfg = LM_MID
    b = LM_SPEC_B
    try:
        params = _lm_serve_params()
        block = LM_SERVE_PAGED_BLOCK
        n_blocks = b * (256 // block) + 1

        def make_engine(spec_k):
            return PagedDecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0, batch_size=b,
                admit_every=8, max_seq=256, block_size=block,
                n_blocks=n_blocks, prefix_cache=False, spec_k=spec_k,
            )

        def stream(eng, n):
            for j in range(n):
                motif, length = LM_SPEC_STREAM[j % len(LM_SPEC_STREAM)]
                m = np.asarray(motif, np.int32)
                eng.submit(
                    np.tile(m, length // m.size + 1)[:length],
                    max_new_tokens=LM_SERVE_NEW,
                )
            return eng.run()

        # warm every program shape on both twins (one compile set,
        # shared jit caches), then time fresh engines
        stream(make_engine(LM_SPEC_K), len(LM_SPEC_STREAM))
        stream(make_engine(0), len(LM_SPEC_STREAM))
        spec = make_engine(LM_SPEC_K)
        t0 = time.time()
        spec_comps = stream(spec, 2 * b)
        spec_wall = time.time() - t0
        spec_rate = sum(c.n_new for c in spec_comps) / spec_wall
        spec_st = spec.stats()
        base = make_engine(0)
        t0 = time.time()
        base_comps = stream(base, 2 * b)
        base_wall = time.time() - t0
        base_rate = sum(c.n_new for c in base_comps) / base_wall
        # greedy spec is token-identical to the baseline: assert it on
        # the bench stream itself (matched by request id — retirement
        # order may differ) so the headline can never be a
        # divergent-output artifact
        golden = all(
            np.array_equal(
                spec.completions[rid].tokens, base.completions[rid].tokens
            )
            for rid in range(2 * b)
        )
        # divergence is a BUG, not a bench datapoint: fail the section
        # loudly (and emit the flag as an int so a 1 -> 0 flip is a
        # diffable regression, not a silently-skipped bool)
        assert golden, "speculative output diverged from the baseline"
        sp = spec_st.get("spec", {})
    finally:
        _lm_cleanup()
    print(
        f"LM serving SPEC (prompt-lookup k={LM_SPEC_K}, repeat-heavy "
        f"stream): {spec_rate:.0f} vs {base_rate:.0f} tok/s baseline "
        f"(x{spec_rate / base_rate if base_rate else 0.0:.2f}); "
        f"acceptance {sp.get('acceptance_rate', 0.0):.2f} "
        f"({sp.get('accepted', 0)}/{sp.get('drafted', 0)} drafts, "
        f"{sp.get('verify_steps', 0)} verifies); golden={golden}",
        file=sys.stderr,
    )
    return [
        {
            "metric": "lm_serve_spec_tokens_per_sec",
            "value": round(spec_rate, 1),
            "unit": "tokens/sec",
            "lm_serve_spec_config": (
                f"mid config paged engine + prompt-lookup speculation: "
                f"B={b} slots, block {LM_SERVE_PAGED_BLOCK}, "
                f"spec_k={LM_SPEC_K} (verify buckets 2/4/8), "
                f"repeat-heavy mixed prompts 16/40/64/120, budget "
                f"{LM_SERVE_NEW}, greedy; baseline twin is the same "
                "engine with spec_k=0, same stream"
            ),
            "lm_serve_spec_vs_baseline": round(
                spec_rate / base_rate if base_rate else 0.0, 4
            ),
            "lm_serve_spec_acceptance_rate": round(
                float(sp.get("acceptance_rate", 0.0)), 4
            ),
            "lm_serve_spec_compiles": spec_st.get("n_programs", 0),
            "lm_serve_spec_baseline_tokens_per_sec": round(base_rate, 1),
            "lm_serve_spec_drafted": sp.get("drafted", 0),
            "lm_serve_spec_accepted": sp.get("accepted", 0),
            "lm_serve_spec_verify_steps": sp.get("verify_steps", 0),
            "lm_serve_spec_golden": int(golden),
        }
    ]


@_section("lm_serve_frontdoor")
def _sec_lm_serve_frontdoor(ctx):
    # FRONT DOOR serving (ISSUE 6): the same mixed-prompt stream
    # replayed through the REAL HTTP surface — concurrent clients POST
    # /generate against a ServingFrontDoor-owned paged engine and read
    # chunked token streams back.  Reported as a SERVICE, not a
    # library: sustained requests/sec over the timed window, host-side
    # TTFT p99 (first streamed token, queue + HTTP included), and the
    # shed/deadline/cancel/restart tallies that say how the admission
    # ladder behaved under the load.
    import http.client
    import threading

    import numpy as np

    from znicz_tpu.services import serve as serve_mod
    from znicz_tpu.services.engine import PagedDecodeEngine
    from znicz_tpu.services.frontdoor import ServingFrontDoor

    cfg, b = LM_MID, LM_MID_B
    n_requests, n_clients = 4 * b, 4
    door = srv = None
    try:
        params = _lm_serve_params()

        def factory():
            return PagedDecodeEngine(
                params, n_heads=cfg["n_heads"], eos_id=0, batch_size=b,
                admit_every=8, max_seq=256,
                block_size=LM_SERVE_PAGED_BLOCK,
            )

        door = ServingFrontDoor(
            factory, max_pending=2 * n_requests,
            default_deadline_s=300.0,
        )
        srv = serve_mod.build_server(directory=".", port=0, frontdoor=door)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        reqs = np.random.default_rng(12)
        prompts = [
            reqs.integers(
                1, cfg["vocab"],
                (LM_SERVE_LENS[j % len(LM_SERVE_LENS)],),
            ).astype(np.int32).tolist()
            for j in range(n_requests)
        ]

        def one_request(prompt):
            t_req = time.time()
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=300
            )
            try:
                conn.request(
                    "POST", "/generate",
                    body=json.dumps(
                        {"prompt": prompt,
                         "max_new_tokens": LM_SERVE_NEW}
                    ),
                )
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    return {"status": resp.status}
                out = {"status": 200, "n_new": 0, "ttft_s": None}
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    rec = json.loads(line)
                    if "token" in rec:
                        if out["ttft_s"] is None:
                            out["ttft_s"] = time.time() - t_req
                        out["n_new"] += 1
                    elif rec.get("done"):
                        out["finish_reason"] = rec.get("finish_reason")
                        out["timings"] = rec.get("timings")
                out["latency_s"] = time.time() - t_req
                return out
            finally:
                conn.close()

        one_request(prompts[0])  # warm every program through HTTP
        todo = list(prompts)
        results: list = []
        lock = threading.Lock()

        def client():
            while True:
                with lock:
                    if not todo:
                        return
                    prompt = todo.pop()
                r = one_request(prompt)
                with lock:
                    results.append(r)

        clients = [
            threading.Thread(target=client, daemon=True)
            for _ in range(n_clients)
        ]
        t0 = time.time()
        for c in clients:
            c.start()
        for c in clients:
            c.join(timeout=600)
        wall = time.time() - t0
        ok = [
            r for r in results
            if r.get("status") == 200
            and r.get("finish_reason") in ("eos", "budget")
        ]
        def pctl(sorted_vals, q):
            if not sorted_vals:
                return 0.0
            i = min(
                len(sorted_vals) - 1,
                int(round(q * (len(sorted_vals) - 1))),
            )
            return sorted_vals[i]

        ttfts = sorted(
            r["ttft_s"] for r in ok if r.get("ttft_s") is not None
        )
        ttft_p99 = pctl(ttfts, 0.99)
        ttft_p50 = pctl(ttfts, 0.5)
        # queue age from the done records' timings breakdown (ISSUE 7):
        # how long requests WAITED (front-door pending + engine queue)
        # before any tower work — the admission-ladder health number
        queue_ages = sorted(
            r["timings"]["queue_s"] for r in ok
            if isinstance(r.get("timings"), dict)
            and r["timings"].get("queue_s") is not None
        )
        queue_age_p99 = pctl(queue_ages, 0.99)
        toks = sum(r.get("n_new", 0) for r in results)
        st = door.stats()
    finally:
        if srv is not None and door is not None:
            serve_mod.shutdown_gracefully(srv, door, grace_s=10.0)
        _lm_cleanup()
    print(
        f"LM serving FRONT DOOR ({n_clients} HTTP clients, "
        f"{n_requests} mixed requests): {len(ok) / wall:.2f} req/s, "
        f"{toks / wall:.0f} tok/s, TTFT p99 {1000 * ttft_p99:.0f} ms; "
        f"shed={sum(st['rejected'].values())} "
        f"deadline={st['deadline_exceeded']} "
        f"restarts={st['watchdog_restarts']}",
        file=sys.stderr,
    )
    return [
        {
            "metric": "lm_serve_frontdoor_rps",
            "value": round(len(ok) / wall, 3),
            "unit": "requests/sec",
            "lm_serve_frontdoor_config": (
                f"mid config paged engine behind ServingFrontDoor + "
                f"HTTP: B={b} slots, block {LM_SERVE_PAGED_BLOCK}, "
                f"{n_clients} concurrent clients streaming "
                f"{n_requests} mixed prompts {LM_SERVE_LENS}, budget "
                f"{LM_SERVE_NEW}"
            ),
            "lm_serve_frontdoor_tokens_per_sec": round(toks / wall, 1),
            "lm_serve_frontdoor_ttft_p99_ms": round(1000 * ttft_p99, 1),
            "lm_serve_frontdoor_ttft_p50_ms": round(1000 * ttft_p50, 1),
            "lm_serve_frontdoor_queue_age_p99_ms": round(
                1000 * queue_age_p99, 1
            ),
            "lm_serve_frontdoor_completed": len(ok),
            "lm_serve_frontdoor_rejected": sum(st["rejected"].values()),
            "lm_serve_frontdoor_deadline_exceeded": st[
                "deadline_exceeded"
            ],
            "lm_serve_frontdoor_cancelled": st["cancelled"],
            "lm_serve_frontdoor_watchdog_restarts": st[
                "watchdog_restarts"
            ],
            "lm_serve_frontdoor_compiles": st["engine"].get(
                "n_programs", 0
            ),
        }
    ]


@_section("lm_serve_router")
def _sec_lm_serve_router(ctx):
    # MULTI-REPLICA ROUTING (ISSUE 8): the shared-system-prompt stream
    # of lm_serve_prefix, but MIXED — several prompt FAMILIES, each a
    # 160-token shared prefix with short per-request tails — replayed
    # through the real router HTTP surface over TWO in-process
    # replicas.  Prefix-affinity placement keeps each family on one
    # replica (one cold prefill per family fleet-wide); the
    # round-robin baseline splits every family across both replicas
    # and pays the cold prefill once per replica.  Reported:
    # lm_serve_router_hit_rate (replica-measured prefix-cache hit
    # fraction under affinity routing) and
    # lm_serve_router_ttft_vs_roundrobin (mean client-clock TTFT
    # ratio, affinity/round-robin — below 1.0 means cache-aware
    # placement pays on this stream).
    import http.client
    import threading

    import numpy as np

    from znicz_tpu.cluster import ServingRouter, build_router_server
    from znicz_tpu.core import prng
    from znicz_tpu.services import serve as serve_mod
    from znicz_tpu.services.engine import PagedDecodeEngine
    from znicz_tpu.services.frontdoor import ServingFrontDoor
    from znicz_tpu.workflow.transformer import init_lm_params

    cfg, b = LM_MID, LM_MID_B
    n_replicas, n_families, per_family = 2, 3, 4
    budget = 24
    block = LM_SERVE_PAGED_BLOCK
    t_max = 384
    try:
        prng.seed_all(95)
        params = init_lm_params(
            cfg["vocab"], cfg["d_model"], cfg["n_layers"],
            cfg["n_heads"], max_seq=t_max,
        )
        gen = np.random.default_rng(14)
        families = [
            gen.integers(1, cfg["vocab"], (LM_PREFIX_SYS,)).astype(
                np.int32
            )
            for _ in range(n_families)
        ]
        # interleaved order: family affinity has to survive the other
        # families' traffic between two same-family requests
        prompts = [
            np.concatenate(
                [
                    families[f],
                    gen.integers(1, cfg["vocab"], (16 + 8 * f,)).astype(
                        np.int32
                    ),
                ]
            )
            for j in range(per_family)
            for f in range(n_families)
        ]

        def one_request(port, prompt):
            t_req = time.time()
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=300
            )
            try:
                conn.request(
                    "POST", "/generate",
                    body=json.dumps(
                        {"prompt": [int(t) for t in prompt],
                         "max_new_tokens": budget}
                    ),
                )
                resp = conn.getresponse()
                if resp.status != 200:
                    resp.read()
                    return {"status": resp.status}
                out = {"status": 200, "n_new": 0, "ttft_s": None}
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    rec = json.loads(line)
                    if "token" in rec:
                        if out["ttft_s"] is None:
                            out["ttft_s"] = time.time() - t_req
                        out["n_new"] += 1
                    elif rec.get("done"):
                        out["router"] = rec.get("router", {})
                return out
            finally:
                conn.close()

        def run_policy(policy):
            # EVERYTHING from the first door on is inside the try: a
            # mid-setup failure must tear down whatever already
            # started (engine threads, bound sockets, the heartbeat)
            # instead of leaking it into the rest of the round
            doors, srvs = [], []
            router = rsrv = None
            try:
                for _ in range(n_replicas):
                    door = ServingFrontDoor(
                        lambda: PagedDecodeEngine(
                            params, n_heads=cfg["n_heads"], eos_id=0,
                            batch_size=b, admit_every=8, max_seq=t_max,
                            block_size=block,
                        ),
                        max_pending=2 * len(prompts),
                    )
                    doors.append(door)
                    srv = serve_mod.build_server(
                        directory=".", port=0, frontdoor=door
                    )
                    srvs.append(srv)
                    threading.Thread(
                        target=srv.serve_forever, daemon=True
                    ).start()
                router = ServingRouter(block_size=block, policy=policy)
                for i, srv in enumerate(srvs):
                    router.register(
                        f"replica-{i}",
                        f"http://127.0.0.1:{srv.server_address[1]}",
                    )
                rsrv = build_router_server(router, port=0)
                threading.Thread(
                    target=rsrv.serve_forever, daemon=True
                ).start()
                port = rsrv.server_address[1]
                # sequential replay: per-request TTFT then measures
                # prefill (cold vs cached), not queueing noise
                t0 = time.time()
                results = [one_request(port, p) for p in prompts]
                wall = time.time() - t0
                ok = [r for r in results if r.get("status") == 200]
                ttfts = [
                    r["ttft_s"] for r in ok
                    if r.get("ttft_s") is not None
                ]
                hits = misses = 0
                for door in doors:
                    pc = door.engine.stats()["prefix_cache"]
                    hits += pc["hits"]
                    misses += pc["misses"]
                stats = router.stats()
                compiles = max(
                    door.engine.stats().get("n_programs", 0)
                    for door in doors
                )
                return {
                    "ok": len(ok),
                    "wall": wall,
                    "tokens": sum(r.get("n_new", 0) for r in ok),
                    "mean_ttft": sum(ttfts) / max(len(ttfts), 1),
                    "hits": hits,
                    "misses": misses,
                    "retries": sum(
                        r.get("router", {}).get("retries", 0)
                        for r in ok
                    ),
                    "replicas_used": len(
                        {
                            r.get("router", {}).get("replica")
                            for r in ok
                        }
                    ),
                    "stats": stats,
                    "compiles": compiles,
                }
            finally:
                for srv in srvs:
                    srv.shutdown()
                    srv.server_close()
                if rsrv is not None:
                    rsrv.shutdown()
                    rsrv.server_close()
                for door in doors:
                    door.close(grace_s=10.0)
                if router is not None:
                    router.close()

        run_policy("prefix_affinity")  # warm every program through HTTP
        aff = run_policy("prefix_affinity")
        rr = run_policy("round_robin")
        hit_rate = aff["hits"] / max(aff["hits"] + aff["misses"], 1)
        rr_hit_rate = rr["hits"] / max(rr["hits"] + rr["misses"], 1)
        ttft_vs_rr = (
            aff["mean_ttft"] / rr["mean_ttft"]
            if rr["mean_ttft"]
            else 0.0
        )
    finally:
        _lm_cleanup()
    print(
        f"LM serving ROUTER ({n_replicas} replicas, {n_families} "
        f"prompt families x {per_family}): affinity hit rate "
        f"{hit_rate:.2f} vs RR {rr_hit_rate:.2f}; TTFT "
        f"affinity/RR {ttft_vs_rr:.3f}; "
        f"{aff['ok']}/{len(prompts)} ok, retries {aff['retries']}",
        file=sys.stderr,
    )
    return [
        {
            "metric": "lm_serve_router_hit_rate",
            "value": round(hit_rate, 4),
            "unit": "fraction",
            "lm_serve_router_config": (
                f"mid config, {n_replicas} in-process paged replicas "
                f"(B={b} slots, block {block}) behind the prefix-"
                f"affinity router; {n_families} families of "
                f"{LM_PREFIX_SYS}-token shared prefixes x "
                f"{per_family} requests, interleaved, budget {budget}; "
                "round-robin twin runs the identical stream on fresh "
                "replicas"
            ),
            "lm_serve_router_ttft_vs_roundrobin": round(ttft_vs_rr, 4),
            "lm_serve_router_roundrobin_hit_rate": round(
                rr_hit_rate, 4
            ),
            "lm_serve_router_tokens_per_sec": round(
                aff["tokens"] / aff["wall"], 1
            ),
            "lm_serve_router_completed": aff["ok"],
            "lm_serve_router_retries": aff["retries"],
            "lm_serve_router_replicas_used": aff["replicas_used"],
            "lm_serve_router_ttft_ms": round(
                1000 * aff["mean_ttft"], 1
            ),
            "lm_serve_router_roundrobin_ttft_ms": round(
                1000 * rr["mean_ttft"], 1
            ),
            "lm_serve_router_compiles": aff["compiles"],
        }
    ]


# ---------------------------------------------------------------------------


def main() -> None:
    """Run every section (or the ``--only <prefix>`` subset) under
    per-section isolation; exit 1 if any section failed — their error
    records (and every other section's metric records) still printed."""
    only = None
    argv = sys.argv[1:]
    if "--only" in argv:
        i = argv.index("--only")
        if i + 1 >= len(argv):
            print("--only needs a metric-prefix argument", file=sys.stderr)
            raise SystemExit(2)
        only = argv[i + 1]
    try:
        _init_backend()
    except Exception as e:
        emit(
            {
                "error": type(e).__name__,
                "section": "backend_init",
                "detail": str(e)[:500],
                "metrics_snapshot": _metrics_snapshot(),
            }
        )
        print(f"bench failed: {type(e).__name__}: {e}", file=sys.stderr)
        raise SystemExit(1)
    failed = run_sections(only=only)
    # full telemetry registry behind this run's numbers: phase
    # histograms, serve counters/latency, cache stats.  The compile
    # ledger's headline rides as TOP-LEVEL numeric fields — the
    # driver's "parsed" merge (and znicz-bench-diff's record flatten)
    # only lift top-level numbers, so nesting them under
    # metrics_snapshot would make the compile-count gate inert
    emit(
        {
            "metric": "bench_sections_failed",
            "value": len(failed),
            "failed_sections": failed,
            **_program_headline(),
            "metrics_snapshot": _metrics_snapshot(),
        }
    )
    if failed:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
