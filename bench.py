"""Headline benchmark: AlexNet training throughput on one TPU chip.

Prints ONE JSON line:
  {"metric": "alexnet_images_per_sec", "value": N, "unit": "images/sec",
   "vs_baseline": mfu/0.35, ...}

``vs_baseline`` is measured model-FLOPs-utilization relative to the
BASELINE.json north-star gate of 35% MFU (the reference itself has no
published numbers to compare against — see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _model_flops_per_image(layers, input_shape) -> float:
    """Analytic fwd FLOPs (2*MACs) through the declarative layer list."""
    import numpy as np

    from znicz_tpu.ops import conv as conv_op, pooling as pool_op

    shape = (1,) + tuple(input_shape)
    total = 0.0
    for spec in layers:
        kind = spec["type"]
        fwd = spec.get("->", {})
        if kind.startswith("conv"):
            out = conv_op.output_shape(
                shape, fwd["n_kernels"], fwd["kx"], fwd["ky"],
                fwd.get("sliding", (1, 1)), fwd.get("padding", (0, 0, 0, 0)),
            )
            total += (
                2.0 * out[1] * out[2] * out[3]
                * fwd["kx"] * fwd["ky"] * shape[3]
            )
            shape = out
        elif kind.endswith("pooling"):
            shape = pool_op.output_shape(
                shape, fwd["kx"], fwd["ky"], fwd.get("sliding")
            )
        elif kind.startswith("all2all") or kind == "softmax":
            n_in = int(np.prod(shape[1:]))
            n_out = int(np.prod(fwd["output_sample_shape"]))
            total += 2.0 * n_in * n_out
            shape = (1, n_out)
    return total


def main() -> None:
    t_setup = time.time()
    import jax

    from znicz_tpu.core import prng
    from znicz_tpu.core.config import root
    from znicz_tpu.models import alexnet

    batch = int(os.environ.get("BENCH_BATCH", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    root.alexnet.loader.update(
        {"minibatch_size": batch, "n_train": batch, "n_valid": 0}
    )
    prng.seed_all(1234)
    wf = alexnet.build_workflow()
    wf.initialize(seed=1234)

    import jax.numpy as jnp

    mb = next(iter(wf.loader.batches("train")))
    x = jnp.asarray(mb.data)
    y = jnp.asarray(mb.labels)
    mask = jnp.asarray(mb.mask)

    # compile + warmup
    state, _ = wf._train_step(wf.state, x, y, mask, 1.0)
    state, metrics = wf._train_step(state, x, y, mask, 1.0)
    jax.block_until_ready(metrics["loss"])
    print(f"setup+compile {time.time()-t_setup:.1f}s", file=sys.stderr)

    # Remote-relay transports add a large fixed sync overhead per fetch;
    # difference two run lengths so the fixed cost cancels and only true
    # per-step device time remains.
    def timed(n):
        nonlocal state
        t0 = time.time()
        for _ in range(n):
            state, m = wf._train_step(state, x, y, mask, 1.0)
        # A value fetch (not just block_until_ready) is the only reliable
        # full-pipeline sync under remote-relay transports.
        float(m["loss"])
        return time.time() - t0

    timed(2)  # absorb the donated-buffer-layout recompile
    timed(2)
    t_short = timed(steps)
    t_long = timed(3 * steps)
    print(
        f"t_short({steps})={t_short:.3f}s t_long({3*steps})={t_long:.3f}s",
        file=sys.stderr,
    )
    dt = (t_long - t_short) / (2 * steps)  # seconds per step
    if dt <= 0:  # fell into noise; use the long run directly
        dt = t_long / (3 * steps)

    images_per_sec = batch / dt

    # secondary metric (BASELINE.json): MNIST MLP step latency
    from znicz_tpu.models import mnist as mnist_model

    root.mnist.loader.update(
        {"minibatch_size": 100, "n_train": 100, "n_test": 0,
         "validation_ratio": 0.0}
    )
    mwf = mnist_model.build_workflow()
    mwf.initialize(seed=1234)
    mmb = next(iter(mwf.loader.batches("train")))
    mx, my, mmask = (
        jnp.asarray(mmb.data), jnp.asarray(mmb.labels), jnp.asarray(mmb.mask)
    )
    mstate = mwf.state

    def mnist_timed(n):
        nonlocal mstate
        t0 = time.time()
        for _ in range(n):
            mstate, mm = mwf._train_step(mstate, mx, my, mmask, 1.0)
        float(mm["loss"])
        return time.time() - t0

    # sub-ms steps drown in relay sync noise; a noisy SHORT run shrinks the
    # difference, so min() would bias low — use the median of three pairs
    mnist_timed(3)
    mnist_timed(3)
    estimates = []
    for _ in range(3):
        m_short, m_long = mnist_timed(300), mnist_timed(900)
        if m_long > m_short:
            estimates.append((m_long - m_short) / 600 * 1000)
    if len(estimates) == 3:
        mnist_step_ms = sorted(estimates)[1]
    elif len(estimates) == 2:  # sorted[1] of two would pick the larger
        mnist_step_ms = sum(estimates) / 2
    elif estimates:
        mnist_step_ms = estimates[0]
    else:
        mnist_step_ms = mnist_timed(900) / 900 * 1000
    if len(estimates) < 3:
        print(
            f"mnist timing: {3 - len(estimates)} noisy pair(s) dropped",
            file=sys.stderr,
        )
    fwd_flops = _model_flops_per_image(
        root.alexnet.get("layers"), wf.loader.sample_shape
    )
    train_flops = 3.0 * fwd_flops  # fwd + input-grad + weight-grad
    # peak: TPU v5e bf16 ~197 TFLOP/s per chip (override for other chips)
    peak = float(os.environ.get("BENCH_PEAK_FLOPS", "197e12"))
    mfu = images_per_sec * train_flops / peak
    print(
        json.dumps(
            {
                "metric": "alexnet_images_per_sec",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(mfu / 0.35, 4),
                "mfu": round(mfu, 4),
                "batch": batch,
                "step_ms": round(1000 * dt, 2),
                "mnist_mlp_step_ms": round(mnist_step_ms, 3),
                "device": str(jax.devices()[0].device_kind),
            }
        )
    )


if __name__ == "__main__":
    main()
