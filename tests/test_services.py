"""Services tests: plotting, CSV metrics, image saver, status writer,
and the HTTP serving front door (streaming / shed-503 / healthz /
graceful shutdown)."""

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.services import (
    AccumulatingPlotter,
    EngineClosedError,
    ImageSaver,
    MetricsCSVWriter,
    PagedDecodeEngine,
    ServingFrontDoor,
    StatusWriter,
    Weights2D,
)
from znicz_tpu.services import serve as serve_mod
from znicz_tpu.utils import faults
from znicz_tpu.workflow import StandardWorkflow

MLP_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


def _wf(tmp_path, services, max_epochs=2):
    loader = datasets.mnist(n_train=64, n_test=32, minibatch_size=32)
    wf = StandardWorkflow(
        loader,
        MLP_LAYERS,
        decision_config={"max_epochs": max_epochs},
        default_hyper={"learning_rate": 0.05},
    )
    wf.services = services
    wf.initialize(seed=4)
    return wf


def test_csv_and_plots_written(tmp_path):
    prng.seed_all(4)
    services = [
        MetricsCSVWriter(str(tmp_path)),
        AccumulatingPlotter(str(tmp_path), metric="loss"),
        Weights2D(str(tmp_path), layer=0),
    ]
    wf = _wf(tmp_path, services)
    wf.run()
    assert (tmp_path / "metrics.csv").exists()
    lines = (tmp_path / "metrics.csv").read_text().strip().splitlines()
    assert len(lines) == 3  # header + 2 epochs
    assert "train_loss" in lines[0]
    assert (tmp_path / "loss.png").stat().st_size > 0
    assert (tmp_path / "weights0.png").stat().st_size > 0


def test_csv_header_merges_across_runs(tmp_path):
    # a second run with different splits must rewrite the merged header,
    # never append rows misaligned with an old header
    import csv

    prng.seed_all(4)
    loader1 = datasets.mnist(n_train=64, n_test=0, minibatch_size=32)
    wf1 = StandardWorkflow(
        loader1, MLP_LAYERS, decision_config={"max_epochs": 1},
    )
    wf1.services = [MetricsCSVWriter(str(tmp_path))]
    wf1.initialize(seed=4)
    wf1.run()
    wf2 = _wf(tmp_path, [MetricsCSVWriter(str(tmp_path))], max_epochs=1)
    wf2.run()
    with open(tmp_path / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 2
    assert rows[0]["test_loss"] == ""  # first run had no test split
    assert rows[1]["test_loss"] != ""


def test_status_writer(tmp_path):
    prng.seed_all(4)
    wf = _wf(tmp_path, [StatusWriter(str(tmp_path))])
    wf.run()
    status = json.loads((tmp_path / "status.json").read_text())
    assert status["epoch"] == 1
    assert status["stopping"] is True
    assert "train" in status["summary"]
    assert "<table>" in (tmp_path / "status.html").read_text()


def test_interactive_shell_service(tmp_path, monkeypatch):
    # the Shell epoch service drops into code.interact with the live
    # workflow in scope, at the configured cadence
    import znicz_tpu.interaction as interaction

    calls = []
    monkeypatch.setattr(
        interaction.code, "interact",
        lambda banner, local, exitmsg: calls.append(local),
    )
    prng.seed_all(4)
    shell = interaction.Shell(every_n_epochs=2)
    shell.enabled = True  # tests have no tty
    wf = _wf(tmp_path, [shell], max_epochs=4)
    wf.run()
    assert len(calls) == 2  # epochs 0 and 2
    assert calls[0]["wf"] is wf
    assert calls[0]["state"] is not None
    assert "verdict" in calls[0]


def test_status_page_embeds_plot_pngs(tmp_path):
    # watch-while-training: plotters writing into the status dir appear as
    # auto-refreshed <img> tags (the live-plot story, SURVEY 2.1 graphics)
    from znicz_tpu.services import AccumulatingPlotter

    prng.seed_all(4)
    wf = _wf(
        tmp_path,
        [AccumulatingPlotter(str(tmp_path), metric="loss"),
         StatusWriter(str(tmp_path))],
    )
    wf.run()
    page = (tmp_path / "status.html").read_text()
    assert '<img src="loss.png?t=' in page


def test_image_saver(tmp_path):
    prng.seed_all(4)
    wf = _wf(tmp_path, [ImageSaver(str(tmp_path), split="test", n_images=3)])
    wf.run()
    files = list((tmp_path / "epoch1").iterdir())
    assert files, "no images saved"
    assert all(f.suffix == ".png" for f in files)


def test_service_failure_does_not_kill_training(tmp_path):
    class Broken:
        def on_epoch(self, wf, verdict):
            raise RuntimeError("boom")

    prng.seed_all(4)
    wf = _wf(tmp_path, [Broken()])
    dec = wf.run()  # must complete despite the failing service
    assert dec.epoch == 2


# -- the HTTP serving front door ------------------------------------------

EOS, HEADS, T_MAX = 14, 4, 64


@pytest.fixture(scope="module")
def lm_params():
    from znicz_tpu.workflow.transformer import init_lm_params

    prng.seed_all(27)
    return init_lm_params(17, 32, 2, HEADS, max_seq=T_MAX)


@pytest.fixture()
def http_door(lm_params, request):
    """A front door + live HTTP server on an ephemeral port; torn down
    whatever the test does."""
    faults.clear()
    kw = getattr(request, "param", {})

    def factory():
        return PagedDecodeEngine(
            lm_params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            block_size=8, max_seq=T_MAX, admit_every=4,
        )

    door = ServingFrontDoor(factory, **kw)
    server = serve_mod.build_server(directory=".", port=0, frontdoor=door)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        yield door, port
    finally:
        faults.clear()
        serve_mod.shutdown_gracefully(server, door, grace_s=2.0)


def _post(port, body, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("POST", "/generate", body=json.dumps(body))
    return conn, conn.getresponse()


def _read_ndjson(resp):
    lines = []
    while True:
        line = resp.readline()
        if not line:
            return lines
        lines.append(json.loads(line))


def test_generate_streams_tokens_and_typed_done_record(lm_params, http_door):
    import jax.numpy as jnp

    from znicz_tpu.workflow import generate as G

    door, port = http_door
    prompt = [1, 2, 3, 4, 5]
    conn, resp = _post(port, {"prompt": prompt, "max_new_tokens": 6})
    assert resp.status == 200
    trace = resp.getheader("X-Znicz-Trace-Id")
    assert trace  # client-visible trace id rides the response header
    lines = _read_ndjson(resp)
    conn.close()
    done = lines[-1]
    assert done["done"] is True and done["trace_id"] == trace
    assert done["finish_reason"] in ("eos", "budget")
    streamed = [rec["token"] for rec in lines[:-1]]
    assert len(streamed) == done["n_new"]
    ref = np.asarray(
        G.generate(
            lm_params, jnp.asarray(prompt, jnp.int32)[None],
            n_heads=HEADS, max_new_tokens=6, eos_id=EOS,
        )
    )[0][len(prompt):]
    hit = np.where(ref == EOS)[0]
    if len(hit):
        ref = ref[: hit[0] + 1]
    assert streamed == list(ref)


@pytest.mark.parametrize(
    "http_door", [{"max_pending": 1, "engine_queue_limit": 0}],
    indirect=True,
)
def test_generate_sheds_503_with_retry_after(http_door):
    door, port = http_door
    c1, r1 = _post(port, {"prompt": [1, 2], "max_new_tokens": 4})
    # engine_queue_limit=0 parks the first request, filling the queue;
    # the second must shed with 503 + Retry-After, not wait
    c2, r2 = _post(port, {"prompt": [1, 2], "max_new_tokens": 4})
    assert r2.status == 503
    assert int(r2.getheader("Retry-After")) >= 1
    body = json.loads(r2.read())
    assert body["error"] == "rejected" and body["reason"] == "queue_full"
    c2.close()
    c1.close()


def test_generate_rejects_bad_and_oversized_requests(http_door):
    _, port = http_door
    c, r = _post(port, {"max_new_tokens": 4})  # no prompt
    assert r.status == 400
    assert json.loads(r.read())["error"] == "bad_request"
    c.close()
    c, r = _post(port, {"prompt": [1, 2], "max_new_tokens": 100_000})
    assert r.status == 400
    assert json.loads(r.read())["error"] == "request_too_large"
    c.close()
    # malformed payloads must answer 400, never crash the engine
    # thread (a str deadline) or drop the connection (a None prompt)
    for bad in (
        {"prompt": [1, 2], "max_new_tokens": 4, "deadline_s": "soon"},
        {"prompt": None, "max_new_tokens": 4},
        {"prompt": [[1, 2], [3]], "max_new_tokens": 4},
    ):
        c, r = _post(port, bad)
        assert r.status == 400, bad
        assert json.loads(r.read())["error"] == "bad_request"
        c.close()
    # a NUMERIC string deadline is coerced, not rejected
    c, r = _post(
        port, {"prompt": [1, 2], "max_new_tokens": 2, "deadline_s": "30"}
    )
    assert r.status == 200
    assert _read_ndjson(r)[-1]["done"] is True
    c.close()


def _eos_free_prompt(params, budget=40):
    """A prompt whose greedy generation never hits EOS inside
    ``budget`` — a natural EOS would end the stream before the
    disconnect is noticed."""
    import jax.numpy as jnp

    from znicz_tpu.workflow import generate as G

    gen = np.random.default_rng(21)
    for _ in range(200):
        p = gen.integers(0, 17, (6,)).astype(np.int32)
        out = np.asarray(
            G.generate(
                params, jnp.asarray(p)[None], n_heads=HEADS,
                max_new_tokens=budget, eos_id=EOS,
            )
        )[0][len(p):]
        if EOS not in out:
            return p.tolist()
    raise AssertionError("no EOS-free prompt found in 200 draws")


def test_client_disconnect_cancels_request(lm_params, http_door):
    import socket

    door, port = http_door
    prompt = _eos_free_prompt(lm_params)
    # slow ticks keep the 40-token request running while we vanish
    faults.inject("frontdoor.slow_tick", delay=0.05)
    conn, resp = _post(port, {"prompt": prompt, "max_new_tokens": 40})
    resp.readline()  # at least one streamed token
    # the caller crashes mid-stream: SHUT_RDWR actually tears the
    # connection down (a plain close() keeps the fd alive under the
    # response's buffered reader)
    conn.sock.shutdown(socket.SHUT_RDWR)
    conn.sock.close()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if door.stats()["cancelled"] == 1:
            break
        time.sleep(0.05)
    faults.clear()
    assert door.stats()["cancelled"] == 1  # blocks reclaimed, not pinned


def test_healthz_tracks_watchdog_state(http_door):
    door, port = http_door

    def healthz():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        conn.close()
        return resp.status, body

    status, body = healthz()
    assert status == 200 and body["state"] == "running"
    door.close(grace_s=0.5)
    status, body = healthz()
    assert status == 503 and body["state"] == "closed"


def test_healthz_without_frontdoor_is_plain_ok(tmp_path):
    server = serve_mod.build_server(directory=str(tmp_path), port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        assert resp.status == 200 and resp.read() == b"ok\n"
        conn.close()
    finally:
        server.shutdown()


def test_graceful_shutdown_drains_and_closes(lm_params):
    def factory():
        return PagedDecodeEngine(
            lm_params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            block_size=8, max_seq=T_MAX, admit_every=4,
        )

    door = ServingFrontDoor(factory)
    server = serve_mod.build_server(directory=".", port=0, frontdoor=door)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    conn, resp = _post(port, {"prompt": [1, 2, 3], "max_new_tokens": 5})
    serve_mod.shutdown_gracefully(server, door, grace_s=10.0)
    # the in-flight stream DRAINED (typed done record), intake closed
    lines = _read_ndjson(resp)
    conn.close()
    assert lines[-1]["done"] is True
    assert lines[-1]["finish_reason"] in ("eos", "budget")
    with pytest.raises(EngineClosedError):
        door.submit([1, 2], 4)


def _get(port, path, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read()
    conn.close()
    return resp.status, body


def test_done_record_carries_timings_breakdown(http_door):
    # ISSUE 7 acceptance: every HTTP done record answers "why was this
    # request slow" — queue/prefill/decode plus preemption/cache counts
    _, port = http_door
    conn, resp = _post(port, {"prompt": [1, 2, 3], "max_new_tokens": 4})
    assert resp.status == 200
    done = _read_ndjson(resp)[-1]
    conn.close()
    assert done["done"] is True
    t = done["timings"]
    assert set(t) == {
        "queue_s", "prefill_s", "decode_s", "preemptions",
        "cached_tokens", "spec_drafted", "spec_accepted",
    }
    assert t["prefill_s"] > 0.0  # it really ran a prefill
    assert done["ttft_ms"] is not None and done["ttft_ms"] > 0.0


def test_debug_requests_endpoint_serves_the_ring(http_door):
    _, port = http_door
    traces = []
    for _ in range(2):
        conn, resp = _post(
            port, {"prompt": [1, 2, 3], "max_new_tokens": 3}
        )
        _read_ndjson(resp)
        traces.append(resp.getheader("X-Znicz-Trace-Id"))
        conn.close()
    status, body = _get(port, "/debug/requests")
    assert status == 200
    recent = json.loads(body)["requests"]
    assert [r["trace_id"] for r in recent[:2]] == traces[::-1]  # newest 1st
    assert recent[0]["timings"]["queue_s"] >= 0.0
    assert recent[0]["finish_reason"] in ("eos", "budget")


def test_debug_requests_404_without_frontdoor(tmp_path):
    server = serve_mod.build_server(directory=str(tmp_path), port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        status, body = _get(port, "/debug/requests")
        assert status == 404
        assert json.loads(body)["error"] == "no_engine"
        # /slo still answers from the process-local fallback monitor
        status, body = _get(port, "/slo")
        assert status == 200
        snap = json.loads(body)
        assert "targets" in snap and "breached" in snap
    finally:
        server.shutdown()
        server.server_close()


def test_slo_fallback_samples_so_polls_build_rolling_windows(tmp_path):
    # the frontdoor-less monitor has no engine thread sampling it; the
    # handler itself must, or every "rolling" window would judge
    # lifetime totals while claiming a 60 s span
    serve_mod._SLO_FALLBACK = None  # fresh monitor for this process
    server = serve_mod.build_server(directory=str(tmp_path), port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        status, body = _get(port, "/slo")
        assert status == 200
        snap = json.loads(body)
        # the poll itself anchored the window: span is the real age of
        # the oldest capture (~0 s), not the window width
        assert snap["rates"]["60"]["span_s"] < 60.0
    finally:
        server.shutdown()
        server.server_close()


def test_slo_endpoint_reports_frontdoor_judgment(http_door):
    _, port = http_door
    conn, resp = _post(port, {"prompt": [1, 2, 3], "max_new_tokens": 3})
    _read_ndjson(resp)
    conn.close()
    status, body = _get(port, "/slo")
    assert status == 200
    snap = json.loads(body)
    assert set(snap["targets"]) == {"ttft", "latency"}
    ttft = snap["targets"]["ttft"]
    assert ttft["metric"] == "znicz_serve_frontdoor_ttft_seconds"
    # at least one rolling window saw this request
    assert any(w["n"] > 0 for w in ttft["windows"].values())
    # breached is a judgment, not a type error (a cold-compile first
    # request CAN breach a 1 s TTFT target — that's the tool working)
    assert isinstance(snap["breached"], bool)


def test_metrics_fallback_exposes_frontdoor_series(tmp_path, http_door):
    # satellite: the live-registry fallback path (no metrics.prom in
    # the status dir) must carry the front-door gauges/counters so a
    # scraper of a pure serving replica sees admission-ladder health
    from znicz_tpu.observability import parse_prometheus_text

    door, port = http_door
    conn, resp = _post(port, {"prompt": [1, 2, 3], "max_new_tokens": 3})
    _read_ndjson(resp)
    conn.close()
    status, body = _get(port, "/metrics")
    assert status == 200
    parsed = parse_prometheus_text(body.decode())
    names = {n for n, _, _ in parsed["samples"]}
    for family in (
        "znicz_serve_frontdoor_pending",
        "znicz_serve_frontdoor_inflight",
        "znicz_serve_frontdoor_ttft_seconds_count",
        "znicz_serve_frontdoor_latency_seconds_count",
        "znicz_serve_watchdog_restarts_total",
    ):
        assert family in names, family


def test_aggregator_fleet_view_includes_frontdoor_series(http_door):
    # satellite: the merged fleet view carries the same front-door
    # series (pushed from a serving replica's live registry), so the
    # router-to-be can schedule against admission state fleet-wide
    from znicz_tpu.observability import (
        get_registry,
        parse_prometheus_text,
    )
    from znicz_tpu.observability.aggregate import MetricsAggregator

    door, port = http_door
    conn, resp = _post(port, {"prompt": [1, 2, 3], "max_new_tokens": 3})
    _read_ndjson(resp)
    conn.close()
    agg = MetricsAggregator()
    agg.push("replica-0", get_registry().snapshot())
    agg.push("replica-1", text=get_registry().prometheus_text())
    parsed = parse_prometheus_text(agg.prometheus_text())
    flat = {
        (n, tuple(sorted(lbl.items()))): v
        for n, lbl, v in parsed["samples"]
    }
    fd_count = flat[("znicz_serve_frontdoor_ttft_seconds_count", ())]
    assert fd_count >= 2.0  # both replicas' series summed
    assert ("znicz_serve_frontdoor_pending", ()) in flat
    assert parsed["types"]["znicz_serve_frontdoor_ttft_seconds"] == (
        "histogram"
    )
