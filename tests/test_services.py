"""Services tests: plotting, CSV metrics, image saver, status writer."""

import json
import os

from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.services import (
    AccumulatingPlotter,
    ImageSaver,
    MetricsCSVWriter,
    StatusWriter,
    Weights2D,
)
from znicz_tpu.workflow import StandardWorkflow

MLP_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


def _wf(tmp_path, services, max_epochs=2):
    loader = datasets.mnist(n_train=64, n_test=32, minibatch_size=32)
    wf = StandardWorkflow(
        loader,
        MLP_LAYERS,
        decision_config={"max_epochs": max_epochs},
        default_hyper={"learning_rate": 0.05},
    )
    wf.services = services
    wf.initialize(seed=4)
    return wf


def test_csv_and_plots_written(tmp_path):
    prng.seed_all(4)
    services = [
        MetricsCSVWriter(str(tmp_path)),
        AccumulatingPlotter(str(tmp_path), metric="loss"),
        Weights2D(str(tmp_path), layer=0),
    ]
    wf = _wf(tmp_path, services)
    wf.run()
    assert (tmp_path / "metrics.csv").exists()
    lines = (tmp_path / "metrics.csv").read_text().strip().splitlines()
    assert len(lines) == 3  # header + 2 epochs
    assert "train_loss" in lines[0]
    assert (tmp_path / "loss.png").stat().st_size > 0
    assert (tmp_path / "weights0.png").stat().st_size > 0


def test_csv_header_merges_across_runs(tmp_path):
    # a second run with different splits must rewrite the merged header,
    # never append rows misaligned with an old header
    import csv

    prng.seed_all(4)
    loader1 = datasets.mnist(n_train=64, n_test=0, minibatch_size=32)
    wf1 = StandardWorkflow(
        loader1, MLP_LAYERS, decision_config={"max_epochs": 1},
    )
    wf1.services = [MetricsCSVWriter(str(tmp_path))]
    wf1.initialize(seed=4)
    wf1.run()
    wf2 = _wf(tmp_path, [MetricsCSVWriter(str(tmp_path))], max_epochs=1)
    wf2.run()
    with open(tmp_path / "metrics.csv", newline="") as f:
        rows = list(csv.DictReader(f))
    assert len(rows) == 2
    assert rows[0]["test_loss"] == ""  # first run had no test split
    assert rows[1]["test_loss"] != ""


def test_status_writer(tmp_path):
    prng.seed_all(4)
    wf = _wf(tmp_path, [StatusWriter(str(tmp_path))])
    wf.run()
    status = json.loads((tmp_path / "status.json").read_text())
    assert status["epoch"] == 1
    assert status["stopping"] is True
    assert "train" in status["summary"]
    assert "<table>" in (tmp_path / "status.html").read_text()


def test_interactive_shell_service(tmp_path, monkeypatch):
    # the Shell epoch service drops into code.interact with the live
    # workflow in scope, at the configured cadence
    import znicz_tpu.interaction as interaction

    calls = []
    monkeypatch.setattr(
        interaction.code, "interact",
        lambda banner, local, exitmsg: calls.append(local),
    )
    prng.seed_all(4)
    shell = interaction.Shell(every_n_epochs=2)
    shell.enabled = True  # tests have no tty
    wf = _wf(tmp_path, [shell], max_epochs=4)
    wf.run()
    assert len(calls) == 2  # epochs 0 and 2
    assert calls[0]["wf"] is wf
    assert calls[0]["state"] is not None
    assert "verdict" in calls[0]


def test_status_page_embeds_plot_pngs(tmp_path):
    # watch-while-training: plotters writing into the status dir appear as
    # auto-refreshed <img> tags (the live-plot story, SURVEY 2.1 graphics)
    from znicz_tpu.services import AccumulatingPlotter

    prng.seed_all(4)
    wf = _wf(
        tmp_path,
        [AccumulatingPlotter(str(tmp_path), metric="loss"),
         StatusWriter(str(tmp_path))],
    )
    wf.run()
    page = (tmp_path / "status.html").read_text()
    assert '<img src="loss.png?t=' in page


def test_image_saver(tmp_path):
    prng.seed_all(4)
    wf = _wf(tmp_path, [ImageSaver(str(tmp_path), split="test", n_images=3)])
    wf.run()
    files = list((tmp_path / "epoch1").iterdir())
    assert files, "no images saved"
    assert all(f.suffix == ".png" for f in files)


def test_service_failure_does_not_kill_training(tmp_path):
    class Broken:
        def on_epoch(self, wf, verdict):
            raise RuntimeError("boom")

    prng.seed_all(4)
    wf = _wf(tmp_path, [Broken()])
    dec = wf.run()  # must complete despite the failing service
    assert dec.epoch == 2
