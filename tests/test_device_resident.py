"""Device-resident loaders across EVERY workflow family.

Round-3 verdict's one silent-wrong-results trap: `device_preproc` used to be
applied only inside the base Workflow's steps, so a device-resident loader
(whose minibatch payload is a bare pool-index vector) fed *indices as data*
to Transformer/SOM/RBM workflows.  The preproc now lives in
``Workflow._finalize_steps`` — these tests pin the contract: for every
workflow family, device_resident=True trains IDENTICALLY to the streaming
loader (same seeds, same order, same math — any index leak would destroy
the equality).
"""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.workflow import StandardWorkflow
from znicz_tpu.workflow.transformer import TransformerLMWorkflow
from znicz_tpu.workflow.unsupervised import KohonenWorkflow, RBMWorkflow


def _assert_histories_equal(a, b, *, rtol=1e-5, atol=1e-7):
    assert len(a) == len(b)
    for ea, eb in zip(a, b):
        assert ea.keys() == eb.keys()
        for split in ea:
            np.testing.assert_allclose(
                ea[split]["loss"], eb[split]["loss"], rtol=rtol, atol=atol
            )


class TestTransformerDeviceResident:
    def _run(self, device_resident: bool):
        prng.seed_all(41)
        gen = np.random.default_rng(5)
        # bigram-ish token streams, [N, T] ints
        tokens = np.cumsum(
            gen.integers(0, 3, (96, 16)), axis=1, dtype=np.int64
        ) % 17
        loader = FullBatchLoader(
            {"train": tokens, "test": tokens[:32]},
            minibatch_size=32,
            device_resident=device_resident,
        )
        wf = TransformerLMWorkflow(
            loader, vocab=17, d_model=16, n_layers=1, n_heads=2,
            max_epochs=3, attention="dot",
        )
        wf.initialize(seed=41)
        if device_resident:
            assert wf._ctx is not None
            assert wf._use_epoch_scan()  # inherits the scan dispatch win
        dec = wf.run()
        return dec.history, np.asarray(wf.state.params[0]["embed"])

    def test_matches_streaming(self):
        h_res, p_res = self._run(True)
        h_str, p_str = self._run(False)
        _assert_histories_equal(h_res, h_str)
        np.testing.assert_allclose(p_res, p_str, rtol=1e-6, atol=1e-7)
        # sanity: the LM actually learned (indices-as-tokens would plateau
        # at uniform CE ~ log(17) = 2.83 or blow up on out-of-vocab values)
        assert h_res[-1]["train"]["loss"] < h_res[0]["train"]["loss"]


class TestKohonenDeviceResident:
    def _run(self, device_resident: bool):
        prng.seed_all(43)
        gen = np.random.default_rng(7)
        data = gen.normal(0.0, 1.0, (128, 12)).astype(np.float32)
        loader = FullBatchLoader(
            {"train": data},
            minibatch_size=32,
            device_resident=device_resident,
        )
        wf = KohonenWorkflow(
            loader, sx=3, sy=3, total_epochs=3, impl="xla"
        )
        wf.initialize(seed=43)
        dec = wf.run()
        return dec.history, np.asarray(wf.state.params["weights"])

    def test_matches_streaming(self):
        h_res, w_res = self._run(True)
        h_str, w_str = self._run(False)
        _assert_histories_equal(h_res, h_str)
        np.testing.assert_allclose(w_res, w_str, rtol=1e-6, atol=1e-7)


class TestRBMDeviceResident:
    def _run(self, device_resident: bool):
        prng.seed_all(47)
        gen = np.random.default_rng(9)
        data = (gen.uniform(0, 1, (128, 24)) > 0.5).astype(np.float32)
        loader = FullBatchLoader(
            {"train": data},
            minibatch_size=32,
            device_resident=device_resident,
        )
        wf = RBMWorkflow(loader, n_hidden=8, max_epochs=3, impl="xla")
        wf.initialize(seed=47)
        dec = wf.run()
        return dec.history, np.asarray(wf.state.params["weights"])

    def test_matches_streaming(self):
        h_res, w_res = self._run(True)
        h_str, w_str = self._run(False)
        _assert_histories_equal(h_res, h_str)
        np.testing.assert_allclose(w_res, w_str, rtol=1e-6, atol=1e-7)


class TestPoolSharded:
    """HBM pool sharded over the data axis: capacity scales with the mesh
    (max rows ~= n_data * HBM_free / bytes_per_sample), gathers stay local
    by construction (per-shard sampling + shard_map)."""

    def _make_wf(self, *, pool_sharded, minibatch_size, n=128, seed=61):
        from znicz_tpu.parallel import DataParallel, make_mesh

        prng.seed_all(seed)
        gen = np.random.default_rng(13)
        images = gen.integers(0, 256, (n, 8, 8, 1), dtype=np.uint8)
        labels = (images.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
        loader = FullBatchLoader(
            {"train": images}, {"train": labels},
            minibatch_size=minibatch_size,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_resident=True,
            pool_sharded=pool_sharded,
        )
        wf = StandardWorkflow(
            loader,
            [{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
             {"type": "softmax", "->": {"output_sample_shape": 2}}],
            decision_config={"max_epochs": 3},
            default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
            parallel=DataParallel(make_mesh(8, 1)),
        )
        wf.initialize(seed=seed)
        return wf

    def test_one_batch_epoch_matches_replicated(self):
        # with ONE minibatch per epoch both modes see the full dataset per
        # step — only the row order inside the batch differs, so losses
        # must agree (batch metrics are order-invariant sums)
        a = self._make_wf(pool_sharded=True, minibatch_size=128)
        b = self._make_wf(pool_sharded=False, minibatch_size=128)
        ha, hb = a.run().history, b.run().history
        for ea, eb in zip(ha, hb):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"],
                rtol=1e-5, atol=1e-7,
            )
            assert ea["train"]["n_err"] == eb["train"]["n_err"]

    def test_pool_really_sharded_and_trains(self):
        wf = self._make_wf(pool_sharded=True, minibatch_size=32)
        pool = wf._ctx["pool"]
        # each device holds 1/8 of the rows — THE capacity win
        assert pool.shape[0] == 128
        assert pool.addressable_shards[0].data.shape[0] == 128 // 8
        hist = wf.run().history
        assert all(np.isfinite(h["train"]["loss"]) for h in hist)
        # the learnable mean-brightness rule is actually learned
        assert hist[-1]["train"]["n_err"] <= hist[0]["train"]["n_err"]

    def test_epoch_covers_every_sample_once(self):
        # per-shard sampling is still an exact epoch: every dataset row
        # appears exactly once, and batch block s only references shard s
        from znicz_tpu.parallel import DataParallel, make_mesh

        prng.seed_all(71)
        gen = np.random.default_rng(17)
        data = gen.normal(size=(96, 4)).astype(np.float32)
        loader = FullBatchLoader(
            {"train": data}, minibatch_size=24,
            device_resident=True, pool_sharded=True,
        )
        loader.set_data_shards(8)
        served = np.concatenate(
            [mb.indices for mb in loader.batches("train")]
        )
        assert sorted(served.tolist()) == list(range(96))
        c, rows_per = 96 // 8, 24 // 8
        for mb in loader.batches("train"):
            np.testing.assert_array_equal(
                mb.indices // c, np.repeat(np.arange(8), rows_per)
            )

    def test_multi_split_pool_and_evaluate(self):
        # the device block interleaves EVERY split's chunk: train/test
        # rows must resolve to their own pool entries (a cross-split
        # offset bug would silently evaluate on training pixels)
        from znicz_tpu.parallel import DataParallel, make_mesh

        gen = np.random.default_rng(29)
        tr = gen.integers(0, 256, (64, 8, 8, 1), dtype=np.uint8)
        te = gen.integers(0, 256, (32, 8, 8, 1), dtype=np.uint8)
        trl = (tr.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
        tel = (te.mean(axis=(1, 2, 3)) > 127).astype(np.int32)

        def run(pool_sharded):
            prng.seed_all(93)
            loader = FullBatchLoader(
                {"train": tr, "test": te},
                {"train": trl, "test": tel},
                minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=True, pool_sharded=pool_sharded,
            )
            wf = StandardWorkflow(
                loader,
                [{"type": "all2all_tanh",
                  "->": {"output_sample_shape": 8}},
                 {"type": "softmax", "->": {"output_sample_shape": 2}}],
                decision_config={"max_epochs": 3},
                default_hyper={"learning_rate": 0.1,
                               "gradient_moment": 0.9},
                parallel=DataParallel(make_mesh(8, 1)),
            )
            wf.initialize(seed=93)
            # evaluate at the (identical) initial params: training
            # trajectories legitimately differ between pool layouts
            # (per-shard batch composition), but addressing must not
            # change evaluation results
            return wf, wf.evaluate("test")

        wf_s, ev_s = run(True)
        _, ev_r = run(False)
        assert ev_s["n_samples"] == ev_r["n_samples"] == 32
        # same one-batch split: metrics must agree across pool layouts
        assert ev_s["n_err"] == ev_r["n_err"]
        np.testing.assert_allclose(ev_s["loss"], ev_r["loss"], rtol=1e-5)
        # and the sharded run still trains fine afterwards
        hist = wf_s.run().history
        assert all(np.isfinite(h["train"]["loss"]) for h in hist)

    def test_misaligned_order_guard(self):
        loader = FullBatchLoader(
            {"train": np.zeros((96, 4), np.float32)}, minibatch_size=24,
            device_resident=True, pool_sharded=True,
        )
        loader.set_data_shards(8)
        loader._order["train"] = np.arange(96)  # NOT blocked
        with np.testing.assert_raises(AssertionError):
            next(loader.batches("train", shuffle=False))

    def test_shape_validation(self):
        loader = FullBatchLoader(
            {"train": np.zeros((100, 4), np.float32)}, minibatch_size=25,
            device_resident=True, pool_sharded=True,
        )
        with np.testing.assert_raises(ValueError):  # 25 % 8 != 0
            loader.set_data_shards(8)
        loader2 = FullBatchLoader(
            {"train": np.zeros((100, 4), np.float32)}, minibatch_size=32,
            device_resident=True, pool_sharded=True,
        )
        with np.testing.assert_raises(ValueError):  # 100 % 32 != 0
            loader2.set_data_shards(8)


class TestPoolShardedUnsupervised:
    def test_som_trains_on_sharded_pool(self):
        # the non-backprop families inherit pool sharding through the
        # same centralized preproc: SOM trains on a data-axis-sharded pool
        from znicz_tpu.parallel import DataParallel, make_mesh

        prng.seed_all(91)
        gen = np.random.default_rng(23)
        data = gen.normal(0.0, 1.0, (128, 12)).astype(np.float32)
        loader = FullBatchLoader(
            {"train": data}, minibatch_size=32,
            device_resident=True, pool_sharded=True,
        )
        wf = KohonenWorkflow(
            loader, sx=3, sy=3, total_epochs=3, impl="xla",
            parallel=DataParallel(make_mesh(8, 1)),
        )
        wf.initialize(seed=91)
        assert wf._ctx["pool"].addressable_shards[0].data.shape[0] == 16
        hist = wf.run().history
        assert all(np.isfinite(h["train"]["loss"]) for h in hist)


class TestAutoencoderDeviceResident:
    def test_target_is_preprocessed_input(self):
        # target="input": the AE target must be the PREPROCESSED batch (the
        # gathered pool rows), never the raw index payload
        def run(device_resident):
            prng.seed_all(53)
            gen = np.random.default_rng(11)
            images = gen.integers(0, 256, (96, 6, 6, 1), dtype=np.uint8)
            loader = FullBatchLoader(
                {"train": images},
                minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=device_resident,
            )
            wf = StandardWorkflow(
                loader,
                [{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                 {"type": "all2all", "->": {"output_sample_shape": (6, 6, 1)}}],
                loss_function="mse",
                target="input",
                decision_config={"max_epochs": 3},
                default_hyper={"learning_rate": 0.05,
                               "gradient_moment": 0.9},
            )
            wf.initialize(seed=53)
            return wf.run().history

        _assert_histories_equal(run(True), run(False))
