"""Device-resident loaders across EVERY workflow family.

Round-3 verdict's one silent-wrong-results trap: `device_preproc` used to be
applied only inside the base Workflow's steps, so a device-resident loader
(whose minibatch payload is a bare pool-index vector) fed *indices as data*
to Transformer/SOM/RBM workflows.  The preproc now lives in
``Workflow._finalize_steps`` — these tests pin the contract: for every
workflow family, device_resident=True trains IDENTICALLY to the streaming
loader (same seeds, same order, same math — any index leak would destroy
the equality).
"""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.workflow import StandardWorkflow
from znicz_tpu.workflow.transformer import TransformerLMWorkflow
from znicz_tpu.workflow.unsupervised import KohonenWorkflow, RBMWorkflow


def _assert_histories_equal(a, b, *, rtol=1e-5, atol=1e-7):
    assert len(a) == len(b)
    for ea, eb in zip(a, b):
        assert ea.keys() == eb.keys()
        for split in ea:
            np.testing.assert_allclose(
                ea[split]["loss"], eb[split]["loss"], rtol=rtol, atol=atol
            )


class TestTransformerDeviceResident:
    def _run(self, device_resident: bool):
        prng.seed_all(41)
        gen = np.random.default_rng(5)
        # bigram-ish token streams, [N, T] ints
        tokens = np.cumsum(
            gen.integers(0, 3, (96, 16)), axis=1, dtype=np.int64
        ) % 17
        loader = FullBatchLoader(
            {"train": tokens, "test": tokens[:32]},
            minibatch_size=32,
            device_resident=device_resident,
        )
        wf = TransformerLMWorkflow(
            loader, vocab=17, d_model=16, n_layers=1, n_heads=2,
            max_epochs=3, attention="dot",
        )
        wf.initialize(seed=41)
        if device_resident:
            assert wf._ctx is not None
            assert wf._use_epoch_scan()  # inherits the scan dispatch win
        dec = wf.run()
        return dec.history, np.asarray(wf.state.params[0]["embed"])

    def test_matches_streaming(self):
        h_res, p_res = self._run(True)
        h_str, p_str = self._run(False)
        _assert_histories_equal(h_res, h_str)
        np.testing.assert_allclose(p_res, p_str, rtol=1e-6, atol=1e-7)
        # sanity: the LM actually learned (indices-as-tokens would plateau
        # at uniform CE ~ log(17) = 2.83 or blow up on out-of-vocab values)
        assert h_res[-1]["train"]["loss"] < h_res[0]["train"]["loss"]


class TestKohonenDeviceResident:
    def _run(self, device_resident: bool):
        prng.seed_all(43)
        gen = np.random.default_rng(7)
        data = gen.normal(0.0, 1.0, (128, 12)).astype(np.float32)
        loader = FullBatchLoader(
            {"train": data},
            minibatch_size=32,
            device_resident=device_resident,
        )
        wf = KohonenWorkflow(
            loader, sx=3, sy=3, total_epochs=3, impl="xla"
        )
        wf.initialize(seed=43)
        dec = wf.run()
        return dec.history, np.asarray(wf.state.params["weights"])

    def test_matches_streaming(self):
        h_res, w_res = self._run(True)
        h_str, w_str = self._run(False)
        _assert_histories_equal(h_res, h_str)
        np.testing.assert_allclose(w_res, w_str, rtol=1e-6, atol=1e-7)


class TestRBMDeviceResident:
    def _run(self, device_resident: bool):
        prng.seed_all(47)
        gen = np.random.default_rng(9)
        data = (gen.uniform(0, 1, (128, 24)) > 0.5).astype(np.float32)
        loader = FullBatchLoader(
            {"train": data},
            minibatch_size=32,
            device_resident=device_resident,
        )
        wf = RBMWorkflow(loader, n_hidden=8, max_epochs=3, impl="xla")
        wf.initialize(seed=47)
        dec = wf.run()
        return dec.history, np.asarray(wf.state.params["weights"])

    def test_matches_streaming(self):
        h_res, w_res = self._run(True)
        h_str, w_str = self._run(False)
        _assert_histories_equal(h_res, h_str)
        np.testing.assert_allclose(w_res, w_str, rtol=1e-6, atol=1e-7)


class TestAutoencoderDeviceResident:
    def test_target_is_preprocessed_input(self):
        # target="input": the AE target must be the PREPROCESSED batch (the
        # gathered pool rows), never the raw index payload
        def run(device_resident):
            prng.seed_all(53)
            gen = np.random.default_rng(11)
            images = gen.integers(0, 256, (96, 6, 6, 1), dtype=np.uint8)
            loader = FullBatchLoader(
                {"train": images},
                minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=device_resident,
            )
            wf = StandardWorkflow(
                loader,
                [{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                 {"type": "all2all", "->": {"output_sample_shape": (6, 6, 1)}}],
                loss_function="mse",
                target="input",
                decision_config={"max_epochs": 3},
                default_hyper={"learning_rate": 0.05,
                               "gradient_moment": 0.9},
            )
            wf.initialize(seed=53)
            return wf.run().history

        _assert_histories_equal(run(True), run(False))
