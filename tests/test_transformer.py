"""Transformer LM workflow tests, incl. ring-attention sequence parallelism."""

import importlib

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader import FullBatchLoader
from znicz_tpu.ops.normalization import layer_norm
from znicz_tpu.parallel import make_mesh
from znicz_tpu.workflow.transformer import (
    TransformerLMWorkflow,
    init_lm_params,
    lm_apply,
)


class TestLayerNorm:
    def test_normalizes(self):
        x = jnp.asarray(np.random.default_rng(0).normal(3.0, 5.0, (4, 16)))
        y = layer_norm(x, jnp.ones(16), jnp.zeros(16))
        np.testing.assert_allclose(np.asarray(y.mean(-1)), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y.std(-1)), 1.0, atol=1e-2)


class TestLMApply:
    def test_shapes_and_causality(self):
        prng.seed_all(3)
        params = init_lm_params(16, 32, 2, 4, max_seq=12)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 16, (2, 12)), jnp.int32
        )
        logits = lm_apply(params, tokens, n_heads=4)
        assert logits.shape == (2, 12, 16)
        # causality: changing a LATER token cannot affect earlier logits
        tokens2 = tokens.at[:, 8].set((tokens[:, 8] + 1) % 16)
        logits2 = lm_apply(params, tokens2, n_heads=4)
        np.testing.assert_allclose(
            np.asarray(logits[:, :8]), np.asarray(logits2[:, :8]),
            rtol=1e-5, atol=1e-6,
        )
        assert not np.allclose(
            np.asarray(logits[:, 8:]), np.asarray(logits2[:, 8:])
        )


def _model_module():
    mod = importlib.import_module("znicz_tpu.models.transformer_lm")
    return importlib.reload(mod)


class TestTransformerWorkflow:
    def test_learns_bigram_structure(self):
        prng.seed_all(1234)
        lm = _model_module()
        root.transformer_lm.loader.update(
            {"n_train": 256, "n_test": 64, "seq_len": 32}
        )
        wf = lm.build_workflow(max_epochs=8)
        wf.initialize(seed=1234)
        dec = wf.run()
        first = dec.history[0]["train"]["loss"]
        last = dec.history[-1]["train"]["loss"]
        # random-guess CE is log(32) ~ 3.47; bigram structure is learnable
        assert last < first * 0.8, (first, last)
        assert last < 3.0
        assert dec.history[-1]["train"]["token_accuracy"] > 0.2

    def test_snapshot_resume(self, tmp_path):
        from znicz_tpu.workflow import Snapshotter

        prng.seed_all(9)
        lm = _model_module()
        root.transformer_lm.loader.update(
            {"n_train": 128, "n_test": 0, "seq_len": 16}
        )
        wf = lm.build_workflow(
            max_epochs=2,
            snapshotter=Snapshotter(str(tmp_path), "lm", compress=False),
        )
        wf.initialize(seed=9)
        wf.run()
        best = tmp_path / "lm_best.pickle"
        assert best.exists()
        prng.seed_all(9)
        wf2 = lm.build_workflow(max_epochs=2)
        wf2.initialize(snapshot=str(best))
        assert int(wf2.state.step) > 0

    def test_flash_attention_matches_dot(self):
        # the blockwise kernel as the workflow's attention: same training
        # trajectory as the jnp twin
        tokens = np.asarray(
            np.random.default_rng(7).integers(0, 16, (16, 24)), np.int32
        )

        def build_and_run(attention):
            prng.seed_all(12)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=1, n_heads=2,
                max_epochs=2, attention=attention,
            )
            wf.initialize(seed=12)
            return wf.run().history

        a = build_and_run("dot")
        b = build_and_run("flash")
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_flash_composes_with_data_parallel(self):
        # the kernel has no GSPMD rule, but the shard_map wrapper runs it
        # per data shard — flash+DP must reproduce single-device flash
        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(7).integers(0, 16, (16, 24)), np.int32
        )

        def build_and_run(parallel, tensor_parallel=False):
            prng.seed_all(12)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=1, n_heads=2,
                max_epochs=2, attention="flash", parallel=parallel,
                tensor_parallel=tensor_parallel,
            )
            wf.initialize(seed=12)
            return wf.run().history

        a = build_and_run(None)
        b = build_and_run(DataParallel(make_mesh(8, 1)))
        c = build_and_run(
            DataParallel(make_mesh(4, 2)), tensor_parallel=True
        )
        for ea, eb, ec in zip(a, b, c):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )
            np.testing.assert_allclose(
                ea["train"]["loss"], ec["train"]["loss"], rtol=1e-4
            )

    def test_remat_matches_and_cuts_activation_memory(self):
        # jax.checkpoint per block: identical training trajectory, smaller
        # compiled activation footprint (the long-context memory lever)
        import jax

        from znicz_tpu.workflow.transformer import init_lm_params, lm_apply

        tokens = np.asarray(
            np.random.default_rng(8).integers(0, 16, (16, 32)), np.int32
        )

        def build_and_run(remat):
            prng.seed_all(44)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=2, attention="dot", remat=remat,
            )
            wf.initialize(seed=44)
            return wf.run().history

        a = build_and_run(False)
        b = build_and_run(True)
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-5
            )

        # the backward's saved residuals shrink on a deep/long config —
        # the semantic, platform-independent measure of what checkpoint
        # changes (CPU XLA temp sizes are not representative of TPU)
        try:
            from jax.ad_checkpoint import saved_residuals
        except ImportError:  # public home moved across jax versions
            from jax._src.ad_checkpoint import saved_residuals

        prng.seed_all(45)
        params = init_lm_params(32, 64, 8, 4, max_seq=256)
        toks = jnp.asarray(
            np.random.default_rng(9).integers(0, 32, (8, 256)), jnp.int32
        )

        def residual_bytes(remat):
            def loss(p):
                return jnp.sum(lm_apply(p, toks, n_heads=4, remat=remat))

            return sum(
                int(np.prod(aval.shape)) * aval.dtype.itemsize
                for aval, _ in saved_residuals(loss, params)
                if hasattr(aval, "shape")
            )

        assert residual_bytes(True) < 0.5 * residual_bytes(False)

    def test_pipeline_composes_with_data_parallel(self):
        # DPxPP on one (data=2, pipe=4) mesh: every data replica runs its
        # own pipeline; stage grads all-reduce over data — losses must
        # match the plain single-device run
        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(5).integers(0, 16, (32, 16)), np.int32
        )

        def build_and_run(parallel, pp):
            prng.seed_all(33)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
                max_epochs=2, attention="dot",
                pipeline_parallel=pp, parallel=parallel,
                pipeline_microbatches=8 if pp else None,
            )
            wf.initialize(seed=33)
            return wf, wf.run().history

        _, a = build_and_run(None, False)
        wf_pp, b = build_and_run(
            DataParallel(make_mesh(2, 1, 4)), True
        )
        # stage params really live sharded over pipe
        import jax

        stages_leaf = jax.tree_util.tree_leaves(
            wf_pp.state.params["stages"]
        )[0]
        assert not stages_leaf.is_fully_replicated
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_pipeline_composes_with_data_and_tensor_parallel(self):
        # DPxPPxTP on ONE (data=2, model=2, pipe=2) mesh — the 3-axis
        # composition every real large-model stack runs: batch over data,
        # stage tower over pipe, stage weights Megatron-sharded over model
        # with explicit psums inside the pipeline shard_map.  Losses must
        # match the plain single-device run.
        import jax.tree_util as jtu

        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(5).integers(0, 16, (32, 16)), np.int32
        )

        def build_and_run(parallel, pp_tp):
            prng.seed_all(33)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
                max_epochs=2, attention="dot",
                pipeline_parallel=pp_tp, tensor_parallel=pp_tp,
                parallel=parallel,
                pipeline_microbatches=8 if pp_tp else None,
            )
            wf.initialize(seed=33)
            return wf, wf.run().history

        _, a = build_and_run(None, False)
        wf3, b = build_and_run(DataParallel(make_mesh(2, 2, 2)), True)
        # stage weights really live sharded over BOTH pipe and model
        wq = next(
            leaf
            for path, leaf in jtu.tree_leaves_with_path(
                wf3.state.params["stages"]
            )
            if "wq" in jtu.keystr(path)
        )
        assert tuple(wq.sharding.spec) == ("pipe", None, "model")
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_moe_lm_learns_and_shards_experts(self):
        # MoE FFN blocks in the flagship LM: trains, and under
        # tensor_parallel=True the expert dim shards over the model axis
        # (DP x EP) with losses matching the single-device run
        import jax.tree_util as jtu

        from znicz_tpu.parallel import DataParallel

        tokens = np.cumsum(
            np.random.default_rng(7).integers(0, 3, (64, 16)), axis=1,
            dtype=np.int64,
        ) % 16

        def run(parallel=None, tp=False):
            prng.seed_all(51)
            ld = FullBatchLoader(
                {"train": tokens.copy()}, minibatch_size=16
            )
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=4, attention="dot",
                moe_experts=4, moe_top_k=2,
                tensor_parallel=tp, parallel=parallel,
            )
            wf.initialize(seed=51)
            return wf, [h["train"]["loss"] for h in wf.run().history]

        _, base = run()
        assert base[-1] < base[0]  # the MoE LM actually learns
        wf_ep, ep = run(DataParallel(make_mesh(4, 2)), tp=True)
        np.testing.assert_allclose(base, ep, rtol=1e-4)
        w1 = next(
            leaf
            for path, leaf in jtu.tree_leaves_with_path(wf_ep.state.params)
            if "moe_w_up" in jtu.keystr(path)
        )
        assert tuple(w1.sharding.spec)[0] == "model"  # experts sharded

    def test_moe_lm_sequence_parallel(self):
        # ring attention (sequence over data) composes with MoE FFNs:
        # the flattened-token expert dispatch runs on the sharded
        # sequence and losses match the single-device run
        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(5).integers(0, 16, (16, 64)), np.int32
        )

        def run(sp):
            prng.seed_all(43)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=8)
            kw = (
                dict(
                    sequence_parallel=True, mesh=make_mesh(8, 1),
                    parallel=DataParallel(make_mesh(8, 1)),
                )
                if sp
                else {}
            )
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=2, attention="dot",
                moe_experts=4, moe_top_k=2, **kw,
            )
            wf.initialize(seed=43)
            return [h["train"]["loss"] for h in wf.run().history]

        np.testing.assert_allclose(run(False), run(True), rtol=1e-4)

    def test_moe_lm_pipeline_parallel(self):
        # MoE blocks stack into pipeline stages (replicated experts)
        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(8).integers(0, 16, (32, 16)), np.int32
        )

        def run(parallel, pp):
            prng.seed_all(53)
            ld = FullBatchLoader(
                {"train": tokens.copy()}, minibatch_size=16
            )
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
                max_epochs=2, attention="dot", moe_experts=4,
                pipeline_parallel=pp, parallel=parallel,
                pipeline_microbatches=8 if pp else None,
            )
            wf.initialize(seed=53)
            return [h["train"]["loss"] for h in wf.run().history]

        base = run(None, False)
        pp = run(DataParallel(make_mesh(2, 1, 4)), True)
        np.testing.assert_allclose(base, pp, rtol=1e-4)

    def test_moe_lm_pipeline_tensor_parallel(self):
        # DPxPPxTPxMoE on ONE (data=2, model=2, pipe=2) mesh: experts
        # shard over the model axis INSIDE the pipeline shard_map (manual
        # EP — apply_local_shard partials + the stage psum); losses must
        # match the plain single-device MoE run
        import jax.tree_util as jtu

        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(11).integers(0, 16, (32, 16)), np.int32
        )

        def run(parallel, pp_tp):
            prng.seed_all(57)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
                max_epochs=2, attention="dot",
                moe_experts=4, moe_top_k=2,
                pipeline_parallel=pp_tp, tensor_parallel=pp_tp,
                parallel=parallel,
                pipeline_microbatches=8 if pp_tp else None,
            )
            wf.initialize(seed=57)
            return wf, [h["train"]["loss"] for h in wf.run().history]

        _, base = run(None, False)
        wf3, comp = run(DataParallel(make_mesh(2, 2, 2)), True)
        # expert leaves really shard (pipe, model, ...); router replicates
        # over model
        w_up = next(
            leaf
            for path, leaf in jtu.tree_leaves_with_path(
                wf3.state.params["stages"]
            )
            if "moe_w_up" in jtu.keystr(path)
        )
        assert tuple(w_up.sharding.spec) == ("pipe", "model")
        router = next(
            leaf
            for path, leaf in jtu.tree_leaves_with_path(
                wf3.state.params["stages"]
            )
            if "moe_router" in jtu.keystr(path)
        )
        assert tuple(router.sharding.spec) in (("pipe",), ("pipe", None, None))
        np.testing.assert_allclose(base, comp, rtol=1e-4)

    def test_pipeline_tensor_parallel_with_flash_attention(self):
        # flash under PPxTP runs the model-axis param sharding with
        # check_vma=False (pallas out_shapes carry no vma info) — this
        # pins that shard_map's transpose still produces correct grads
        # there: losses match the single-device dense run
        from znicz_tpu.parallel import DataParallel

        tokens = np.asarray(
            np.random.default_rng(5).integers(0, 16, (32, 64)), np.int32
        )

        def run(attention, pp_tp):
            prng.seed_all(33)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            kw = (
                dict(
                    pipeline_parallel=True, tensor_parallel=True,
                    parallel=DataParallel(make_mesh(2, 2, 2)),
                    pipeline_microbatches=8,
                )
                if pp_tp
                else {}
            )
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
                max_epochs=2, attention=attention, **kw,
            )
            wf.initialize(seed=33)
            return [h["train"]["loss"] for h in wf.run().history]

        base = run("dot", False)
        flash = run("flash", True)  # interpret-mode kernel on CPU
        np.testing.assert_allclose(base, flash, rtol=2e-4)

    def test_pipeline_default_microbatches_keep_bubble_low(self):
        from znicz_tpu.parallel.pipeline import bubble_fraction

        tokens = np.asarray(
            np.random.default_rng(6).integers(0, 16, (48, 16)), np.int32
        )
        ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=24)
        wf = TransformerLMWorkflow(
            ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
            pipeline_parallel=True, mesh=make_mesh(1, 1, 4),
        )
        assert wf.pipeline_microbatches == 24  # 6 * n_stages
        assert bubble_fraction(4, wf.pipeline_microbatches) <= 0.16
        # the default holds the bound for EVERY stage count
        for s in (2, 4, 8, 16, 64):
            assert bubble_fraction(s, 6 * s) <= 0.16
        # ... and clamps to a batch divisor instead of crashing configs
        # whose minibatch doesn't divide 6S (here 32 -> 16)
        ld2 = FullBatchLoader({"train": tokens.copy()}, minibatch_size=32)
        wf2 = TransformerLMWorkflow(
            ld2, vocab=16, d_model=32, n_layers=4, n_heads=2,
            pipeline_parallel=True, mesh=make_mesh(1, 1, 4),
        )
        assert wf2.pipeline_microbatches == 16
        # under DPxPP the auto-selection must also keep microbatch rows
        # divisible by the data axis: bs=24, S=2, data=4 — the plain
        # divisor search would pick m=12 (rows 2, not divisible by 4) and
        # fail later in pipeline_apply; the constrained search picks m=6
        from znicz_tpu.parallel import DataParallel

        ld3 = FullBatchLoader({"train": tokens.copy()}, minibatch_size=24)
        wf3 = TransformerLMWorkflow(
            ld3, vocab=16, d_model=32, n_layers=4, n_heads=2,
            pipeline_parallel=True, parallel=DataParallel(make_mesh(4, 1, 2)),
        )
        assert wf3.pipeline_microbatches == 6
        assert (24 // wf3.pipeline_microbatches) % 4 == 0

    def test_sequence_parallel_flash_inner_matches_dense(self):
        # SP long context at kernel speed: ring(inner=flash) trains to the
        # same losses as ring(inner=dense)
        tokens = np.asarray(
            np.random.default_rng(11).integers(0, 16, (8, 64)), np.int32
        )

        def build_and_run(attention):
            prng.seed_all(21)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=8)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=1, n_heads=2,
                max_epochs=2, attention=attention,
                sequence_parallel=True, mesh=make_mesh(8, 1),
            )
            wf.initialize(seed=21)
            return wf.run().history

        a = build_and_run("dot")  # dense ring inner
        b = build_and_run("flash")  # flash kernel ring inner
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_pipeline_parallel_matches_single_device(self):
        # block tower pipelined over a 4-stage pipe mesh == plain run
        import jax
        from jax.sharding import Mesh

        tokens = np.asarray(
            np.random.default_rng(4).integers(0, 16, (16, 16)), np.int32
        )
        pipe_mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))

        def build(pp):
            prng.seed_all(6)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=4, n_heads=2,
                max_epochs=2, pipeline_parallel=pp,
                pipeline_microbatches=4 if pp else None,
                mesh=pipe_mesh if pp else None,
            )
            wf.initialize(seed=6)
            return wf

        wf_pp = build(True)
        # stage params actually live sharded over the pipe axis
        w_up = wf_pp.state.params["stages"][0]["w_up"]
        assert not w_up.is_fully_replicated
        a = build(False).run().history
        b = wf_pp.run().history
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )
            np.testing.assert_allclose(
                ea["train"]["token_accuracy"],
                eb["train"]["token_accuracy"],
                rtol=1e-4,
            )

    def test_pipeline_with_flash_attention(self):
        # the chosen attention kernel must survive into the pipelined
        # stages (it is passed through stage_fn, not silently dropped)
        import jax
        from jax.sharding import Mesh

        tokens = np.asarray(
            np.random.default_rng(9).integers(0, 16, (8, 16)), np.int32
        )
        pipe_mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))

        def run_with(attention):
            prng.seed_all(14)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=8)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=2, attention=attention,
                pipeline_parallel=True, pipeline_microbatches=2,
                mesh=pipe_mesh,
            )
            wf.initialize(seed=14)
            return wf.run().history

        a = run_with("dot")
        b = run_with("flash")
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_pipeline_snapshot_resume(self, tmp_path):
        # the stacked-stage dict pytree round-trips through the
        # snapshotter's exact-resume contract like every other workflow
        import jax
        from jax.sharding import Mesh

        from znicz_tpu.workflow import Snapshotter

        tokens = np.asarray(
            np.random.default_rng(11).integers(0, 16, (8, 16)), np.int32
        )
        pipe_mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))

        def build(snapshotter=None):
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=8)
            return TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=2, pipeline_parallel=True,
                pipeline_microbatches=2, mesh=pipe_mesh,
                snapshotter=snapshotter,
            )

        prng.seed_all(15)
        wf = build(Snapshotter(str(tmp_path), "pplm", compress=False))
        wf.initialize(seed=15)
        wf.run()
        best = tmp_path / "pplm_best.pickle"
        assert best.exists()
        prng.seed_all(15)
        wf2 = build()
        wf2.initialize(snapshot=str(best))
        assert int(wf2.state.step) > 0
        w_a = np.asarray(wf.state.params["stages"][0]["w_up"])
        w_b = np.asarray(wf2.state.params["stages"][0]["w_up"])
        np.testing.assert_array_equal(w_a, w_b)
        # the resumed workflow keeps training
        verdict = wf2.run_epoch()
        assert np.isfinite(verdict["summary"]["train"]["loss"])

    def test_pipeline_via_config_tree(self):
        # config-file-only route: root.transformer_lm.pipeline_stages
        prng.seed_all(8)
        lm = _model_module()
        root.transformer_lm.loader.update(
            {"n_train": 64, "n_test": 0, "seq_len": 16, "minibatch_size": 32}
        )
        root.transformer_lm.update(
            {"n_layers": 4, "pipeline_stages": 4, "pipeline_microbatches": 2}
        )
        wf = lm.build_workflow(max_epochs=2)
        assert wf.pipeline_parallel and wf._n_stages == 4
        wf.initialize(seed=8)
        dec = wf.run()
        assert np.isfinite(dec.history[-1]["train"]["loss"])

    def test_sequence_parallel_matches_single_device(self):
        prng.seed_all(5)
        mesh = make_mesh(8, 1)
        tokens = np.asarray(
            np.random.default_rng(2).integers(0, 16, (16, 32)), np.int32
        )

        def build(sp):
            prng.seed_all(5)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=1, n_heads=2,
                max_epochs=2, sequence_parallel=sp,
                mesh=mesh if sp else None,
            )
            wf.initialize(seed=5)
            return wf.run().history

        a = build(False)
        b = build(True)
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )
