"""Self-healing training: chaos suite (docs/TRAINING.md).

The SURVEY §4 functional-test pattern, upgraded: every failure mode the
self-healing layer claims to survive is INJECTED here (utils/faults.py)
and the run must complete with the documented typed events/counters —
and, where the contract is exactness, the recovered trajectory must
golden-match the unfaulted run: crash at an epoch boundary, SIGTERM
mid-epoch, snapshot-write failure, corrupt/truncated snapshots, an
injected NaN step (anomaly-triggered rollback) and a flaky loader
fetch.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from znicz_tpu import observability
from znicz_tpu.core import prng
from znicz_tpu.loader import LoaderFetchError, PrefetchProducerError, datasets
from znicz_tpu.observability import pipeline as pipeline_mod
from znicz_tpu.observability.pipeline import PipelineAttribution
from znicz_tpu.observability.registry import MetricsRegistry, get_registry
from znicz_tpu.utils import faults
from znicz_tpu.workflow import (
    RecoveryPolicy,
    RollbackExhaustedError,
    SnapshotCorruptError,
    SnapshotWriteError,
    StandardWorkflow,
    Snapshotter,
    TrainingPreempted,
    find_latest_valid,
    load_snapshot,
)
from znicz_tpu.workflow.snapshotter import verify_snapshot

MLP = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


@pytest.fixture(autouse=True)
def _clean_faults_and_gauges():
    faults.clear()
    yield
    faults.clear()
    # the give-up gauge is process-global; a budget test must not leak
    # a "looping" verdict into later registry reads
    observability.gauge(pipeline_mod.ROLLBACK_GIVE_UP_METRIC).set(0.0)


def _mnist_workflow(tmp_path=None, *, seed=77, max_epochs=4,
                    loader_kwargs=None, **kw):
    prng.seed_all(seed)
    loader = datasets.mnist(
        n_train=192, n_test=32, minibatch_size=64,
        **(loader_kwargs or {}),
    )
    kw.setdefault("decision_config", {"max_epochs": max_epochs})
    kw.setdefault(
        "default_hyper", {"learning_rate": 0.1, "gradient_moment": 0.9}
    )
    wf = StandardWorkflow(
        loader, MLP,
        snapshot_dir=str(tmp_path) if tmp_path else None,
        **kw,
    )
    return wf


def _history_key(dec):
    return [
        (h["train"]["n_err"], round(h["train"]["loss"], 8))
        for h in dec.history
    ]


# ---------------------------------------------------------------------------
class TestSnapshotIntegrity:
    def _write_one(self, tmp_path, tag="epoch0", compress=False):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.nn.train_state import TrainState

        snap = Snapshotter(str(tmp_path), "t", compress=compress)
        st = TrainState.create(
            [{"w": jnp.arange(8.0)}], jax.random.key(3)
        )
        return snap, snap.save(st, {"decision": {"epoch": 1}}, tag=tag)

    def test_sidecar_written_and_verifies(self, tmp_path):
        _, path = self._write_one(tmp_path)
        assert os.path.exists(path + ".sha256")
        verify_snapshot(path)  # no raise
        state, host = load_snapshot(path)
        assert host["decision"]["epoch"] == 1

    def test_truncated_file_is_typed_corrupt(self, tmp_path):
        _, path = self._write_one(tmp_path)
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])
        with pytest.raises(SnapshotCorruptError):
            load_snapshot(path)
        with pytest.raises(SnapshotCorruptError):
            verify_snapshot(path)

    def test_bitflip_fails_digest(self, tmp_path):
        _, path = self._write_one(tmp_path)
        with open(path, "r+b") as f:
            f.seek(30)
            b = f.read(1)
            f.seek(30)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.raises(SnapshotCorruptError, match="sha256"):
            load_snapshot(path)

    def test_truncated_gz_without_sidecar_is_typed(self, tmp_path):
        # pre-sidecar snapshots (or a lost sidecar) still fail TYPED:
        # decode errors map to SnapshotCorruptError, not EOFError
        _, path = self._write_one(tmp_path, compress=True)
        os.remove(path + ".sha256")
        with open(path, "rb") as f:
            raw = f.read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])
        with pytest.raises(SnapshotCorruptError):
            load_snapshot(path)

    def test_missing_sidecar_still_loads(self, tmp_path):
        _, path = self._write_one(tmp_path)
        os.remove(path + ".sha256")
        load_snapshot(path)  # back-compat: verified by decode
        verify_snapshot(path)

    def test_find_latest_valid_skips_corrupt_newest(self, tmp_path):
        _, old = self._write_one(tmp_path, tag="epoch0")
        _, new = self._write_one(tmp_path, tag="epoch1")
        # force a clear mtime ordering, then corrupt the newest
        now = time.time()
        os.utime(old, (now - 60, now - 60))
        os.utime(new, (now, now))
        with open(new, "wb") as f:
            f.write(b"garbage")
        assert find_latest_valid(str(tmp_path)) == old
        assert find_latest_valid(str(tmp_path), prefix="t") == old
        assert find_latest_valid(str(tmp_path), prefix="other") is None

    def test_find_latest_valid_empty_dir(self, tmp_path):
        assert find_latest_valid(str(tmp_path)) is None
        assert find_latest_valid(str(tmp_path / "absent")) is None

    def test_version_skewed_snapshot_is_skipped_not_resumed(
        self, tmp_path
    ):
        # a sidecar-valid snapshot recording a FOREIGN format version
        # must not be selected for resume (load would ValueError and
        # crash-loop the supervisor); find_latest_valid falls through
        import znicz_tpu.workflow.snapshotter as snap_mod

        _, old = self._write_one(tmp_path, tag="epoch0")
        _, new = self._write_one(tmp_path, tag="epoch1")
        now = time.time()
        os.utime(old, (now - 60, now - 60))
        # rewrite the newest sidecar claiming a future format version
        with open(new, "rb") as f:
            digest = __import__("hashlib").sha256(f.read()).hexdigest()
        with open(new + ".sha256", "w") as f:
            f.write(
                f"{digest}  {os.path.basename(new)}  "
                f"v{snap_mod.FORMAT_VERSION + 1}\n"
            )
        with pytest.raises(ValueError, match="format"):
            verify_snapshot(new)
        assert find_latest_valid(str(tmp_path)) == old

    def test_injected_load_fault_is_typed(self, tmp_path):
        _, path = self._write_one(tmp_path)
        with faults.injected("snapshot.load", times=1):
            with pytest.raises(SnapshotCorruptError):
                load_snapshot(path)
        load_snapshot(path)  # disarmed: loads fine


class TestSnapshotWriteFailure:
    def test_direct_save_raises_typed_and_cleans_tmp(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.nn.train_state import TrainState

        snap = Snapshotter(str(tmp_path), "t", compress=False)
        st = TrainState.create([{"w": jnp.ones(2)}], jax.random.key(0))
        with faults.injected("snapshot.write", times=1):
            with pytest.raises(SnapshotWriteError):
                snap.save(st, {}, tag="x")
        leftovers = [
            p for p in os.listdir(tmp_path) if p.endswith(".tmp")
        ]
        assert leftovers == []
        assert not os.path.exists(snap._path("x"))

    def test_sidecar_failure_after_replace_drops_stale_sidecar(
        self, tmp_path, monkeypatch
    ):
        # disk dies between the data replace and the sidecar replace
        # while OVERWRITING a tag: the new data file already landed, so
        # the save is a SUCCESS (warning logged), the stale old sidecar
        # must not condemn the good new file, and the path stays in the
        # retention/resume bookkeeping
        import jax
        import jax.numpy as jnp

        import znicz_tpu.workflow.snapshotter as snap_mod
        from znicz_tpu.nn.train_state import TrainState

        snap = Snapshotter(str(tmp_path), "t", compress=False)
        st = TrainState.create([{"w": jnp.ones(2)}], jax.random.key(0))
        path = snap.save(st, {"n": 1}, tag="best")
        real_replace = os.replace

        def flaky_replace(src, dst):
            if dst.endswith(".sha256"):
                raise OSError("disk full writing sidecar")
            return real_replace(src, dst)

        monkeypatch.setattr(snap_mod.os, "replace", flaky_replace)
        assert snap.save(st, {"n": 2}, tag="best") == path
        monkeypatch.setattr(snap_mod.os, "replace", real_replace)
        # no stale sidecar left; the new data file verifies by decode
        assert not os.path.exists(path + ".sha256")
        verify_snapshot(path)
        _, host = load_snapshot(path)
        assert host["n"] == 2  # the NEW content, loadable
        assert find_latest_valid(str(tmp_path)) == path

    def test_run_survives_snapshot_write_failure(self, tmp_path):
        # chaos acceptance: one failed checkpoint write costs a
        # checkpoint, never the run — counted, logged, next interval
        # snapshots fine
        before = _snapshot_failures_total()
        wf = _mnist_workflow(
            tmp_path, snapshot_config={"interval": 1, "compress": False}
        )
        wf.initialize(seed=77)
        # the FIRST write (best or epoch0) fails; everything later lands
        faults.inject("snapshot.write", times=1)
        dec = wf.run()
        assert dec.epoch == 4  # run completed
        assert _snapshot_failures_total() == before + 1
        assert (tmp_path / "StandardWorkflow_epoch3.pickle").exists()
        assert find_latest_valid(str(tmp_path)) is not None


def _snapshot_failures_total() -> float:
    fam = get_registry().metrics().get(
        pipeline_mod.SNAPSHOT_FAILURES_METRIC
    )
    if fam is None:
        return 0.0
    return sum(c.value for c in fam.children().values())


class TestPruneByVerifiedSet:
    def test_never_deletes_only_valid_snapshot(self, tmp_path):
        # regression (ISSUE 14 satellite): keep=1 with a corrupt NEWEST
        # file must retain the older valid snapshot past the bound
        import jax
        import jax.numpy as jnp

        from znicz_tpu.nn.train_state import TrainState

        st = TrainState.create([{"w": jnp.ones(2)}], jax.random.key(0))
        snap = Snapshotter(
            str(tmp_path), "t", interval=1, keep=1, compress=False
        )
        p0 = snap.save(st, {}, tag="epoch0")
        p1 = snap.save(st, {}, tag="epoch1")
        with open(p1, "wb") as f:
            f.write(b"garbage")  # newest corrupt (sidecar now mismatches)
        # a fresh process recovers both into the retention ledger
        snap2 = Snapshotter(
            str(tmp_path), "t", interval=1, keep=1, compress=False
        )
        snap2.prune()
        assert os.path.exists(p0), "the only valid snapshot was deleted"
        assert find_latest_valid(str(tmp_path)) == p0

    def test_prunes_normally_when_newer_are_valid(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from znicz_tpu.nn.train_state import TrainState

        st = TrainState.create([{"w": jnp.ones(2)}], jax.random.key(0))
        snap = Snapshotter(
            str(tmp_path), "t", interval=1, keep=1, compress=False
        )
        for e in range(3):
            snap.maybe_save(st, {}, epoch=e, improved=False)
        files = sorted(
            p for p in os.listdir(tmp_path) if p.endswith(".pickle")
        )
        assert files == ["t_epoch2.pickle"]


# ---------------------------------------------------------------------------
class TestAnomalyTriggeredRollback:
    def test_nan_rollback_golden_matches_unfaulted(self, tmp_path):
        # the acceptance golden: injected NaN -> rollback to the last
        # good snapshot -> with perturbation off the replay is
        # byte-identical to a run that never faulted
        wf_a = _mnist_workflow(tmp_path / "a",
                               snapshot_config={"interval": 1})
        wf_a.initialize(seed=77)
        dec_a = wf_a.run()

        pol = RecoveryPolicy(
            max_rollbacks=2, lr_backoff=1.0, perturb=False
        )
        wf_b = _mnist_workflow(
            tmp_path / "b", snapshot_config={"interval": 1},
            recovery=pol,
        )
        wf_b.initialize(seed=77)
        faults.inject("train.step_nan", flag=True, times=1, after=7)
        dec_b = wf_b.run()
        assert pol.rollbacks_used == 1
        assert pol.events[0]["kind"] == "rollback"
        assert pol.events[0]["reason"] == "non_finite_loss"
        assert _history_key(dec_a) == _history_key(dec_b)
        np.testing.assert_array_equal(
            np.asarray(wf_a.state.params[0]["weights"]),
            np.asarray(wf_b.state.params[0]["weights"]),
        )

    def test_rollback_counter_and_status_surface(self, tmp_path):
        from znicz_tpu.services.web_status import StatusWriter

        before = _counter_total(pipeline_mod.ROLLBACKS_METRIC)
        pol = RecoveryPolicy(max_rollbacks=3, lr_backoff=0.5)
        wf = _mnist_workflow(
            tmp_path, snapshot_config={"interval": 1}, recovery=pol
        )
        wf.services.append(StatusWriter(str(tmp_path / "status")))
        wf.initialize(seed=77)
        faults.inject("train.step_nan", flag=True, times=1, after=7)
        wf.run()
        assert pol.rollbacks_used == 1
        assert pol.lr_scale == 0.5  # backoff applied
        assert (
            _counter_total(pipeline_mod.ROLLBACKS_METRIC) >= before + 1
        )
        status = json.loads(
            (tmp_path / "status" / "status.json").read_text()
        )
        assert status["recovery"]["rollbacks_used"] == 1
        assert status["recovery"]["events"][0]["kind"] == "rollback"
        # metrics.prom carries the counter the doctor reads
        prom = (tmp_path / "status" / "metrics.prom").read_text()
        assert pipeline_mod.ROLLBACKS_METRIC in prom

    def test_budget_exhaustion_is_typed_give_up(self, tmp_path):
        pol = RecoveryPolicy(max_rollbacks=1, perturb=False,
                             lr_backoff=1.0)
        wf = _mnist_workflow(
            tmp_path, snapshot_config={"interval": 1}, recovery=pol
        )
        wf.initialize(seed=77)
        # every step's loss reads NaN: rollback once, re-fault, give up
        faults.inject("train.step_nan", flag=True)
        with pytest.raises(RollbackExhaustedError):
            wf.run()
        faults.clear()
        assert pol.gave_up
        assert pol.rollbacks_used == 1
        assert pol.events[-1]["kind"] == "give_up"
        gauge = get_registry().metrics()[
            pipeline_mod.ROLLBACK_GIVE_UP_METRIC
        ]
        assert any(
            c.value == 1.0 for c in gauge.children().values()
        )

    def test_epoch_start_buffer_fallback_without_snapshotter(self):
        # no snapshot dir at all: rollback restores the in-memory
        # epoch-START buffer and the run still completes
        pol = RecoveryPolicy(max_rollbacks=2, perturb=False,
                             lr_backoff=1.0)
        wf_a = _mnist_workflow()
        wf_a.initialize(seed=77)
        dec_a = wf_a.run()
        wf = _mnist_workflow(recovery=pol)
        wf.initialize(seed=77)
        faults.inject("train.step_nan", flag=True, times=1, after=7)
        dec_b = wf.run()
        assert pol.rollbacks_used == 1
        assert pol.events[0]["source"] == "epoch-start buffer"
        assert _history_key(dec_a) == _history_key(dec_b)

    def test_perturbed_rollback_still_converges(self, tmp_path):
        pol = RecoveryPolicy(
            max_rollbacks=2, lr_backoff=0.5, perturb=True
        )
        wf = _mnist_workflow(
            tmp_path, snapshot_config={"interval": 1}, recovery=pol,
            max_epochs=5,
        )
        wf.initialize(seed=77)
        faults.inject("train.step_nan", flag=True, times=1, after=7)
        dec = wf.run()
        assert pol.rollbacks_used == 1
        assert pol.lr_scale == 0.5
        # perturbed replay differs from the golden path but still learns
        assert dec.history[-1]["train"]["err_pct"] < 10.0

    def test_scan_path_rollback(self, tmp_path):
        # scanned dispatch: verdicts surface at the epoch's metric
        # sync; the rollback discards the poisoned epoch and replays
        from znicz_tpu.loader.fullbatch import FullBatchLoader

        def build(recovery=None, out=None):
            prng.seed_all(31)
            gen = np.random.default_rng(5)
            imgs = gen.integers(0, 256, (128, 8, 8, 1), dtype=np.uint8)
            labels = (imgs.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
            ld = FullBatchLoader(
                {"train": imgs}, {"train": labels}, minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=True,
            )
            wf = StandardWorkflow(
                ld,
                [{"type": "all2all_tanh",
                  "->": {"output_sample_shape": 8}},
                 {"type": "softmax", "->": {"output_sample_shape": 2}}],
                decision_config={"max_epochs": 3},
                default_hyper={"learning_rate": 0.1},
                epoch_dispatch="scan",
                snapshot_dir=out,
                snapshot_config={"interval": 1} if out else None,
                recovery=recovery,
            )
            wf.initialize(seed=31)
            assert wf._use_epoch_scan()
            return wf

        dec_a = build().run()
        pol = RecoveryPolicy(max_rollbacks=2, perturb=False,
                             lr_backoff=1.0)
        wf_b = build(recovery=pol, out=str(tmp_path))
        # poison one scan row of epoch 1 (after epoch 0's 4 rows)
        faults.inject("train.step_nan", flag=True, times=1, after=5)
        dec_b = wf_b.run()
        assert pol.rollbacks_used == 1
        assert _history_key(dec_a) == _history_key(dec_b)

    def test_recovery_requires_detector(self):
        with pytest.raises(ValueError, match="anomaly"):
            _mnist_workflow(
                anomaly=False, recovery=RecoveryPolicy()
            )

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            RecoveryPolicy(max_rollbacks=0)
        with pytest.raises(ValueError):
            RecoveryPolicy(lr_backoff=0.0)
        with pytest.raises(ValueError):
            RecoveryPolicy(rollback_on_spike=-1)

    def test_zero_new_programs_across_rollback(self, tmp_path):
        # acceptance pin: restore re-feeds the ALREADY-COMPILED step —
        # nothing lands in the device ledger / compile counters, and
        # the train step stays ONE jit cache entry through a rollback
        from znicz_tpu.observability import device

        ledger_before = device.program_count()
        compile_hist = get_registry().metrics().get(
            "znicz_compile_seconds"
        )
        obs_before = (
            sum(c.count for c in compile_hist.children().values())
            if compile_hist is not None
            else 0
        )
        pol = RecoveryPolicy(max_rollbacks=2, perturb=False,
                             lr_backoff=1.0)
        wf = _mnist_workflow(
            tmp_path, snapshot_config={"interval": 1}, recovery=pol
        )
        wf.initialize(seed=77)
        faults.inject("train.step_nan", flag=True, times=1, after=7)
        wf.run()
        assert pol.rollbacks_used == 1
        assert wf._train_step._cache_size() == 1
        assert device.program_count() == ledger_before
        compile_hist = get_registry().metrics().get(
            "znicz_compile_seconds"
        )
        obs_after = (
            sum(c.count for c in compile_hist.children().values())
            if compile_hist is not None
            else 0
        )
        assert obs_after == obs_before


# ---------------------------------------------------------------------------
class TestLoaderFaultTolerance:
    def test_flaky_fetch_retries_transparently(self, tmp_path):
        before = _counter_total(pipeline_mod.LOADER_RETRIES_METRIC)
        wf_a = _mnist_workflow()
        wf_a.initialize(seed=77)
        dec_a = wf_a.run()
        wf_b = _mnist_workflow(
            loader_kwargs={"fetch_retries": 3, "fetch_backoff_s": 0.0}
        )
        wf_b.initialize(seed=77)
        faults.inject("loader.fetch_flaky", times=2)
        dec_b = wf_b.run()
        # retries are invisible to the trajectory
        assert _history_key(dec_a) == _history_key(dec_b)
        assert (
            _counter_total(pipeline_mod.LOADER_RETRIES_METRIC)
            >= before + 2
        )

    def test_retry_budget_exhaustion_is_typed(self):
        wf = _mnist_workflow(
            loader_kwargs={"fetch_retries": 1, "fetch_backoff_s": 0.0}
        )
        wf.initialize(seed=77)
        faults.inject("loader.fetch_flaky")  # every attempt fails
        with pytest.raises(LoaderFetchError):
            wf.run()
        faults.clear()

    def test_skip_bad_batch_counted(self):
        before = _counter_total(pipeline_mod.LOADER_SKIPPED_METRIC)
        wf = _mnist_workflow(
            max_epochs=1,
            loader_kwargs={
                "fetch_retries": 0,
                "skip_bad_batches": True,
            },
        )
        wf.initialize(seed=77)
        faults.inject("loader.fetch_flaky", times=1)
        dec = wf.run()
        assert (
            _counter_total(pipeline_mod.LOADER_SKIPPED_METRIC)
            == before + 1
        )
        # one 64-row train batch dropped from the 192-sample epoch
        assert dec.history[0]["train"]["n_samples"] == 128.0

    def test_dead_producer_is_typed_not_a_hang(self, monkeypatch):
        import threading as threading_mod

        from znicz_tpu.loader import prefetch as prefetch_mod

        class _DeadThread:
            def __init__(self, *a, **k):
                pass

            def start(self):
                pass

            def is_alive(self):
                return False

        monkeypatch.setattr(
            prefetch_mod.threading, "Thread", _DeadThread
        )
        assert threading_mod.Thread is _DeadThread  # same module object
        with pytest.raises(PrefetchProducerError):
            list(prefetch_mod.prefetch(iter([1, 2, 3]), 2))

    def test_producer_exception_reraises_typed_original(self):
        from znicz_tpu.loader.prefetch import prefetch

        def boom():
            yield 1
            raise LoaderFetchError("flaky source died")

        out = []
        with pytest.raises(LoaderFetchError, match="flaky source"):
            for item in prefetch(boom(), 2):
                out.append(item)
        assert out == [1]


def _counter_total(name: str) -> float:
    fam = get_registry().metrics().get(name)
    if fam is None:
        return 0.0
    return sum(c.value for c in fam.children().values())


# ---------------------------------------------------------------------------
class TestGracefulStop:
    def test_stop_between_epochs_writes_emergency_snapshot(
        self, tmp_path
    ):
        wf = _mnist_workflow(tmp_path)
        wf.initialize(seed=77)
        assert wf.run_epoch() is not None
        wf.request_stop()
        with pytest.raises(TrainingPreempted) as exc_info:
            wf.run_epoch()
        path = exc_info.value.snapshot_path
        assert path and "emergency" in path
        verify_snapshot(path)
        assert find_latest_valid(str(tmp_path)) == path

    def test_mid_epoch_stop_resumes_golden(self, tmp_path):
        # SIGTERM-equivalent mid-epoch: the emergency snapshot is the
        # epoch-START buffer, so the resumed run replays the aborted
        # epoch exactly and the whole trajectory golden-matches
        wf_a = _mnist_workflow(tmp_path / "a")
        wf_a.initialize(seed=77)
        dec_a = wf_a.run()

        wf_b = _mnist_workflow(tmp_path / "b")
        wf_b.enable_emergency_snapshots()
        wf_b.initialize(seed=77)

        def stop_at(base, step):
            if step == 4:  # mid epoch 1 (3 steps per epoch)
                wf_b.request_stop()
            return base

        wf_b.lr_policy = stop_at
        with pytest.raises(TrainingPreempted):
            wf_b.run()
        snap = find_latest_valid(str(tmp_path / "b"))
        assert snap and "emergency" in snap

        prng.seed_all(77)
        wf_c = _mnist_workflow(tmp_path / "c")
        wf_c.initialize(snapshot=snap)
        assert wf_c.decision.epoch == 1  # replays the aborted epoch
        dec_c = wf_c.run()
        assert _history_key(dec_a) == _history_key(dec_c)
        np.testing.assert_array_equal(
            np.asarray(wf_a.state.params[0]["weights"]),
            np.asarray(wf_c.state.params[0]["weights"]),
        )

    def test_mid_epoch_stop_deferred_sync_resumes_golden(self, tmp_path):
        # deferred sync + save_best: mid-epoch, self.state is the NEXT
        # epoch's partial state — the flush must write the pending
        # epoch from the RETAINED buffer and the emergency snapshot is
        # the (retained state, flushed decision) start quadruple, so
        # the resume still golden-matches
        def build(out):
            return _mnist_workflow(out, epoch_sync="deferred")

        wf_a = build(tmp_path / "a")
        wf_a.initialize(seed=77)
        dec_a = wf_a.run()

        wf_b = build(tmp_path / "b")
        wf_b.initialize(seed=77)

        def stop_at(base, step):
            if step == 7:  # mid epoch 2 (3 steps/epoch)
                wf_b.request_stop()
            return base

        wf_b.lr_policy = stop_at
        with pytest.raises(TrainingPreempted):
            wf_b.run()
        snap = find_latest_valid(str(tmp_path / "b"))
        assert snap and "emergency" in snap

        prng.seed_all(77)
        wf_c = _mnist_workflow(tmp_path / "c")  # resume in sync mode
        wf_c.initialize(snapshot=snap)
        assert wf_c.decision.epoch == 2  # replays the aborted epoch
        dec_c = wf_c.run()
        assert _history_key(dec_a) == _history_key(dec_c)

    def test_stop_without_snapshotter_still_typed(self):
        wf = _mnist_workflow()
        wf.initialize(seed=77)
        wf.request_stop()
        with pytest.raises(TrainingPreempted) as exc_info:
            wf.run_epoch()
        assert exc_info.value.snapshot_path is None


# ---------------------------------------------------------------------------
class TestTransformerChaosResume:
    def test_crash_at_epoch_k_resumes_golden(self, tmp_path):
        # the exact-resume-under-chaos contract for the SECOND workflow
        # family: crash (process death simulated by abandoning the
        # object) after epoch 1 -> find_latest_valid -> golden
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.workflow import TransformerLMWorkflow

        tokens = np.asarray(
            np.random.default_rng(7).integers(0, 16, (16, 24)), np.int32
        )

        def build(max_epochs, snap_dir=None):
            prng.seed_all(13)
            ld = FullBatchLoader(
                {"train": tokens.copy()}, minibatch_size=16
            )
            snapshotter = (
                Snapshotter(
                    snap_dir, "lm", interval=1, compress=False
                )
                if snap_dir
                else None
            )
            return TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=1, n_heads=2,
                max_epochs=max_epochs, snapshotter=snapshotter,
            )

        wf_a = build(4)
        wf_a.initialize(seed=13)
        dec_a = wf_a.run()

        wf_b = build(4, str(tmp_path))
        wf_b.initialize(seed=13)
        faults.inject("train.crash", after=2, times=1)
        with pytest.raises(faults.FaultInjected):
            wf_b.run()
        faults.clear()

        snap = find_latest_valid(str(tmp_path), prefix="lm")
        assert snap is not None
        prng.seed_all(13)
        wf_c = build(4)
        wf_c.initialize(snapshot=snap)
        assert wf_c.decision.epoch == 2
        dec_c = wf_c.run()
        a_losses = [
            round(h["train"]["loss"], 8) for h in dec_a.history
        ]
        c_losses = [
            round(h["train"]["loss"], 8) for h in dec_c.history
        ]
        assert a_losses == c_losses


# ---------------------------------------------------------------------------
_CHILD_MODULE = """
import json
import os
import signal

import numpy as np

from znicz_tpu.loader import datasets
from znicz_tpu.workflow import StandardWorkflow

LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


def run(load, main):
    loader = datasets.mnist(n_train=192, n_test=32, minibatch_size=64)
    wf = load(
        StandardWorkflow, loader, LAYERS,
        decision_config={"max_epochs": 4},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
    )
    sigterm_step = int(os.environ.get("ZNICZ_TEST_SIGTERM_STEP", "0"))
    if sigterm_step:
        def pol(base, step):
            if step == sigterm_step:
                os.kill(os.getpid(), signal.SIGTERM)
            return base
        wf.lr_policy = pol
    main()
    dec = wf.decision
    digest = float(
        np.abs(np.asarray(wf.state.params[0]["weights"])).sum()
    )
    print("RESULT " + json.dumps({
        "epochs": dec.epoch,
        "history": [
            [h["train"]["n_err"], round(h["train"]["loss"], 8)]
            for h in dec.history
        ],
        "digest": round(digest, 5),
    }))
"""


def _run_child(module_path, extra_args, *, env_extra=None, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ZNICZ_FAULTS", None)
    env.update(env_extra or {})
    proc = subprocess.run(
        [sys.executable, "-m", "znicz_tpu", str(module_path),
         "--random-seed", "7"] + extra_args,
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    return proc


def _parse_result(stdout: str):
    for line in reversed(stdout.splitlines()):
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise AssertionError(f"no RESULT line in child output:\n{stdout}")


class TestSupervisedAutoResumeE2E:
    """The full subprocess acceptance: a REAL crash / SIGTERM, a REAL
    supervisor, and the resumed trajectory golden vs the uninterrupted
    run (4 jax child processes — the heaviest tests in the chaos
    suite, kept tier-1 because they ARE the acceptance criterion)."""

    @pytest.fixture(scope="class")
    def child_module(self, tmp_path_factory):
        path = tmp_path_factory.mktemp("mod") / "wf_mod.py"
        path.write_text(_CHILD_MODULE)
        return path

    @pytest.fixture(scope="class")
    def baseline(self, child_module, tmp_path_factory):
        snap_dir = tmp_path_factory.mktemp("base_snaps")
        proc = _run_child(
            child_module,
            ["--snapshot-dir", str(snap_dir), "--snapshot-interval", "1"],
        )
        assert proc.returncode == 0, proc.stderr
        return _parse_result(proc.stdout)

    def test_crash_under_supervisor_resumes_golden(
        self, child_module, baseline, tmp_path
    ):
        snap_dir = tmp_path / "snaps"
        proc = _run_child(
            child_module,
            [
                "--snapshot-dir", str(snap_dir),
                "--snapshot-interval", "1",
                "--resume", "auto",
                "--supervise",
                "--max-restarts", "2",
                "--restart-backoff", "0.1",
            ],
            # crash entering epoch 2 (fires 1+2 pass epochs 0/1);
            # the restarted child re-arms but its 2 remaining epochs
            # only consume the passthrough budget
            env_extra={"ZNICZ_FAULTS": "train.crash:after=2:times=1"},
        )
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        result = _parse_result(proc.stdout)
        assert result == baseline  # exact resume: history AND params
        sup = json.loads((snap_dir / "supervisor.json").read_text())
        assert sup["restarts"] == 1
        assert sup["history"][0]["exit_code"] not in (0, 75)
        snaps = [
            p for p in os.listdir(snap_dir) if ".pickle" in p
        ]
        assert snaps  # snapshots from both children present

    def test_sigterm_mid_epoch_exits_75_then_resumes_golden(
        self, child_module, baseline, tmp_path
    ):
        snap_dir = tmp_path / "snaps"
        proc = _run_child(
            child_module,
            ["--snapshot-dir", str(snap_dir)],
            # self-SIGTERM mid epoch 1 (3 steps/epoch)
            env_extra={"ZNICZ_TEST_SIGTERM_STEP": "4"},
        )
        assert proc.returncode == 75, (proc.stdout, proc.stderr)
        emergency = find_latest_valid(str(snap_dir))
        assert emergency and "emergency" in emergency

        proc2 = _run_child(
            child_module,
            ["--snapshot-dir", str(snap_dir), "--resume", "auto"],
        )
        assert proc2.returncode == 0, (proc2.stdout, proc2.stderr)
        result = _parse_result(proc2.stdout)
        assert result == baseline


# ---------------------------------------------------------------------------
class TestDoctorSelfHealingGate:
    def _prom(self, **series) -> str:
        reg = MetricsRegistry()
        rb = series.pop("rollbacks", {})
        if rb:
            c = reg.counter(
                pipeline_mod.ROLLBACKS_METRIC, "", ("reason",)
            )
            for reason, n in rb.items():
                c.labels(reason=reason).inc(n)
        for name, value in series.items():
            if name.endswith("_total"):
                reg.counter(name, "").inc(value)
            else:
                reg.gauge(name, "").set(value)
        return reg.prometheus_text()

    def test_recovery_summary_fields(self):
        text = self._prom(
            rollbacks={"non_finite_loss": 2},
            **{
                pipeline_mod.RESTARTS_METRIC: 1,
                pipeline_mod.RESTART_BUDGET_METRIC: 3,
                pipeline_mod.LOADER_RETRIES_METRIC: 5,
                pipeline_mod.SNAPSHOT_FAILURES_METRIC: 1,
            },
        )
        rec = PipelineAttribution.from_prometheus(
            text
        ).recovery_summary()
        assert rec["rollbacks"] == {"non_finite_loss": 2}
        assert rec["rollbacks_total"] == 2
        assert rec["restarts"] == 1
        assert rec["restart_budget"] == 3
        assert rec["loader_retries"] == 5
        assert rec["snapshot_failures"] == 1
        assert not rec["looping"]

    def test_doctor_exits_1_on_restart_loop(self, tmp_path, capsys):
        from znicz_tpu.observability import doctor

        prom = tmp_path / "m.prom"
        prom.write_text(
            self._prom(
                **{
                    pipeline_mod.RESTARTS_METRIC: 3,
                    pipeline_mod.RESTART_BUDGET_METRIC: 3,
                }
            )
        )
        rc = doctor.main([str(prom)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "LOOPING" in out and "restart budget" in out

    def test_doctor_exits_1_on_rollback_give_up(self, tmp_path, capsys):
        from znicz_tpu.observability import doctor

        prom = tmp_path / "m.prom"
        prom.write_text(
            self._prom(
                rollbacks={"non_finite_loss": 2},
                **{pipeline_mod.ROLLBACK_GIVE_UP_METRIC: 1},
            )
        )
        rc = doctor.main([str(prom), "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert out["recovery"]["looping"]
        assert out["recovery"]["rollback_give_up"]

    def test_doctor_healthy_with_counters_under_budget(
        self, tmp_path, capsys
    ):
        from znicz_tpu.observability import doctor

        prom = tmp_path / "m.prom"
        prom.write_text(
            self._prom(
                rollbacks={"loss_spike": 1},
                **{
                    pipeline_mod.RESTARTS_METRIC: 1,
                    pipeline_mod.RESTART_BUDGET_METRIC: 3,
                    pipeline_mod.LOADER_RETRIES_METRIC: 2,
                },
            )
        )
        rc = doctor.main([str(prom)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "self-healing:" in out
        assert "rollbacks 1" in out
        assert "restarts 1/3" in out
        assert "LOOPING" not in out

    def test_doctor_smoke_on_real_rollback_run(self, tmp_path, capsys):
        # tier-1 smoke (ISSUE satellite): a real run that rolled back
        # writes metrics.prom; the doctor reports the counter and,
        # absent an active anomaly window, still gates correctly
        from znicz_tpu.observability import doctor
        from znicz_tpu.services.web_status import StatusWriter

        pol = RecoveryPolicy(max_rollbacks=3, perturb=False,
                             lr_backoff=1.0)
        wf = _mnist_workflow(
            tmp_path / "snaps", snapshot_config={"interval": 1},
            recovery=pol,
        )
        wf.services.append(StatusWriter(str(tmp_path / "status")))
        wf.initialize(seed=77)
        faults.inject("train.step_nan", flag=True, times=1, after=7)
        wf.run()
        assert pol.rollbacks_used == 1
        rc = doctor.main(
            [str(tmp_path / "status" / "metrics.prom"), "--json"]
        )
        out = json.loads(capsys.readouterr().out)
        assert out["recovery"]["rollbacks_total"] >= 1
        assert rc in (0, 1)  # 1 iff the anomaly window is still active


class TestBenchDiffSelfHealingMarkers:
    def test_direction_markers(self):
        from znicz_tpu.utils.bench_diff import metric_direction

        for name in (
            "znicz_train_rollbacks_total",
            "znicz_train_restarts_total",
            "znicz_loader_retries_total",
            "znicz_loader_skipped_batches_total",
        ):
            assert metric_direction(name, set(), set()) == "lower", name

    def test_rise_from_zero_is_regression(self):
        from znicz_tpu.utils.bench_diff import compare

        rows, _ = compare(
            {"znicz_train_rollbacks_total": 0.0},
            {"znicz_train_rollbacks_total": 2.0},
        )
        assert rows[0]["regressed"]
        rows, _ = compare(
            {"znicz_train_restarts_total": 1.0},
            {"znicz_train_restarts_total": 0.0},
        )
        assert not rows[0]["regressed"]


class TestAutoResumeFallThrough:
    def test_digest_valid_but_unloadable_snapshot_is_quarantined(
        self, tmp_path
    ):
        # the sidecar digest is a byte check, not a decode check: a
        # digest-valid file can still fail to unpickle.  --resume auto
        # must quarantine it and fall through to an older snapshot
        # instead of crash-looping the supervisor on the same file.
        import argparse
        import hashlib

        from znicz_tpu.launcher import Launcher, make_parser

        wf = _mnist_workflow(tmp_path, max_epochs=2,
                             snapshot_config={"interval": 1,
                                              "compress": False})
        wf.initialize(seed=77)
        wf.run()
        good = find_latest_valid(str(tmp_path))
        # forge a NEWER snapshot: garbage bytes with a MATCHING sidecar
        bad = str(tmp_path / "StandardWorkflow_epoch9.pickle")
        with open(bad, "wb") as f:
            f.write(b"not a pickle at all")
        with open(bad + ".sha256", "w") as f:
            f.write(
                hashlib.sha256(b"not a pickle at all").hexdigest()
                + "  StandardWorkflow_epoch9.pickle  v1\n"
            )
        now = time.time() + 60
        os.utime(bad, (now, now))
        assert find_latest_valid(str(tmp_path)) == bad  # digest passes

        prng.seed_all(77)
        wf2 = _mnist_workflow(tmp_path, max_epochs=2)
        args = make_parser().parse_args(
            ["dummy.py", "--snapshot-dir", str(tmp_path),
             "--resume", "auto", "--random-seed", "77"]
        )
        launcher = Launcher(args)
        launcher.workflow = wf2
        launcher._initialize_with_auto_resume()
        assert launcher.args.snapshot == good  # fell through past bad
        assert wf2.decision.epoch == 2


class TestLauncherHelpers:
    def test_child_argv_strips_supervisor_flags(self):
        from znicz_tpu.launcher import _child_argv

        argv = [
            "wf.py", "--supervise", "--max-restarts", "5",
            "--restart-backoff", "0.5", "--resume", "auto",
            "--snapshot-dir", "/tmp/x", "--stop-after", "4",
        ]
        assert _child_argv(argv) == [
            "wf.py", "--resume", "auto", "--snapshot-dir", "/tmp/x",
            "--stop-after", "4",
        ]
        assert _child_argv(["a.py", "--max-restarts=7"]) == ["a.py"]

    def test_exit_preempted_is_documented_75(self):
        from znicz_tpu.launcher import EXIT_PREEMPTED

        assert EXIT_PREEMPTED == 75

    def test_restart_telemetry_export(self, monkeypatch):
        from znicz_tpu.launcher import _export_restart_telemetry

        before = _counter_total(pipeline_mod.RESTARTS_METRIC)
        monkeypatch.setenv("ZNICZ_RESTARTS", "2")
        monkeypatch.setenv("ZNICZ_RESTART_BUDGET", "5")
        _export_restart_telemetry()
        assert (
            _counter_total(pipeline_mod.RESTARTS_METRIC) == before + 2
        )
        gauge = get_registry().metrics()[
            pipeline_mod.RESTART_BUDGET_METRIC
        ]
        assert any(
            c.value == 5.0 for c in gauge.children().values()
        )
