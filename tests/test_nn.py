"""Tests for the nn layer: optimizer update rule, evaluators, decision."""

import jax.numpy as jnp
import numpy as np

from znicz_tpu.nn import decision, evaluator, lr_adjust, optimizer
from znicz_tpu.nn.train_state import TrainState


class TestOptimizer:
    def test_plain_sgd_matches_manual(self):
        w = jnp.array([1.0, -2.0])
        g = jnp.array([0.5, 0.5])
        v = jnp.zeros(2)
        hyper = optimizer.HyperParams(learning_rate=0.1)
        new_w, new_v = optimizer.update_param(w, g, v, "weights", hyper)
        np.testing.assert_allclose(new_w, w - 0.1 * g, rtol=1e-6)
        np.testing.assert_allclose(new_v, -0.1 * g, rtol=1e-6)

    def test_momentum_accumulates(self):
        # two steps with the same gradient: v2 = m*v1 - lr*g
        hyper = optimizer.HyperParams(learning_rate=0.1, gradient_moment=0.9)
        w = jnp.zeros(3)
        g = jnp.ones(3)
        v = jnp.zeros(3)
        w, v = optimizer.update_param(w, g, v, "weights", hyper)
        w2, v2 = optimizer.update_param(w, g, v, "weights", hyper)
        np.testing.assert_allclose(v2, 0.9 * v - 0.1 * g, rtol=1e-6)
        np.testing.assert_allclose(w2, w + v2, rtol=1e-6)

    def test_l2_decay(self):
        hyper = optimizer.HyperParams(learning_rate=1.0, weights_decay=0.1)
        w = jnp.array([2.0])
        new_w, _ = optimizer.update_param(w, jnp.zeros(1), jnp.zeros(1), "weights", hyper)
        np.testing.assert_allclose(new_w, w - 0.1 * w, rtol=1e-6)

    def test_l1_decay_sign(self):
        hyper = optimizer.HyperParams(
            learning_rate=1.0, weights_decay=0.1, l1_vs_l2=1.0
        )
        w = jnp.array([2.0, -3.0])
        new_w, _ = optimizer.update_param(
            w, jnp.zeros(2), jnp.zeros(2), "weights", hyper
        )
        np.testing.assert_allclose(new_w, w - 0.1 * jnp.sign(w), rtol=1e-6)

    def test_bias_lr_multiplier(self):
        hyper = optimizer.HyperParams(learning_rate=0.1, learning_rate_bias=0.2)
        g = jnp.ones(2)
        z = jnp.zeros(2)
        new_w, _ = optimizer.update_param(z, g, z, "weights", hyper)
        new_b, _ = optimizer.update_param(z, g, z, "bias", hyper)
        np.testing.assert_allclose(new_b, 2.0 * new_w, rtol=1e-6)

    def test_model_update_skips_empty_layers(self):
        params = [{"weights": jnp.ones((2, 2))}, {}, {"bias": jnp.ones(2)}]
        grads = [{"weights": jnp.ones((2, 2))}, {}, {"bias": jnp.ones(2)}]
        vel = [{"weights": jnp.zeros((2, 2))}, {}, {"bias": jnp.zeros(2)}]
        hyper = optimizer.HyperParams(learning_rate=0.5)
        new_p, new_v = optimizer.update(params, grads, vel, hyper)
        assert new_p[1] == {}
        np.testing.assert_allclose(new_p[0]["weights"], 0.5 * np.ones((2, 2)))

    def test_per_layer_hyper(self):
        params = [{"weights": jnp.ones(1)}, {"weights": jnp.ones(1)}]
        grads = [{"weights": jnp.ones(1)}, {"weights": jnp.ones(1)}]
        vel = [{"weights": jnp.zeros(1)}, {"weights": jnp.zeros(1)}]
        hyper = [
            optimizer.HyperParams(learning_rate=0.1),
            optimizer.HyperParams(learning_rate=0.3),
        ]
        new_p, _ = optimizer.update(params, grads, vel, hyper)
        np.testing.assert_allclose(new_p[0]["weights"], [0.9], rtol=1e-6)
        np.testing.assert_allclose(new_p[1]["weights"], [0.7], rtol=1e-6)

    def test_clip_gradients(self):
        grads = [{"weights": jnp.array([3.0, 4.0])}]
        clipped = optimizer.clip_gradients(grads, 1.0)
        np.testing.assert_allclose(
            np.linalg.norm(clipped[0]["weights"]), 1.0, rtol=1e-5
        )
        assert optimizer.clip_gradients(grads, None) is grads


class TestEvaluators:
    def test_softmax_metrics(self):
        logits = jnp.array([[10.0, 0.0], [0.0, 10.0], [10.0, 0.0]])
        labels = jnp.array([0, 1, 1])  # third is wrong
        m = evaluator.softmax(logits, labels)
        assert int(m["n_err"]) == 1
        assert float(m["loss"]) > 0
        assert float(m["n_samples"]) == 3.0

    def test_softmax_mask_excludes_padding(self):
        logits = jnp.array([[10.0, 0.0], [10.0, 0.0]])
        labels = jnp.array([0, 1])  # second wrong but masked out
        m = evaluator.softmax(logits, labels, mask=jnp.array([1.0, 0.0]))
        assert int(m["n_err"]) == 0
        assert float(m["n_samples"]) == 1.0

    def test_softmax_confusion(self):
        logits = jnp.array([[5.0, 0.0], [0.0, 5.0], [5.0, 0.0]])
        labels = jnp.array([0, 1, 1])
        m = evaluator.softmax(logits, labels, compute_confusion=True)
        conf = np.asarray(m["confusion"])
        assert conf[0, 0] == 1 and conf[1, 1] == 1 and conf[1, 0] == 1

    def test_mse_metrics(self):
        out = jnp.array([[1.0, 1.0], [0.0, 0.0]])
        tgt = jnp.array([[0.0, 0.0], [0.0, 0.0]])
        m = evaluator.mse(out, tgt)
        np.testing.assert_allclose(float(m["loss"]), 0.5, rtol=1e-6)
        np.testing.assert_allclose(float(m["max_diff"]), 1.0, rtol=1e-6)
        m2 = evaluator.mse(out, tgt, mask=jnp.array([0.0, 1.0]))
        np.testing.assert_allclose(float(m2["loss"]), 0.0, atol=1e-7)

    def test_epoch_extras_aggregation(self):
        # mean-style extras average sample-weighted; max_* keep the peak
        d = decision.Decision(max_epochs=5)
        d.add_minibatch(
            "train",
            {"n_samples": 10, "loss": 1.0, "some_metric": 2.0, "max_diff": 5.0},
        )
        d.add_minibatch(
            "train",
            {"n_samples": 30, "loss": 1.0, "some_metric": 6.0, "max_diff": 3.0},
        )
        s = d.on_epoch_end()["summary"]["train"]
        np.testing.assert_allclose(s["some_metric"], 5.0)  # (2*10+6*30)/40
        np.testing.assert_allclose(s["max_diff"], 5.0)


class TestDecision:
    def _epoch(self, d, n_err, split="valid"):
        d.add_minibatch(split, {"n_samples": 100, "n_err": n_err, "loss": n_err / 100})
        return d.on_epoch_end()

    def test_improvement_and_stop_on_max_epochs(self):
        d = decision.Decision(max_epochs=3, fail_iterations=100)
        r1 = self._epoch(d, 50)
        assert r1["improved"] and not r1["stop"]
        r2 = self._epoch(d, 40)
        assert r2["improved"] and not r2["stop"]
        r3 = self._epoch(d, 45)
        assert not r3["improved"] and r3["stop"]
        assert d.best_value == 40 and d.best_epoch == 1

    def test_stop_on_no_improvement(self):
        d = decision.Decision(fail_iterations=2)
        self._epoch(d, 10)
        assert not self._epoch(d, 11)["stop"]
        assert self._epoch(d, 12)["stop"]

    def test_train_split_fallback(self):
        d = decision.Decision(max_epochs=10)
        r = self._epoch(d, 5, split="train")
        assert r["improved"]

    def test_state_roundtrip(self):
        d = decision.Decision(max_epochs=10)
        self._epoch(d, 7)
        state = d.state_dict()
        d2 = decision.Decision(max_epochs=10)
        d2.load_state_dict(state)
        assert d2.best_value == 7 and d2.epoch == 1


class TestLrAdjust:
    def test_policies(self):
        assert lr_adjust.get("constant")(0.1, 100) == 0.1
        np.testing.assert_allclose(
            lr_adjust.get("step", step_size=10, gamma=0.5)(1.0, 25), 0.25
        )
        np.testing.assert_allclose(
            lr_adjust.get("exp", gamma=0.9)(1.0, 2), 0.81
        )
        np.testing.assert_allclose(
            lr_adjust.get("inv", gamma=1.0, power=1.0)(1.0, 3), 0.25
        )
        pol = lr_adjust.get("arbitrary", points=[(0, 1.0), (10, 0.1)])
        assert pol(1.0, 5) == 1.0 and abs(pol(1.0, 15) - 0.1) < 1e-9
        wc = lr_adjust.get("warmup_cosine", warmup=10, total=100)
        assert wc(1.0, 0) < wc(1.0, 9) and wc(1.0, 99) < 0.01

    def test_unknown_raises(self):
        import pytest

        with pytest.raises(ValueError):
            lr_adjust.get("nope")


class TestTrainState:
    def test_create(self):
        import jax

        params = [{"weights": jnp.ones((2, 2))}]
        ts = TrainState.create(params, jax.random.key(0))
        assert int(ts.step) == 0
        np.testing.assert_allclose(ts.velocity[0]["weights"], 0.0)
        # must be a pytree usable in jit
        leaves = jax.tree_util.tree_leaves(ts)
        assert len(leaves) >= 3
