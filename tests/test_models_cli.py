"""Model zoo + launcher CLI tests.

Functional-test style per SURVEY.md §4: each sample workflow trains a couple
of epochs under a fixed seed and must hit a tolerance band; the CLI drives a
workflow module end-to-end with a config override file (the reference
two-file UX, SURVEY.md 3.1).
"""

import sys

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.launcher import run_args


def _fresh(module):
    """(Re)import a model module so its root defaults are applied."""
    import importlib

    mod = importlib.import_module(f"znicz_tpu.models.{module}")
    return importlib.reload(mod)


class TestModelZoo:
    def test_wine_converges_to_zero_err(self):
        prng.seed_all(1234)
        wine = _fresh("wine")
        root.wine.decision.update({"max_epochs": 30})
        wf = wine.build_workflow()
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.best_value == 0.0  # wine is linearly easy; reference too

    def test_mnist_mlp(self):
        prng.seed_all(1234)
        mnist = _fresh("mnist")
        root.mnist.loader.update({"n_train": 400, "n_test": 100})
        root.mnist.decision.update({"max_epochs": 3})
        wf = mnist.build_workflow()
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.history[-1]["train"]["err_pct"] < 15.0

    def test_cifar_conv(self):
        prng.seed_all(1234)
        cifar = _fresh("cifar")
        root.cifar.loader.update(
            {"n_train": 200, "n_test": 50, "minibatch_size": 50}
        )
        root.cifar.decision.update({"max_epochs": 2})
        wf = cifar.build_workflow()
        wf.initialize(seed=1234)
        dec = wf.run()
        assert np.isfinite(dec.history[-1]["train"]["loss"])
        assert (
            dec.history[-1]["train"]["loss"]
            < dec.history[0]["train"]["loss"]
        )

    def test_mnist_ae(self):
        prng.seed_all(1234)
        ae = _fresh("mnist_ae")
        root.mnist_ae.loader.update(
            {"n_train": 200, "n_test": 0, "minibatch_size": 50}
        )
        root.mnist_ae.decision.update({"max_epochs": 3})
        wf = ae.build_workflow()
        assert wf.loss_function == "mse"
        wf.initialize(seed=1234)
        dec = wf.run()
        assert (
            dec.history[-1]["train"]["loss"]
            < dec.history[0]["train"]["loss"]
        )

    def test_kohonen_model(self):
        prng.seed_all(1234)
        km = _fresh("kohonen")
        root.kohonen.loader.update({"n_train": 200, "n_test": 0})
        wf = km.build_workflow(total_epochs=3)
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]

    def test_mnist_rbm_model(self):
        prng.seed_all(1234)
        rbm = _fresh("mnist_rbm")
        root.mnist_rbm.loader.update({"n_train": 200, "n_test": 0})
        wf = rbm.build_workflow(max_epochs=3, learning_rate=0.5)
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]

    def test_kanji_model(self):
        prng.seed_all(1234)
        kanji = _fresh("kanji")
        root.kanji.loader.update({"n_train": 200, "n_test": 50})
        wf = kanji.build_workflow(decision_config={"max_epochs": 2})
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]

    def test_yale_faces_model(self):
        prng.seed_all(1234)
        yf = _fresh("yale_faces")
        root.yale_faces.loader.update({"n_train": 150, "n_test": 30})
        wf = yf.build_workflow(decision_config={"max_epochs": 2})
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]

    def test_video_ae_model(self):
        prng.seed_all(1234)
        vae = _fresh("video_ae")
        root.video_ae.loader.update({"n_sequences": 5, "frames_per_seq": 20})
        wf = vae.build_workflow(decision_config={"max_epochs": 3})
        assert wf.loss_function == "mse"
        wf.initialize(seed=1234)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]

    def test_image_dir_models_train_on_real_files(self, tmp_path):
        # kanji / yale_faces / video_ae accept a data_dir of real images
        # (reference image-dir pipelines) instead of the synthetic stand-in
        import matplotlib

        matplotlib.use("Agg", force=True)
        import matplotlib.image as mpimg

        gen = np.random.default_rng(3)
        for split, n in (("train", 6), ("test", 2)):
            for cls in ("a", "b", "c"):
                d = tmp_path / split / cls
                d.mkdir(parents=True)
                for i in range(n):
                    img = gen.random((12, 12)).astype(np.float32)
                    mpimg.imsave(
                        str(d / f"{i}.png"), img, cmap="gray"
                    )
        for module, cfg_node, extra in (
            ("kanji", root.kanji, {"side": 12, "minibatch_size": 9}),
            ("yale_faces", root.yale_faces,
             {"side": 12, "minibatch_size": 9}),
            ("video_ae", root.video_ae,
             {"side": 12, "minibatch_size": 9}),
        ):
            prng.seed_all(1234)
            mod = _fresh(module)
            cfg_node.loader.update({"data_dir": str(tmp_path), **extra})
            wf = mod.build_workflow(decision_config={"max_epochs": 2})
            from znicz_tpu.loader.image import ImageDirectoryLoader

            assert isinstance(wf.loader, ImageDirectoryLoader), module
            wf.initialize(seed=1234)
            dec = wf.run()
            assert np.isfinite(dec.history[-1]["train"]["loss"]), module
            if module != "video_ae":
                # classifier heads follow the directory's class count
                assert wf.model.output_shape == (3,), module

    def test_alexnet_builds(self):
        # full run is the bench's job; here: builds + one forward shape check
        prng.seed_all(1234)
        alex = _fresh("alexnet")
        root.alexnet.loader.update(
            {"n_train": 4, "n_valid": 0, "minibatch_size": 4, "image_size": 227}
        )
        wf = alex.build_workflow()
        import jax.numpy as jnp

        y = wf.model.apply(wf.model.params, jnp.zeros((2, 227, 227, 3)))
        assert y.shape == (2, 1000)


class TestLauncherCLI:
    def test_run_workflow_with_config_override(self, tmp_path):
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        cfg_py = tmp_path / "cfg.py"
        cfg_py.write_text(
            "from znicz_tpu.core.config import root\n"
            "root.wine.decision.update({'max_epochs': 2})\n"
        )
        launcher = run_args(
            [str(wf_py), str(cfg_py), "--random-seed", "1234"]
        )
        assert launcher.result is not None
        assert launcher.result.epoch == 2  # config override respected

    def test_stop_after_flag(self, tmp_path):
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        launcher = run_args(
            [str(wf_py), "--random-seed", "1", "--stop-after", "1"]
        )
        assert launcher.result.epoch == 1

    def test_epoch_sync_flag(self, tmp_path):
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        launcher = run_args(
            [str(wf_py), "--random-seed", "1", "--stop-after", "2",
             "--epoch-sync", "deferred"]
        )
        assert launcher.workflow.epoch_sync == "deferred"
        assert launcher.result.epoch == 2  # exact stop despite the lag

    def test_epoch_sync_with_interval_snapshots(self, tmp_path):
        # the deferred-compatible snapshot kind is reachable from the CLI
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        launcher = run_args(
            [str(wf_py), "--random-seed", "1", "--stop-after", "4",
             "--epoch-sync", "deferred",
             "--snapshot-dir", str(tmp_path / "snaps"),
             "--snapshot-interval", "2"]
        )
        snap = launcher.workflow.snapshotter
        assert snap.interval == 2 and snap.save_best
        import os as _os

        names = sorted(_os.listdir(tmp_path / "snaps"))
        assert any("epoch1" in n for n in names), names
        assert any("epoch3" in n for n in names), names
        # best-model snapshots survive deferred sync (retained buffer)
        assert any("best" in n for n in names), names

    def test_dry_run(self, tmp_path):
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        launcher = run_args([str(wf_py), "--dry-run"])
        assert launcher.result is None
        assert launcher.workflow.state is not None

    def test_snapshot_resume_via_cli(self, tmp_path):
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        run_args(
            [
                str(wf_py),
                "--random-seed", "7",
                "--stop-after", "2",
                "--snapshot-dir", str(tmp_path / "snaps"),
            ]
        )
        best = tmp_path / "snaps" / "WineWorkflow_best.pickle.gz"
        assert best.exists()
        launcher = run_args(
            [
                str(wf_py),
                "--stop-after", "3",
                "--snapshot", str(best),
                "--snapshot-dir", str(tmp_path / "snaps2"),
            ]
        )
        assert launcher.result.epoch == 3

    def test_evaluate_only_mode(self, tmp_path, capsys):
        # the reference's test-mode run: restore a snapshot, evaluate one
        # split with the confusion matrix, no training
        import json

        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        run_args(
            [
                str(wf_py),
                "--random-seed", "7",
                "--stop-after", "3",
                "--snapshot-dir", str(tmp_path / "snaps"),
            ]
        )
        best = tmp_path / "snaps" / "WineWorkflow_best.pickle.gz"
        launcher = run_args(
            [
                str(wf_py),
                "--snapshot", str(best),
                "--evaluate", "train",
            ]
        )
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["split"] == "train"
        assert out["n_samples"] > 0
        assert 0.0 <= out["err_pct"] <= 100.0
        conf = np.asarray(out["confusion"])
        assert conf.shape == (3, 3)  # wine has 3 classes
        assert conf.sum() == out["n_samples"]
        # no training happened: result is the eval dict, not a Decision
        assert launcher.result["err_pct"] == out["err_pct"]

    def test_evaluate_missing_split_errors(self, tmp_path):
        # wine has no test split: a silent 0-sample "perfect" evaluation
        # must be a hard error, and --optimize conflicts up front
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        with pytest.raises(SystemExit, match="no samples"):
            run_args([str(wf_py), "--evaluate", "test"])
        with pytest.raises(SystemExit, match="conflict"):
            run_args([str(wf_py), "--optimize", "1", "--evaluate"])

    def test_export_flag(self, tmp_path):
        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        out = tmp_path / "wine.znicz"
        run_args(
            [
                str(wf_py),
                "--random-seed", "3",
                "--stop-after", "1",
                "--export", str(out),
            ]
        )
        blob = out.read_bytes()
        assert blob[:8] == b"ZNICZT01"

    def test_missing_run_convention_errors(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1\n")
        with pytest.raises(SystemExit):
            run_args([str(bad)])


@pytest.fixture(autouse=True)
def _isolate_workflow_modules():
    yield
    for name in ("__znicz_workflow__", "__znicz_config__"):
        sys.modules.pop(name, None)
