"""KV-cache autoregressive decode vs the full forward (golden parity).

The decode path (znicz_tpu/workflow/generate.py) must reproduce
``lm_apply``'s logits position-by-position — prefill and incremental steps
both — and ``generate`` must emit exactly the tokens a full re-forward
would choose (greedy) while never re-running earlier positions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params, lm_apply


def _setup(moe_experts=0, seed=27, t_max=24):
    prng.seed_all(seed)
    vocab, d, heads = 17, 32, 4
    params = init_lm_params(
        vocab, d, 2, heads, max_seq=t_max, moe_experts=moe_experts
    )
    tokens = np.random.default_rng(7).integers(
        0, vocab, (3, 12)
    ).astype(np.int32)
    return params, tokens, heads, vocab


class TestDecodeGolden:
    def test_teacher_forced_logits_match_full_forward(self):
        params, tokens, heads, _ = _setup()
        full = np.asarray(lm_apply(params, jnp.asarray(tokens), n_heads=heads))
        caches = G.init_kv_cache(params, 3, 12, n_heads=heads)
        caches, lg = G.prefill(
            params, jnp.asarray(tokens[:, :4]), caches, n_heads=heads
        )
        np.testing.assert_allclose(
            np.asarray(lg), full[:, 3], rtol=1e-4, atol=1e-5
        )
        for p in range(4, 12):
            caches, lg = G.decode_step(
                params, caches, jnp.asarray(tokens[:, p]), p, n_heads=heads
            )
            np.testing.assert_allclose(
                np.asarray(lg), full[:, p], rtol=1e-4, atol=1e-5
            )

    def test_moe_decode_matches_full_forward(self):
        # the MoE FFN rides the same _block_ffn in both paths
        params, tokens, heads, _ = _setup(moe_experts=4, seed=31)
        full = np.asarray(
            lm_apply(params, jnp.asarray(tokens), n_heads=heads, moe_top_k=2)
        )
        caches = G.init_kv_cache(params, 3, 12, n_heads=heads)
        caches, lg = G.prefill(
            params, jnp.asarray(tokens[:, :6]), caches,
            n_heads=heads, moe_top_k=2,
        )
        np.testing.assert_allclose(
            np.asarray(lg), full[:, 5], rtol=1e-4, atol=1e-5
        )
        for p in range(6, 12):
            caches, lg = G.decode_step(
                params, caches, jnp.asarray(tokens[:, p]), p,
                n_heads=heads, moe_top_k=2,
            )
            np.testing.assert_allclose(
                np.asarray(lg), full[:, p], rtol=1e-4, atol=1e-5
            )

    def test_greedy_generate_matches_full_reforward(self):
        # every emitted token == the argmax a full forward over the
        # (prompt + generated-so-far) prefix would choose
        params, tokens, heads, _ = _setup()
        out = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=8,
            )
        )
        assert out.shape == (3, 12)
        assert (out[:, :4] == tokens[:, :4]).all()
        full = np.asarray(lm_apply(params, jnp.asarray(out), n_heads=heads))
        for p in range(4, 12):
            np.testing.assert_array_equal(
                out[:, p], np.argmax(full[:, p - 1], axis=-1)
            )

    def test_temperature_sampling_reproducible_and_in_vocab(self):
        params, tokens, heads, vocab = _setup()
        kw = dict(n_heads=heads, max_new_tokens=6, temperature=0.8)
        a = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                rng=jax.random.key(5), **kw,
            )
        )
        b = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                rng=jax.random.key(5), **kw,
            )
        )
        np.testing.assert_array_equal(a, b)  # same key -> same draw
        assert (a[:, 4:] >= 0).all() and (a[:, 4:] < vocab).all()
        c = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                rng=jax.random.key(6), **kw,
            )
        )
        assert not (a == c).all()  # different key -> different draw

    def test_capacity_exceeded_raises(self):
        params, tokens, heads, _ = _setup(t_max=10)
        with pytest.raises(ValueError, match="positional table"):
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=8,
            )

    def test_workflow_generate_method(self):
        # the user-facing path: train a workflow, call wf.generate()
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.workflow.transformer import TransformerLMWorkflow

        tokens = np.random.default_rng(3).integers(
            0, 16, (16, 24)
        ).astype(np.int32)
        prng.seed_all(77)
        ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=8)
        wf = TransformerLMWorkflow(
            ld, vocab=16, d_model=16, n_layers=1, n_heads=2, max_epochs=1,
        )
        wf.initialize(seed=77)
        wf.run()
        out = np.asarray(
            wf.generate(tokens[:2, :6], max_new_tokens=8)
        )
        assert out.shape == (2, 14)
        # tokens equal what the module-level greedy path produces
        ref = np.asarray(
            G.generate(
                wf.state.params, jnp.asarray(tokens[:2, :6]),
                n_heads=2, max_new_tokens=8,
            )
        )
        np.testing.assert_array_equal(out, ref)

    def test_workflow_generate_rejects_pipelined(self):
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.parallel import DataParallel, make_mesh
        from znicz_tpu.workflow.transformer import TransformerLMWorkflow

        tokens = np.zeros((32, 16), np.int32)
        ld = FullBatchLoader({"train": tokens}, minibatch_size=16)
        wf = TransformerLMWorkflow(
            ld, vocab=4, d_model=8, n_layers=2, n_heads=2, max_epochs=1,
            pipeline_parallel=True, parallel=DataParallel(make_mesh(4, 1, 2)),
        )
        wf.initialize(seed=5)
        with pytest.raises(ValueError, match="pipelined"):
            wf.generate(tokens[:2, :4], max_new_tokens=2)

    def test_tp_sharded_params_decode_matches_replicated(self):
        # decode at scale: generate() is one jitted scan, so GSPMD
        # partitions it for lm_tp_rules-sharded params (head/QKV column,
        # wo/w_down row) with the same tokens as the replicated run
        import jax.tree_util as jtu
        from jax.sharding import NamedSharding

        from znicz_tpu.parallel import make_mesh
        from znicz_tpu.workflow.transformer import lm_tp_rules

        params, tokens, heads, _ = _setup()
        # vocab 17 does not divide the 4-way model axis; re-init at 16
        prng.seed_all(27)
        from znicz_tpu.workflow.transformer import init_lm_params

        params = init_lm_params(16, 32, 2, heads, max_seq=24)
        prompt = jnp.asarray(tokens[:, :6] % 16)
        ref = np.asarray(
            G.generate(params, prompt, n_heads=heads, max_new_tokens=10)
        )
        mesh = make_mesh(2, 4)

        def place(path, leaf):
            spec = lm_tp_rules(jtu.keystr(path), leaf)
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        sharded = jtu.tree_map_with_path(place, params)
        assert not sharded[1]["wq"].is_fully_replicated
        out = np.asarray(
            G.generate(sharded, prompt, n_heads=heads, max_new_tokens=10)
        )
        np.testing.assert_array_equal(ref, out)

    def test_temperature_without_rng_raises(self):
        params, tokens, heads, _ = _setup()
        with pytest.raises(ValueError, match="rng"):
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=2, temperature=0.7,
            )


class TestSamplingTruncation:
    def test_top_k_1_equals_greedy(self):
        params, tokens, heads, _ = _setup()
        greedy = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=6,
            )
        )
        k1 = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=6,
                temperature=1.0, top_k=1, rng=jax.random.key(2),
            )
        )
        np.testing.assert_array_equal(greedy, k1)

    def test_tiny_top_p_equals_greedy(self):
        # top_p -> 0 keeps only the argmax token (always retained)
        params, tokens, heads, _ = _setup()
        greedy = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=6,
            )
        )
        p0 = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=6,
                temperature=1.0, top_p=1e-6, rng=jax.random.key(2),
            )
        )
        np.testing.assert_array_equal(greedy, p0)

    def test_top_k_restricts_support(self):
        # with top_k=2 every sampled token must be one of the 2 highest-
        # logit tokens of its actual decode distribution; verify via
        # teacher-forced re-scoring of the emitted sequence
        params, tokens, heads, _ = _setup()
        out = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=8,
                temperature=1.3, top_k=2, rng=jax.random.key(3),
            )
        )
        from znicz_tpu.workflow.transformer import lm_apply

        full = np.asarray(lm_apply(params, jnp.asarray(out), n_heads=heads))
        for p in range(4, 12):
            top2 = np.argsort(full[:, p - 1], axis=-1)[:, -2:]
            for b in range(out.shape[0]):
                assert out[b, p] in top2[b], (b, p)

    def test_bad_truncation_args_rejected(self):
        params, tokens, heads, _ = _setup()
        with pytest.raises(ValueError, match="top_k"):
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=2,
                temperature=1.0, top_p=0.0, rng=jax.random.key(0),
            )

    def test_top_k_above_vocab_clamps_to_full_support(self):
        params, tokens, heads, vocab = _setup()
        out = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=4,
                temperature=1.0, top_k=vocab + 30, rng=jax.random.key(1),
            )
        )
        ref = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :4]),
                n_heads=heads, max_new_tokens=4,
                temperature=1.0, rng=jax.random.key(1),
            )
        )
        np.testing.assert_array_equal(out, ref)

    def test_temperature_sweep_reuses_one_compile(self):
        # temperature/top_p are traced operands: distinct values must not
        # recompile the decode program
        params, tokens, heads, _ = _setup()
        prompt = jnp.asarray(tokens[:, :4])
        kw = dict(n_heads=heads, max_new_tokens=3, rng=jax.random.key(0))
        G.generate(params, prompt, temperature=0.7, top_p=0.9, **kw)
        n0 = G._generate_impl._cache_size()
        G.generate(params, prompt, temperature=1.3, top_p=0.8, **kw)
        assert G._generate_impl._cache_size() == n0


class TestServingDecode:
    """Bucketed / left-padded / EOS serving semantics (docs/SERVING.md).

    The contract: bucketing is INVISIBLE — left-padded decode matches
    the unpadded reference position-by-position, EOS early-exit matches
    the full-budget run up to EOS, and request streams inside one bucket
    never recompile."""

    def test_bucket_for_ladder(self):
        assert G.bucket_for(1, (16, 32)) == 16
        assert G.bucket_for(16, (16, 32)) == 16
        assert G.bucket_for(17, (16, 32)) == 32
        # past the top rung: keep doubling (geometric, never rejects)
        assert G.bucket_for(33, (16, 32)) == 64
        assert G.bucket_for(200, (16, 32)) == 256
        with pytest.raises(ValueError, match="positive"):
            G.bucket_for(0, (16,))

    def test_pack_prompts_left_pads_ragged(self):
        toks, start = G.pack_prompts(
            [np.asarray([1, 2, 3]), np.asarray([4])], 8, pad_id=7
        )
        np.testing.assert_array_equal(
            np.asarray(toks),
            [[7, 7, 7, 7, 7, 1, 2, 3], [7, 7, 7, 7, 7, 7, 7, 4]],
        )
        np.testing.assert_array_equal(np.asarray(start), [5, 7])
        with pytest.raises(ValueError, match="empty"):
            G.pack_prompts([np.asarray([], np.int32)], 8, pad_id=0)
        with pytest.raises(ValueError, match="exceeds bucket"):
            G.pack_prompts([np.arange(9)], 8, pad_id=0)

    def test_left_padded_decode_matches_unpadded_per_position(self):
        # golden parity: pad 3 prompts of length 5 into a 16-bucket and
        # teacher-force the rest — every logit vector must match the
        # full unpadded forward position-by-position
        params, tokens, heads, _ = _setup()
        full = np.asarray(
            lm_apply(params, jnp.asarray(tokens), n_heads=heads)
        )
        bucket = 16
        padded, start = G.pack_prompts(list(tokens[:, :5]), bucket, pad_id=0)
        caches = G.init_kv_cache(params, 3, bucket + 7, n_heads=heads)
        caches, lg = G.prefill(
            params, padded, caches, n_heads=heads, start=start
        )
        np.testing.assert_allclose(
            np.asarray(lg), full[:, 4], rtol=1e-4, atol=1e-5
        )
        for p in range(5, 12):
            caches, lg = G.decode_step(
                params, caches, jnp.asarray(tokens[:, p]),
                bucket + p - 5, n_heads=heads, start=start,
            )
            np.testing.assert_allclose(
                np.asarray(lg), full[:, p], rtol=1e-4, atol=1e-5
            )

    def test_generate_serve_matches_generate_token_for_token(self):
        params, tokens, heads, _ = _setup()
        ref = np.asarray(
            G.generate(
                params, jnp.asarray(tokens[:, :5]),
                n_heads=heads, max_new_tokens=6,
            )
        )
        out = np.asarray(
            G.generate_serve(
                params, tokens[:, :5], n_heads=heads, max_new_tokens=6
            )
        )
        np.testing.assert_array_equal(ref, out)

    def test_eos_early_exit_matches_full_budget_up_to_eos(self):
        # pick an EOS id the greedy run actually emits; rows must match
        # the full-budget run up to (and including) their first EOS and
        # emit EOS for the rest of the budget
        params, tokens, heads, _ = _setup()
        prompt = jnp.asarray(tokens[:, :4])
        ref = np.asarray(
            G.generate(params, prompt, n_heads=heads, max_new_tokens=8)
        )
        eos = int(ref[0, 4 + 2])
        out = np.asarray(
            G.generate(
                params, prompt, n_heads=heads, max_new_tokens=8,
                eos_id=eos,
            )
        )
        assert (out[:, :4] == np.asarray(prompt)).all()
        for b in range(out.shape[0]):
            new_ref, new_out = ref[b, 4:], out[b, 4:]
            hit = np.where(new_ref == eos)[0]
            k = hit[0] + 1 if len(hit) else len(new_ref)
            np.testing.assert_array_equal(new_out[:k], new_ref[:k])
            assert (new_out[k:] == eos).all()

    def test_serve_eos_matches_generate_eos(self):
        params, tokens, heads, _ = _setup()
        prompt = tokens[:, :5]
        ref = np.asarray(
            G.generate(
                params, jnp.asarray(prompt), n_heads=heads,
                max_new_tokens=7,
            )
        )
        eos = int(ref[1, 5 + 1])
        a = np.asarray(
            G.generate(
                params, jnp.asarray(prompt), n_heads=heads,
                max_new_tokens=7, eos_id=eos,
            )
        )
        b = np.asarray(
            G.generate_serve(
                params, prompt, n_heads=heads, max_new_tokens=7,
                eos_id=eos,
            )
        )
        np.testing.assert_array_equal(a, b)

    def test_second_request_same_bucket_zero_recompiles(self):
        # the serving acceptance criterion: a second request with a
        # DIFFERENT prompt length in the same bucket (and a different
        # budget on the same rung) reuses the compiled executable
        params, tokens, heads, _ = _setup()
        G.reset_serve_cache()
        G.generate_serve(params, tokens[:, :5], n_heads=heads,
                         max_new_tokens=6)
        st0 = G.serve_cache_stats()
        assert st0["programs"] == 1 and st0["hits"] == 0
        out = np.asarray(
            G.generate_serve(params, tokens[:, :9], n_heads=heads,
                             max_new_tokens=3)
        )
        st1 = G.serve_cache_stats()
        assert st1["programs"] == 1  # same (bucket, structure): no compile
        assert st1["hits"] == 1 and st1["requests"] == 2
        ref = np.asarray(
            G.generate(params, jnp.asarray(tokens[:, :9]),
                       n_heads=heads, max_new_tokens=3)
        )
        np.testing.assert_array_equal(ref, out)
        # a different sampling STRUCTURE is a different program
        G.generate_serve(
            params, tokens[:, :5], n_heads=heads, max_new_tokens=6,
            temperature=0.8, rng=jax.random.key(1),
        )
        assert G.serve_cache_stats()["programs"] == 2

    def test_serve_sampling_reproducible(self):
        params, tokens, heads, vocab = _setup()
        kw = dict(n_heads=heads, max_new_tokens=5, temperature=0.9)
        a = np.asarray(
            G.generate_serve(params, tokens[:, :5],
                             rng=jax.random.key(4), **kw)
        )
        b = np.asarray(
            G.generate_serve(params, tokens[:, :5],
                             rng=jax.random.key(4), **kw)
        )
        np.testing.assert_array_equal(a, b)
        assert (a[:, 5:] >= 0).all() and (a[:, 5:] < vocab).all()

    def test_zero_budget_rejected_with_clear_error(self):
        params, tokens, heads, _ = _setup()
        for fn in (G.generate, G.generate_serve):
            with pytest.raises(ValueError, match="max_new_tokens"):
                fn(params, tokens[:, :4], n_heads=heads, max_new_tokens=0)

    def test_serve_capacity_clamps_then_falls_back_exact(self):
        # rounding a budget up a rung must never reject a request the
        # positional table can serve: the rung clamps into the table,
        # and if that underruns the request, shapes go exact
        params, tokens, heads, _ = _setup(t_max=24)
        ref = np.asarray(
            G.generate(params, jnp.asarray(tokens[:, :5]),
                       n_heads=heads, max_new_tokens=8)
        )
        out = np.asarray(
            G.generate_serve(params, tokens[:, :5], n_heads=heads,
                             max_new_tokens=8)  # 16 + 16 > 24: clamps
        )
        np.testing.assert_array_equal(ref, out)
        ref9 = np.asarray(
            G.generate(params, jnp.asarray(tokens[:, :5]),
                       n_heads=heads, max_new_tokens=9)
        )
        out9 = np.asarray(
            G.generate_serve(params, tokens[:, :5], n_heads=heads,
                             max_new_tokens=9)  # clamp underruns: exact
        )
        np.testing.assert_array_equal(ref9, out9)
        with pytest.raises(ValueError, match="positional table"):
            G.generate_serve(params, tokens[:, :5], n_heads=heads,
                             max_new_tokens=25)
