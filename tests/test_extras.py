"""Tests for ensembles, publishing, profiling, and the small aux ops."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.ensemble import Ensemble
from znicz_tpu.loader import datasets
from znicz_tpu.ops import (
    accumulator,
    resizable_all2all,
    weights_zerofilling as wzf,
)
from znicz_tpu.services.publishing import MarkdownReporter
from znicz_tpu.utils.profiling import StepTimer
from znicz_tpu.workflow import StandardWorkflow

MLP_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


def _build():
    loader = datasets.mnist(n_train=128, n_test=64, minibatch_size=64)
    return StandardWorkflow(
        loader,
        MLP_LAYERS,
        decision_config={"max_epochs": 2},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
    )


class TestEnsemble:
    def test_train_and_aggregate(self):
        ens = Ensemble(_build, n_models=3, base_seed=50)
        decisions = ens.train()
        assert len(decisions) == 3 and len(ens.workflows) == 3
        # members differ (different seeds)
        w0 = np.asarray(ens.workflows[0].state.params[0]["weights"])
        w1 = np.asarray(ens.workflows[1].state.params[0]["weights"])
        assert not np.allclose(w0, w1)
        result = ens.evaluate("test")
        assert result["n_samples"] == 64
        assert 0.0 <= result["ensemble_err_pct"] <= 100.0

    def test_members_share_one_dataset(self):
        # members must differ by INIT, not by task: the synthetic dataset
        # generation stream is pinned across member builds
        ens = Ensemble(_build, n_models=2, base_seed=70)
        ens.train()
        d0 = ens.workflows[0].loader.data["train"]
        d1 = ens.workflows[1].loader.data["train"]
        np.testing.assert_array_equal(d0, d1)
        l0 = ens.workflows[0].loader.labels["train"]
        l1 = ens.workflows[1].loader.labels["train"]
        np.testing.assert_array_equal(l0, l1)

    @pytest.mark.slow
    def test_train_from_module_concurrent_matches_serial(self, tmp_path):
        # process-level ensemble training (reference veles/ensemble mode):
        # deterministic given seeds, identical for every worker count
        from znicz_tpu.ensemble import train_from_module

        wf_py = tmp_path / "wf.py"
        wf_py.write_text("from znicz_tpu.models.wine import run\n")
        kw = dict(n_models=2, base_seed=90, stop_after=2)
        ens2 = train_from_module(str(wf_py), n_workers=2, **kw)
        ens1 = train_from_module(str(wf_py), n_workers=1, **kw)
        b2 = [d.best_value for d in ens2.decisions]
        b1 = [d.best_value for d in ens1.decisions]
        assert b2 == b1 and all(np.isfinite(v) for v in b2)
        # members differ by init (different seeds), not by task
        w0 = np.asarray(ens2.workflows[0].state.params[0]["weights"])
        w1 = np.asarray(ens2.workflows[1].state.params[0]["weights"])
        assert not np.allclose(w0, w1)
        # aggregation works on the grafted member params
        x = ens2.workflows[0].loader.data["train"][:8]
        assert ens2.predict(x, vote="soft").shape == (8,)
        result = ens2.evaluate("train")
        assert 0.0 <= result["ensemble_err_pct"] <= 100.0

    def test_soft_and_hard_vote_shapes(self):
        ens = Ensemble(_build, n_models=2, base_seed=60)
        ens.train()
        x = ens.workflows[0].loader.data["test"][:10]
        assert ens.predict(x, vote="soft").shape == (10,)
        assert ens.predict(x, vote="hard").shape == (10,)
        probs = ens.predict_proba(x)
        np.testing.assert_allclose(np.asarray(probs.sum(axis=1)), 1.0, rtol=1e-5)


class TestPublishing:
    def test_report_written_on_stop(self, tmp_path):
        prng.seed_all(9)
        wf = _build()
        wf.services = [MarkdownReporter(str(tmp_path))]
        wf.initialize(seed=9)
        wf.run()
        report = (tmp_path / "report.md").read_text()
        assert "# Run report" in report
        assert "all2all_tanh" in report
        assert "| epoch |" in report
        assert (tmp_path / "report.json").exists()


class TestProfiling:
    def test_step_timer(self):
        t = StepTimer()
        with t.phase("a"):
            pass
        with t.phase("a"):
            pass
        with t.phase("b"):
            pass
        s = t.summary()
        assert s["a"]["count"] == 2 and s["b"]["count"] == 1
        t.reset()
        assert t.summary() == {}


class TestAuxOps:
    def test_resizable_grow_preserves_overlap(self):
        prng.seed_all(4)
        p = resizable_all2all.init_params(8, 4)
        grown = resizable_all2all.resize(p, 6)
        assert grown["weights"].shape == (8, 6)
        np.testing.assert_array_equal(grown["weights"][:, :4], p["weights"])
        np.testing.assert_array_equal(grown["bias"][:4], p["bias"])
        shrunk = resizable_all2all.resize(p, 2)
        np.testing.assert_array_equal(shrunk["weights"], p["weights"][:, :2])
        assert resizable_all2all.resize(p, 4) is p

    def test_accumulator_stats(self):
        stats = accumulator.init(3)
        x1 = jnp.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]])
        stats = accumulator.update(stats, x1)
        x2 = jnp.array([[-1.0, 0.0, 10.0], [99.0, 99.0, 99.0]])
        stats = accumulator.update(stats, x2, mask=jnp.array([1.0, 0.0]))
        np.testing.assert_allclose(stats.lo, [-1.0, 0.0, 3.0])
        np.testing.assert_allclose(stats.hi, [4.0, 5.0, 10.0])
        np.testing.assert_allclose(stats.mean, [4 / 3, 7 / 3, 19 / 3], rtol=1e-6)
        assert float(stats.count) == 3.0

    def test_zerofill_group_mask_and_update_wrap(self):
        mask = wzf.make_group_mask(4, 6, 2)
        assert mask.shape == (4, 6)
        np.testing.assert_array_equal(mask[:2, 3:], 0.0)
        np.testing.assert_array_equal(mask[:2, :3], 1.0)

        from znicz_tpu.nn import optimizer

        params = [{"weights": jnp.ones((4, 6))}]
        grads = [{"weights": jnp.ones((4, 6))}]
        vel = [{"weights": jnp.zeros((4, 6))}]
        update = wzf.masked_update(
            optimizer.update, {0: {"weights": mask}}
        )
        new_p, _ = update(
            params, grads, vel, optimizer.HyperParams(learning_rate=0.1)
        )
        # masked entries exactly zero, others updated
        np.testing.assert_array_equal(np.asarray(new_p[0]["weights"])[:2, 3:], 0.0)
        np.testing.assert_allclose(np.asarray(new_p[0]["weights"])[:2, :3], 0.9)
