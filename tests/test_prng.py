"""Key-splitting discipline for the named-generator registry.

The contract ZNC004 (prng-key hygiene) enforces statically is verified
dynamically here: every consumer that derives keys through the
sanctioned helpers (``prng.get(name).key()`` / ``.keys(n)``) must get a
key no other consumer ever saw — across draws, across generators, and
across a ``seed_all`` reseed with distinct seeds.
"""

import jax
import numpy as np

from znicz_tpu.core import prng


def key_bits(key) -> tuple:
    """Hashable raw key material (works for typed keys and uint32)."""
    return tuple(np.asarray(jax.random.key_data(key)).ravel().tolist())


def test_sequential_draws_from_one_generator_are_distinct():
    gen = prng.get("disc-a")
    seen = {key_bits(gen.key()) for _ in range(32)}
    assert len(seen) == 32


def test_draws_across_named_generators_never_collide():
    consumers = ("workflow", "loader", "dropout", "init", "disc-b")
    seen = set()
    for name in consumers:
        for _ in range(8):
            bits = key_bits(prng.get(name).key())
            assert bits not in seen, (
                f"generator {name!r} handed out a key another consumer "
                "already received"
            )
            seen.add(bits)
    assert len(seen) == len(consumers) * 8


def test_batch_keys_are_distinct_and_advance_the_stream():
    gen = prng.get("disc-c")
    batch = gen.keys(16)
    bits = {key_bits(k) for k in batch}
    assert len(bits) == 16
    # the next single draw must not repeat anything from the batch
    assert key_bits(gen.key()) not in bits


def test_seed_all_decorrelates_generators():
    prng.seed_all(777)
    a = prng.get("disc-d")
    b = prng.get("disc-e")
    assert a.initial_seed != b.initial_seed
    assert key_bits(a.key()) != key_bits(b.key())


def test_reseed_reproduces_the_same_stream():
    prng.seed_all(42)
    first = [key_bits(prng.get("disc-f").key()) for _ in range(4)]
    prng.seed_all(42)
    again = [key_bits(prng.get("disc-f").key()) for _ in range(4)]
    assert first == again
