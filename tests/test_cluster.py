"""Multi-replica control plane: affinity routing, failover, liveness.

The router's contract (docs/SERVING.md "The router"): every request a
live fleet can serve IS served — golden-identical to a direct
``generate()`` call — whatever single-replica event happens under it
(connect refusal, mid-stream death, shed), and the router itself sheds
(503 + Retry-After) only when no live replica could take the request.
Placement is prefix-affine: requests sharing a cached prefix co-locate
on one replica, learned router-side from routing decisions alone.
Every failover path is forced deterministically via the
``router.connect`` / ``router.stream`` / ``router.heartbeat`` fault
points; replicas are real ``ServingFrontDoor``s behind the real HTTP
surface, all in-process.
"""

import http.client
import json
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.cluster import (
    STATE_DEAD,
    STATE_DEGRADED,
    STATE_HEALTHY,
    PrefixAffinityIndex,
    ReplicaRegistry,
    ServingRouter,
    build_router_server,
)
from znicz_tpu.core import prng
from znicz_tpu.observability.aggregate import MetricsAggregator
from znicz_tpu.services import PagedDecodeEngine, ServingFrontDoor
from znicz_tpu.services import serve as serve_mod
from znicz_tpu.services.engine import DecodeEngine, prefix_block_keys
from znicz_tpu.utils import faults
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 14
HEADS = 4
T_MAX = 64
BS = 8  # paged block size == router key block size


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    prng.seed_all(27)
    return init_lm_params(17, 32, 2, HEADS, max_seq=T_MAX)


def _engine_kwargs(**kw):
    kw.setdefault("n_heads", HEADS)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("max_seq", T_MAX)
    kw.setdefault("admit_every", 4)
    return kw


@pytest.fixture(scope="module", autouse=True)
def _warm(params):
    """Compile every program the cluster scenarios will run (prefill,
    decode-window rungs up to the longest request below) ONCE, so the
    zero-new-compiles assertion and the timing-sensitive failover
    tests never eat a first-compile stall."""
    eng = PagedDecodeEngine(params, **_engine_kwargs())
    gen = np.random.default_rng(3)
    # a long request walks the x2 window ladder through every rung the
    # tests can reach; short ones cover admission-at-rung-1
    eng.submit(gen.integers(0, 17, (21,)).astype(np.int32), 30)
    eng.submit(gen.integers(0, 17, (5,)).astype(np.int32), 8)
    eng.run()
    return dict(eng.compile_stats()["programs"])


def _reference(params, prompt, budget, eos=EOS):
    out = np.asarray(
        G.generate(
            params, jnp.asarray(prompt)[None], n_heads=HEADS,
            max_new_tokens=budget, eos_id=eos,
        )
    )[0]
    new = out[len(prompt):]
    hit = np.where(new == eos)[0]
    if len(hit):
        new = new[: hit[0] + 1]
    return [int(t) for t in new]


def _wait_until(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


class _Fleet:
    """N in-process replicas (front door + HTTP server) behind one
    router server — built and torn down per test."""

    def __init__(self, params, n=2, router_kw=None, door_kw=None):
        self.doors, self.srvs = [], []
        for _ in range(n):
            door = ServingFrontDoor(
                lambda: PagedDecodeEngine(params, **_engine_kwargs()),
                max_pending=8,
                **(door_kw or {}),
            )
            srv = serve_mod.build_server(
                directory=".", port=0, frontdoor=door
            )
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            self.doors.append(door)
            self.srvs.append(srv)
        kw = {"block_size": BS, "heartbeat_interval_s": 60.0}
        kw.update(router_kw or {})
        self.router = ServingRouter(**kw)
        for i, srv in enumerate(self.srvs):
            self.router.register(f"rep-{i}", self.url(i))
        self.rsrv = build_router_server(self.router, port=0)
        threading.Thread(
            target=self.rsrv.serve_forever, daemon=True
        ).start()
        self.port = self.rsrv.server_address[1]

    def url(self, i):
        return f"http://127.0.0.1:{self.srvs[i].server_address[1]}"

    def post(self, prompt, max_new=12, timeout=60, port=None):
        conn = http.client.HTTPConnection(
            "127.0.0.1", port or self.port, timeout=timeout
        )
        try:
            conn.request(
                "POST", "/generate",
                body=json.dumps(
                    {"prompt": [int(t) for t in prompt],
                     "max_new_tokens": max_new}
                ),
            )
            resp = conn.getresponse()
            if resp.status != 200:
                return {
                    "status": resp.status,
                    "body": json.loads(resp.read() or b"{}"),
                    "retry_after": resp.getheader("Retry-After"),
                }
            out = {
                "status": 200,
                "tokens": [],
                "done": None,
                "replica_header": resp.getheader("X-Znicz-Replica"),
                "trace_header": resp.getheader("X-Znicz-Trace-Id"),
            }
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if "token" in rec:
                    out["tokens"].append(rec["token"])
                elif rec.get("done"):
                    out["done"] = rec
            return out
        finally:
            conn.close()

    def close(self):
        for srv in self.srvs:
            srv.shutdown()
            srv.server_close()
        self.rsrv.shutdown()
        self.rsrv.server_close()
        for door in self.doors:
            door.close(grace_s=10.0)
        self.router.close()


@pytest.fixture
def fleet(params):
    f = _Fleet(params)
    yield f
    f.close()


def _counter_value(name, **labels):
    metric = obs.counter(name, "", tuple(labels))
    return (metric.labels(**labels) if labels else metric).value


# -- unit: the affinity index ----------------------------------------------


class TestAffinityIndex:
    def test_learn_overlap_prefix_semantics(self):
        idx = PrefixAffinityIndex()
        idx.learn("a", ["k1", "k2", "k3"])
        assert idx.overlap("a", ["k1", "k2", "k3"]) == 3
        # chain semantics: a missing lead key means NO overlap even if
        # later keys are known
        assert idx.overlap("a", ["kX", "k2"]) == 0
        assert idx.overlap("a", ["k1", "kX", "k3"]) == 1
        assert idx.overlap("b", ["k1"]) == 0

    def test_ttl_decay(self):
        idx = PrefixAffinityIndex(ttl_s=0.05)
        idx.learn("a", ["k1", "k2"])
        assert idx.overlap("a", ["k1", "k2"]) == 2
        time.sleep(0.08)
        assert idx.overlap("a", ["k1", "k2"]) == 0
        assert idx.prune() >= 0  # idempotent after the lookup dropped

    def test_capacity_lru_eviction(self):
        idx = PrefixAffinityIndex(max_keys_per_replica=3)
        idx.learn("a", ["k1", "k2", "k3"])
        idx.learn("a", ["k4"])  # evicts k1 (LRU)
        assert idx.overlap("a", ["k1"]) == 0
        assert idx.overlap("a", ["k4"]) == 1
        # re-touch moves to MRU: k2 survives the next insertion
        idx.learn("a", ["k2"])
        idx.learn("a", ["k5"])
        assert idx.overlap("a", ["k2"]) == 1
        assert idx.overlap("a", ["k3"]) == 0

    def test_drop_replica(self):
        idx = PrefixAffinityIndex()
        idx.learn("a", ["k1", "k2"])
        assert idx.drop("a") == 2
        assert idx.overlap("a", ["k1"]) == 0
        assert idx.drop("a") == 0


# -- unit: the faults after= field -----------------------------------------


class TestFaultsAfter:
    def test_after_skips_then_fires(self):
        faults.inject("t.after", after=2, times=1)
        fired = []
        for _ in range(5):
            try:
                faults.fire("t.after")
                fired.append(False)
            except faults.FaultInjected:
                fired.append(True)
        assert fired == [False, False, True, False, False]

    def test_env_spec_parses_after(self):
        faults._parse_env("t.env:after=1:times=1")
        assert faults.armed("t.env")
        faults.fire("t.env")  # pass-through
        with pytest.raises(faults.FaultInjected):
            faults.fire("t.env")
        assert not faults.armed("t.env")


# -- unit: prefix probe (the engine-privates firewall) ---------------------


class TestPrefixProbe:
    def test_paged_probe_matches_public_keys_and_cache(self, params):
        eng = PagedDecodeEngine(params, **_engine_kwargs())
        gen = np.random.default_rng(11)
        prompt = gen.integers(0, 17, (20,)).astype(np.int32)
        probe = eng.prefix_probe(prompt)
        assert probe["prefix_cache"] is True
        assert probe["block_size"] == BS
        assert probe["block_keys"] == prefix_block_keys(prompt, BS)
        assert len(probe["block_keys"]) == 20 // BS
        assert probe["cached_blocks"] == 0
        # serve it: retirement publishes the full prompt blocks
        eng.submit(prompt, 8)
        eng.run()
        probe2 = eng.prefix_probe(prompt)
        assert probe2["cached_blocks"] == len(probe2["block_keys"])
        assert probe2["cached_tokens"] == probe2["cached_blocks"] * BS
        # a diverging prompt misses from the divergence on
        other = prompt.copy()
        other[2] = (other[2] + 1) % 17
        assert eng.prefix_probe(other)["cached_blocks"] == 0

    def test_dense_probe_is_empty(self, params):
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            max_seq=T_MAX,
        )
        probe = eng.prefix_probe(np.arange(12, dtype=np.int32))
        assert probe == {
            "prefix_cache": False, "block_size": None,
            "block_keys": [], "cached_blocks": 0, "cached_tokens": 0,
        }

    def test_frontdoor_delegates_and_http_endpoint(self, fleet, params):
        gen = np.random.default_rng(13)
        prompt = gen.integers(0, 17, (16,)).astype(np.int32)
        r = fleet.post(prompt, max_new=6)
        assert r["status"] == 200
        # the replica that served it now reports the cached blocks both
        # via the door hook and over HTTP
        idx = int(r["done"]["router"]["replica"].split("-")[1])
        door_probe = fleet.doors[idx].prefix_probe(prompt)
        assert door_probe["cached_blocks"] == 2
        conn = http.client.HTTPConnection(
            "127.0.0.1", fleet.srvs[idx].server_address[1], timeout=10
        )
        try:
            conn.request(
                "POST", "/prefix_probe",
                body=json.dumps({"prompt": [int(t) for t in prompt]}),
            )
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read()) == door_probe
            # malformed body answers 400, not a dropped connection
            conn.request("POST", "/prefix_probe", body=b"{}")
            resp = conn.getresponse()
            assert resp.status == 400
            resp.read()
        finally:
            conn.close()


# -- the registry state machine --------------------------------------------


class TestRegistry:
    def test_heartbeat_fault_ejects_then_readmits(self, fleet):
        reg = fleet.router.registry
        assert reg.get("rep-0").state == STATE_HEALTHY
        # dead_after consecutive heartbeat timeouts eject
        faults.inject("router.heartbeat", times=2 * reg.dead_after)
        for _ in range(reg.dead_after):
            reg.probe_all()
        assert reg.get("rep-0").state == STATE_DEAD
        assert reg.get("rep-1").state == STATE_DEAD
        assert reg.get("rep-0").ejections == 1
        faults.clear("router.heartbeat")
        # the first answered probe re-admits
        reg.probe_all()
        assert reg.get("rep-0").state == STATE_HEALTHY
        assert reg.get("rep-0").readmissions == 1

    def test_real_server_death_and_rebirth(self, fleet):
        reg = fleet.router.registry
        # seed affinity so the ejection flush is observable
        fleet.router.affinity.learn("rep-0", ["k1", "k2"])
        port = fleet.srvs[0].server_address[1]
        fleet.srvs[0].shutdown()
        fleet.srvs[0].server_close()
        for _ in range(reg.dead_after):
            reg.probe("rep-0")
        assert reg.get("rep-0").state == STATE_DEAD
        # ejection flushed the dead replica's affinity entries
        assert fleet.router.affinity.stats()["keys_per_replica"].get(
            "rep-0", 0
        ) == 0
        # rebirth on the SAME port (allow_reuse_address): one answered
        # probe re-admits without re-registration
        srv = serve_mod.build_server(
            directory=".", port=port, frontdoor=fleet.doors[0]
        )
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        fleet.srvs[0] = srv
        assert reg.probe("rep-0") == STATE_HEALTHY
        r = fleet.post(np.arange(1, 10, dtype=np.int32), max_new=4)
        assert r["status"] == 200

    def test_healthz_carries_load_signal(self, fleet):
        rep = fleet.router.registry.get("rep-0")
        assert rep.health["state"] == "running"
        assert "pending" in rep.health
        assert rep.health["pool_free_frac"] == pytest.approx(1.0)

    def test_degraded_demotion_and_note_success(self, fleet):
        reg = fleet.router.registry
        reg.note_failure("rep-0")
        assert reg.get("rep-0").state == STATE_DEGRADED
        # a streaming 200 heals a transport-blip demotion
        assert reg.note_success("rep-0") == STATE_HEALTHY
        assert reg.get("rep-0").failures == 0

    def test_note_success_does_not_override_self_reported_trouble(
        self, fleet
    ):
        """A replica whose own watchdog reported trouble (probe
        answered, state degraded) stays degraded after a streaming
        200 — serving one stream does not refute 'my watchdog says
        stalled'; only the next probe may promote it."""
        reg = fleet.router.registry
        rep = reg.get("rep-0")
        reg._apply(rep, "degraded", {"state": "stalled"})
        assert rep.state == STATE_DEGRADED and rep.failures == 0
        reg.note_failure("rep-0")  # one transport blip on top
        assert reg.note_success("rep-0") == STATE_DEGRADED
        assert rep.failures == 0
        # replica truth (an answered probe) is what promotes it
        assert reg.probe("rep-0") == STATE_HEALTHY


# -- routing: affinity goldens ---------------------------------------------


class TestRouting:
    def test_shared_prefix_coloc_and_goldens(self, fleet, params):
        gen = np.random.default_rng(5)
        groups = []
        for _ in range(2):
            shared = gen.integers(0, 17, (2 * BS,)).astype(np.int32)
            groups.append(
                [
                    np.concatenate(
                        [shared,
                         gen.integers(0, 17, (5,)).astype(np.int32)]
                    )
                    for _ in range(3)
                ]
            )
        hits0 = _counter_value(
            "znicz_router_affinity_total", signal="hit"
        )
        used = [set(), set()]
        for i in range(3):  # interleave the groups
            for g, prompts in enumerate(groups):
                r = fleet.post(prompts[i])
                assert r["status"] == 200
                assert r["tokens"] == _reference(
                    params, prompts[i], 12
                ), f"group {g} request {i} diverged from generate()"
                assert r["trace_header"]
                assert r["done"]["trace_id"] == r["trace_header"]
                used[g].add(r["done"]["router"]["replica"])
        # each group co-located on ONE replica, and the index said so
        assert all(len(u) == 1 for u in used), used
        assert _counter_value(
            "znicz_router_affinity_total", signal="hit"
        ) - hits0 >= 4  # requests 2..3 of each group routed by overlap
        # the replicas actually HIT their prefix caches (the router's
        # learned index agreed with replica truth)
        total_hits = sum(
            d.engine.stats()["prefix_cache"]["hits"]
            for d in fleet.doors
        )
        assert total_hits >= 4

    def test_least_loaded_spread_without_affinity(self, fleet):
        # distinct prompts (no shared prefix): placement falls back to
        # load and SPREADS across both replicas rather than piling on
        gen = np.random.default_rng(23)
        used = set()
        for _ in range(6):
            prompt = gen.integers(0, 17, (5,)).astype(np.int32)
            r = fleet.post(prompt, max_new=4)
            assert r["status"] == 200
            used.add(r["done"]["router"]["replica"])
            assert r["done"]["router"]["affinity_blocks"] == 0
        assert used == {"rep-0", "rep-1"}

    def test_aggregator_overrides_heartbeat_load(self):
        # pure unit: per-instance aggregator gauges drive the tiebreak
        agg = MetricsAggregator()

        def gauge_fam(value):
            return {
                "znicz_serve_frontdoor_pending": {
                    "type": "gauge", "help": "",
                    "series": [{"labels": {}, "value": value}],
                }
            }

        agg.push("a", gauge_fam(5.0))
        agg.push("b", gauge_fam(1.0))
        assert agg.instance_value(
            "a", "znicz_serve_frontdoor_pending"
        ) == 5.0
        reg = ReplicaRegistry(start=False)
        router = ServingRouter(
            reg, block_size=BS, aggregator=agg
        )
        reg.register("a", "http://127.0.0.1:1", probe=False)
        reg.register("b", "http://127.0.0.1:2", probe=False)
        order = [rep.instance for rep, _ in router.rank([])]
        assert order == ["b", "a"]  # lighter replica first
        router.close()

    def test_slo_burn_rate_demotes_in_the_tiebreak(self):
        """The ROADMAP rung: per-replica /slo burn rates (exported as
        the znicz_serve_slo_burn_rate gauge, pushed per instance) join
        the load tiebreak — a replica burning its error budget ranks
        behind every non-burning peer even when it is otherwise the
        lightest."""
        agg = MetricsAggregator()

        def fam(pending, burn):
            return {
                "znicz_serve_frontdoor_pending": {
                    "type": "gauge", "help": "",
                    "series": [{"labels": {}, "value": pending}],
                },
                "znicz_serve_slo_burn_rate": {
                    "type": "gauge", "help": "",
                    "series": [{"labels": {}, "value": burn}],
                },
            }

        # "a" is idle but BURNING; "b" is busier but healthy
        agg.push("a", fam(pending=0.0, burn=2.5))
        agg.push("b", fam(pending=6.0, burn=0.1))
        reg = ReplicaRegistry(start=False)
        router = ServingRouter(reg, block_size=BS, aggregator=agg)
        reg.register("a", "http://127.0.0.1:1", probe=False)
        reg.register("b", "http://127.0.0.1:2", probe=False)
        order = [rep.instance for rep, _ in router.rank([])]
        assert order == ["b", "a"]  # burn band beats queue depth
        # ...and beats AFFINITY too: the burning replica holds the
        # whole prefix, yet shared-prefix traffic must not keep
        # landing on a breached replica (the band sorts above overlap,
        # like the health band)
        keys = [f"k{i:02d}" for i in range(4)]
        router.affinity.learn("a", keys)
        ranked = router.rank(keys)
        assert [rep.instance for rep, _ in ranked] == ["b", "a"]
        assert dict(
            (rep.instance, ov) for rep, ov in ranked
        )["a"] == 4  # the overlap was seen, the burn band overrode it
        # under the breach threshold affinity rules again
        router.slo_burn_threshold = 5.0
        order = [rep.instance for rep, _ in router.rank(keys)]
        assert order == ["a", "b"]
        router.close()

    def test_frontdoor_publishes_the_burn_gauge(self, fleet, params):
        """The gauge the tiebreak consumes really is written by the
        serving door on its SLO sample cadence."""
        gen = np.random.default_rng(43)
        r = fleet.post(gen.integers(0, 17, (5,)).astype(np.int32),
                       max_new=4)
        assert r["status"] == 200
        fleet.doors[0]._publish_burn()  # engine-thread cadence, forced
        gauge = obs.gauge(
            "znicz_serve_slo_burn_rate",
            "max SLO burn rate across targets and windows with data "
            "(the router load tiebreak's per-instance input)",
        )
        assert gauge.value >= 0.0  # published, readable


# -- failover ---------------------------------------------------------------


class TestFailover:
    def test_connect_refused_fails_over(self, fleet, params):
        gen = np.random.default_rng(31)
        prompt = gen.integers(0, 17, (9,)).astype(np.int32)
        retries0 = _counter_value(
            "znicz_router_retries_total", reason="connect"
        )
        faults.inject("router.connect", times=1)
        r = fleet.post(prompt)
        assert r["status"] == 200
        assert r["tokens"] == _reference(params, prompt, 12)
        assert r["done"]["router"]["retries"] == 1
        assert _counter_value(
            "znicz_router_retries_total", reason="connect"
        ) - retries0 == 1

    def test_midstream_crash_rerouted_golden(self, fleet, params):
        """The acceptance scenario: a replica dies mid-stream after
        tokens were already delivered; the router re-routes to the
        next-best replica, skips the delivered prefix on the resumed
        stream, and the client sees one complete, golden token stream
        — no hang, no duplicate, no gap."""
        gen = np.random.default_rng(37)
        prompt = gen.integers(0, 17, (2 * BS + 3,)).astype(np.int32)
        ref = _reference(params, prompt, 12)
        assert len(ref) >= 4, "need a stream long enough to die inside"
        # 3 records (2 tokens) pass, the next upstream read dies
        faults.inject("router.stream", after=2, times=1)
        r = fleet.post(prompt)
        assert r["status"] == 200
        assert r["tokens"] == ref
        assert r["done"]["router"]["retries"] == 1
        assert r["done"]["finish_reason"] in ("eos", "budget")
        # the abandoned replica's request was cancelled by the dropped
        # connection: its pool sweeps back to fully free
        for door in fleet.doors:
            _wait_until(
                lambda d=door: not d.has_work(),
                what="abandoned request reclaimed",
            )

    def test_all_replicas_crash_typed_error_no_hang(self, fleet):
        """Out of replicas mid-stream: the client still gets a typed
        done record (finish_reason error), never a hang — and the
        router's own ledger counts the request FAILED, not ok."""
        failed0 = _counter_value(
            "znicz_router_requests_total", outcome="failed"
        )
        gen = np.random.default_rng(41)
        prompt = gen.integers(0, 17, (9,)).astype(np.int32)
        # every upstream read attempt dies, on both replicas
        faults.inject("router.stream")
        r = fleet.post(prompt)
        faults.clear("router.stream")
        assert r["status"] == 200  # headers were committed pre-fault
        assert r["done"] is not None
        assert r["done"]["finish_reason"] == "error"
        assert "router" in r["done"]
        assert _counter_value(
            "znicz_router_requests_total", outcome="failed"
        ) - failed0 == 1

    def test_replica_4xx_is_a_client_error_not_failover(self, fleet):
        """A request that passes the router's shallow validation but
        fails replica-side (too large for the KV pool) answers 400 —
        it must not burn a retry, note a failure against the healthy
        replica, or come back as a retryable 503."""
        retries0 = _counter_value(
            "znicz_router_retries_total", reason="connect"
        )
        r = fleet.post(
            np.arange(1, 10, dtype=np.int32), max_new=10_000
        )
        assert r["status"] == 400
        assert "rejected the request" in r["body"]["detail"]
        for rep in fleet.router.registry.replicas():
            assert rep.state == STATE_HEALTHY
            assert rep.failures == 0
        assert _counter_value(
            "znicz_router_retries_total", reason="connect"
        ) - retries0 == 0

    def test_fleet_saturation_503_retry_after(self, fleet):
        """503 + Retry-After ONLY when every live replica shed: park
        both engines in an injected slow tick, fill both pending
        queues to their admission limit, and watch the router shed
        with reason fleet_saturated."""
        from znicz_tpu.services import RejectedError

        for door in fleet.doors:
            door.max_pending = 1
        faults.inject("frontdoor.slow_tick", delay=0.5)
        time.sleep(0.1)  # both engine threads now inside a sleeping tick
        handles = []
        for door in fleet.doors:
            # fill the pending queue to its watermark; the slow tick
            # keeps it from draining (if a submit slipped through into
            # the engine before the fault took hold, the next one parks)
            for _ in range(3):
                try:
                    handles.append(
                        door.submit(np.arange(1, 6, dtype=np.int32), 4)
                    )
                except RejectedError:
                    break
                if len(door._pending) >= door.max_pending:
                    break
            assert len(door._pending) >= door.max_pending
        r = fleet.post(np.arange(1, 8, dtype=np.int32), max_new=4)
        assert r["status"] == 503
        assert r["body"]["reason"] == "fleet_saturated"
        assert int(r["retry_after"]) >= 1
        faults.clear("frontdoor.slow_tick")
        for h in handles:  # the parked requests complete after disarm
            assert h.result(timeout=30.0).finish_reason in (
                "eos", "budget"
            )

    def test_transport_walk_bounded_by_max_retries(self, params):
        """A partitioned fleet must answer 503 after max_retries + 1
        connect timeouts, not one per registered replica."""
        fleet = _Fleet(params, router_kw={"max_retries": 0})
        try:
            connect0 = _counter_value(
                "znicz_router_retries_total", reason="connect"
            )
            faults.inject("router.connect")  # every connect refused
            r = fleet.post(np.arange(1, 8, dtype=np.int32), max_new=4)
            faults.clear("router.connect")
            assert r["status"] == 503
            assert r["body"]["reason"] == "no_upstream"
            # exactly ONE transport attempt was paid (max_retries=0),
            # though two replicas were registered
            assert _counter_value(
                "znicz_router_retries_total", reason="connect"
            ) - connect0 == 1
        finally:
            fleet.close()

    def test_failed_requests_excluded_from_latency_histogram(
        self, fleet
    ):
        def latency_count():
            snap = obs.get_registry().snapshot()[
                "znicz_router_request_seconds"
            ]
            return sum(s["count"] for s in snap["series"])

        n0 = latency_count()
        faults.inject("router.stream")  # every stream read dies
        r = fleet.post(np.arange(1, 8, dtype=np.int32), max_new=4)
        faults.clear("router.stream")
        assert r["done"]["finish_reason"] == "error"
        # a fast terminal error is not a latency measurement (the
        # PR 7 front-door convention, carried to the router)
        assert latency_count() == n0
        r = fleet.post(np.arange(1, 8, dtype=np.int32), max_new=4)
        assert r["status"] == 200
        assert latency_count() == n0 + 1

    def test_garbage_http_replica_counts_as_heartbeat_failure(self):
        """A port reclaimed by a non-HTTP process (BadStatusLine) must
        count toward ejection, not abort the probe sweep."""
        import socket

        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("127.0.0.1", 0))
        sock.listen(4)
        port = sock.getsockname()[1]
        stop = threading.Event()

        def garbage_server():
            sock.settimeout(0.2)
            while not stop.is_set():
                try:
                    conn, _ = sock.accept()
                except socket.timeout:
                    continue
                conn.sendall(b"not http at all\r\n")
                conn.close()

        t = threading.Thread(target=garbage_server, daemon=True)
        t.start()
        try:
            reg = ReplicaRegistry(start=False, dead_after=2)
            rep = reg.register("junk", f"http://127.0.0.1:{port}")
            assert rep.failures == 1  # the registration probe counted
            assert reg.probe("junk") == STATE_DEAD
        finally:
            stop.set()
            t.join(timeout=5.0)
            sock.close()

    def test_no_live_replicas_503(self, params):
        reg = ReplicaRegistry(start=False)
        router = ServingRouter(reg, block_size=BS)
        rsrv = build_router_server(router, port=0)
        threading.Thread(target=rsrv.serve_forever, daemon=True).start()
        port = rsrv.server_address[1]
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=10
            )
            conn.request(
                "POST", "/generate",
                body=json.dumps(
                    {"prompt": [1, 2, 3], "max_new_tokens": 4}
                ),
            )
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 503
            assert body["reason"] == "no_replicas"
            assert resp.getheader("Retry-After") is not None
            conn.close()
            # router healthz mirrors it
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=10
            )
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 503
            resp.read()
            conn.close()
        finally:
            rsrv.shutdown()
            rsrv.server_close()
            router.close()


    def test_misconfigured_instance_fails_over_and_is_noted(
        self, fleet
    ):
        """A registered URL that answers HTTP but is not a replica
        (here: a metrics aggregator — /healthz 200, /generate 404)
        must fail over to a real replica AND count a failure against
        the bogus entry, not surface as a client 400."""
        from znicz_tpu.observability.aggregate import (
            build_aggregator_server,
        )

        asrv = build_aggregator_server(port=0)
        threading.Thread(target=asrv.serve_forever, daemon=True).start()
        try:
            fleet.router.register(
                "bogus",
                f"http://127.0.0.1:{asrv.server_address[1]}",
            )
            assert (
                fleet.router.registry.get("bogus").state
                == STATE_HEALTHY
            )  # its /healthz answers 200 — only traffic exposes it
            # force the bogus entry to be ranked first via affinity
            prompt = np.arange(1, 2 * BS + 1, dtype=np.int32)
            keys = prefix_block_keys(prompt, BS)
            fleet.router.affinity.learn("bogus", keys)
            r = fleet.post(prompt, max_new=4)
            assert r["status"] == 200
            assert r["done"]["router"]["replica"] != "bogus"
            assert r["done"]["router"]["retries"] == 1
            assert fleet.router.registry.get("bogus").failures == 1
            # QUARANTINE: its 200-answering /healthz washes the state
            # back to healthy every probe (the flip-flop), but the
            # traffic-failure streak survives probes — at dead_after
            # the wash stops working and the entry stays degraded
            reg = fleet.router.registry
            for i in range(reg.dead_after - 1):
                assert reg.probe("bogus") == STATE_HEALTHY  # the wash
                p2 = np.arange(
                    3 + i, 3 + i + 2 * BS, dtype=np.int32
                )
                fleet.router.affinity.learn(
                    "bogus", prefix_block_keys(p2, BS)
                )
                assert fleet.post(p2, max_new=4)["status"] == 200
            assert (
                reg.get("bogus").traffic_failures >= reg.dead_after
            )
            assert reg.probe("bogus") == STATE_DEGRADED
            # real served traffic is what lifts the quarantine
            assert reg.note_success("bogus") is not None
            assert reg.get("bogus").traffic_failures == 0
            assert reg.probe("bogus") == STATE_HEALTHY
        finally:
            asrv.shutdown()
            asrv.server_close()
            fleet.router.registry.deregister("bogus")

    def test_sheds_do_not_consume_the_retry_budget(self, fleet):
        """Shed answers are instant: they count in the REPORTED retry
        tally but leave the max_retries budget for the expensive
        failovers (connect timeouts, mid-stream recomputes), and a
        shed replica stays eligible for a later re-route."""
        from znicz_tpu.cluster.router import RoutedStream

        rs = RoutedStream(
            fleet.router, {"prompt": [1], "max_new_tokens": 4}, []
        )
        rs.retries = 5  # five sheds reported...
        assert rs._budget_used == 0
        assert rs._can_retry()  # ...and the crash budget is untouched
        rs._budget_used = fleet.router.max_retries
        assert not rs._can_retry()
        # end-to-end: a persistently shedding replica is walked
        # through (reported) while the healthy one serves
        fleet.doors[0].max_pending = 1
        faults.inject("frontdoor.slow_tick", delay=0.5)
        time.sleep(0.1)
        parked = []
        from znicz_tpu.services import RejectedError
        for _ in range(3):
            try:
                parked.append(
                    fleet.doors[0].submit(
                        np.arange(1, 6, dtype=np.int32), 4
                    )
                )
            except RejectedError:
                break
            if len(fleet.doors[0]._pending) >= 1:
                break
        prompt = np.arange(2, 2 * BS + 2, dtype=np.int32)
        fleet.router.affinity.learn(
            "rep-0", prefix_block_keys(prompt, BS)
        )  # rank the shedding replica first
        r = fleet.post(prompt, max_new=4)
        assert r["status"] == 200
        assert r["done"]["router"]["replica"] == "rep-1"
        assert r["done"]["router"]["retries"] == 1  # the shed, reported
        faults.clear("frontdoor.slow_tick")
        for h in parked:
            h.result(timeout=30.0)

    def test_done_record_n_new_reconciles_with_streamed_tokens(
        self, fleet
    ):
        """A done record from a failover replica that terminated while
        the skipped prefix was still recomputing (e.g. deadline expiry
        mid-recompute) must not claim fewer tokens than the client
        already received from the first replica."""
        from znicz_tpu.cluster.router import RoutedStream

        rs = RoutedStream(
            fleet.router, {"prompt": [1], "max_new_tokens": 8}, []
        )
        rs._sent = 3  # the first replica delivered 3 tokens
        rec = rs._finish(
            {"done": True, "finish_reason": "deadline_exceeded",
             "n_new": 0}
        )
        assert rec["n_new"] == 3
        # the normal path is a no-op clamp
        rs2 = RoutedStream(
            fleet.router, {"prompt": [1], "max_new_tokens": 8}, []
        )
        rs2._sent = 3
        rec2 = rs2._finish(
            {"done": True, "finish_reason": "budget", "n_new": 3}
        )
        assert rec2["n_new"] == 3

    def test_reroute_forwards_remaining_deadline(self, fleet):
        """A failover attempt carries the REMAINING client budget, not
        a fresh full deadline — each retry must not multiply the
        wall-clock a deadline_s=N request can burn."""
        from znicz_tpu.cluster.router import RoutedStream

        rs = RoutedStream(
            fleet.router,
            {"prompt": [1, 2, 3], "max_new_tokens": 4,
             "deadline_s": 5.0},
            [],
        )
        rs._t0 = time.monotonic() - 3.0  # 3 s already burned
        d = rs.payload_now()["deadline_s"]
        assert 1.8 <= d <= 2.1, d
        rs._t0 = time.monotonic() - 60.0  # budget exhausted
        assert rs.payload_now()["deadline_s"] == pytest.approx(0.001)
        # no deadline: payload passes through untouched
        rs2 = RoutedStream(
            fleet.router, {"prompt": [1], "max_new_tokens": 4}, []
        )
        assert "deadline_s" not in rs2.payload_now()


# -- the HTTP surface -------------------------------------------------------


class TestRouterHTTP:
    def test_bad_request_400(self, fleet):
        for body in (
            b"not json",
            json.dumps({"max_new_tokens": 4}).encode(),
            json.dumps({"prompt": "nope", "max_new_tokens": 4}).encode(),
            # a DIGIT string must not be reinterpreted as [1, 2, 3]
            json.dumps({"prompt": "123", "max_new_tokens": 4}).encode(),
            json.dumps({"prompt": [], "max_new_tokens": 4}).encode(),
        ):
            conn = http.client.HTTPConnection(
                "127.0.0.1", fleet.port, timeout=10
            )
            try:
                conn.request("POST", "/generate", body=body)
                resp = conn.getresponse()
                assert resp.status == 400, body
                resp.read()
            finally:
                conn.close()

    def test_replicas_endpoint(self, fleet):
        conn = http.client.HTTPConnection(
            "127.0.0.1", fleet.port, timeout=10
        )
        try:
            conn.request("GET", "/replicas")
            resp = conn.getresponse()
            assert resp.status == 200
            body = json.loads(resp.read())
        finally:
            conn.close()
        assert body["policy"] == "prefix_affinity"
        assert {r["instance"] for r in body["replicas"]} == {
            "rep-0", "rep-1"
        }
        assert all(
            r["state"] == STATE_HEALTHY for r in body["replicas"]
        )
        assert "keys_per_replica" in body["affinity"]

    def test_round_robin_policy_alternates(self, params):
        fleet = _Fleet(
            params, router_kw={"policy": "round_robin"}
        )
        try:
            gen = np.random.default_rng(43)
            shared = gen.integers(0, 17, (BS,)).astype(np.int32)
            seen = []
            for _ in range(4):
                r = fleet.post(shared, max_new=4)
                assert r["status"] == 200
                seen.append(r["done"]["router"]["replica"])
            # same prompt, yet RR alternates — the baseline the bench
            # compares affinity against
            assert seen[0] != seen[1]
            assert seen[0] == seen[2] and seen[1] == seen[3]
        finally:
            fleet.close()


# -- the compile story ------------------------------------------------------


class TestZeroNewPrograms:
    def test_router_and_replicas_add_zero_programs(
        self, fleet, params, _warm
    ):
        """Two replicas + the router serve a mixed affinity stream and
        compile NOTHING beyond the warm single-engine ladder — pinned
        against each engine's ledger AND the process-wide
        znicz_serve_compiles_total."""
        compiles = obs.counter(
            "znicz_serve_compiles_total", "", ("kind", "bucket")
        )
        total0 = sum(
            child.value for child in compiles.children().values()
        )
        gen = np.random.default_rng(47)
        shared = gen.integers(0, 17, (2 * BS,)).astype(np.int32)
        for i in range(4):
            tail = gen.integers(0, 17, (3 + i,)).astype(np.int32)
            r = fleet.post(np.concatenate([shared, tail]), max_new=8)
            assert r["status"] == 200
        total1 = sum(
            child.value for child in compiles.children().values()
        )
        assert total1 - total0 == 0, (
            "routing across replicas compiled new programs"
        )
        for door in fleet.doors:
            extra = set(
                door.engine.compile_stats()["programs"]
            ) - set(_warm)
            assert not extra, f"unexpected programs: {extra}"
