"""Unified telemetry tests: registry, tracer, phase timer, exports.

Covers the ISSUE 3 acceptance criteria end to end: registry unit
semantics (labels, cardinality cap, histogram bucket edges, Prometheus
text that a parser accepts), tracer nesting + valid Chrome-trace JSONL,
and the engine integration — one serve run through ``DecodeEngine``
must yield a parseable ``/metrics`` exposition with non-zero
tokens/compile/latency series over real HTTP, and a trace whose
``serve/admit`` span count equals the requests processed, with the
registry counters cross-checked against ``compile_stats()`` and the
submitted request count.
"""

import functools
import http.server
import json
import logging
import math
import threading
import urllib.request
from collections import Counter

import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.observability.phases import PhaseTimer
from znicz_tpu.observability.registry import (
    MetricsRegistry,
    parse_prometheus_text,
)
from znicz_tpu.observability.tracing import Tracer


def _series(name, **labels):
    """A (possibly absent) child series of the default registry."""
    m = obs.get_registry().metrics().get(name)
    if m is None:
        return None
    key = tuple(str(labels[n]) for n in m.labelnames)
    return m.children().get(key)


def _counter_value(name, **labels):
    child = _series(name, **labels)
    return 0.0 if child is None else child.value


def _counter_total(name):
    """Sum over every label set (e.g. retirements across reasons)."""
    m = obs.get_registry().metrics().get(name)
    if m is None:
        return 0.0
    return sum(c.value for c in m.children().values())


def _hist_count(name, **labels):
    child = _series(name, **labels)
    return 0 if child is None else child.count


# -- registry --------------------------------------------------------------


class TestRegistry:
    def test_counters_and_gauges(self):
        r = MetricsRegistry()
        c = r.counter("reqs_total", "requests", ("kind",))
        c.labels(kind="a").inc()
        c.labels(kind="a").inc(2)
        c.labels("b").inc()
        assert c.labels(kind="a").value == 3
        assert c.labels(kind="b").value == 1
        g = r.gauge("depth", "queue depth")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3
        with pytest.raises(ValueError):
            c.labels(kind="a").inc(-1)  # counters only go up

    def test_get_or_create_shares_and_conflicts(self):
        r = MetricsRegistry()
        a = r.counter("x_total", "first")
        b = r.counter("x_total", "again")
        assert a is b  # two subsystems share the series, no second ledger
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("x_total")
        with pytest.raises(ValueError, match="already registered"):
            r.counter("x_total", labelnames=("k",))
        with pytest.raises(ValueError, match="invalid metric name"):
            r.counter("bad name")
        with pytest.raises(ValueError, match="invalid label"):
            r.counter("y_total", labelnames=("le",))

    def test_label_cardinality_capped(self):
        r = MetricsRegistry(max_series_per_metric=2)
        c = r.counter("x_total", "", ("k",))
        c.labels(k="1").inc()
        c.labels(k="2").inc()
        c.labels(k="1").inc()  # existing series: always fine
        with pytest.raises(ValueError, match="cardinality"):
            c.labels(k="3")

    def test_histogram_bucket_edges(self):
        # le semantics: a sample exactly AT an upper bound belongs to
        # that bucket; past the last finite edge lands in +Inf only
        r = MetricsRegistry()
        h = r.histogram("h_seconds", "", buckets=(1.0, 2.0))
        h.observe(1.0)
        h.observe(1.5)
        h.observe(5.0)
        child = r.metrics()["h_seconds"].children()[()]
        cum = dict(child.cumulative())
        assert cum[1.0] == 1 and cum[2.0] == 2 and cum[math.inf] == 3
        assert child.count == 3
        assert child.sum == pytest.approx(7.5)

    def test_histogram_quantile_estimates(self):
        r = MetricsRegistry()
        h = r.histogram("q_seconds", "", buckets=(0.1, 1.0, 10.0))
        for _ in range(99):
            h.observe(0.05)
        h.observe(5.0)
        child = r.metrics()["q_seconds"].children()[()]
        assert child.quantile(0.5) <= 0.1
        assert child.quantile(0.999) > 1.0
        empty = r.histogram("e_seconds", "", buckets=(1.0,))
        assert empty._default().quantile(0.5) is None

    def test_prometheus_text_parses(self):
        r = MetricsRegistry()
        r.counter("a_total", "with \"quotes\"", ("k",)).labels(
            k='va"l\\ue'
        ).inc(2)
        r.gauge("g", "gauge").set(-1.5)
        h = r.histogram("h_seconds", "hist", ("phase",), buckets=(0.1, 1))
        h.labels(phase="x").observe(0.5)
        text = r.prometheus_text()
        parsed = parse_prometheus_text(text)
        assert parsed["types"] == {
            "a_total": "counter", "g": "gauge", "h_seconds": "histogram"
        }
        samples = {
            (n, tuple(sorted(l.items()))): v
            for n, l, v in parsed["samples"]
        }
        assert samples[("a_total", (("k", 'va"l\\ue'),))] == 2
        assert samples[("g", ())] == -1.5
        assert samples[
            ("h_seconds_count", (("phase", "x"),))
        ] == 1
        # the real Prometheus client parser accepts it too, if present
        try:
            from prometheus_client.parser import (
                text_string_to_metric_families,
            )
        except ImportError:
            pass
        else:
            fams = {f.name: f for f in text_string_to_metric_families(text)}
            assert fams["h_seconds"].type == "histogram"

    def test_parser_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_prometheus_text("not a metric line at all!")
        with pytest.raises(ValueError):
            parse_prometheus_text("# TYPE x sometype\n")
        with pytest.raises(ValueError, match="le"):
            parse_prometheus_text(
                "# TYPE h histogram\nh_bucket 5\nh_count 5\n"
            )

    def test_snapshot_is_json_able(self):
        r = MetricsRegistry()
        r.counter("c_total", "c").inc(7)
        r.histogram("h_seconds", "h", buckets=(1.0,)).observe(0.5)
        snap = json.loads(json.dumps(r.snapshot()))
        assert snap["c_total"]["series"][0]["value"] == 7
        hseries = snap["h_seconds"]["series"][0]
        assert hseries["count"] == 1
        assert hseries["buckets"]["+Inf"] == 1
        assert hseries["p50"] is not None

    def test_reset_zeroes_but_keeps_registration(self):
        r = MetricsRegistry()
        c = r.counter("c_total", "c", ("k",))
        c.labels(k="a").inc(5)
        r.reset()
        assert c.labels(k="a").value == 0
        assert r.counter("c_total", "c", ("k",)) is c


# -- tracer ----------------------------------------------------------------


class TestTracer:
    def test_nesting_and_jsonl(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        tr = Tracer()
        tr.start(path=str(path))
        with tr.span("outer", n=1):
            with tr.span("inner"):
                pass
        tr.instant("mark", note="x")
        events = tr.stop()
        by = {e["name"]: e for e in events}
        inner, outer = by["inner"], by["outer"]
        # the child completes first but nests inside the parent
        assert events[0]["name"] == "inner"
        assert inner["args"]["parent"] == "outer"
        assert outer["ts"] <= inner["ts"]
        assert (
            inner["ts"] + inner["dur"]
            <= outer["ts"] + outer["dur"] + 0.01
        )
        assert outer["args"]["n"] == 1
        assert by["mark"]["ph"] == "i"
        assert tr.span_counts() == Counter(outer=1, inner=1)
        # the streamed JSONL is line-for-line the event list
        lines = path.read_text().splitlines()
        assert len(lines) == len(events) == 3
        for line in lines:
            ev = json.loads(line)
            assert ev["ph"] in ("X", "i")
            assert {"name", "ts", "pid", "tid"} <= set(ev)

    def test_not_recording_is_noop(self):
        tr = Tracer()
        with tr.span("ghost"):
            pass
        assert tr.events() == []

    def test_memory_cap_does_not_truncate_file(self, tmp_path):
        # the in-memory buffer caps; the streamed JSONL stays complete
        path = tmp_path / "capped.jsonl"
        tr = Tracer(max_events=2)
        tr.start(path=str(path))
        for i in range(5):
            with tr.span(f"s{i}"):
                pass
        events = tr.stop()
        assert len(events) == 2 and tr.dropped == 3
        assert len(path.read_text().splitlines()) == 5

    def test_file_rotation_caps_disk_and_keeps_newest(self, tmp_path):
        # ISSUE 7 satellite: the streamed file is size-capped — it
        # rotates to <path>.1 instead of growing without bound on a
        # long-running server; the newest window stays in <path>
        path = tmp_path / "trace.jsonl"
        tr = Tracer()
        tr.start(path=str(path), max_file_bytes=400)
        for i in range(40):
            with tr.span(f"span-{i:03d}"):
                pass
        tr.stop()
        assert tr.rotations >= 1
        rotated = tmp_path / "trace.jsonl.1"
        assert rotated.exists()
        assert rotated.stat().st_size <= 400 + 200  # one line of slack
        # every line in both generations is valid JSONL, no torn writes
        names = []
        for p in (rotated, path):
            for line in p.read_text().splitlines():
                names.append(json.loads(line)["name"])
        # the newest span is in the live file; rotation loses only the
        # OLDEST generation (at most one cap's worth)
        assert json.loads(
            path.read_text().splitlines()[-1]
        )["name"] == "span-039"
        assert names == sorted(names)  # contiguous suffix, in order

    def test_doubly_failed_rotation_degrades_to_memory_buffer(
        self, tmp_path, monkeypatch
    ):
        # rename fails AND the append-reopen fails (dir deleted, EROFS):
        # the stream is lost, _file goes None — the NEXT span must land
        # in the in-memory buffer, not raise AttributeError on the
        # instrumented thread
        path = tmp_path / "doomed.jsonl"
        tr = Tracer()
        tr.start(path=str(path), max_file_bytes=120)

        def _boom(*a, **kw):
            raise OSError("gone")

        monkeypatch.setattr("znicz_tpu.observability.tracing.os.replace",
                            _boom)
        monkeypatch.setattr("builtins.open", _boom)
        for i in range(20):
            with tr.span(f"s{i}"):
                pass
        monkeypatch.undo()
        events = tr.stop()
        assert [e["name"] for e in events[-3:]] == ["s17", "s18", "s19"]

    def test_rotation_disabled_streams_unbounded(self, tmp_path):
        path = tmp_path / "unbounded.jsonl"
        tr = Tracer()
        tr.start(path=str(path), max_file_bytes=None)
        for i in range(50):
            with tr.span(f"s{i}"):
                pass
        tr.stop()
        assert tr.rotations == 0
        assert len(path.read_text().splitlines()) == 50

    def test_shutdown_gracefully_flushes_the_tracer(self, tmp_path):
        # run_server's SIGTERM path calls shutdown_gracefully, which
        # must stop a recording tracer so the JSONL is flushed/closed
        from znicz_tpu.observability import get_tracer
        from znicz_tpu.services import serve as serve_mod

        path = tmp_path / "drain.jsonl"
        tracer = get_tracer()
        tracer.start(path=str(path))
        try:
            with tracer.span("final-request"):
                pass
            server = serve_mod.build_server(
                directory=str(tmp_path), port=0
            )
            threading.Thread(
                target=server.serve_forever, daemon=True
            ).start()
            serve_mod.shutdown_gracefully(server)
            server.server_close()
            assert tracer.recording is False
            lines = path.read_text().splitlines()
            assert any(
                json.loads(ln)["name"] == "final-request"
                for ln in lines
            )
        finally:
            if tracer.recording:
                tracer.stop()

    def test_start_twice_raises_and_write_jsonl(self, tmp_path):
        tr = Tracer()
        tr.start()
        with pytest.raises(RuntimeError):
            tr.start()
        with tr.span("a"):
            pass
        tr.stop()
        out = tmp_path / "later.jsonl"
        tr.write_jsonl(str(out))
        assert json.loads(out.read_text().splitlines()[0])["name"] == "a"


# -- phase timer -----------------------------------------------------------


class TestPipelineSpans:
    """ROADMAP observability next-rung: snapshot writes and the loader
    prefetch producer thread must appear on the Perfetto timeline."""

    def test_snapshot_and_prefetch_producer_spans(self, tmp_path):
        from znicz_tpu.loader.prefetch import prefetch
        from znicz_tpu.workflow.snapshotter import Snapshotter

        tr = obs.get_tracer()
        tr.start()
        try:
            snap = Snapshotter(str(tmp_path), compress=False)
            snap.save(
                {"w": np.zeros((2, 2), np.float32)}, {"epoch": 1},
                tag="best",
            )
            out = list(prefetch(iter(range(5)), depth=2))
        finally:
            events = tr.stop()
        assert out == list(range(5))
        counts = Counter(
            e["name"] for e in events if e.get("ph") == "X"
        )
        assert counts["snapshot/save"] == 1
        assert counts["snapshot/gather"] == 1
        assert counts["snapshot/write"] == 1
        # the produce span is stage-split (PR 13): one fetch span per
        # item + the final end-of-stream pull
        assert counts["loader/fetch"] == 6
        # gather/write nest inside the save span
        write = next(e for e in events if e["name"] == "snapshot/write")
        assert write["args"]["parent"] == "snapshot/save"
        # producer spans carry the WORKER thread's tid — their own
        # Perfetto track, next to (not under) the consumer's spans
        prod = [
            e for e in events if e["name"] == "loader/fetch"
        ]
        assert all(e["tid"] != threading.get_ident() for e in prod)

    def test_snapshot_save_untraced_still_writes(self, tmp_path):
        # spans must be pure observation: with the tracer idle the save
        # path writes the same file
        from znicz_tpu.workflow.snapshotter import Snapshotter, load_snapshot

        snap = Snapshotter(str(tmp_path), compress=False)
        path = snap.save(
            {"w": np.ones((2,), np.float32)}, {"epoch": 2}, tag="best"
        )
        state, host = load_snapshot(path)
        np.testing.assert_array_equal(state["w"], np.ones((2,)))
        assert host == {"epoch": 2}


class TestPhaseTimer:
    def test_summary_is_windowed_over_shared_series(self):
        r = MetricsRegistry()
        tr = Tracer()
        t1 = PhaseTimer("p_seconds", registry=r, tracer=tr)
        with t1.phase("a"):
            pass
        with t1.phase("a"):
            pass
        with t1.phase("b"):
            pass
        s = t1.summary()
        assert s["a"]["count"] == 2 and s["b"]["count"] == 1
        assert s["a"]["total_s"] >= 0 and "mean_ms" in s["a"]
        # a second instance on the SAME metric starts a fresh window...
        t2 = PhaseTimer("p_seconds", registry=r, tracer=tr)
        assert t2.summary() == {}
        with t2.phase("a"):
            pass
        assert t2.summary()["a"]["count"] == 1
        # ...while the first keeps counting from ITS baseline and the
        # registry holds the process-lifetime truth
        assert t1.summary()["a"]["count"] == 3
        assert r.metrics()["p_seconds"].children()[("a",)].count == 3
        t1.reset()
        assert t1.summary() == {}

    def test_phase_emits_span_with_args(self):
        r = MetricsRegistry()
        tr = Tracer()
        t = PhaseTimer("p_seconds", registry=r, tracer=tr, span_prefix="w/")
        tr.start()
        with t.phase("c", tag=7):
            pass
        events = tr.stop()
        assert events[0]["name"] == "w/c"
        assert events[0]["args"]["tag"] == 7


# -- bounded latency stats (satellite) -------------------------------------


class TestLatencyStats:
    def test_ring_bound_and_p99(self):
        from znicz_tpu.utils.profiling import LatencyStats

        seen = []
        ls = LatencyStats(max_samples=4, observe=seen.append)
        for v in [1.0] * 6 + [0.001] * 4:
            ls.record(v)
        # lifetime count survives the bound; the observer saw every one
        assert len(ls) == 10 and len(seen) == 10
        s = ls.summary()
        assert s["count"] == 10
        # percentiles describe the retained window (the last 4 samples)
        assert s["p99_ms"] == pytest.approx(1.0)
        assert s["max_ms"] == pytest.approx(1.0)
        ls.reset()
        assert ls.summary() == {"count": 0}

    def test_summary_has_all_percentile_keys(self):
        from znicz_tpu.utils.profiling import LatencyStats

        ls = LatencyStats()
        ls.record(0.25)
        assert {"count", "mean_ms", "p50_ms", "p95_ms", "p99_ms",
                "max_ms"} <= set(ls.summary())

    def test_rejects_bad_capacity(self):
        from znicz_tpu.utils.profiling import LatencyStats

        with pytest.raises(ValueError):
            LatencyStats(max_samples=0)


# -- idempotent logging setup (satellite) ----------------------------------


class TestSetupLogging:
    def test_existing_handlers_survive_unless_forced(self):
        from znicz_tpu.core import logger as L

        root = logging.getLogger()
        saved_handlers = root.handlers[:]
        saved_level = root.level
        saved_flag = L._configured
        try:
            marker = logging.NullHandler()
            root.handlers[:] = [marker]
            root.setLevel(logging.WARNING)
            L._configured = False
            L.setup_logging()  # pre-configured root: must not clobber
            assert root.handlers == [marker]
            # ...but a default-WARNING root must not eat INFO logs
            assert root.level == logging.INFO
            # a deliberately-verbose root is never QUIETED
            root.setLevel(logging.DEBUG)
            L.setup_logging()
            assert root.level == logging.DEBUG
            L.setup_logging(force=True)  # explicit escape hatch
            assert root.handlers != [marker]
            assert len(root.handlers) == 1
            installed = root.handlers[:]
            L.setup_logging()  # repeat call: idempotent
            assert root.handlers == installed
        finally:
            root.handlers[:] = saved_handlers
            root.setLevel(saved_level)
            L._configured = saved_flag


# -- status writer export surface (satellite + tentpole) -------------------


class _StubDecision:
    epoch = 2
    max_epochs = 3
    best_value = 0.1
    best_epoch = 1
    history = [1, 2]


class _StubWorkflow:
    name = "stub"
    decision = _StubDecision()
    timer = None


_VERDICT = {
    "improved": False,
    "stop": False,
    "summary": {"train": {"n_samples": 8, "loss": 0.5, "err_pct": 2.0}},
}


class TestStatusWriterTelemetry:
    def test_snapshot_embedded_and_writes_atomic(self, tmp_path):
        from znicz_tpu.services.web_status import StatusWriter

        obs.counter(
            "znicz_test_status_total", "status-writer test series"
        ).inc(3)
        w = StatusWriter(str(tmp_path))
        w.on_epoch(_StubWorkflow(), _VERDICT)
        status = json.loads((tmp_path / "status.json").read_text())
        assert status["epoch"] == 1
        snap = status["metrics"]
        assert (
            snap["znicz_test_status_total"]["series"][0]["value"] >= 3
        )
        # the Prometheus twin parses, and no temp files leak (atomic
        # replace means a poller can never read a truncated file)
        parsed = parse_prometheus_text(
            (tmp_path / "metrics.prom").read_text()
        )
        assert "znicz_test_status_total" in parsed["types"]
        assert not [p for p in tmp_path.iterdir() if ".tmp." in p.name]
        assert "metrics registry snapshot" in (
            tmp_path / "status.html"
        ).read_text()


# -- /metrics endpoint -----------------------------------------------------


def _serve_dir(directory):
    from znicz_tpu.services.serve import StatusRequestHandler

    handler = functools.partial(
        StatusRequestHandler, directory=str(directory)
    )
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _get(srv, path):
    port = srv.server_address[1]
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.read().decode(), resp.headers.get("Content-Type")


class TestMetricsEndpoint:
    def test_prefers_training_written_files(self, tmp_path):
        # both endpoints must read the TRAINING process's exports when
        # present — never one from the file and one from the live
        # registry (a dashboard would see contradictory worlds)
        (tmp_path / "metrics.prom").write_text(
            "# TYPE from_training counter\nfrom_training 42\n"
        )
        (tmp_path / "status.json").write_text(
            json.dumps({"metrics": {"from_training": {
                "type": "counter", "help": "",
                "series": [{"labels": {}, "value": 42}],
            }}})
        )
        srv = _serve_dir(tmp_path)
        try:
            body, ctype = _get(srv, "/metrics")
            jbody, _ = _get(srv, "/metrics.json")
        finally:
            srv.shutdown()
        assert "from_training 42" in body
        assert ctype.startswith("text/plain")
        parse_prometheus_text(body)
        snap = json.loads(jbody)
        assert snap["from_training"]["series"][0]["value"] == 42

    def test_json_derives_from_prom_when_status_lacks_metrics(
        self, tmp_path
    ):
        # metrics.prom alone (older StatusWriter, crash between writes):
        # /metrics.json must derive from the SAME file /metrics serves,
        # never fall back to the serve process's unrelated registry
        (tmp_path / "metrics.prom").write_text(
            "# TYPE from_training counter\nfrom_training 42\n"
        )
        srv = _serve_dir(tmp_path)
        try:
            jbody, _ = _get(srv, "/metrics.json")
        finally:
            srv.shutdown()
        snap = json.loads(jbody)
        assert snap["from_training"]["series"][0]["value"] == 42
        assert snap["from_training"]["type"] == "counter"

    def test_falls_back_to_live_registry_and_json(self, tmp_path):
        obs.counter(
            "znicz_test_endpoint_total", "endpoint test series"
        ).inc()
        srv = _serve_dir(tmp_path)  # no metrics.prom in the directory
        try:
            body, _ = _get(srv, "/metrics")
            jbody, jtype = _get(srv, "/metrics.json")
        finally:
            srv.shutdown()
        assert "znicz_test_endpoint_total" in parse_prometheus_text(
            body
        )["types"]
        assert jtype == "application/json"
        assert "znicz_test_endpoint_total" in json.loads(jbody)


# -- engine integration: the acceptance criteria ---------------------------


EOS = 14
HEADS = 4


def _params():
    from znicz_tpu.core import prng
    from znicz_tpu.workflow.transformer import init_lm_params

    prng.seed_all(27)
    # vocab 19: a geometry no OTHER test file uses, so the process-wide
    # first-compile ledger is cold and the registry compile delta below
    # cross-checks EXACTLY against this engine's n_programs
    return init_lm_params(19, 32, 2, HEADS, max_seq=64)


class TestEngineTelemetry:
    def test_serve_run_feeds_registry_tracer_and_metrics_endpoint(
        self, tmp_path
    ):
        from znicz_tpu.services.engine import DecodeEngine

        params = _params()
        base = {
            "submitted": _counter_value(
                "znicz_serve_requests_submitted_total"
            ),
            "admitted": _counter_value(
                "znicz_serve_requests_admitted_total"
            ),
            "retired": _counter_total(
                "znicz_serve_requests_retired_total"
            ),
            "tokens": _counter_value("znicz_serve_tokens_generated_total"),
            "compiles": _counter_total("znicz_serve_compiles_total"),
            "latency": _hist_count("znicz_serve_request_latency_seconds"),
            "ttft": _hist_count("znicz_serve_ttft_seconds"),
        }
        gen = np.random.default_rng(3)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32) for n in (5, 12, 3)
        ]
        trace_path = tmp_path / "serve.trace.jsonl"
        tracer = obs.get_tracer()
        tracer.start(path=str(trace_path))
        try:
            eng = DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, batch_size=2,
                admit_every=4,
            )
            for p in prompts:
                eng.submit(p, max_new_tokens=5)
            comps = eng.run()
        finally:
            events = tracer.stop()
        n = len(prompts)
        assert len(comps) == n
        new_tokens = sum(c.n_new for c in comps)

        # (b) Chrome-trace JSONL: span counts match requests processed
        counts = Counter(e["name"] for e in events if e["ph"] == "X")
        assert counts["serve/admit"] == n
        assert counts["serve/decode"] >= 1
        lines = trace_path.read_text().splitlines()
        assert len(lines) == len(events) > 0
        for line in lines:
            ev = json.loads(line)
            assert {"name", "ph", "ts", "pid", "tid"} <= set(ev)

        # registry counters cross-check against the engine's own ledgers
        assert (
            _counter_value("znicz_serve_requests_submitted_total")
            - base["submitted"]
        ) == n
        assert (
            _counter_value("znicz_serve_requests_admitted_total")
            - base["admitted"]
        ) == n
        assert (
            _counter_total("znicz_serve_requests_retired_total")
            - base["retired"]
        ) == n
        assert (
            _counter_value("znicz_serve_tokens_generated_total")
            - base["tokens"]
        ) == new_tokens == eng.stats()["generated_tokens"]
        assert (
            _counter_total("znicz_serve_compiles_total")
            - base["compiles"]
        ) == eng.compile_stats()["n_programs"]
        assert (
            _hist_count("znicz_serve_request_latency_seconds")
            - base["latency"]
        ) == n
        assert (
            _hist_count("znicz_serve_ttft_seconds") - base["ttft"]
        ) == n
        assert _counter_value(
            "znicz_serve_queue_depth"
        ) == 0 and _counter_value("znicz_serve_active_slots") == 0

        # a SECOND engine with the same geometry rides the shared jit
        # caches — the process-wide compile counter must not re-count
        compiles_after = _counter_total("znicz_serve_compiles_total")
        eng2 = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            admit_every=4,
        )
        eng2.submit(prompts[0], max_new_tokens=3)
        eng2.run()
        assert eng2.compile_stats()["n_programs"] == 2
        assert (
            _counter_total("znicz_serve_compiles_total") == compiles_after
        )

        # (a) /metrics over real HTTP: parseable, with non-zero
        # tokens / compile / latency series
        srv = _serve_dir(tmp_path)  # no metrics.prom: live registry
        try:
            body, ctype = _get(srv, "/metrics")
        finally:
            srv.shutdown()
        assert ctype.startswith("text/plain")
        parsed = parse_prometheus_text(body)
        samples = {}
        for name, labels, value in parsed["samples"]:
            samples[name] = samples.get(name, 0.0) + value
        assert samples["znicz_serve_tokens_generated_total"] >= new_tokens
        assert samples["znicz_serve_compiles_total"] > 0
        assert samples["znicz_serve_request_latency_seconds_count"] >= n
        try:
            from prometheus_client.parser import (
                text_string_to_metric_families,
            )
        except ImportError:
            pass
        else:
            assert list(text_string_to_metric_families(body))
