"""Op-level golden tests: each op vs a naive numpy implementation.

This is the rebuild of the reference's cross-backend unit tests
(znicz/tests/unit/test_*.py, SURVEY.md section 4): the naive numpy loops below
play the role of numpy_run; the jnp/XLA ops must match within tolerance, and
gradients are finite-difference checked.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.ops import (
    activation,
    all2all,
    conv,
    cutter,
    deconv,
    dropout,
    kohonen,
    normalization,
    pooling,
    rbm,
)

RTOL, ATOL = 1e-5, 1e-5


def rand(*shape, seed=0):
    return np.random.default_rng(seed).standard_normal(shape).astype(np.float32)


class TestActivation:
    def test_tanh_scaled(self):
        x = rand(8)
        np.testing.assert_allclose(
            activation.tanh(x), 1.7159 * np.tanh(0.6666 * x), rtol=1e-4, atol=1e-5
        )

    def test_relu_is_softplus(self):
        x = rand(8)
        np.testing.assert_allclose(
            activation.relu(x), np.log1p(np.exp(x)), rtol=1e-4
        )

    def test_strict_relu(self):
        x = rand(8)
        np.testing.assert_allclose(activation.strict_relu(x), np.maximum(x, 0))

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            activation.get("nope")


class TestAll2All:
    def test_forward_matches_numpy(self):
        params = all2all.init_params(10, 5)
        x = rand(4, 10)
        got = all2all.apply(params, x)
        want = x @ np.asarray(params["weights"]) + np.asarray(params["bias"])
        np.testing.assert_allclose(got, want, rtol=RTOL, atol=ATOL)

    def test_flattens_input(self):
        params = all2all.init_params(12, 3)
        x = rand(2, 2, 3, 2)
        assert all2all.apply(params, x).shape == (2, 3)

    def test_softmax_rows_sum_to_one(self):
        params = all2all.init_params(10, 7)
        y = all2all.softmax_apply(params, rand(4, 10))
        np.testing.assert_allclose(np.sum(np.asarray(y), axis=1), 1.0, rtol=1e-4)

    def test_grad_finite_difference(self):
        params = all2all.init_params(6, 4)
        x = jnp.asarray(rand(3, 6))

        def loss(w):
            return jnp.sum(
                jnp.square(all2all.apply({"weights": w, "bias": params["bias"]}, x))
            )

        g = jax.grad(loss)(params["weights"])
        eps = 1e-3
        w0 = np.asarray(params["weights"]).copy()
        for idx in [(0, 0), (3, 2)]:
            wp, wm = w0.copy(), w0.copy()
            wp[idx] += eps
            wm[idx] -= eps
            num = (loss(jnp.asarray(wp)) - loss(jnp.asarray(wm))) / (2 * eps)
            np.testing.assert_allclose(g[idx], num, rtol=1e-2)


def naive_conv(x, w, b, stride=(1, 1)):
    n, h, wdt, cin = x.shape
    ky, kx, _, cout = w.shape
    oh = (h - ky) // stride[0] + 1
    ow = (wdt - kx) // stride[1] + 1
    out = np.zeros((n, oh, ow, cout), np.float32)
    for bi in range(n):
        for i in range(oh):
            for j in range(ow):
                patch = x[
                    bi, i * stride[0] : i * stride[0] + ky, j * stride[1] : j * stride[1] + kx
                ]
                out[bi, i, j] = np.tensordot(patch, w, axes=3) + b
    return out


class TestConv:
    def test_forward_matches_naive(self):
        params = conv.init_params(3, 4, kx=3, ky=3)
        x = rand(2, 8, 8, 3)
        got = conv.apply(params, x)
        want = naive_conv(x, np.asarray(params["weights"]), np.asarray(params["bias"]))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_strided_padded_shape(self):
        params = conv.init_params(3, 8, kx=5, ky=5)
        x = rand(2, 16, 16, 3)
        y = conv.apply(params, x, sliding=(2, 2), padding=(2, 2, 2, 2))
        assert y.shape == conv.output_shape(x.shape, 8, 5, 5, (2, 2), (2, 2, 2, 2))
        assert y.shape == (2, 8, 8, 8)

    def test_grad_runs(self):
        params = conv.init_params(2, 3, kx=3, ky=3)
        x = jnp.asarray(rand(1, 6, 6, 2))
        g = jax.grad(lambda p: jnp.sum(jnp.square(conv.apply(p, x))))(params)
        assert g["weights"].shape == params["weights"].shape
        assert bool(jnp.any(g["weights"] != 0))

    @pytest.mark.parametrize(
        "h,c,k,ksz,s,padding",
        [
            (35, 3, 8, 11, 4, (0, 0, 0, 0)),  # AlexNet-conv1-shaped
            (32, 3, 8, 4, 4, (0, 0, 0, 0)),   # kernel == stride (slice)
            (34, 4, 8, 5, 2, (0, 0, 0, 0)),   # stride 2, odd kernel
            (33, 2, 8, 3, 3, (1, 2, 1, 2)),   # explicit padding
        ],
    )
    def test_space_to_depth_exact(self, h, c, k, ksz, s, padding):
        # the re-layout computes the SAME conv (see ops/conv._s2d_conv);
        # grads compared at reassociation tolerance
        params = conv.init_params(c, k, kx=ksz, ky=ksz)
        x = jnp.asarray(rand(2, h, h, c))
        kw = dict(sliding=(s, s), padding=padding)
        ref = conv.apply(params, x, space_to_depth="never", **kw)
        s2d = conv.apply(params, x, space_to_depth="always", **kw)
        np.testing.assert_allclose(
            np.asarray(s2d), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

        def loss(mode):
            return lambda p: jnp.sum(
                jnp.sin(conv.apply(p, x, space_to_depth=mode, **kw))
            )

        g1 = jax.grad(loss("never"))(params)
        g2 = jax.grad(loss("always"))(params)
        for a, b in zip(
            jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )


class TestPooling:
    def test_max_matches_naive(self):
        x = rand(2, 6, 6, 3)
        got = pooling.max_pool(x, 2, 2)
        want = x.reshape(2, 3, 2, 3, 2, 3).max(axis=(2, 4))
        np.testing.assert_allclose(got, want)

    def test_avg_matches_naive(self):
        x = rand(2, 6, 6, 3)
        got = pooling.avg_pool(x, 2, 2)
        want = x.reshape(2, 3, 2, 3, 2, 3).mean(axis=(2, 4))
        np.testing.assert_allclose(got, want, rtol=RTOL)

    def test_max_abs_keeps_sign(self):
        x = np.array([[[[-5.0], [1.0]], [[2.0], [3.0]]]], np.float32)
        got = pooling.max_abs_pool(x, 2, 2)
        assert got.reshape(()) == -5.0

    def test_max_with_offset_roundtrip(self):
        x = rand(2, 4, 4, 3)
        vals, offset = pooling.max_pool_with_offset(x, 2, 2)
        np.testing.assert_allclose(vals, pooling.max_pool(x, 2, 2))
        up = deconv.depool_with_offset(vals, offset, x.shape)
        # scattered values appear exactly at argmax positions
        mask = np.asarray(up) != 0
        np.testing.assert_allclose(np.asarray(up)[mask], np.asarray(x)[mask])

    def test_stochastic_eval_is_expectation(self):
        x = np.abs(rand(1, 4, 4, 2)) + 0.1
        got = pooling.stochastic_pool(x, 2, 2, train=False)
        p = x.reshape(1, 2, 2, 2, 2, 2)
        # windows: axes 2,4
        flat = np.moveaxis(p, (2, 4), (3, 4)).reshape(1, 2, 2, 4, 2)
        probs = flat / flat.sum(axis=3, keepdims=True)
        want = (probs * flat).sum(axis=3)
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_stochastic_all_negative_window_falls_back_to_max_abs(self):
        x = np.array([[[[-5.0], [-1.0]], [[-2.0], [-3.0]]]], np.float32)
        got = pooling.stochastic_pool(x, 2, 2, rng=jax.random.key(0), train=True)
        assert float(got.reshape(())) == -5.0

    def test_stochastic_train_picks_window_members(self):
        x = np.abs(rand(1, 4, 4, 1)) + 0.1
        got = np.asarray(
            pooling.stochastic_pool(x, 2, 2, rng=jax.random.key(0), train=True)
        )
        flat = np.moveaxis(x.reshape(1, 2, 2, 2, 2, 1), (2, 4), (3, 4)).reshape(
            1, 2, 2, 4, 1
        )
        for i in range(2):
            for j in range(2):
                assert got[0, i, j, 0] in flat[0, i, j, :, 0]


class TestLRN:
    def test_matches_naive(self):
        x = rand(2, 3, 3, 8)
        got = normalization.lrn(x, alpha=1e-4, beta=0.75, k=2.0, n=5)
        want = np.empty_like(x)
        for c in range(8):
            lo, hi = max(0, c - 2), min(8, c + 3)
            s = np.sum(np.square(x[..., lo:hi]), axis=-1)
            want[..., c] = x[..., c] / np.power(2.0 + 1e-4 * s, 0.75)
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_grad_finite(self):
        x = jnp.asarray(rand(1, 2, 2, 6))
        g = jax.grad(lambda t: jnp.sum(jnp.square(normalization.lrn(t))))(x)
        assert np.all(np.isfinite(np.asarray(g)))


class TestDropoutCutter:
    def test_dropout_eval_identity(self):
        x = rand(4, 10)
        np.testing.assert_array_equal(
            dropout.dropout(x, dropout_ratio=0.5, train=False), x
        )

    def test_dropout_preserves_mean(self):
        x = np.ones((100, 100), np.float32)
        y = dropout.dropout(
            x, dropout_ratio=0.3, rng=jax.random.key(0), train=True
        )
        assert abs(float(jnp.mean(y)) - 1.0) < 0.05

    def test_cutter(self):
        x = rand(1, 6, 8, 2)
        y = cutter.cut(x, (1, 2, 3, 0))
        assert y.shape == cutter.output_shape(x.shape, (1, 2, 3, 0)) == (1, 4, 4, 2)
        np.testing.assert_array_equal(y, x[:, 2:6, 1:5, :])


class TestDeconv:
    def test_adjoint_of_conv(self):
        """<conv(x), y> == <x, deconv(y)> with shared weights — exact adjoint."""
        params = conv.init_params(2, 3, kx=3, ky=3)
        dparams = {"weights": params["weights"]}
        x = jnp.asarray(rand(1, 6, 6, 2, seed=1))
        y = jnp.asarray(rand(1, 4, 4, 3, seed=2))
        fwd = conv.apply(params, x) - params["bias"]
        back = deconv.apply(dparams, y)
        lhs = float(jnp.sum(fwd * y))
        rhs = float(jnp.sum(x * back))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-4)

    def test_upsample(self):
        y = rand(1, 2, 2, 1)
        up = deconv.upsample(y, 2, 2)
        assert up.shape == (1, 4, 4, 1)
        np.testing.assert_allclose(up[0, :2, :2, 0], y[0, 0, 0, 0])


class TestKohonen:
    def test_winner_matches_naive(self):
        params = kohonen.init_params(4, 4, 8)
        x = rand(10, 8)
        got = np.asarray(kohonen.winners(params, x))
        w = np.asarray(params["weights"])
        want = np.argmin(
            np.sum((x[:, None, :] - w[None, :, :]) ** 2, axis=2), axis=1
        )
        np.testing.assert_array_equal(got, want)

    def test_train_moves_winner_toward_sample(self):
        params = kohonen.init_params(3, 3, 4)
        coords = kohonen.grid_coords(3, 3)
        x = np.abs(rand(1, 4)) + 1.0
        win0 = int(kohonen.winners(params, jnp.asarray(x))[0])
        d0 = np.linalg.norm(np.asarray(params["weights"])[win0] - x[0])
        new, win = kohonen.train_step(
            params,
            jnp.asarray(x),
            coords,
            learning_rate=jnp.float32(0.5),
            sigma=jnp.float32(1.0),
        )
        assert int(win[0]) == win0
        d1 = np.linalg.norm(np.asarray(new["weights"])[win0] - x[0])
        assert d1 < d0

    def test_convergence_on_clusters(self):
        """SOM should land units near two well-separated clusters."""
        rng = np.random.default_rng(0)
        a = rng.normal(0, 0.05, (50, 2)) + np.array([1.0, 0.0])
        b = rng.normal(0, 0.05, (50, 2)) + np.array([-1.0, 0.0])
        data = np.concatenate([a, b]).astype(np.float32)
        params = kohonen.init_params(4, 4, 2, weights_stddev=0.1)
        coords = kohonen.grid_coords(4, 4)
        step = jax.jit(
            lambda p, x, lr, s: kohonen.train_step(
                p, x, coords, learning_rate=lr, sigma=s
            )[0]
        )
        for i in range(100):
            lr, sigma = kohonen.decay_schedule(i, 100, sx=4, sy=4, sigma1=0.3)
            params = step(params, jnp.asarray(data), jnp.float32(lr), jnp.float32(sigma))
        w = np.asarray(params["weights"])
        d_a = np.min(np.linalg.norm(w - np.array([1.0, 0.0]), axis=1))
        d_b = np.min(np.linalg.norm(w - np.array([-1.0, 0.0]), axis=1))
        assert d_a < 0.25 and d_b < 0.25


class TestRBM:
    def test_cd_reduces_reconstruction_error(self):
        prngs = np.random.default_rng(0)
        data = (prngs.uniform(size=(64, 16)) < 0.3).astype(np.float32)
        params = rbm.init_params(16, 8)
        step = jax.jit(
            lambda p, k: rbm.cd_step(p, jnp.asarray(data), k, learning_rate=0.5)
        )
        key = jax.random.key(0)
        errs = []
        for i in range(40):
            key, sub = jax.random.split(key)
            params, err = step(params, sub)
            errs.append(float(err))
        assert np.mean(errs[-5:]) < np.mean(errs[:5])

    def test_probs_in_range(self):
        params = rbm.init_params(10, 6)
        v = (rand(4, 10) > 0).astype(np.float32)
        h = np.asarray(rbm.hidden_probs(params, v))
        assert np.all(h >= 0) and np.all(h <= 1)
