"""Prefix-cache serving: goldens, COW, eviction, preemption, leaks.

ISSUE 5 acceptance: the prefix-cached paged engine must stay a
TRANSPARENT batching layer — every completion golden-matches the
single-request ``generate()`` output — through cross-request prefix
sharing, copy-on-write divergence, LRU eviction under pool pressure,
and preemption of requests holding SHARED blocks (refcounts must keep
survivors' blocks alive).  And prefix reuse must add ZERO compiled
programs: it only skips iterations of the existing chunk program.
"""

import jax.numpy as jnp
import numpy as np

from znicz_tpu import observability as obs
from znicz_tpu.core import prng
from znicz_tpu.services.engine import PagedDecodeEngine
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 15  # never greedily emitted by this seed's LM at small budgets
HEADS = 4
T_MAX = 96
BS = 8


def _params(seed=27, max_seq=T_MAX):
    prng.seed_all(seed)
    return init_lm_params(17, 32, 2, HEADS, max_seq=max_seq)


def _reference(params, prompt, budget, eos=EOS):
    out = np.asarray(
        G.generate(
            params, jnp.asarray(prompt)[None], n_heads=HEADS,
            max_new_tokens=budget, eos_id=eos,
        )
    )[0]
    new = out[len(prompt):]
    hit = np.where(new == eos)[0]
    if len(hit):
        new = new[: hit[0] + 1]
    return np.concatenate([prompt, new])


def _engine(params, **kw):
    kw.setdefault("n_heads", HEADS)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("max_seq", T_MAX)
    kw.setdefault("admit_every", 4)
    return PagedDecodeEngine(params, **kw)


def _counter_value(name):
    m = obs.get_registry().metrics().get(name)
    return 0.0 if m is None else m.value


def _compiles_total():
    """Registry sum of the labeled znicz_serve_compiles_total family."""
    m = obs.get_registry().metrics().get("znicz_serve_compiles_total")
    if m is None:
        return 0.0
    return sum(c.value for c in m.children().values())


def _assert_no_leaks(eng):
    """Refcount-leak sweep: after every request retires and the cache
    is flushed, the free list holds the whole pool minus the reserved
    null block, and no refcount is outstanding."""
    assert eng.active == 0 and eng.prefilling == 0 and eng.pending == 0
    eng.flush_prefix_cache()
    assert len(eng._cache) == 0 and len(eng._block_hash) == 0
    assert len(eng._lru) == 0
    assert sorted(eng._free) == list(range(1, eng.n_blocks))
    assert (eng._ref == 0).all()


def _tokens(rng, n):
    return rng.integers(0, 17, (n,)).astype(np.int32)


class TestSharedPrefix:
    def test_two_requests_share_a_long_prefix(self):
        # (a) S is 2 full blocks; A = S + 5, B = S + 7 different tokens.
        # After A retires, B's admission must map S's blocks from the
        # cache and chunk-prefill ONLY the tail: prefill_chunks ==
        # ceil(tail / block_size), zero chunks for the shared part.
        params = _params()
        rng = np.random.default_rng(41)
        s = _tokens(rng, 2 * BS)
        pa = np.concatenate([s, _tokens(rng, 5)])
        pb = np.concatenate([s, _tokens(rng, 7)])
        eng = _engine(params)
        ra = eng.submit(pa, 6)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[ra].tokens, _reference(params, pa, 6)
        )
        hits0 = _counter_value("znicz_serve_prefix_hits_total")
        toks0 = _counter_value("znicz_serve_prefix_cached_tokens_total")
        chunks0 = _counter_value("znicz_serve_prefill_chunks_total")
        rb = eng.submit(pb, 6)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[rb].tokens, _reference(params, pb, 6)
        )
        # B: 23 tokens = 2 cached blocks + a 7-token tail -> ONE chunk
        assert (
            _counter_value("znicz_serve_prefill_chunks_total") - chunks0
            == 1
        )
        assert _counter_value("znicz_serve_prefix_hits_total") - hits0 == 2
        assert (
            _counter_value("znicz_serve_prefix_cached_tokens_total")
            - toks0
            == 2 * BS
        )
        st = eng.stats()["prefix_cache"]
        assert st["enabled"] and st["hits"] >= 2
        assert st["cached_tokens"] >= 2 * BS
        _assert_no_leaks(eng)

    def test_multi_turn_reuses_generated_blocks(self):
        # the cache covers GENERATED positions too: turn 2's prompt is
        # turn 1's full output, so its cached chain extends past turn
        # 1's prompt into blocks decode filled
        params = _params()
        rng = np.random.default_rng(43)
        p1 = _tokens(rng, 11)
        eng = _engine(params)
        r1 = eng.submit(p1, 8)
        eng.run()
        out1 = eng.completions[r1].tokens
        p2 = np.concatenate([out1, _tokens(rng, 4)])
        hits0 = _counter_value("znicz_serve_prefix_hits_total")
        r2 = eng.submit(p2, 5)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[r2].tokens, _reference(params, p2, 5)
        )
        # out1 is 11 + ~8 tokens: at least the first 2 blocks (16
        # positions, the last of them decode-written) must have hit
        assert _counter_value("znicz_serve_prefix_hits_total") - hits0 >= 2
        _assert_no_leaks(eng)

    def test_prefix_hits_do_not_consume_allocation(self):
        # a hit maps resident blocks: submitting B after A must
        # allocate only B's tail blocks (white-box: pool accounting)
        params = _params()
        rng = np.random.default_rng(45)
        s = _tokens(rng, 2 * BS)
        eng = _engine(params, batch_size=1)
        eng.submit(np.concatenate([s, _tokens(rng, 3)]), 4)
        eng.run()
        cached = len(eng._lru)
        assert cached >= 2  # S's blocks are cache-only now
        eng.submit(np.concatenate([s, _tokens(rng, 6)]), 4)
        eng._admit_pending()
        row = eng._row_blocks[0]
        assert len(row) == 2  # mapped, not allocated: tail not yet run
        assert all(eng._ref[b] == 1 for b in row)
        eng.run()
        _assert_no_leaks(eng)


class TestCopyOnWrite:
    def test_fully_cached_prompt_cow_reruns_final_block(self):
        # (b) an identical block-aligned prompt resubmitted: every block
        # hits, but the first token needs logits, so the final block's
        # chunk re-runs after a COW split — the CACHED block must stay
        # pristine (a third submission hits it again), and the output
        # must golden-match
        params = _params()
        rng = np.random.default_rng(47)
        p = _tokens(rng, 2 * BS)  # exactly 2 blocks, aligned
        ref = _reference(params, p, 6)
        eng = _engine(params)
        r1 = eng.submit(p, 6)
        eng.run()
        np.testing.assert_array_equal(eng.completions[r1].tokens, ref)
        chunks0 = _counter_value("znicz_serve_prefill_chunks_total")
        r2 = eng.submit(p, 6)
        eng.run()
        np.testing.assert_array_equal(eng.completions[r2].tokens, ref)
        st = eng.stats()["prefix_cache"]
        assert st["cow_splits"] >= 1
        # only the re-run chunk executed (1 of 2 blocks)
        assert (
            _counter_value("znicz_serve_prefill_chunks_total") - chunks0
            == 1
        )
        # the COW preserved the cache: a third run hits both blocks again
        hits0 = _counter_value("znicz_serve_prefix_hits_total")
        r3 = eng.submit(p, 6)
        eng.run()
        np.testing.assert_array_equal(eng.completions[r3].tokens, ref)
        assert _counter_value("znicz_serve_prefix_hits_total") - hits0 == 2
        _assert_no_leaks(eng)

    def test_divergence_mid_block_misses_from_that_block_on(self):
        # (b) divergence MID-block: B shares only A's first block-and-a-
        # half of tokens; the chain must hit block 0 and miss block 1,
        # and both outputs golden-match
        params = _params()
        rng = np.random.default_rng(49)
        pa = _tokens(rng, 2 * BS + 3)
        pb = pa.copy()[: 2 * BS]
        pb[BS + 4] = (pb[BS + 4] + 1) % 17  # diverge inside block 1
        eng = _engine(params)
        ra = eng.submit(pa, 5)
        eng.run()
        hits0 = _counter_value("znicz_serve_prefix_hits_total")
        miss0 = _counter_value("znicz_serve_prefix_misses_total")
        rb = eng.submit(pb, 5)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[ra].tokens, _reference(params, pa, 5)
        )
        np.testing.assert_array_equal(
            eng.completions[rb].tokens, _reference(params, pb, 5)
        )
        assert _counter_value("znicz_serve_prefix_hits_total") - hits0 == 1
        assert (
            _counter_value("znicz_serve_prefix_misses_total") - miss0 == 1
        )
        _assert_no_leaks(eng)

    def test_decode_write_guard_copies_shared_content(self):
        # white-box: force the decode write-guard's COPYING split by
        # caching the row's tail block mid-flight (as an eager publish-
        # on-fill policy would).  The copy must preserve the prompt's
        # K/V — the golden catches a miscopy — and the original block's
        # content stays cached
        params = _params()
        rng = np.random.default_rng(51)
        p = _tokens(rng, 5)
        eng = _engine(params, batch_size=1)
        eng.submit(p, 8)
        eng._admit_pending()
        eng._prefill_tick()  # admitted: block 0 holds the prompt K/V
        blk = int(eng._row_blocks[0][0])
        eng._cache[b"eager-fill"] = blk
        eng._block_hash[blk] = b"eager-fill"
        eng.run()
        comp = next(iter(eng.completions.values()))
        np.testing.assert_array_equal(
            comp.tokens, _reference(params, p, 8)
        )
        st = eng.stats()
        assert st["prefix_cache"]["cow_splits"] >= 1
        assert ("cow", BS) in st["programs"]
        _assert_no_leaks(eng)


class TestEvictionUnderPressure:
    def test_cache_evicts_before_preemption_and_readmits(self):
        # (c) a pool too small for two cached prefixes: the second
        # stream must EVICT cache (never preempt — nobody is live), and
        # re-admitting the evicted prefix recomputes and still matches
        params = _params()
        rng = np.random.default_rng(53)
        pa = _tokens(rng, 2 * BS)
        pb = _tokens(rng, 2 * BS)
        # 4 usable blocks: one 16-token prompt + budget 8 peaks at 3,
        # leaving too little to keep both retired prefixes cached
        eng = _engine(params, batch_size=1, n_blocks=5)
        ra = eng.submit(pa, 8)
        eng.run()
        ev0 = _counter_value("znicz_serve_prefix_evictions_total")
        rb = eng.submit(pb, 8)
        eng.run()
        assert (
            _counter_value("znicz_serve_prefix_evictions_total") - ev0
            >= 1
        )
        assert eng.stats()["preemptions"] == 0
        # pa's chain was (at least partly) evicted; resubmit: recompute
        # whatever is gone, goldens regardless
        r2 = eng.submit(pa, 8)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[ra].tokens, eng.completions[r2].tokens
        )
        np.testing.assert_array_equal(
            eng.completions[r2].tokens, _reference(params, pa, 8)
        )
        np.testing.assert_array_equal(
            eng.completions[rb].tokens, _reference(params, pb, 8)
        )
        _assert_no_leaks(eng)


class TestPreemptionWithSharedBlocks:
    def test_survivor_keeps_shared_blocks_through_preemption(self):
        # (d) A and B both map S's cached blocks; pool pressure preempts
        # the younger B — the refcounts must keep S's blocks alive for
        # A, and BOTH outputs still golden-match after B's recompute
        # seed 59: neither request greedily emits EOS inside its
        # 12-token budget (verified against the reference), so decode
        # growth genuinely reaches peak block demand
        params = _params()
        rng = np.random.default_rng(59)
        s = _tokens(rng, 2 * BS)
        pa = np.concatenate([s, _tokens(rng, 3)])
        pb = np.concatenate([s, _tokens(rng, 6)])
        # pool: 6 usable.  Seeding S caches 2 blocks; A and B map them
        # (shared, ref 2) and need 2 + 3 private blocks at peak — one
        # more than the 4 the free list holds, and the only cached
        # blocks are the CLAIMED (unevictable) shared pair, so the
        # youngest (B) must be preempted, readmitted after A retires,
        # and recompute — with A's output untouched because refcounts
        # kept the shared pair alive through B's release
        eng = _engine(params, n_blocks=7)
        r0 = eng.submit(s, 1)
        eng.run()
        pre0 = eng.stats()["preemptions"]
        ia, ib = eng.submit(pa, 12), eng.submit(pb, 12)
        eng.run()
        st = eng.stats()
        assert st["preemptions"] - pre0 >= 1
        np.testing.assert_array_equal(
            eng.completions[ia].tokens, _reference(params, pa, 12)
        )
        np.testing.assert_array_equal(
            eng.completions[ib].tokens, _reference(params, pb, 12)
        )
        assert eng.completions[r0] is not None
        _assert_no_leaks(eng)


class TestZeroNewPrograms:
    def test_prefix_reuse_compiles_nothing(self):
        # (e) after a cold request warms the ONE prefill program and the
        # decode-window rung, a prefix-sharing request adds ZERO
        # compiled programs: reuse only SKIPS iterations of the existing
        # chunk program.  Cross-checked against the engine ledger, the
        # process-wide jit caches AND the registry compile counter.
        params = _params()
        rng = np.random.default_rng(59)
        s = _tokens(rng, 2 * BS)
        eng = _engine(params)
        # cold: 21-token prompt, budget 6 -> window rung 4 blocks
        ra = eng.submit(np.concatenate([s, _tokens(rng, 5)]), 6)
        eng.run()
        st0 = eng.compile_stats()
        c0 = _compiles_total()
        # warm: shares S, same window rung, cache hits > 0
        rb = eng.submit(np.concatenate([s, _tokens(rng, 7)]), 6)
        eng.run()
        st1 = eng.compile_stats()
        assert eng.stats()["prefix_cache"]["hits"] >= 2
        assert st1["programs"] == st0["programs"]
        assert st1["prefill_jit_entries"] == st0["prefill_jit_entries"]
        assert (
            st1["paged_chunk_jit_entries"]
            == st0["paged_chunk_jit_entries"]
        )
        assert st1["cow_jit_entries"] == st0["cow_jit_entries"]
        assert _compiles_total() == c0
        for rid in (ra, rb):
            assert eng.completions[rid].n_new >= 1
        _assert_no_leaks(eng)


class TestLeakSweep:
    def test_mixed_stream_leaves_no_dangling_refcounts(self):
        # (f) sharing + COW + eviction + preemption in one stream, then
        # the sweep: free-list == pool minus the null block, refs all 0
        params = _params()
        rng = np.random.default_rng(61)
        s = _tokens(rng, 2 * BS)
        eng = _engine(params, n_blocks=9)
        eng.submit(s, 1)
        eng.run()
        ids = [
            eng.submit(np.concatenate([s, _tokens(rng, k)]), 10)
            for k in (3, 6, 4)
        ]
        eng.submit(s, 6)  # fully-cached resubmit: COW re-run
        eng.run()
        for rid in ids:
            assert eng.completions[rid].finish_reason in ("eos", "budget")
        _assert_no_leaks(eng)
        # flushing again is idempotent
        assert eng.flush_prefix_cache() == 0

    def test_disabled_cache_keeps_plain_free_list(self):
        params = _params()
        rng = np.random.default_rng(63)
        eng = _engine(params, prefix_cache=False)
        eng.submit(_tokens(rng, 2 * BS), 6)
        eng.run()
        st = eng.stats()["prefix_cache"]
        assert not st["enabled"]
        assert st["hits"] == st["cached_tokens"] == 0
        assert len(eng._free) == eng.usable_blocks
        _assert_no_leaks(eng)


class TestTtft:
    def test_completions_carry_ttft(self):
        params = _params()
        rng = np.random.default_rng(65)
        eng = _engine(params)
        rid = eng.submit(_tokens(rng, 9), 4)
        eng.run()
        c = eng.completions[rid]
        assert c.ttft_s is not None and 0 < c.ttft_s <= c.latency_s
