"""Paged KV-cache engine: goldens, block pool, preemption, compiles.

The paged engine (`services.engine.PagedDecodeEngine`) must be a
TRANSPARENT batching layer exactly like the dense one: every
completion's tokens equal the single-request ``generate()`` output for
that prompt (up to EOS), through chunked prefill, lazy block
allocation, block reuse after retirement, and preemption-with-
recompute under pool pressure.  And the whole stream must stay
recompile-free on ONE prefill program (the [1, block_size] chunk —
every prompt length) plus a logarithmic x2 ladder of decode-chunk
variants keyed by the active block-window rung — verified against the
engine's ledger, the process-wide jit caches, AND the
``znicz_serve_compiles_total`` registry counter (the ISSUE 4 CI
criterion: zero recompiles after warmup across a growth-past-one-block
stream).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.core import prng
from znicz_tpu.services.engine import DecodeEngine, PagedDecodeEngine
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 14
HEADS = 4
T_MAX = 64
BS = 8  # block size under test (buckets irrelevant on the paged path)


def _params(seed=27, max_seq=T_MAX):
    prng.seed_all(seed)
    return init_lm_params(17, 32, 2, HEADS, max_seq=max_seq)


def _reference(params, prompt, budget, eos=EOS):
    """Single-request greedy generate(), trimmed at (and including) the
    first EOS — what the engine promises each request, paging aside."""
    out = np.asarray(
        G.generate(
            params, jnp.asarray(prompt)[None], n_heads=HEADS,
            max_new_tokens=budget, eos_id=eos,
        )
    )[0]
    new = out[len(prompt):]
    hit = np.where(new == eos)[0]
    if len(hit):
        new = new[: hit[0] + 1]
    return np.concatenate([prompt, new])


def _engine(params, **kw):
    kw.setdefault("n_heads", HEADS)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("max_seq", T_MAX)
    kw.setdefault("admit_every", 4)
    return PagedDecodeEngine(params, **kw)


def _compiles_total():
    """Registry sum of znicz_serve_compiles_total over the PAGED kinds."""
    m = obs.get_registry().metrics().get("znicz_serve_compiles_total")
    if m is None:
        return 0.0
    return sum(
        c.value for key, c in m.children().items()
        if key[0] in ("prefill", "paged_chunk")
    )


def _counter_value(name):
    m = obs.get_registry().metrics().get(name)
    return 0.0 if m is None else m.value


def _hist_count(name):
    m = obs.get_registry().metrics().get(name)
    child = None if m is None else m.children().get(())
    return 0 if child is None else child.count


class TestPagedGoldens:
    def test_mixed_lengths_including_left_padded_rows(self):
        # 5 ragged requests through 2 slots: lengths 5 and 3 left-pad
        # inside one block, 12 and 17 span multiple chunks; slot reuse,
        # chunked prefill and the shared pool must all stay invisible
        params = _params()
        gen = np.random.default_rng(7)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32)
            for n in (5, 12, 3, 9, 17)
        ]
        budgets = [6, 4, 8, 5, 7]
        eng = _engine(params)
        ids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        comps = eng.run()
        assert len(comps) == 5 and eng.pending == 0 and eng.active == 0
        for p, b, rid in zip(prompts, budgets, ids):
            np.testing.assert_array_equal(
                eng.completions[rid].tokens, _reference(params, p, b)
            )
        # every block returned to the pool at retirement
        st = eng.stats()
        assert st["kv_backend"] == "paged"
        assert st["pool_blocks_free"] == st["pool_blocks"]
        assert st["preemptions"] == 0
        c = comps[0]
        assert c.latency_s > 0 and c.tokens_per_sec > 0
        assert set(eng.stats()["phases"]) >= {"admit", "decode"}

    def test_long_prompt_prefills_in_chunks(self):
        # a 17-token prompt pads to 24 = 3 chunks of the ONE compiled
        # prefill program; the chunk counter proves the interleaving
        # unit actually ran per-block
        params = _params()
        gen = np.random.default_rng(9)
        p = gen.integers(0, 17, (17,)).astype(np.int32)
        chunks0 = _counter_value("znicz_serve_prefill_chunks_total")
        eng = _engine(params)
        rid = eng.submit(p, 5)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[rid].tokens, _reference(params, p, 5)
        )
        chunks1 = _counter_value("znicz_serve_prefill_chunks_total")
        assert chunks1 - chunks0 == 3

    def test_budget_one_and_immediate_eos_retire_at_admit(self):
        params = _params()
        gen = np.random.default_rng(13)
        p = gen.integers(0, 17, (6,)).astype(np.int32)
        eng = _engine(params)
        rid = eng.submit(p, 1)
        (comp,) = eng.run()
        assert comp.id == rid and comp.n_new == 1
        assert comp.finish_reason in ("eos", "budget")
        np.testing.assert_array_equal(
            comp.tokens, _reference(params, p, 1)
        )
        assert eng.stats()["pool_blocks_free"] == eng.usable_blocks

    def test_sampling_mode_deterministic_and_in_vocab(self):
        params = _params()
        gen = np.random.default_rng(11)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32) for n in (4, 10, 6)
        ]

        def serve():
            eng = _engine(
                params, admit_every=3, temperature=0.9,
                rng=jax.random.key(8),
            )
            ids = [eng.submit(p, 5) for p in prompts]
            eng.run()
            return [eng.completions[i].tokens for i in ids]

        a, b = serve(), serve()
        for ta, tb, p in zip(a, b, prompts):
            np.testing.assert_array_equal(ta, tb)
            new = ta[len(p):]
            assert (new >= 0).all() and (new < 17).all()
            assert 1 <= len(new) <= 5


class TestBlockPool:
    def test_retire_frees_and_readmit_reuses_blocks(self):
        # white-box allocator check: a retired request's blocks return
        # to the pool and the next admission reuses them (LIFO free
        # list) instead of fragmenting toward fresh blocks.  Prefix
        # cache OFF: with it on, retired blocks park in the cache
        # instead of the free list (tests/test_engine_prefix.py)
        params = _params()
        gen = np.random.default_rng(21)
        pa = gen.integers(0, 17, (12,)).astype(np.int32)  # 2 blocks
        pb = gen.integers(0, 17, (10,)).astype(np.int32)
        eng = _engine(params, batch_size=1, prefix_cache=False)
        ra = eng.submit(pa, 4)
        eng._admit_pending()
        # nothing is decoding, so the whole prompt prefills this tick:
        # both blocks of the padded-16 prompt get allocated
        eng._prefill_tick()
        used_a = set(eng._row_blocks[0])
        assert len(used_a) == 2
        comps = eng.run()
        assert [c.id for c in comps] == [ra]
        assert len(eng._free) == eng.usable_blocks  # all returned
        rb = eng.submit(pb, 4)
        eng._admit_pending()
        eng._prefill_tick()
        used_b = set(eng._row_blocks[0])
        assert used_b & used_a  # reuse, not fresh allocation
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[rb].tokens, _reference(params, pb, 4)
        )

    def test_pool_gauges_track_occupancy(self):
        params = _params()
        gen = np.random.default_rng(23)
        eng = _engine(params, batch_size=1)
        eng.submit(gen.integers(0, 17, (5,)).astype(np.int32), 4)
        eng._admit_pending()
        eng._prefill_tick()
        m = obs.get_registry().metrics()["znicz_serve_kv_pool_blocks"]
        free = m.children()[("free",)].value
        used = m.children()[("used",)].value
        # gauges are last-setter-wins; this engine allocated last, so
        # they reflect ITS pool: one prompt block out
        assert used == len(eng._row_blocks[0]) == 1
        assert free == eng.usable_blocks - 1
        assert free + used == eng.usable_blocks
        eng.run()
        m = obs.get_registry().metrics()["znicz_serve_kv_pool_blocks"]
        assert m.children()[("used",)].value == 0

    def test_lazy_allocation_grows_with_decode(self):
        # a 5-token prompt (1 block) with a 20-token budget must NOT
        # reserve its worst case up front: blocks arrive as decode
        # crosses boundaries
        params = _params()
        gen = np.random.default_rng(25)
        p = gen.integers(0, 17, (5,)).astype(np.int32)
        eng = _engine(
            params, batch_size=1, eos_id=15, admit_every=4,
            prefix_cache=False,
        )
        eng.submit(p, 20)
        eng._admit_pending()
        eng._prefill_tick()
        n0 = len(eng._row_blocks[0])
        assert n0 == 1  # prompt block only — nothing reserved for decode
        eng._run_chunk()
        assert len(eng._row_blocks[0]) >= n0  # grew on demand
        eng.run()
        assert len(eng._free) == eng.usable_blocks


class TestPreemption:
    def test_pool_pressure_preempts_youngest_and_recomputes(self):
        # pool of 6 usable blocks; two full-budget requests need 5 + 4
        # blocks at peak -> the YOUNGER (second) must be preempted,
        # requeued, and still match its dense golden after recompute.
        # eos_id=15 is never greedily emitted by this seed's LM, so
        # both rows run their whole budget (verified by the reference).
        params = _params()
        gen = np.random.default_rng(7)
        pa = gen.integers(0, 17, (10,)).astype(np.int32)
        pb = gen.integers(0, 17, (5,)).astype(np.int32)
        ra = _reference(params, pa, 20, eos=15)
        rb = _reference(params, pb, 20, eos=15)
        assert len(ra) - len(pa) == 20 and len(rb) - len(pb) == 20
        before = _counter_value("znicz_serve_preemptions_total")
        admitted0 = _counter_value("znicz_serve_requests_admitted_total")
        ttft0 = _hist_count("znicz_serve_ttft_seconds")
        eng = _engine(params, eos_id=15, n_blocks=7)
        ia, ib = eng.submit(pa, 20), eng.submit(pb, 20)
        comps = eng.run()
        assert len(comps) == 2
        # ONE admission event per request, preemption-recompute aside:
        # readmission must not re-fire admitted/TTFT (PR-3 invariant:
        # admit events == requests)
        assert (
            _counter_value("znicz_serve_requests_admitted_total")
            - admitted0 == 2
        )
        assert _hist_count("znicz_serve_ttft_seconds") - ttft0 == 2
        np.testing.assert_array_equal(eng.completions[ia].tokens, ra)
        np.testing.assert_array_equal(eng.completions[ib].tokens, rb)
        st = eng.stats()
        assert st["preemptions"] >= 1
        after = _counter_value("znicz_serve_preemptions_total")
        assert after - before == st["preemptions"]
        # the pool is whole again
        assert st["pool_blocks_free"] == st["pool_blocks"]
        # the OLDER request was never preempted: it retired first
        assert comps[0].id == ia

    def test_single_request_never_self_deadlocks(self):
        # a request whose worst case equals the whole pool must run to
        # completion alone (validation guarantees it fits; preemption
        # must not evict the only occupant into a livelock)
        params = _params()
        gen = np.random.default_rng(29)
        p = gen.integers(0, 17, (10,)).astype(np.int32)  # padded 16
        # padded 16 + budget 24 = 40 tokens = 5 blocks = whole pool
        eng = _engine(params, batch_size=1, eos_id=15, n_blocks=6)
        rid = eng.submit(p, 24)
        eng.run()
        np.testing.assert_array_equal(
            eng.completions[rid].tokens, _reference(params, p, 24, eos=15)
        )
        assert eng.stats()["preemptions"] == 0


class TestPagedCompiles:
    """ISSUE 4 CI criterion: exactly one compile per paged program
    across a growth-past-one-block stream, cross-checked against
    compile_stats AND the znicz_serve_compiles_total registry counter;
    a second same-geometry engine adds ZERO."""

    def test_two_programs_cover_growth_past_one_block(self):
        params = _params()
        # unique geometry for this test (block_size 4, admit_every 5,
        # batch 3) so the process-wide first-compile ledger and jit
        # caches attribute deltas to THIS stream alone
        kw = dict(block_size=4, admit_every=5, batch_size=3, eos_id=15)
        structure = (True, 0, False)  # greedy, no top_k, no nucleus

        def stream(eng):
            # mixed lengths; budgets push every row well past its first
            # block (growth exercises lazy allocation + the chunk
            # program at several depths).  Fresh identical rng per call:
            # warm and cold streams are byte-identical, so the warm run
            # can reach no rung the cold one did not
            gen = np.random.default_rng(31)
            for n, b in ((3, 9), (6, 11), (10, 7), (5, 12)):
                eng.submit(
                    gen.integers(0, 17, (n,)).astype(np.int32), b
                )
            return eng.run()

        c0 = _compiles_total()
        eng = _engine(params, **kw)
        stream(eng)
        st = eng.compile_stats()
        # exactly ONE prefill program, every prompt length included,
        # plus decode-chunk variants keyed ONLY by the x2 window rung
        # (logarithmic in T_max/block_size — never per request shape)
        assert st["programs"][("prefill", 4, structure)] == 1
        chunk_keys = [
            k for k in st["programs"] if k[0] == "paged_chunk"
        ]
        assert chunk_keys and all(
            st["programs"][k] == 1 for k in chunk_keys
        )
        windows = sorted(k[3] for k in chunk_keys)
        assert len(set(windows)) == len(windows)  # one per rung
        assert all(w & (w - 1) == 0 for w in windows)  # powers of two
        assert st["n_programs"] == 1 + len(chunk_keys)
        c1 = _compiles_total()
        # registry agrees: every ledger entry was a true first compile
        assert c1 - c0 == st["n_programs"]
        n_pre = st["prefill_jit_entries"]
        n_chn = st["paged_chunk_jit_entries"]

        # warm path: a fresh same-geometry engine over a fresh stream
        # compiles NOTHING (jit caches untouched, registry delta zero)
        eng2 = _engine(params, **kw)
        stream(eng2)
        st2 = eng2.compile_stats()
        assert st2["prefill_jit_entries"] == n_pre
        assert st2["paged_chunk_jit_entries"] == n_chn
        assert _compiles_total() == c1
        assert st2["programs"] == st["programs"]
        assert st2["program_hits"] > 0

    def test_goldens_hold_across_the_growth_stream(self):
        params = _params()
        gen = np.random.default_rng(33)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32)
            for n in (3, 6, 10, 5)
        ]
        budgets = [9, 11, 7, 12]
        eng = _engine(
            params, block_size=4, admit_every=5, batch_size=3, eos_id=15
        )
        ids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        eng.run()
        for p, b, rid in zip(prompts, budgets, ids):
            np.testing.assert_array_equal(
                eng.completions[rid].tokens,
                _reference(params, p, b, eos=15),
            )


class TestConcurrencyBeyondDense:
    def test_pool_packs_more_rows_than_the_dense_layout(self):
        # the ISSUE acceptance criterion: concurrent rows whose summed
        # DENSE demand exceeds the memory budget.  16 usable blocks x 8
        # = 128 cached tokens; a dense [n_slots, T_max=64] layout in
        # the same memory holds 2 slots — the paged engine decodes 4
        # rows at once (4 * 64 = 256 dense-tokens of demand)
        params = _params()
        gen = np.random.default_rng(35)
        prompts = [
            gen.integers(0, 17, (5,)).astype(np.int32) for _ in range(4)
        ]
        eng = _engine(
            params, batch_size=4, n_blocks=17, eos_id=15, admit_every=2
        )
        ids = [eng.submit(p, 9) for p in prompts]
        eng.run()
        for p, rid in zip(prompts, ids):
            np.testing.assert_array_equal(
                eng.completions[rid].tokens,
                _reference(params, p, 9, eos=15),
            )
        st = eng.stats()
        dense_slots_same_memory = (st["pool_blocks"] * BS) // T_MAX
        assert dense_slots_same_memory == 2
        assert st["peak_active"] == 4
        assert st["peak_active"] * T_MAX > st["pool_blocks"] * BS
        assert st["preemptions"] == 0  # fits — pressure never triggered


class TestPagedValidation:
    def test_submit_names_the_paged_backend(self):
        params = _params()
        eng = _engine(params, n_blocks=5)  # 4 usable blocks = 32 tokens
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.asarray([], np.int32), 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.asarray([1, 2], np.int32), 0)
        with pytest.raises(ValueError, match="paged KV pool"):
            eng.submit(np.arange(5, dtype=np.int32), 30)  # 8+30 -> 5 blk
        with pytest.raises(ValueError, match="positional window"):
            eng.submit(np.arange(5, dtype=np.int32), 60)  # 8+60 > t_max

    def test_dense_submit_names_the_dense_backend(self):
        params = _params()
        eng = DecodeEngine(params, n_heads=HEADS, eos_id=EOS, batch_size=2)
        with pytest.raises(ValueError, match="dense KV buffer"):
            eng.submit(np.arange(5, dtype=np.int32), 60)

    def test_constructor_validation(self):
        params = _params()
        with pytest.raises(ValueError, match="block_size"):
            _engine(params, block_size=0)
        with pytest.raises(ValueError, match="n_blocks"):
            _engine(params, n_blocks=1)
