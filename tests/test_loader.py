"""Tests for the loader layer: split bookkeeping, masking, shuffling."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.loader import FullBatchLoader, datasets, normalizers
from znicz_tpu.loader.base import split_sizes


def _loader(n_train=25, bs=10, **kw):
    x = np.arange(n_train * 4, dtype=np.float32).reshape(n_train, 4)
    y = np.arange(n_train, dtype=np.int32) % 3
    return FullBatchLoader({"train": x}, {"train": y}, minibatch_size=bs, **kw)


class TestFullBatchLoader:
    def test_static_shapes_and_mask(self):
        ld = _loader(25, 10, shuffle=False)
        batches = list(ld.batches("train"))
        assert len(batches) == 3
        for mb in batches:
            assert mb.data.shape == (10, 4)
            assert mb.mask.shape == (10,)
        # last batch: 5 valid rows
        assert batches[-1].mask.sum() == 5.0
        assert batches[0].mask.sum() == 10.0

    def test_covers_all_samples_once(self):
        ld = _loader(25, 10)
        seen = []
        for mb in ld.batches("train"):
            seen.extend(mb.indices[mb.mask > 0].tolist())
        assert sorted(seen) == list(range(25))

    def test_shuffle_changes_order_deterministically(self):
        prng.seed_all(7)
        ld = _loader(25, 25)
        first = next(iter(ld.batches("train"))).indices.copy()
        second = next(iter(ld.batches("train"))).indices.copy()
        assert not np.array_equal(first, second)  # reshuffled between epochs
        # same seed -> same orders
        prng.seed_all(7)
        ld2 = _loader(25, 25)
        np.testing.assert_array_equal(
            next(iter(ld2.batches("train"))).indices, first
        )

    def test_labels_follow_indices(self):
        ld = _loader(12, 5)
        for mb in ld.batches("train"):
            np.testing.assert_array_equal(mb.labels, mb.indices % 3)

    def test_epoch_iterates_splits(self):
        x = np.zeros((8, 2), np.float32)
        ld = FullBatchLoader(
            {"train": x, "valid": x[:4], "test": x[:2]},
            {"train": np.zeros(8, np.int32)},
            minibatch_size=4,
        )
        tags = [split for split, _ in ld.epoch()]
        assert tags == ["train", "train", "valid", "test"]
        assert ld.epoch_number == 1

    def test_state_roundtrip(self):
        ld = _loader(25, 10)
        list(ld.batches("train"))
        state = ld.state_dict()
        ld2 = _loader(25, 10)
        ld2.load_state_dict(state)
        np.testing.assert_array_equal(
            ld._split_order("train"), ld2._split_order("train")
        )

    def test_normalization_mean_disp(self):
        ld = _loader(20, 20, normalization="mean_disp", shuffle=False)
        mb = next(iter(ld.batches("train")))
        np.testing.assert_allclose(mb.data.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(mb.data.std(axis=0), 1.0, atol=1e-4)


class TestNormalizers:
    def test_linear_range(self):
        data = np.array([[0.0, 10.0], [5.0, 20.0]], np.float32)
        st = normalizers.fit("linear", data)
        out = normalizers.apply(st, data)
        assert out.min() == -1.0 and out.max() == 1.0

    def test_range(self):
        st = normalizers.fit("range", np.zeros((1, 1)), scale=255.0, shift=-0.5)
        out = normalizers.apply(st, np.array([[255.0]]))
        np.testing.assert_allclose(out, [[0.5]])

    def test_external_mean(self):
        st = normalizers.fit(
            "external_mean", np.zeros((1, 2)), mean=np.array([1.0, 2.0])
        )
        np.testing.assert_allclose(
            normalizers.apply(st, np.array([[1.0, 2.0]])), [[0.0, 0.0]]
        )


class TestDatasets:
    def test_mnist_synthetic_shapes(self):
        ld = datasets.mnist(n_train=50, n_test=20, minibatch_size=25)
        assert ld.class_lengths == {"train": 50, "test": 20}
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (25, 784)
        assert mb.labels.min() >= 0 and mb.labels.max() < 10

    def test_mnist_conv_layout(self):
        ld = datasets.mnist(n_train=10, n_test=4, flat=False, minibatch_size=10)
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (10, 28, 28, 1)

    def test_mnist_validation_split(self):
        ld = datasets.mnist(n_train=100, n_test=10, validation_ratio=0.2)
        assert ld.class_lengths["valid"] == 20
        assert ld.class_lengths["train"] == 80

    def test_cifar_synthetic(self):
        ld = datasets.cifar10(n_train=20, n_test=8, minibatch_size=10)
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (10, 32, 32, 3)

    def test_wine(self):
        ld = datasets.wine()
        assert ld.class_lengths["train"] == 178
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (10, 13)

    def test_determinism_under_seed(self):
        prng.seed_all(42)
        a = datasets.mnist(n_train=10, n_test=5)
        prng.seed_all(42)
        b = datasets.mnist(n_train=10, n_test=5)
        np.testing.assert_array_equal(a.data["train"], b.data["train"])


class TestReviewRegressions:
    def test_partial_mnist_dir_raises(self, tmp_path):
        # only a labels file present -> must not silently mix real/synthetic
        import gzip
        import struct

        lab = tmp_path / "t10k-labels-idx1-ubyte.gz"
        with gzip.open(lab, "wb") as f:
            f.write(struct.pack(">ii", 0x00000801, 2) + bytes([1, 2]))
        im = tmp_path / "t10k-images-idx3-ubyte.gz"
        with gzip.open(im, "wb") as f:
            f.write(
                struct.pack(">iiii", 0x00000803, 2, 2, 2) + bytes(8)
            )
        import pytest

        with pytest.raises(FileNotFoundError):
            datasets.mnist(str(tmp_path))

    def test_normalizer_without_train_split_raises(self):
        import pytest

        with pytest.raises(ValueError):
            FullBatchLoader(
                {"valid": np.zeros((4, 2), np.float32)}, normalization="linear"
            )

    def test_resume_reproduces_shuffle_stream(self):
        prng.seed_all(5)
        ld = _loader(25, 25)
        list(ld.batches("train"))
        state = ld.state_dict()
        later = [next(iter(ld.batches("train"))).indices for _ in range(3)]
        # "restart the process": fresh prng registry, different seed history
        prng.reset()
        prng.seed_all(999)
        ld2 = _loader(25, 25)
        ld2.load_state_dict(state)
        resumed = [next(iter(ld2.batches("train"))).indices for _ in range(3)]
        for a, b in zip(later, resumed):
            np.testing.assert_array_equal(a, b)


class TestBalancedShuffle:
    def test_every_batch_has_proportional_mix(self):
        # 90/10 imbalance: with balanced=True each size-10 batch holds ~1
        # minority sample instead of clumping
        prng.seed_all(3)
        x = np.zeros((100, 4), np.float32)
        y = np.array([0] * 90 + [1] * 10, np.int32)
        ld = FullBatchLoader(
            {"train": x}, {"train": y}, minibatch_size=10, balanced=True
        )
        for mb in ld.batches("train"):
            minority = int((mb.labels[mb.mask > 0] == 1).sum())
            assert minority in (0, 1, 2)  # near-proportional, never clumped
        # all samples still served exactly once
        seen = np.concatenate(
            [mb.indices[mb.mask > 0] for mb in ld.batches("train")]
        )
        assert sorted(seen.tolist()) == list(range(100))

    def test_unbalanced_default_unchanged(self):
        prng.seed_all(3)
        x = np.zeros((20, 2), np.float32)
        ld = FullBatchLoader({"train": x}, minibatch_size=5)
        assert ld.balanced is False
        list(ld.batches("train"))


class TestImageDirectoryLoader:
    def _make_tree(self, tmp_path, n_per_class=4, classes=("cat", "dog")):
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.image as mpimg

        rng = np.random.default_rng(0)
        for split, n in (("train", n_per_class), ("test", 2)):
            for ci, cls in enumerate(classes):
                d = tmp_path / split / cls
                d.mkdir(parents=True, exist_ok=True)
                for i in range(n):
                    img = rng.random((8, 8, 3)).astype(np.float32)
                    img[:, :, ci % 3] = 1.0  # class-correlated channel
                    mpimg.imsave(str(d / f"{i}.png"), img)
        return tmp_path

    def test_loads_and_labels(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader

        root_dir = self._make_tree(tmp_path)
        ld = ImageDirectoryLoader(str(root_dir), minibatch_size=4)
        assert ld.class_lengths == {"train": 8, "test": 4}
        assert ld.classes == ["cat", "dog"]
        assert ld.sample_shape == (8, 8, 3)
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (4, 8, 8, 3)
        assert mb.data.max() <= 1.0
        # labels come from directory names
        seen = set()
        for b in ld.batches("train"):
            seen.update(b.labels[b.mask > 0].tolist())
        assert seen == {0, 1}

    def test_resize_and_grayscale(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader

        root_dir = self._make_tree(tmp_path)
        ld = ImageDirectoryLoader(
            str(root_dir),
            target_shape=(4, 4),
            grayscale=True,
            minibatch_size=4,
        )
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (4, 4, 4, 1)

    def test_missing_dir_raises(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader

        with pytest.raises(FileNotFoundError):
            ImageDirectoryLoader(str(tmp_path / "nope"))

    def test_balanced_uses_directory_labels(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader

        root_dir = self._make_tree(tmp_path, n_per_class=8)
        ld = ImageDirectoryLoader(
            str(root_dir), minibatch_size=4, balanced=True
        )
        labels = ld.split_labels("train")
        assert sorted(labels.tolist()) == [0] * 8 + [1] * 8
        for mb in ld.batches("train"):
            valid = mb.labels[mb.mask > 0]
            assert set(valid.tolist()) == {0, 1}  # every batch mixed

    def test_empty_class_dir_ignored(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader

        root_dir = self._make_tree(tmp_path)
        (root_dir / "train" / "phantom").mkdir()
        (root_dir / "train" / "phantom" / "notes.txt").write_text("x")
        ld = ImageDirectoryLoader(str(root_dir), minibatch_size=4)
        assert ld.classes == ["cat", "dog"]

    def test_grayscale_inferred_shape(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader

        root_dir = self._make_tree(tmp_path)
        ld = ImageDirectoryLoader(
            str(root_dir), grayscale=True, minibatch_size=4
        )
        # inferred target must honor grayscale, and averaging (not
        # red-channel slicing) must be used: cat images have red=1.0
        assert ld.sample_shape == (8, 8, 1)
        mb = next(iter(ld.batches("train")))
        assert float(mb.data.max()) < 1.0  # mean of (1, r, r) < 1

    def test_trains_in_workflow(self, tmp_path):
        from znicz_tpu.loader.image import ImageDirectoryLoader
        from znicz_tpu.workflow import StandardWorkflow

        root_dir = self._make_tree(tmp_path, n_per_class=8)
        ld = ImageDirectoryLoader(str(root_dir), minibatch_size=8)
        wf = StandardWorkflow(
            ld,
            [
                {"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                {"type": "softmax", "->": {"output_sample_shape": 2}},
            ],
            decision_config={"max_epochs": 8},
            default_hyper={"learning_rate": 0.2, "gradient_moment": 0.9},
        )
        wf.initialize(seed=3)
        dec = wf.run()
        assert dec.history[-1]["train"]["n_err"] == 0  # separable by channel


class TestPrefetch:
    def test_order_preserved(self):
        from znicz_tpu.loader.prefetch import prefetch

        assert list(prefetch(iter(range(100)), depth=4)) == list(range(100))

    def test_abandoned_iterator_stops_worker(self):
        import threading
        import time

        from znicz_tpu.loader.prefetch import prefetch

        before = threading.active_count()
        it = prefetch(iter(range(1000)), depth=2)
        next(it)
        it.close()  # abandon mid-stream with a full queue
        time.sleep(0.5)
        assert threading.active_count() <= before + 1  # worker exited

    def test_producer_exception_propagates(self):
        from znicz_tpu.loader.prefetch import prefetch

        def gen():
            yield 1
            raise RuntimeError("decode failed")

        it = prefetch(gen(), depth=2)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="decode failed"):
            list(it)

    def test_workflow_results_identical_with_and_without(self):
        from znicz_tpu.workflow import StandardWorkflow

        def run(prefetch_batches):
            prng.seed_all(55)
            loader = datasets.mnist(n_train=128, n_test=32, minibatch_size=32)
            wf = StandardWorkflow(
                loader,
                [
                    {"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
                    {"type": "softmax", "->": {"output_sample_shape": 10}},
                ],
                decision_config={"max_epochs": 2},
                default_hyper={"learning_rate": 0.1},
                prefetch_batches=prefetch_batches,
            )
            wf.initialize(seed=55)
            return wf.run().history

        # identical losses: prefetch must not change draw order or batching
        a = run(0)
        b = run(2)
        for ea, eb in zip(a, b):
            assert ea["train"]["loss"] == eb["train"]["loss"]


def test_split_sizes():
    s = split_sizes(100, [0.1, 0.2])
    assert s == {"train": 70, "valid": 10, "test": 20}
