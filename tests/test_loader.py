"""Tests for the loader layer: split bookkeeping, masking, shuffling."""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader import FullBatchLoader, datasets, normalizers
from znicz_tpu.loader.base import split_sizes


def _loader(n_train=25, bs=10, **kw):
    x = np.arange(n_train * 4, dtype=np.float32).reshape(n_train, 4)
    y = np.arange(n_train, dtype=np.int32) % 3
    return FullBatchLoader({"train": x}, {"train": y}, minibatch_size=bs, **kw)


class TestFullBatchLoader:
    def test_static_shapes_and_mask(self):
        ld = _loader(25, 10, shuffle=False)
        batches = list(ld.batches("train"))
        assert len(batches) == 3
        for mb in batches:
            assert mb.data.shape == (10, 4)
            assert mb.mask.shape == (10,)
        # last batch: 5 valid rows
        assert batches[-1].mask.sum() == 5.0
        assert batches[0].mask.sum() == 10.0

    def test_covers_all_samples_once(self):
        ld = _loader(25, 10)
        seen = []
        for mb in ld.batches("train"):
            seen.extend(mb.indices[mb.mask > 0].tolist())
        assert sorted(seen) == list(range(25))

    def test_shuffle_changes_order_deterministically(self):
        prng.seed_all(7)
        ld = _loader(25, 25)
        first = next(iter(ld.batches("train"))).indices.copy()
        second = next(iter(ld.batches("train"))).indices.copy()
        assert not np.array_equal(first, second)  # reshuffled between epochs
        # same seed -> same orders
        prng.seed_all(7)
        ld2 = _loader(25, 25)
        np.testing.assert_array_equal(
            next(iter(ld2.batches("train"))).indices, first
        )

    def test_labels_follow_indices(self):
        ld = _loader(12, 5)
        for mb in ld.batches("train"):
            np.testing.assert_array_equal(mb.labels, mb.indices % 3)

    def test_epoch_iterates_splits(self):
        x = np.zeros((8, 2), np.float32)
        ld = FullBatchLoader(
            {"train": x, "valid": x[:4], "test": x[:2]},
            {"train": np.zeros(8, np.int32)},
            minibatch_size=4,
        )
        tags = [split for split, _ in ld.epoch()]
        assert tags == ["train", "train", "valid", "test"]
        assert ld.epoch_number == 1

    def test_state_roundtrip(self):
        ld = _loader(25, 10)
        list(ld.batches("train"))
        state = ld.state_dict()
        ld2 = _loader(25, 10)
        ld2.load_state_dict(state)
        np.testing.assert_array_equal(
            ld._split_order("train"), ld2._split_order("train")
        )

    def test_normalization_mean_disp(self):
        ld = _loader(20, 20, normalization="mean_disp", shuffle=False)
        mb = next(iter(ld.batches("train")))
        np.testing.assert_allclose(mb.data.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(mb.data.std(axis=0), 1.0, atol=1e-4)


class TestNormalizers:
    def test_linear_range(self):
        data = np.array([[0.0, 10.0], [5.0, 20.0]], np.float32)
        st = normalizers.fit("linear", data)
        out = normalizers.apply(st, data)
        assert out.min() == -1.0 and out.max() == 1.0

    def test_range(self):
        st = normalizers.fit("range", np.zeros((1, 1)), scale=255.0, shift=-0.5)
        out = normalizers.apply(st, np.array([[255.0]]))
        np.testing.assert_allclose(out, [[0.5]])

    def test_external_mean(self):
        st = normalizers.fit(
            "external_mean", np.zeros((1, 2)), mean=np.array([1.0, 2.0])
        )
        np.testing.assert_allclose(
            normalizers.apply(st, np.array([[1.0, 2.0]])), [[0.0, 0.0]]
        )


class TestDatasets:
    def test_mnist_synthetic_shapes(self):
        ld = datasets.mnist(n_train=50, n_test=20, minibatch_size=25)
        assert ld.class_lengths == {"train": 50, "test": 20}
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (25, 784)
        assert mb.labels.min() >= 0 and mb.labels.max() < 10

    def test_mnist_conv_layout(self):
        ld = datasets.mnist(n_train=10, n_test=4, flat=False, minibatch_size=10)
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (10, 28, 28, 1)

    def test_mnist_validation_split(self):
        ld = datasets.mnist(n_train=100, n_test=10, validation_ratio=0.2)
        assert ld.class_lengths["valid"] == 20
        assert ld.class_lengths["train"] == 80

    def test_cifar_synthetic(self):
        ld = datasets.cifar10(n_train=20, n_test=8, minibatch_size=10)
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (10, 32, 32, 3)

    def test_wine(self):
        ld = datasets.wine()
        assert ld.class_lengths["train"] == 178
        mb = next(iter(ld.batches("train")))
        assert mb.data.shape == (10, 13)

    def test_determinism_under_seed(self):
        prng.seed_all(42)
        a = datasets.mnist(n_train=10, n_test=5)
        prng.seed_all(42)
        b = datasets.mnist(n_train=10, n_test=5)
        np.testing.assert_array_equal(a.data["train"], b.data["train"])


class TestReviewRegressions:
    def test_partial_mnist_dir_raises(self, tmp_path):
        # only a labels file present -> must not silently mix real/synthetic
        import gzip
        import struct

        lab = tmp_path / "t10k-labels-idx1-ubyte.gz"
        with gzip.open(lab, "wb") as f:
            f.write(struct.pack(">ii", 0x00000801, 2) + bytes([1, 2]))
        im = tmp_path / "t10k-images-idx3-ubyte.gz"
        with gzip.open(im, "wb") as f:
            f.write(
                struct.pack(">iiii", 0x00000803, 2, 2, 2) + bytes(8)
            )
        import pytest

        with pytest.raises(FileNotFoundError):
            datasets.mnist(str(tmp_path))

    def test_normalizer_without_train_split_raises(self):
        import pytest

        with pytest.raises(ValueError):
            FullBatchLoader(
                {"valid": np.zeros((4, 2), np.float32)}, normalization="linear"
            )

    def test_resume_reproduces_shuffle_stream(self):
        prng.seed_all(5)
        ld = _loader(25, 25)
        list(ld.batches("train"))
        state = ld.state_dict()
        later = [next(iter(ld.batches("train"))).indices for _ in range(3)]
        # "restart the process": fresh prng registry, different seed history
        prng.reset()
        prng.seed_all(999)
        ld2 = _loader(25, 25)
        ld2.load_state_dict(state)
        resumed = [next(iter(ld2.batches("train"))).indices for _ in range(3)]
        for a, b in zip(later, resumed):
            np.testing.assert_array_equal(a, b)


def test_split_sizes():
    s = split_sizes(100, [0.1, 0.2])
    assert s == {"train": 70, "valid": 10, "test": 20}
