"""Core layer tests: config tree, PRNG registry, logger."""

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import Config, root


class TestConfig:
    def test_autovivify(self):
        cfg = Config()
        cfg.mnist.learning_rate = 0.03
        assert cfg.mnist.learning_rate == 0.03
        assert cfg.to_dict() == {"mnist": {"learning_rate": 0.03}}

    def test_deep_update(self):
        cfg = Config()
        cfg.update({"a": {"b": 1, "c": 2}})
        cfg.update({"a": {"c": 3}, "d": 4})
        assert cfg.to_dict() == {"a": {"b": 1, "c": 3}, "d": 4}

    def test_get_nonvivifying(self):
        cfg = Config()
        assert cfg.get("missing", 42) == 42
        assert "missing" not in cfg.to_dict()

    def test_global_root(self):
        root.update({"test_marker": {"x": 1}})
        assert root.test_marker.x == 1

    def test_mapping_access(self):
        cfg = Config()
        cfg["k"] = 5
        assert cfg["k"] == 5
        assert "k" in cfg


class TestPrng:
    def test_named_generators_deterministic(self):
        prng.seed_all(77)
        a = prng.get("w").normal((4, 4))
        prng.seed_all(77)
        b = prng.get("w").normal((4, 4))
        np.testing.assert_array_equal(a, b)

    def test_streams_decorrelated(self):
        prng.seed_all(77)
        a = prng.get("w").normal((100,))
        b = prng.get("b").normal((100,))
        assert abs(np.corrcoef(a, b)[0, 1]) < 0.5

    def test_jax_keys_advance(self):
        import jax.random

        g = prng.get("default")
        k1, k2 = g.key(), g.key()
        assert not np.array_equal(
            np.asarray(jax.random.key_data(k1)), np.asarray(jax.random.key_data(k2))
        )

    def test_permutation_reproducible(self):
        prng.seed_all(5)
        p1 = prng.get("loader").permutation(10)
        prng.seed_all(5)
        p2 = prng.get("loader").permutation(10)
        np.testing.assert_array_equal(p1, p2)
