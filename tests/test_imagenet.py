"""ImageNet pipeline tests: pack, crop/flip augmentation, device-side
normalize, end-to-end training on disk-backed images.

Covers the reference ImageNet loader pipeline semantics [SURVEY.md 2.3
"Znicz loaders": resize / random crop + flip / mean subtract / eval center
crop] through the TPU-first rebuild (``znicz_tpu/loader/imagenet.py``).
"""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.loader import ImageNetLoader, native, pack_image_dir
from znicz_tpu.loader.datasets import imagenet_synthetic
from znicz_tpu.workflow import StandardWorkflow


def _write_png(path, arr_u8):
    import matplotlib.image as mpimg

    mpimg.imsave(path, arr_u8)


@pytest.fixture(scope="module")
def image_dir(tmp_path_factory):
    """Tiny 2-class image tree with varied sizes (exercises short-side
    resize); class 0 is dark, class 1 is bright — linearly separable."""
    gen = np.random.default_rng(7)
    root = tmp_path_factory.mktemp("imgs")
    sizes = [(40, 56), (64, 40), (48, 48), (56, 44)]
    for split, n in (("train", 16), ("valid", 8)):
        for cls, base in (("dark", 60), ("bright", 190)):
            d = root / split / cls
            d.mkdir(parents=True)
            for i in range(n):
                h, w = sizes[i % len(sizes)]
                img = np.clip(
                    base + gen.normal(0, 25, (h, w, 3)), 0, 255
                ).astype(np.uint8)
                _write_png(str(d / f"{i:03d}.png"), img)
    return str(root)


@pytest.fixture(scope="module")
def packed_dir(image_dir, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("packed"))
    counts = pack_image_dir(image_dir, out, size=32)
    assert counts == {"train": 32, "valid": 16}
    return out


class TestPack:
    def test_packed_files_and_shapes(self, packed_dir):
        imgs = np.load(os.path.join(packed_dir, "train_images.npy"))
        labs = np.load(os.path.join(packed_dir, "train_labels.npy"))
        assert imgs.shape == (32, 32, 32, 3) and imgs.dtype == np.uint8
        assert labs.shape == (32,) and set(labs) == {0, 1}
        assert os.path.exists(os.path.join(packed_dir, "mean_rgb.json"))

    def test_mean_is_plausible(self, packed_dir):
        import json

        mean = json.load(open(os.path.join(packed_dir, "mean_rgb.json")))
        # dark(60) and bright(190) classes average near 125/255 ~ 0.49
        assert all(0.3 < m < 0.7 for m in mean)

    def test_class_brightness_separation(self, packed_dir):
        imgs = np.load(os.path.join(packed_dir, "train_images.npy"))
        labs = np.load(os.path.join(packed_dir, "train_labels.npy"))
        # classes.json order is directory order: bright=0, dark=1
        bright = imgs[labs == 0].mean()
        dark = imgs[labs == 1].mean()
        assert bright > dark + 50


class TestCropGather:
    def test_native_matches_numpy(self):
        gen = np.random.default_rng(3)
        data = gen.integers(0, 256, (10, 16, 20, 3)).astype(np.uint8)
        idx = gen.integers(0, 10, (6,)).astype(np.int64)
        oy = gen.integers(0, 16 - 8 + 1, (6,)).astype(np.int64)
        ox = gen.integers(0, 20 - 12 + 1, (6,)).astype(np.int64)
        flip = np.array([0, 1, 0, 1, 1, 0], np.uint8)
        out = native.crop_gather_u8(data, idx, oy, ox, flip, 8, 12)
        assert out.shape == (6, 8, 12, 3) and out.dtype == np.uint8
        for i in range(6):
            win = data[idx[i], oy[i] : oy[i] + 8, ox[i] : ox[i] + 12]
            exp = win[:, ::-1] if flip[i] else win
            np.testing.assert_array_equal(out[i], exp)

    def test_out_of_bounds_rejected(self):
        data = np.zeros((2, 8, 8, 3), np.uint8)
        with pytest.raises(IndexError):
            native.crop_gather_u8(
                data, np.array([0]), np.array([5]), np.array([0]),
                np.array([0], np.uint8), 4, 4,
            )
        with pytest.raises(IndexError):
            native.crop_gather_u8(
                data, np.array([2]), np.array([0]), np.array([0]),
                np.array([0], np.uint8), 4, 4,
            )


class TestImageNetLoader:
    def test_train_batches_are_u8_crops(self, packed_dir):
        loader = ImageNetLoader(packed_dir, crop_size=27, minibatch_size=8)
        mb = next(iter(loader.batches("train")))
        assert mb.data.shape == (8, 27, 27, 3)
        assert mb.data.dtype == np.uint8
        assert loader.sample_shape == (27, 27, 3)

    def test_eval_center_crop_deterministic(self, packed_dir):
        loader = ImageNetLoader(packed_dir, crop_size=27, minibatch_size=8)
        a = [mb.data for mb in loader.batches("valid", shuffle=False)]
        b = [mb.data for mb in loader.batches("valid", shuffle=False)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_train_crops_vary(self, packed_dir):
        prng.seed_all(11)
        loader = ImageNetLoader(packed_dir, crop_size=27, minibatch_size=32)
        a = next(iter(loader.batches("train", shuffle=False))).data
        b = next(iter(loader.batches("train", shuffle=False))).data
        # same order (no shuffle) but fresh random crops: batches differ
        assert not np.array_equal(a, b)

    def test_device_preproc_subtracts_mean(self, packed_dir):
        loader = ImageNetLoader(
            packed_dir, crop_size=27, minibatch_size=8,
            mean_rgb=(0.25, 0.5, 0.75),
        )
        pre = loader.device_preproc()
        x = np.full((2, 27, 27, 3), 255, np.uint8)
        out = np.asarray(pre(jnp.asarray(x), None))
        np.testing.assert_allclose(
            out[0, 0, 0], [0.75, 0.5, 0.25], atol=1e-6
        )

    def test_device_resident_matches_native_crops(self, packed_dir):
        # the on-device crop+flip+normalize must produce EXACTLY what the
        # native host path produces given the same PRNG draws
        import jax

        def batch(device_resident):
            prng.seed_all(42)
            loader = ImageNetLoader(
                packed_dir, crop_size=27, minibatch_size=8,
                device_resident=device_resident,
            )
            mb = next(iter(loader.batches("train", shuffle=False)))
            pre = loader.device_preproc()
            ctx_host = loader.device_context()
            ctx = None if ctx_host is None else jax.device_put(ctx_host)
            return np.asarray(pre(jnp.asarray(mb.data), ctx)), mb

        host, mb_h = batch(False)
        dev, mb_d = batch(True)
        assert mb_d.data.shape == (8, 4)  # [B, (row, oy, ox, flip)] only
        assert mb_d.data.dtype == np.int32
        np.testing.assert_array_equal(mb_h.labels, mb_d.labels)
        np.testing.assert_allclose(host, dev, atol=1e-6)

    def test_device_resident_eval_center_crop(self, packed_dir):
        import jax

        prng.seed_all(7)
        loader = ImageNetLoader(
            packed_dir, crop_size=27, minibatch_size=8,
            device_resident=True,
        )
        assert loader.epoch_scan_friendly
        pre = loader.device_preproc()
        ctx = jax.device_put(loader.device_context())
        a = [
            np.asarray(pre(jnp.asarray(mb.data), ctx))
            for mb in loader.batches("valid", shuffle=False)
        ]
        prng.seed_all(99)  # eval crops must not depend on the PRNG
        b = [
            np.asarray(pre(jnp.asarray(mb.data), ctx))
            for mb in loader.batches("valid", shuffle=False)
        ]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_device_resident_trains_end_to_end(self, packed_dir):
        from znicz_tpu.workflow import StandardWorkflow

        prng.seed_all(13)
        loader = ImageNetLoader(
            packed_dir, crop_size=27, minibatch_size=8,
            device_resident=True,
        )
        wf = StandardWorkflow(
            loader,
            [
                {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 5,
                                             "ky": 5}},
                {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
                {"type": "softmax", "->": {"output_sample_shape": 3}},
            ],
            decision_config={"max_epochs": 2},
            default_hyper={"learning_rate": 0.05, "gradient_moment": 0.9},
        )
        wf.initialize(seed=13)
        verdict = wf.run_epoch()
        assert np.isfinite(verdict["summary"]["train"]["loss"])

    def test_pool_sharded_matches_host_crops(self, packed_dir):
        # data-axis-sharded pool: the shard_map gather+crop must produce
        # EXACTLY the native host crops for the same indices and draws
        # (payload carries the draws, so this is closed-loop)
        import jax

        from znicz_tpu.loader import native
        from znicz_tpu.parallel import DataParallel, make_mesh

        prng.seed_all(41)
        loader = ImageNetLoader(
            packed_dir, crop_size=27, minibatch_size=16,
            device_resident=True, pool_sharded=True,
        )
        loader.set_data_shards(8)
        ctx = loader.place_device_context(DataParallel(make_mesh(8, 1)))
        # each device holds 1/8 of train+valid rows — the capacity win
        assert ctx["pool"].shape[0] == 48
        assert ctx["pool"].addressable_shards[0].data.shape[0] == 6
        pre = loader.device_preproc()
        for split in ("train", "valid"):
            for mb in loader.batches(split, shuffle=False):
                out = np.asarray(pre(jnp.asarray(mb.data), ctx))
                exp_u8 = native.crop_gather_u8(
                    loader.images[split], mb.indices,
                    mb.data[:, 1].astype(np.int64),
                    mb.data[:, 2].astype(np.int64),
                    mb.data[:, 3].astype(np.uint8), 27, 27,
                )
                exp = (
                    exp_u8.astype(np.float32) / 255.0
                    - loader.mean_rgb
                )
                np.testing.assert_allclose(out, exp, atol=1e-6)

    def test_pool_sharded_trains_end_to_end(self, packed_dir):
        from znicz_tpu.parallel import DataParallel, make_mesh
        from znicz_tpu.workflow import StandardWorkflow

        prng.seed_all(17)
        loader = ImageNetLoader(
            packed_dir, crop_size=27, minibatch_size=16,
            device_resident=True, pool_sharded=True,
        )
        wf = StandardWorkflow(
            loader,
            [
                {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 5,
                                             "ky": 5}},
                {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
                {"type": "softmax", "->": {"output_sample_shape": 3}},
            ],
            decision_config={"max_epochs": 2},
            default_hyper={"learning_rate": 0.05, "gradient_moment": 0.9},
            parallel=DataParallel(make_mesh(8, 1)),
        )
        wf.initialize(seed=17)
        assert wf._use_epoch_scan()
        verdict = wf.run_epoch()
        assert verdict["summary"]["train"]["n_samples"] == 32
        assert np.isfinite(verdict["summary"]["train"]["loss"])

    def test_raw_image_dir_autopacks(self, image_dir):
        loader = ImageNetLoader(
            image_dir, crop_size=24, pack_size=28, minibatch_size=8
        )
        assert os.path.exists(
            os.path.join(image_dir, ".packed28", "train_images.npy")
        )
        mb = next(iter(loader.batches("train")))
        assert mb.data.shape == (8, 24, 24, 3)

    def test_crop_larger_than_pack_rejected(self, packed_dir):
        with pytest.raises(ValueError):
            ImageNetLoader(packed_dir, crop_size=64, minibatch_size=8)


class TestEndToEnd:
    def test_train_on_disk_images_converges(self, packed_dir):
        prng.seed_all(42)
        loader = ImageNetLoader(packed_dir, crop_size=27, minibatch_size=16)
        wf = StandardWorkflow(
            loader,
            [
                {"type": "conv_relu",
                 "->": {"n_kernels": 8, "kx": 5, "ky": 5, "sliding": (2, 2)}},
                {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
                {"type": "softmax", "->": {"output_sample_shape": 2}},
            ],
            decision_config={"max_epochs": 6},
            default_hyper={"learning_rate": 0.05, "gradient_moment": 0.9},
        )
        wf.initialize(seed=42)
        dec = wf.run()
        first = dec.history[0]["train"]["loss"]
        last = dec.history[-1]["train"]["loss"]
        assert last < first
        # brightness-separable task: the net must actually learn it
        assert dec.history[-1]["valid"]["err_pct"] <= 25.0

    def test_u8_device_path_matches_f32_path(self):
        """imagenet_synthetic(store_u8) trains identically (up to
        quantization) to an eagerly-normalized f32 loader on the same data."""
        prng.seed_all(5)
        u8_loader = imagenet_synthetic(
            image_size=16, n_classes=4, n_train=64, n_valid=0,
            minibatch_size=32,
        )
        mb = next(iter(u8_loader.batches("train", shuffle=False)))
        assert mb.data.dtype == np.uint8
        pre = u8_loader.device_preproc()
        assert pre is not None
        x_dev = np.asarray(pre(jnp.asarray(mb.data), None))
        x_host = mb.data.astype(np.float32) / 255.0 - 0.5
        np.testing.assert_allclose(x_dev, x_host, atol=1e-6)

    def test_alexnet_uses_imagenet_loader_with_data_dir(self, image_dir):
        from znicz_tpu.core.config import root
        from znicz_tpu.models import alexnet

        prng.seed_all(1)
        saved = root.alexnet.to_dict()
        try:
            # raw image dir: auto-packs at 256, trains at the real 227 crop
            root.alexnet.loader.update(
                {"data_dir": image_dir, "minibatch_size": 8}
            )
            wf = alexnet.build_workflow()
        finally:
            root.alexnet.clear()
            root.alexnet.update(saved)
        assert isinstance(wf.loader, ImageNetLoader)
        assert wf.loader.sample_shape == (227, 227, 3)
        # head resized to the dataset's 2 classes
        assert wf.model.output_shape == (2,)
        mb = next(iter(wf.loader.batches("train")))
        assert mb.data.dtype == np.uint8 and mb.data.shape[1:] == (227, 227, 3)
