"""Mixture-of-experts layer tests, incl. expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.ops import moe
from znicz_tpu.parallel import make_mesh


class TestMoE:
    def _params(self, e=4, f=8, h=16, seed=2):
        prng.seed_all(seed)
        return moe.init_params(f, h, e)

    def test_top1_uses_single_expert(self):
        params = self._params()
        x = jax.random.normal(jax.random.key(0), (6, 8))
        out = moe.apply(params, x, top_k=1)
        # manual: per token, the argmax expert's output exactly
        logits = x @ params["router"]
        best = jnp.argmax(logits, axis=-1)
        h = jnp.tanh(
            jnp.einsum("bf,efh->ebh", x, params["w1"])
            + params["b1"][:, None, :]
        )
        y = (
            jnp.einsum("ebh,ehf->ebf", h, params["w2"])
            + params["b2"][:, None, :]
        )
        manual = y[best, jnp.arange(6)]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(manual), rtol=1e-5, atol=1e-6
        )

    def test_tied_logits_use_exactly_k_experts(self):
        # a zero row ties every router logit; top_k=1 must still route to
        # exactly one expert (index order), not the mean of all experts
        params = self._params()
        p2 = dict(params)
        p2["w1"] = jnp.zeros_like(params["w1"])
        p2["b1"] = jnp.zeros_like(params["b1"])
        p2["w2"] = jnp.zeros_like(params["w2"])
        # distinct per-expert constant outputs
        p2["b2"] = jnp.arange(4, dtype=jnp.float32)[:, None] * jnp.ones(
            (4, 8)
        )
        x = jnp.zeros((3, 8))
        out = moe.apply(p2, x, top_k=1)
        chosen = np.unique(np.asarray(out))
        assert len(chosen) == 1  # one expert's constant, not a mean

    def test_topk_gates_sum_to_one(self):
        params = self._params()
        x = jax.random.normal(jax.random.key(1), (5, 8))
        # with ones as expert outputs the gate normalization is observable:
        # top-k softmax renormalizes, so output of identity experts == 1
        p2 = dict(params)
        p2["w1"] = jnp.zeros_like(params["w1"])
        p2["b1"] = jnp.zeros_like(params["b1"])
        p2["w2"] = jnp.zeros_like(params["w2"])
        p2["b2"] = jnp.ones_like(params["b2"])
        out = moe.apply(p2, x, top_k=2)
        np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-5)

    def test_trains(self):
        params = self._params(e=4, f=8, h=16, seed=5)
        x = jax.random.normal(jax.random.key(2), (32, 8))
        target = jnp.sin(2 * x)

        @jax.jit
        def step(p):
            def loss(p):
                return jnp.mean(
                    jnp.square(moe.apply(p, x, top_k=2) - target)
                )

            val, g = jax.value_and_grad(loss)(p)
            return (
                jax.tree_util.tree_map(lambda w, gw: w - 0.3 * gw, p, g),
                val,
            )

        losses = []
        for _ in range(40):
            params, val = step(params)
            losses.append(float(val))
        assert losses[-1] < losses[0] * 0.5

    def test_capacity_matches_dense_when_ample(self):
        # with capacity >= every expert's worst-case load, no token drops
        # and the two dispatch modes compute identical math
        params = self._params(e=8, f=8, h=16, seed=3)
        x = jax.random.normal(jax.random.key(4), (24, 8))
        dense = moe.apply(params, x, top_k=2, dispatch="dense")
        cap = moe.apply(
            params, x, top_k=2, dispatch="capacity", capacity_factor=8.0
        )
        np.testing.assert_allclose(
            np.asarray(cap), np.asarray(dense), rtol=1e-5, atol=1e-6
        )

    def test_capacity_drops_overflow_tokens(self):
        # router forces every token onto expert 0; with capacity_factor=1
        # and E=4, capacity = ceil(B/4) so later tokens get zero output
        params = self._params(e=4, f=8, h=16, seed=9)
        p2 = dict(params)
        router = np.zeros((8, 4), np.float32)
        router[:, 0] = 0.0  # zero x still ties; use biased inputs instead
        p2["router"] = jnp.asarray(router)
        x = jnp.ones((8, 8))
        out = moe.apply(
            p2, x, top_k=1, dispatch="capacity", capacity_factor=1.0
        )
        # capacity = ceil(1*8/4 * 1.0) = 2: tokens 0-1 served, 2-7 dropped
        out = np.asarray(out)
        assert np.abs(out[:2]).max() > 0
        np.testing.assert_allclose(out[2:], 0.0, atol=1e-7)
        # identical tokens: the served rows agree with the dense gate value
        dense = np.asarray(moe.apply(p2, x, top_k=1))
        np.testing.assert_allclose(out[0], dense[0], rtol=1e-5, atol=1e-6)

    def test_capacity_flops_independent_of_expert_count(self):
        # the VERDICT gate: for fixed k, compiled FLOPs must not scale
        # with E under capacity dispatch (dense scales linearly)
        def flops(e, dispatch):
            prng.seed_all(11)
            params = moe.init_params(64, 128, e)
            x = jnp.ones((256, 64))
            fn = jax.jit(
                lambda p, x: moe.apply(
                    p, x, top_k=2, dispatch=dispatch, capacity_factor=1.0
                )
            )
            analysis = fn.lower(params, x).compile().cost_analysis()
            if isinstance(analysis, list):  # older jax returns [dict]
                analysis = analysis[0]
            return analysis["flops"]

        cap4, cap16 = flops(4, "capacity"), flops(16, "capacity")
        dense4, dense16 = flops(4, "dense"), flops(16, "dense")
        assert cap16 < 1.6 * cap4, (cap4, cap16)
        assert dense16 > 2.5 * dense4, (dense4, dense16)  # the contrast

    def test_capacity_e64_memory_stays_off_the_bec_wall(self):
        # VERDICT r2 weak #3: the one-hot formulation materialized [B,E,C]
        # tensors.  The sort/segment dispatch must compile WITHOUT any
        # B*E*C-sized intermediate at E=64 — checked against the compiled
        # HLO's buffer shapes, and numerics must still match dense when
        # capacity is ample.
        b, e, f, h, k = 512, 64, 32, 64, 2
        prng.seed_all(21)
        params = moe.init_params(f, h, e)
        x = jax.random.normal(jax.random.key(8), (b, f))
        fn = jax.jit(
            lambda p, x: moe.apply(
                p, x, top_k=k, dispatch="capacity", capacity_factor=1.25
            )
        )
        compiled = fn.lower(params, x).compile()
        cap = moe.expert_capacity(b, e, k, 1.25)
        bec = b * e * cap  # 1.3M elements at this size; 4*10^9 at scale
        import re

        hlo = compiled.as_text()
        big = [
            shape
            for shape in re.findall(r"f32\[([\d,]+)\]", hlo)
            if np.prod([int(d) for d in shape.split(",")]) >= bec
        ]
        assert not big, f"B*E*C-scale buffers in HLO: {set(big)}"
        # and the math is right: ample capacity == dense
        ample = moe.apply(
            params, x, top_k=k, dispatch="capacity", capacity_factor=float(e)
        )
        dense = moe.apply(params, x, top_k=k, dispatch="dense")
        np.testing.assert_allclose(
            np.asarray(ample), np.asarray(dense), rtol=2e-5, atol=1e-5
        )

    def test_capacity_grads_match_dense_when_ample(self):
        # the scatter/gather dispatch must be differentiable along the
        # same paths as the einsum form (gates, dispatched x, expert outs)
        params = self._params(e=8, f=8, h=16, seed=17)
        x = jax.random.normal(jax.random.key(10), (24, 8))

        def loss(dispatch):
            return lambda p, x: jnp.sum(
                jnp.square(
                    moe.apply(
                        p, x, top_k=2, dispatch=dispatch,
                        capacity_factor=8.0,
                    )
                )
            )

        gd = jax.grad(loss("dense"), argnums=(0, 1))(params, x)
        gc = jax.grad(loss("capacity"), argnums=(0, 1))(params, x)
        for a, b in zip(jax.tree_util.tree_leaves(gd),
                        jax.tree_util.tree_leaves(gc)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )

    def test_capacity_topk_ge_experts_warns_dense_fallback(self):
        import warnings

        params = self._params(e=4, f=8, h=16, seed=15)
        x = jax.random.normal(jax.random.key(9), (8, 8))
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = moe.apply(params, x, top_k=4, dispatch="capacity")
        assert any("degrades to the dense path" in str(x.message) for x in w)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(moe.apply(params, x, top_k=4, dispatch="dense")),
            rtol=1e-6,
        )

    def test_expert_parallel_capacity_sharded_matches_replicated(self):
        # E=16 sharded 4-way on the model axis == replicated (VERDICT #9)
        mesh = make_mesh(2, 4)
        params = self._params(e=16, f=8, h=16, seed=13)
        x = jax.random.normal(jax.random.key(5), (32, 8))
        ref = moe.apply(
            params, x, top_k=2, dispatch="capacity", capacity_factor=2.0
        )
        sharded = moe.expert_sharding(mesh)(params)
        assert not sharded["w1"].is_fully_replicated
        out = jax.jit(
            lambda p, x: moe.apply(
                p, x, top_k=2, dispatch="capacity", capacity_factor=2.0
            )
        )(sharded, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_expert_parallel_sharding_matches_replicated(self):
        mesh = make_mesh(2, 4)  # 4-way expert/model axis
        params = self._params(e=4, f=8, h=16, seed=7)
        x = jax.random.normal(jax.random.key(3), (16, 8))
        ref = moe.apply(params, x, top_k=1)
        sharded = moe.expert_sharding(mesh)(params)
        assert not sharded["w1"].is_fully_replicated
        out = jax.jit(lambda p, x: moe.apply(p, x, top_k=1))(sharded, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_local_shard_partials_sum_to_dense(self):
        # apply_local_shard is the manual-EP building block for the PPxTP
        # stage forward: the per-shard contributions must SUM to the dense
        # dispatch exactly (that sum is the psum in _block_forward_tp)
        params = self._params(e=8, f=8, h=16, seed=11)
        x = jax.random.normal(jax.random.key(5), (12, 8))
        ref = moe.apply(params, x, top_k=2)
        n_shards = 4
        e_local = 8 // n_shards
        total = jnp.zeros_like(ref)
        for s in range(n_shards):
            local = {
                "router": params["router"],  # replicated
                **{
                    k: params[k][s * e_local:(s + 1) * e_local]
                    for k in ("w1", "b1", "w2", "b2")
                },
            }
            total = total + moe.apply_local_shard(
                local, x, top_k=2, shard_index=s
            )
        np.testing.assert_allclose(
            np.asarray(total), np.asarray(ref), rtol=1e-5, atol=1e-6
        )
