"""Distribution tests on the 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8 — SURVEY.md §4's rebuild strategy)."""

import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.parallel import DataParallel, make_mesh
from znicz_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from znicz_tpu.workflow import StandardWorkflow

MLP_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


def _workflow(parallel=None, minibatch_size=64, max_epochs=2):
    loader = datasets.mnist(
        n_train=256, n_test=64, minibatch_size=minibatch_size
    )
    wf = StandardWorkflow(
        loader,
        MLP_LAYERS,
        decision_config={"max_epochs": max_epochs},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
    )
    wf.parallel = parallel
    return wf


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh()
        assert m.shape[DATA_AXIS] == 8 and m.shape[MODEL_AXIS] == 1
        m2 = make_mesh(4, 2)
        assert m2.shape[DATA_AXIS] == 4 and m2.shape[MODEL_AXIS] == 2

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            make_mesh(16, 1)

    def test_shard_batch_placement(self):
        dp = DataParallel(make_mesh(8, 1))
        x = dp.shard_batch(np.zeros((16, 4), np.float32))
        assert len(x.sharding.device_set) == 8
        with pytest.raises(ValueError):
            dp.shard_batch(np.zeros((10, 4), np.float32))

    def test_process_contiguous_data_axis_check(self):
        # the multi-host loader row contract needs each process to own one
        # contiguous block of the data axis; the checker only touches
        # .axis_names/.devices/.process_index, so duck-typed meshes with
        # fake process placements exercise both verdicts
        from types import SimpleNamespace

        from znicz_tpu.parallel.mesh import (
            verify_process_contiguous_data_axis,
        )

        def fake_mesh(proc_grid, axis_names=("data", "model")):
            devices = np.vectorize(
                lambda p: SimpleNamespace(process_index=int(p))
            )(np.asarray(proc_grid))
            return SimpleNamespace(axis_names=axis_names, devices=devices)

        # contiguous: processes 0,0,1,1 down the data axis (model in-proc)
        verify_process_contiguous_data_axis(
            fake_mesh([[0, 0], [0, 0], [1, 1], [1, 1]])
        )
        # interleaved processes along data
        with pytest.raises(ValueError, match="contiguous block"):
            verify_process_contiguous_data_axis(
                fake_mesh([[0, 0], [1, 1], [0, 0], [1, 1]])
            )
        # a data-axis row mixing two processes
        with pytest.raises(ValueError, match="contiguous block"):
            verify_process_contiguous_data_axis(
                fake_mesh([[0, 1], [0, 1], [0, 1], [0, 1]])
            )
        # 1-D (data-only) meshes must be checked too, not crash
        verify_process_contiguous_data_axis(
            fake_mesh([0, 0, 1, 1], axis_names=("data",))
        )
        with pytest.raises(ValueError, match="contiguous block"):
            verify_process_contiguous_data_axis(
                fake_mesh([0, 1, 0, 1], axis_names=("data",))
            )
        # contiguous but UNEQUAL shares break the loader's 1/P row contract
        with pytest.raises(ValueError, match="equal"):
            verify_process_contiguous_data_axis(
                fake_mesh([0, 0, 0, 1], axis_names=("data",))
            )


class TestDataParallelTraining:
    def test_dp_matches_single_device(self):
        """The SPMD replacement must converge identically to single-device
        (replacing the reference master-slave aggregation, SURVEY.md 3.4)."""
        prng.seed_all(99)
        wf_single = _workflow(None)
        wf_single.initialize(seed=99)
        dec_s = wf_single.run()

        prng.seed_all(99)
        wf_dp = _workflow(DataParallel(make_mesh(8, 1)))
        wf_dp.initialize(seed=99)
        dec_p = wf_dp.run()

        for es, ep in zip(dec_s.history, dec_p.history):
            assert es["train"]["n_err"] == ep["train"]["n_err"]
            np.testing.assert_allclose(
                es["train"]["loss"], ep["train"]["loss"], rtol=1e-4
            )

    def test_tensor_parallel_shards_and_trains(self):
        prng.seed_all(5)
        dp = DataParallel(make_mesh(4, 2), tp=True, tp_min_features=32)
        wf = _workflow(dp, max_epochs=1)
        wf.initialize(seed=5)
        # FC weights sharded over model axis
        w = wf.state.params[0]["weights"]
        assert not w.is_fully_replicated
        verdict = wf.run_epoch()
        assert np.isfinite(verdict["summary"]["train"]["loss"])

    def test_tp_small_params_replicated(self):
        dp = DataParallel(make_mesh(4, 2), tp=True, tp_min_features=4096)
        wf = _workflow(dp, max_epochs=1)
        wf.initialize(seed=5)
        assert wf.state.params[0]["weights"].is_fully_replicated

    def test_cnn_tp_rules_shard_conv_kernels(self):
        """Channel-aware conv TP (VERDICT r2 #7): conv kernels — the FLOPs
        carriers — shard over the model axis (col/row alternation), and
        the run matches single-device losses."""
        CONV_LAYERS = [
            {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 5, "ky": 5}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
            {"type": "conv_relu", "->": {"n_kernels": 16, "kx": 3, "ky": 3}},
            {"type": "all2all_relu", "->": {"output_sample_shape": 64}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ]

        def build(parallel):
            prng.seed_all(77)
            loader = datasets.mnist(
                n_train=128, n_test=0, minibatch_size=32, flat=False
            )
            wf = StandardWorkflow(
                loader,
                CONV_LAYERS,
                decision_config={"max_epochs": 2},
                default_hyper={"learning_rate": 0.05,
                               "gradient_moment": 0.9},
            )
            wf.parallel = parallel
            wf.initialize(seed=77)
            if parallel is not None:
                # placement at initialize (after a train step GSPMD may
                # legitimately re-propagate output shardings): conv1
                # column-sharded on out-channels, conv2 row-sharded on in
                from jax.sharding import PartitionSpec as P

                w1 = wf.state.params[0]["weights"]
                w2 = wf.state.params[2]["weights"]
                assert w1.sharding.spec == P(
                    None, None, None, MODEL_AXIS
                )
                assert w2.sharding.spec == P(
                    None, None, MODEL_AXIS, None
                )
            return wf, wf.run().history

        _, base = build(None)
        wf_tp, hist = build(DataParallel(make_mesh(4, 2), tp=True))
        for ea, eb in zip(base, hist):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=2e-3
            )


class TestUnsupervisedDataParallel:
    def test_kohonen_dp_matches_single_device(self):
        from znicz_tpu.workflow import KohonenWorkflow

        def build(parallel):
            prng.seed_all(31)
            loader = datasets.mnist(
                n_train=128, n_test=0, minibatch_size=64,
                normalization="mean_disp",
            )
            wf = KohonenWorkflow(
                loader, sx=4, sy=4, total_epochs=2, parallel=parallel
            )
            wf.initialize(seed=31)
            return wf.run().history

        a = build(None)
        b = build(DataParallel(make_mesh(8, 1)))
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_kohonen_dp_pallas_kernel_matches_xla(self):
        # the FUSED kernel under data parallel (shard_map + psum rule)
        # reproduces the XLA composition's single-device training run
        from znicz_tpu.workflow import KohonenWorkflow

        def build(parallel, impl):
            prng.seed_all(37)
            loader = datasets.mnist(
                n_train=128, n_test=0, minibatch_size=64,
                normalization="mean_disp",
            )
            wf = KohonenWorkflow(
                loader, sx=4, sy=4, total_epochs=2,
                parallel=parallel, impl=impl,
            )
            wf.initialize(seed=37)
            return wf.run().history

        a = build(None, "xla")
        b = build(DataParallel(make_mesh(8, 1)), "pallas")
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=1e-4
            )

    def test_rbm_dp_runs(self):
        from znicz_tpu.workflow import RBMWorkflow

        prng.seed_all(33)
        loader = datasets.mnist(n_train=128, n_test=0, minibatch_size=64)
        wf = RBMWorkflow(
            loader, n_hidden=32, max_epochs=2,
            parallel=DataParallel(make_mesh(8, 1)),
        )
        wf.initialize(seed=33)
        dec = wf.run()
        assert np.isfinite(dec.history[-1]["train"]["loss"])


class TestGraftEntry:
    def test_dryrun_multichip_8(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "/root/repo/__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)
