"""Topology introspection tests (model summary + DOT export)."""

from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.workflow import (
    StandardWorkflow,
    model_summary,
    to_dot,
)


def _wf():
    prng.seed_all(2)
    loader = datasets.mnist(n_train=32, n_test=0, minibatch_size=16)
    return StandardWorkflow(
        loader,
        [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
            {"type": "dropout", "->": {"dropout_ratio": 0.5}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ],
        decision_config={"max_epochs": 1},
    )


def test_model_summary_counts_params():
    wf = _wf()
    s = model_summary(wf.model)
    assert "all2all_tanh" in s and "dropout" in s and "softmax" in s
    # 784*8+8 + 0 + 8*10+10 = 6370
    assert "6,370" in s.replace(" ", ",")


def test_to_dot_structure(tmp_path):
    from znicz_tpu.services import MetricsCSVWriter

    wf = _wf()
    wf.services = [
        MetricsCSVWriter(str(tmp_path / "a")),
        MetricsCSVWriter(str(tmp_path / "b")),
    ]
    dot = to_dot(wf)
    assert dot.startswith("digraph workflow")
    assert "loader" in dot and "Decision" in dot
    assert "layer0" in dot and "layer2" in dot
    # same-class services stay distinct nodes
    assert "svc_0_MetricsCSVWriter" in dot
    assert "svc_1_MetricsCSVWriter" in dot
    assert dot.count("{") == dot.count("}")
