"""Speculative decoding in the paged engine: goldens, rollback, leaks.

ISSUE 12 acceptance: greedy speculative decode must be TOKEN-IDENTICAL
to non-speculative decode (same engine, spec off) — through mixed
prompt lengths, chunked prefill, preemption-with-rollback and the
prefix cache — because verification scores the drafted tokens with
exactly the decode path's math and keeps only the longest agreeing
prefix.  Rejected drafts roll back by TRUNCATING the block table
(refcounts reclaim the blocks — the leak sweep must come back clean),
and the bucketed verify ladder must add ZERO compiled programs per
accepted length (pinned against the engine ledger, the jit caches AND
``znicz_serve_compiles_total``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.core import prng
from znicz_tpu.services.engine import DecodeEngine, PagedDecodeEngine
from znicz_tpu.services.errors import SpeculationUnsupportedError
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.generate import PromptLookupDrafter
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 15  # never greedily emitted by this seed's LM at small budgets
HEADS = 4
T_MAX = 96
BS = 8


def _params(seed=27, max_seq=T_MAX):
    prng.seed_all(seed)
    return init_lm_params(17, 32, 2, HEADS, max_seq=max_seq)


def _reference(params, prompt, budget, eos=EOS):
    out = np.asarray(
        G.generate(
            params, jnp.asarray(prompt)[None], n_heads=HEADS,
            max_new_tokens=budget, eos_id=eos,
        )
    )[0]
    new = out[len(prompt):]
    hit = np.where(new == eos)[0]
    if len(hit):
        new = new[: hit[0] + 1]
    return np.concatenate([prompt, new])


def _engine(params, **kw):
    kw.setdefault("n_heads", HEADS)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("max_seq", T_MAX)
    kw.setdefault("admit_every", 4)
    kw.setdefault("spec_k", 7)
    return PagedDecodeEngine(params, **kw)


def _tokens(rng, n):
    return rng.integers(1, 17, (n,)).astype(np.int32)


def _compiles_total():
    m = obs.get_registry().metrics().get("znicz_serve_compiles_total")
    if m is None:
        return 0.0
    return sum(c.value for c in m.children().values())


def _assert_no_leaks(eng):
    assert eng.active == 0 and eng.prefilling == 0 and eng.pending == 0
    eng.flush_prefix_cache()
    assert len(eng._lru) == 0
    assert sorted(eng._free) == list(range(1, eng.n_blocks))
    assert (eng._ref == 0).all()


class OracleDrafter:
    """Test drafter with perfect foresight: proposes the REFERENCE
    continuation of whatever context it is shown, so every draft is
    accepted — the deterministic way to exercise the accept path.
    ``sizes`` cycles the per-call draft length (None = always k)."""

    def __init__(self, refs, sizes=None):
        self.refs = [np.asarray(r, np.int32) for r in refs]
        self.sizes = list(sizes) if sizes else None
        self._call = 0

    def propose(self, context, k: int) -> np.ndarray:
        ctx = np.asarray(context, np.int32)
        if self.sizes:
            k = min(k, self.sizes[self._call % len(self.sizes)])
            self._call += 1
        for ref in self.refs:
            if ctx.size < ref.size and np.array_equal(
                ref[: ctx.size], ctx
            ):
                return ref[ctx.size: ctx.size + k].copy()
        return np.zeros((0,), np.int32)


class JunkDrafter:
    """Always proposes the same (almost always wrong) tokens — the
    deterministic way to exercise full rollback every verify."""

    def __init__(self, token=1):
        self.token = token

    def propose(self, context, k: int) -> np.ndarray:
        return np.full((k,), self.token, np.int32)


class TestGreedyGoldens:
    def test_mixed_lengths_golden_vs_nonspec(self):
        # mixed prompt lengths (several chunked-prefill shapes) with
        # the REAL prompt-lookup drafter: spec engine == spec-off
        # engine == per-request generate(), token for token
        params = _params()
        rng = np.random.default_rng(5)
        prompts = [_tokens(rng, n) for n in (5, 12, 20, 9, 17, 33)]
        prompts.append(np.tile(np.array([3, 5, 7, 2], np.int32), 8))
        engines = {
            "off": _engine(params, spec_k=0),
            "spec": _engine(
                params, drafter=PromptLookupDrafter(3, 1)
            ),
        }
        ids = {
            name: [eng.submit(p, 24) for p in prompts]
            for name, eng in engines.items()
        }
        for eng in engines.values():
            eng.run()
        for i, p in enumerate(prompts):
            ref = _reference(params, p, 24)
            for name, eng in engines.items():
                got = eng.completions[ids[name][i]].tokens
                assert np.array_equal(got, ref), (name, i)
        assert engines["spec"].spec_stats()["verify_steps"] > 0
        _assert_no_leaks(engines["spec"])

    def test_oracle_drafter_accepts_everything(self):
        # perfect drafts: acceptance rate 1.0, and the whole budget
        # arrives in a handful of verify steps
        params = _params()
        rng = np.random.default_rng(5)
        p = _tokens(rng, 10)
        ref = _reference(params, p, 20)
        assert ref.size == p.size + 20  # long run: drafting has work
        eng = _engine(params, drafter=OracleDrafter([ref]))
        rid = eng.submit(p, 20)
        eng.run()
        comp = eng.completions[rid]
        assert np.array_equal(comp.tokens, ref)
        sp = eng.spec_stats()
        assert sp["enabled"] and sp["drafted"] > 0
        assert sp["accepted"] == sp["drafted"]
        assert sp["rejected"] == 0
        assert sp["acceptance_rate"] == 1.0
        # far fewer verify steps than emitted tokens
        assert sp["verify_steps"] < comp.n_new
        # the per-request breakdown carries the same tallies
        assert comp.timings["spec_drafted"] == sp["drafted"]
        assert comp.timings["spec_accepted"] == sp["accepted"]
        _assert_no_leaks(eng)

    def test_junk_drafter_rolls_everything_back(self):
        # every draft rejected: still golden (the bonus token IS the
        # greedy token), every rejected block reclaimed
        params = _params()
        rng = np.random.default_rng(13)
        prompts = [_tokens(rng, n) for n in (6, 14)]
        eng = _engine(params, drafter=JunkDrafter(token=2))
        ids = [eng.submit(p, 16) for p in prompts]
        eng.run()
        for rid, p in zip(ids, prompts):
            assert np.array_equal(
                eng.completions[rid].tokens, _reference(params, p, 16)
            )
        sp = eng.spec_stats()
        assert sp["drafted"] > 0
        # the constant junk token may collide with the true greedy
        # token occasionally; rejection must dominate
        assert sp["rejected"] > sp["accepted"]
        _assert_no_leaks(eng)

    def test_eos_inside_accepted_prefix_retires_exactly(self):
        # a draft that includes the true EOS retires the row AT the
        # EOS, not past it — same contract as the chunk collection loop
        params = _params()
        rng = np.random.default_rng(17)
        for n in (4, 7, 11, 19, 26):
            p = _tokens(rng, n)
            ref = _reference(params, p, 40)
            eng = _engine(params, drafter=OracleDrafter([ref]))
            rid = eng.submit(p, 40)
            eng.run()
            comp = eng.completions[rid]
            assert np.array_equal(comp.tokens, ref)
            if ref[-1] == EOS:
                assert comp.finish_reason == "eos"
            else:
                assert comp.finish_reason == "budget"
            _assert_no_leaks(eng)


class TestRollback:
    def test_rollback_truncates_the_block_table(self):
        # white-box: a junk verify allocates blocks for the full
        # bucketed width, then rollback shrinks the row back to the
        # accepted prefix — tables and row_blocks agree, and the freed
        # blocks are allocatable again
        params = _params()
        rng = np.random.default_rng(5)
        p = _tokens(rng, BS - 1)  # one block of prompt, 30-token run
        # cache OFF: released blocks must come back to the FREE list
        # (cache-on parks published blocks in the LRU instead)
        eng = _engine(
            params, batch_size=1, drafter=JunkDrafter(),
            prefix_cache=False,
        )
        rid = eng.submit(p, 30)
        # drive tick by tick so we can observe mid-stream state
        free0 = len(eng._free)
        while eng._has_work():
            eng._admit_pending()
            eng._prefill_tick()
            if eng.active:
                eng._run_chunk()
            row = eng._row_blocks[0]
            # invariant after every tick: the table NEVER keeps blocks
            # past the valid-KV prefix + 0 or 1 in-progress block
            if eng._slots[0] is not None and eng._slots[0]["mode"] == "decode":
                keep = (int(eng._pos[0]) - 1) // BS + 1
                assert len(row) == keep
                assert all(
                    int(eng._tables[0, j]) == row[j]
                    for j in range(len(row))
                )
        assert np.array_equal(
            eng.completions[rid].tokens, _reference(params, p, 30)
        )
        assert len(eng._free) == free0
        _assert_no_leaks(eng)

    def test_preemption_under_spec_pressure_stays_golden(self):
        # a pool too small for everyone + spec verify allocating ahead:
        # preemption (publish + release + requeue + recompute) must
        # interleave with speculative rollback without corrupting anyone
        params = _params()
        rng = np.random.default_rng(23)
        prompts = [_tokens(rng, n) for n in (2 * BS, 2 * BS + 3, BS + 1)]
        eng = _engine(
            params, batch_size=3, n_blocks=10,
            drafter=PromptLookupDrafter(3, 1),
        )
        ids = [eng.submit(p, 24) for p in prompts]
        eng.run()
        for rid, p in zip(ids, prompts):
            assert np.array_equal(
                eng.completions[rid].tokens, _reference(params, p, 24)
            )
        _assert_no_leaks(eng)

    def test_forced_preemption_with_oracle_drafts(self):
        # oracle drafts make every verify allocate the full width, so
        # a tight pool MUST preempt; survivors and victims both golden
        params = _params()
        rng = np.random.default_rng(29)
        prompts = [_tokens(rng, n) for n in (BS, BS + 2, BS - 1)]
        refs = [_reference(params, p, 30) for p in prompts]
        eng = _engine(
            params, batch_size=3, n_blocks=9,
            drafter=OracleDrafter(refs),
        )
        ids = [eng.submit(p, 30) for p in prompts]
        eng.run()
        for rid, ref in zip(ids, refs):
            assert np.array_equal(eng.completions[rid].tokens, ref)
        _assert_no_leaks(eng)


class TestPrefixCacheInteraction:
    def test_spec_decode_fills_publishable_blocks(self):
        # multi-turn: turn 1 decodes speculatively; turn 2's prompt
        # extends turn 1's full output and must map the blocks spec
        # decode filled — cached_tokens > 0 AND both turns golden
        params = _params()
        rng = np.random.default_rng(31)
        p1 = _tokens(rng, BS)
        ref1 = _reference(params, p1, 18)
        eng = _engine(params, drafter=OracleDrafter([ref1]))
        r1 = eng.submit(p1, 18)
        eng.run()
        assert np.array_equal(eng.completions[r1].tokens, ref1)
        p2 = np.concatenate([ref1, _tokens(rng, 3)])
        ref2 = _reference(params, p2, 12)
        eng.drafter = OracleDrafter([ref2])
        r2 = eng.submit(p2, 12)
        eng.run()
        assert np.array_equal(eng.completions[r2].tokens, ref2)
        st = eng.stats()
        assert st["prefix_cache"]["hits"] > 0
        assert eng.completions[r2].timings["cached_tokens"] > 0
        _assert_no_leaks(eng)

    def test_shared_prefix_admission_then_spec_golden(self):
        # two requests sharing a long prefix, spec on: the second maps
        # cached blocks, then speculates on top of them
        params = _params()
        rng = np.random.default_rng(37)
        s = _tokens(rng, 2 * BS)
        eng = _engine(params, drafter=PromptLookupDrafter(3, 1))
        pa = np.concatenate([s, _tokens(rng, 5)])
        pb = np.concatenate([s, _tokens(rng, 7)])
        ra = eng.submit(pa, 10)
        eng.run()
        rb = eng.submit(pb, 10)
        eng.run()
        assert np.array_equal(
            eng.completions[ra].tokens, _reference(params, pa, 10)
        )
        assert np.array_equal(
            eng.completions[rb].tokens, _reference(params, pb, 10)
        )
        assert eng.stats()["prefix_cache"]["hits"] >= 2
        _assert_no_leaks(eng)


class TestZeroNewPrograms:
    def test_verify_ladder_and_accepted_lengths_compile_nothing_new(self):
        # drive every verify bucket (draft sizes 1/3/7 -> widths 2/4/8)
        # on a warm engine: the ledger, the jit caches and the registry
        # counter must agree, and a SECOND engine with the same
        # geometry — replaying varied accepted lengths — adds ZERO
        params = _params()
        rng = np.random.default_rng(4)

        def build():
            p = _tokens(rng, 6)
            ref = _reference(params, p, 26)
            assert ref.size == p.size + 26  # full-budget run
            eng = _engine(
                params, batch_size=1,
                drafter=OracleDrafter([ref], sizes=(1, 3, 7)),
            )
            return eng, p, ref

        eng, p, ref = build()
        rid = eng.submit(p, 26)
        eng.run()
        assert np.array_equal(eng.completions[rid].tokens, ref)
        st0 = eng.compile_stats()
        widths = {
            key[1] for key in st0["programs"] if key[0] == "spec_verify"
        }
        assert widths == {2, 4, 8}
        c0 = _compiles_total()
        # second same-geometry engine: different prompt, different
        # accepted lengths, same bucket ladder -> all cache hits
        eng2, p2, ref2 = build()
        rid2 = eng2.submit(p2, 26)
        eng2.run()
        assert np.array_equal(eng2.completions[rid2].tokens, ref2)
        st1 = eng2.compile_stats()
        assert set(st1["programs"]) <= set(st0["programs"])
        assert (
            st1["spec_verify_jit_entries"]
            == st0["spec_verify_jit_entries"]
        )
        assert st1["prefill_jit_entries"] == st0["prefill_jit_entries"]
        assert (
            st1["paged_chunk_jit_entries"]
            == st0["paged_chunk_jit_entries"]
        )
        assert _compiles_total() == c0
        _assert_no_leaks(eng)
        _assert_no_leaks(eng2)

    def test_spec_off_engine_never_touches_verify_program(self):
        params = _params()
        rng = np.random.default_rng(43)
        eng = _engine(params, spec_k=0)
        eng.submit(_tokens(rng, 9), 8)
        eng.run()
        assert not any(
            key[0] == "spec_verify" for key in eng.compile_stats()["programs"]
        )
        assert eng.spec_stats() == {
            "enabled": False,
            "k": 0,
            "buckets": list(G.DEFAULT_SPEC_BUCKETS),
            "drafted": 0,
            "accepted": 0,
            "rejected": 0,
            "verify_steps": 0,
            "acceptance_rate": 0.0,
        }


class TestSampledSpec:
    def test_sampled_path_completes_in_vocab(self):
        # temperature > 0: distribution-level correctness (standard
        # rejection against the point-mass draft) is not goldenable
        # token-wise; pin what is checkable — typed completions, tokens
        # in vocab, spec accounting consistent, no leaks
        params = _params()
        rng = np.random.default_rng(47)
        eng = _engine(
            params, spec_k=3, temperature=0.8, top_k=5,
            rng=jax.random.key(3), drafter=PromptLookupDrafter(3, 1),
        )
        ids = [eng.submit(_tokens(rng, n), 12) for n in (5, 9, 14, 21)]
        eng.run()
        for rid in ids:
            comp = eng.completions[rid]
            assert comp.finish_reason in ("eos", "budget")
            assert (comp.tokens >= 0).all() and (comp.tokens < 17).all()
        sp = eng.spec_stats()
        assert sp["drafted"] == sp["accepted"] + sp["rejected"]
        _assert_no_leaks(eng)


class TestSpecConfig:
    def test_dense_engine_rejects_speculation(self):
        params = _params()
        with pytest.raises(ValueError, match="paged backend"):
            DecodeEngine(params, n_heads=HEADS, eos_id=EOS, spec_k=2)
        # typed: the ValueError IS the config-error subclass
        with pytest.raises(SpeculationUnsupportedError):
            DecodeEngine(params, n_heads=HEADS, eos_id=EOS, spec_k=2)
        # a drafter or bucket ladder without spec_k is config noise on
        # the dense backend too — same typed rejection
        with pytest.raises(SpeculationUnsupportedError):
            DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS,
                drafter=PromptLookupDrafter(),
            )
        with pytest.raises(SpeculationUnsupportedError):
            DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, spec_buckets=(2, 4),
            )

    def test_dense_stats_carry_disabled_spec_subdict(self):
        params = _params()
        eng = DecodeEngine(params, n_heads=HEADS, eos_id=EOS)
        assert eng.stats()["spec"] == {"enabled": False}

    def test_paged_validates_spec_args(self):
        params = _params()
        with pytest.raises(ValueError, match="spec_k"):
            _engine(params, spec_k=-1)
        with pytest.raises(ValueError, match="spec_buckets"):
            _engine(params, spec_buckets=(1, 4))
        with pytest.raises(ValueError, match="spec_buckets"):
            _engine(params, spec_buckets=(4, 2))
        # a drafter with speculation OFF is a config trap, not a no-op
        # (the dense backend raises for the same noise)
        with pytest.raises(ValueError, match="spec_k"):
            _engine(params, spec_k=0, drafter=PromptLookupDrafter())
        eng = _engine(params, spec_k=0)
        assert eng.drafter is None

    def test_spec_stats_in_paged_report(self):
        params = _params()
        eng = _engine(params, spec_k=3)
        sp = eng.stats()["spec"]
        assert sp["enabled"] and sp["k"] == 3
        assert sp["buckets"] == list(G.DEFAULT_SPEC_BUCKETS)


class TestPromptLookupDrafter:
    def test_most_recent_match_wins(self):
        d = PromptLookupDrafter(ngram_max=2, ngram_min=2)
        #        [1 2] -> 3 ... [1 2] -> 4 ...   query tail [1 2]
        ctx = [1, 2, 3, 9, 1, 2, 4, 9, 1, 2]
        assert d.propose(ctx, 1).tolist() == [4]

    def test_periodic_run_drafts_full_k(self):
        # inside a long run the latest occurrence with k continuation
        # tokens is preferred — a period-1 run drafts k tokens, not 1
        d = PromptLookupDrafter()
        ctx = [9, 4] + [7] * 10
        assert d.propose(ctx, 4).tolist() == [7, 7, 7, 7]

    def test_k_clamp_and_no_match(self):
        d = PromptLookupDrafter()
        assert d.propose([1, 2, 3, 4], 4).size == 0  # no repeat
        assert d.propose([5, 6, 7], 0).size == 0  # k=0
        # short tail continuation clamps below k
        assert d.propose([1, 2, 8, 1, 2], 5).tolist() == [8, 1, 2]

    def test_longer_ngram_preferred(self):
        d = PromptLookupDrafter(ngram_max=3, ngram_min=1)
        # 1-gram [2] would match index 1 (-> 9); the 3-gram match is
        # the truthier continuation and must win
        ctx = [1, 2, 9, 3, 1, 2, 5, 8, 3, 1, 2]
        assert d.propose(ctx, 1).tolist() == [5]

    def test_validation(self):
        with pytest.raises(ValueError):
            PromptLookupDrafter(ngram_max=0)
        with pytest.raises(ValueError):
            PromptLookupDrafter(ngram_max=2, ngram_min=3)


class TestObservability:
    def test_counters_and_histogram_advance(self):
        params = _params()
        rng = np.random.default_rng(53)
        reg = obs.get_registry().metrics()

        def val(name):
            m = obs.get_registry().metrics().get(name)
            return sum(c.value for c in m.children().values()) if m else 0.0

        d0 = val("znicz_serve_spec_drafted_total")
        a0 = val("znicz_serve_spec_accepted_total")
        r0 = val("znicz_serve_spec_rejected_total")
        h = obs.get_registry().metrics().get(
            "znicz_serve_spec_accept_length"
        )
        h0 = sum(c.count for c in h.children().values()) if h else 0
        p = _tokens(rng, 10)
        ref = _reference(params, p, 16)
        eng = _engine(params, drafter=OracleDrafter([ref]))
        eng.submit(p, 16)
        eng.run()
        sp = eng.spec_stats()
        assert val("znicz_serve_spec_drafted_total") - d0 == sp["drafted"]
        assert val("znicz_serve_spec_accepted_total") - a0 == sp["accepted"]
        assert val("znicz_serve_spec_rejected_total") - r0 == sp["rejected"]
        h = obs.get_registry().metrics()["znicz_serve_spec_accept_length"]
        h1 = sum(c.count for c in h.children().values())
        assert h1 - h0 == sp["verify_steps"]
        assert reg is not None  # registry untouched shape-wise
