"""Native C++ inference engine vs the Python model (libZnicz parity).

Builds native/znicz_infer with g++ once per session, exports trained-ish
models through znicz_tpu.export, and cross-checks forward outputs — the
deployment-path analog of the golden kernel tests (SURVEY.md §4, 2.4).
"""

import os
import subprocess

import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.export import export_model
from znicz_tpu.workflow import build

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="session")
def znicz_infer(tmp_path_factory):
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("g++ not available: skipping native-engine parity tests")
    exe = str(tmp_path_factory.mktemp("native") / "znicz_infer")
    subprocess.run(
        [
            "g++", "-O2", "-std=c++17",
            os.path.join(REPO, "native", "znicz_infer.cc"),
            "-o", exe,
        ],
        check=True,
        capture_output=True,
    )
    return exe


def _roundtrip(znicz_infer, tmp_path, model, x):
    model_path = str(tmp_path / "model.znicz")
    export_model(model, model_path)
    in_path = str(tmp_path / "in.f32")
    out_path = str(tmp_path / "out.f32")
    np.asarray(x, np.float32).tofile(in_path)
    subprocess.run(
        [znicz_infer, model_path, in_path, out_path, str(x.shape[0])],
        check=True,
        capture_output=True,
    )
    y = np.fromfile(out_path, np.float32)
    return y.reshape((x.shape[0],) + model.output_shape)


class TestNativeInference:
    def test_mlp_matches_python(self, znicz_infer, tmp_path):
        prng.seed_all(3)
        model = build(
            [
                {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
                {"type": "softmax", "->": {"output_sample_shape": 10}},
            ],
            (64,),
        )
        x = np.asarray(
            prng.get("t").normal((5, 64)), np.float32
        )
        y_py = np.asarray(model.predict(model.params, jnp.asarray(x)))
        y_cc = _roundtrip(znicz_infer, tmp_path, model, x)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-4, atol=1e-5)

    def test_conv_stack_matches_python(self, znicz_infer, tmp_path):
        prng.seed_all(4)
        model = build(
            [
                {
                    "type": "conv_relu",
                    "->": {
                        "n_kernels": 8, "kx": 3, "ky": 3,
                        "padding": (1, 1, 1, 1), "sliding": (2, 2),
                    },
                },
                {"type": "norm", "->": {"n": 5}},
                {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
                {"type": "avg_pooling", "->": {"kx": 2, "ky": 2}},
                {"type": "all2all_sigmoid", "->": {"output_sample_shape": 7}},
            ],
            (16, 16, 3),
        )
        x = np.asarray(
            prng.get("t").normal((3, 16, 16, 3)), np.float32
        )
        y_py = np.asarray(model.apply(model.params, jnp.asarray(x)))
        y_cc = _roundtrip(znicz_infer, tmp_path, model, x)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-3, atol=1e-4)

    def test_dropout_is_inference_noop(self, znicz_infer, tmp_path):
        prng.seed_all(5)
        model = build(
            [
                {"type": "all2all_str", "->": {"output_sample_shape": 16}},
                {"type": "dropout", "->": {"dropout_ratio": 0.5}},
                {"type": "all2all", "->": {"output_sample_shape": 4}},
            ],
            (8,),
        )
        x = np.asarray(prng.get("t").normal((2, 8)), np.float32)
        y_py = np.asarray(model.apply(model.params, jnp.asarray(x)))
        y_cc = _roundtrip(znicz_infer, tmp_path, model, x)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-4, atol=1e-5)

    def test_autoencoder_matches_python(self, znicz_infer, tmp_path):
        # the mnist_ae deployment path (VERDICT r1 #6): conv encoder ->
        # deconv decoder round-trips through the native engine
        prng.seed_all(7)
        model = build(
            [
                {
                    "type": "conv_tanh",
                    "->": {
                        "n_kernels": 6, "kx": 5, "ky": 5, "sliding": (3, 3),
                    },
                },
                {
                    "type": "deconv",
                    "->": {"n_channels": 1, "kx": 5, "ky": 5,
                           "sliding": (3, 3)},
                },
            ],
            (14, 14, 1),
        )
        x = np.asarray(prng.get("t").normal((3, 14, 14, 1)), np.float32)
        y_py = np.asarray(model.apply(model.params, jnp.asarray(x)))
        assert y_py.shape == (3, 14, 14, 1)  # exact inverse geometry
        y_cc = _roundtrip(znicz_infer, tmp_path, model, x)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-4, atol=1e-5)

    def test_deconv_strided_padded_matches_python(self, znicz_infer, tmp_path):
        prng.seed_all(8)
        model = build(
            [
                {"type": "cutter", "->": {"padding": (1, 2, 1, 0)}},
                {
                    "type": "conv_relu",
                    "->": {"n_kernels": 4, "kx": 3, "ky": 3,
                           "sliding": (2, 2), "padding": (1, 1, 1, 1)},
                },
                {
                    "type": "deconv",
                    "->": {"n_channels": 2, "kx": 3, "ky": 3,
                           "sliding": (2, 2), "padding": (1, 1, 1, 1)},
                },
            ],
            (12, 10, 2),
        )
        x = np.asarray(prng.get("t").normal((2, 12, 10, 2)), np.float32)
        y_py = np.asarray(model.apply(model.params, jnp.asarray(x)))
        y_cc = _roundtrip(znicz_infer, tmp_path, model, x)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-4, atol=1e-5)

    def test_mnist_ae_model_exports(self, tmp_path):
        # the shipped mnist_ae config passes the export precheck now
        from znicz_tpu.export import validate_exportable
        from znicz_tpu.models import mnist_ae

        prng.seed_all(9)
        wf = mnist_ae.build_workflow()
        validate_exportable(wf.model)  # must not raise

    def test_describe(self, znicz_infer, tmp_path):
        prng.seed_all(6)
        model = build(
            [{"type": "softmax", "->": {"output_sample_shape": 3}}], (5,)
        )
        path = str(tmp_path / "m.znicz")
        export_model(model, path)
        out = subprocess.run(
            [znicz_infer, path, "--describe"],
            check=True,
            capture_output=True,
            text=True,
        ).stdout
        assert "input_shape: 5" in out
        assert "softmax" in out


class TestNativeLMInference:
    def test_lm_forward_matches_python(self, znicz_infer, tmp_path):
        # the beyond-parity flagship deploys natively too (SURVEY.md 2.4):
        # 2-block causal LM, C++ logits == python lm_apply logits
        from znicz_tpu.export import export_lm_model
        from znicz_tpu.workflow.transformer import init_lm_params, lm_apply

        prng.seed_all(27)
        vocab, d, heads, t = 17, 32, 4, 12
        params = init_lm_params(vocab, d, 2, heads, max_seq=t)
        tokens = np.random.default_rng(7).integers(
            0, vocab, (3, t)
        ).astype(np.int32)
        y_py = np.asarray(
            lm_apply(params, jnp.asarray(tokens), n_heads=heads)
        )

        model_path = str(tmp_path / "lm.znicz")
        export_lm_model(params, model_path, n_heads=heads)
        in_path, out_path = str(tmp_path / "in.f32"), str(tmp_path / "o.f32")
        tokens.astype(np.float32).tofile(in_path)
        subprocess.run(
            [znicz_infer, model_path, in_path, out_path, "3"],
            check=True, capture_output=True,
        )
        y_cc = np.fromfile(out_path, np.float32).reshape(3, t, vocab)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-4, atol=1e-4)

    def test_lm_describe_and_token_guard(self, znicz_infer, tmp_path):
        from znicz_tpu.export import export_lm_model
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(28)
        params = init_lm_params(9, 16, 1, 2, max_seq=6)
        model_path = str(tmp_path / "lm.znicz")
        export_lm_model(params, model_path, n_heads=2)
        out = subprocess.run(
            [znicz_infer, model_path, "--describe"],
            check=True, capture_output=True, text=True,
        ).stdout
        assert "lm_embed lm_block lm_head" in out
        # out-of-vocab token ids must fail loudly, not read garbage
        bad = np.full((1, 6), 42.0, np.float32)
        in_path, out_path = str(tmp_path / "b.f32"), str(tmp_path / "bo.f32")
        bad.tofile(in_path)
        r = subprocess.run(
            [znicz_infer, model_path, in_path, out_path, "1"],
            capture_output=True, text=True,
        )
        assert r.returncode != 0
        assert "vocabulary" in r.stderr

    def test_moe_lm_forward_matches_python(self, znicz_infer, tmp_path):
        # MoE blocks deploy natively too: dense-dispatch gated experts in
        # C++ must reproduce ops/moe.apply through the whole LM
        from functools import partial

        from znicz_tpu.export import export_lm_model
        from znicz_tpu.workflow.transformer import init_lm_params, lm_apply

        prng.seed_all(31)
        vocab, d, heads, t = 17, 32, 4, 12
        params = init_lm_params(vocab, d, 2, heads, max_seq=t, moe_experts=4)
        tokens = np.random.default_rng(9).integers(
            0, vocab, (3, t)
        ).astype(np.int32)
        y_py = np.asarray(
            lm_apply(
                params, jnp.asarray(tokens), n_heads=heads, moe_top_k=2
            )
        )

        model_path = str(tmp_path / "moe_lm.znicz")
        export_lm_model(params, model_path, n_heads=heads, moe_top_k=2)
        in_path, out_path = str(tmp_path / "mi.f32"), str(tmp_path / "mo.f32")
        tokens.astype(np.float32).tofile(in_path)
        subprocess.run(
            [znicz_infer, model_path, in_path, out_path, "3"],
            check=True, capture_output=True,
        )
        y_cc = np.fromfile(out_path, np.float32).reshape(3, t, vocab)
        np.testing.assert_allclose(y_cc, y_py, rtol=1e-4, atol=1e-4)


class TestNativeLMDecode:
    def test_generate_matches_python_greedy(self, znicz_infer, tmp_path):
        # the C++ --generate KV-cache decode emits token-for-token what
        # workflow/generate.py's greedy generate produces
        from znicz_tpu.export import export_lm_model
        from znicz_tpu.workflow.generate import generate
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(27)
        vocab, heads = 17, 4
        params = init_lm_params(vocab, 32, 2, heads, max_seq=20)
        prompt = np.random.default_rng(7).integers(
            0, vocab, (3, 6)
        ).astype(np.int32)
        py = np.asarray(
            generate(
                params, jnp.asarray(prompt), n_heads=heads,
                max_new_tokens=10,
            )
        )
        model_path = str(tmp_path / "lm.znicz")
        export_lm_model(params, model_path, n_heads=heads)
        ip, op = str(tmp_path / "p.f32"), str(tmp_path / "o.f32")
        prompt.astype(np.float32).tofile(ip)
        subprocess.run(
            [znicz_infer, model_path, ip, op, "3", "--generate", "10"],
            check=True, capture_output=True,
        )
        cc = np.fromfile(op, np.float32).reshape(3, 16).astype(np.int32)
        np.testing.assert_array_equal(py, cc)

    def test_moe_generate_matches_python_greedy(self, znicz_infer, tmp_path):
        from znicz_tpu.export import export_lm_model
        from znicz_tpu.workflow.generate import generate
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(31)
        vocab, heads = 17, 4
        params = init_lm_params(
            vocab, 32, 2, heads, max_seq=18, moe_experts=4
        )
        prompt = np.random.default_rng(9).integers(
            0, vocab, (2, 5)
        ).astype(np.int32)
        py = np.asarray(
            generate(
                params, jnp.asarray(prompt), n_heads=heads,
                max_new_tokens=8, moe_top_k=2,
            )
        )
        model_path = str(tmp_path / "moe_lm.znicz")
        export_lm_model(params, model_path, n_heads=heads, moe_top_k=2)
        ip, op = str(tmp_path / "mp.f32"), str(tmp_path / "mo.f32")
        prompt.astype(np.float32).tofile(ip)
        subprocess.run(
            [znicz_infer, model_path, ip, op, "2", "--generate", "8"],
            check=True, capture_output=True,
        )
        cc = np.fromfile(op, np.float32).reshape(2, 13).astype(np.int32)
        np.testing.assert_array_equal(py, cc)

    def test_generate_capacity_guard(self, znicz_infer, tmp_path):
        # decoding past the positional table must fail loudly
        from znicz_tpu.export import export_lm_model
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(28)
        params = init_lm_params(9, 16, 1, 2, max_seq=8)
        model_path = str(tmp_path / "lm.znicz")
        export_lm_model(params, model_path, n_heads=2)
        prompt = np.zeros((1, 6), np.float32)
        ip, op = str(tmp_path / "p.f32"), str(tmp_path / "o.f32")
        prompt.tofile(ip)
        r = subprocess.run(
            [znicz_infer, model_path, ip, op, "1", "--generate", "5"],
            capture_output=True, text=True,
        )
        assert r.returncode != 0
        assert "positional table" in r.stderr

    def test_generate_rejects_non_lm(self, znicz_infer, tmp_path):
        from znicz_tpu.export import export_model

        prng.seed_all(3)
        model = build(
            [{"type": "softmax", "->": {"output_sample_shape": 4}}], (8,)
        )
        model_path = str(tmp_path / "mlp.znicz")
        export_model(model, model_path)
        prompt = np.zeros((1, 4), np.float32)
        ip, op = str(tmp_path / "p.f32"), str(tmp_path / "o.f32")
        prompt.tofile(ip)
        r = subprocess.run(
            [znicz_infer, model_path, ip, op, "1", "--generate", "3"],
            capture_output=True, text=True,
        )
        assert r.returncode != 0
        assert "not an LM" in r.stderr
