"""Training flight recorder: pipeline attribution, step anomalies, doctor.

The PR 13 observability layer in one suite: attribution fractions must
partition the step wall (~1.0), bottleneck naming must be deterministic
under the ``loader.fetch``/``loader.h2d`` fault fixtures, an injected
NaN loss must produce a typed ring verdict AND an exit-1 from
``znicz-doctor``, the watch-vector piggyback must compile ZERO new
programs, and the doctor smoke runs against a REAL short training
epoch's ``metrics.prom``.
"""

import json
import math
import time

import numpy as np
import pytest

from znicz_tpu.observability import (
    MetricsRegistry,
    PipelineAttribution,
    StepAnomalyDetector,
    get_registry,
)
from znicz_tpu.observability import anomaly as anomaly_mod
from znicz_tpu.observability import doctor
from znicz_tpu.observability import pipeline
from znicz_tpu.utils import faults
from znicz_tpu.utils.bench_diff import metric_direction
from znicz_tpu.workflow import StandardWorkflow

MLP = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


def _stream_workflow(n=512, bs=64, data=None, **kw):
    """Streaming (device_resident=False) stepwise workflow on synthetic
    images — the regime the attribution instrument targets."""
    from znicz_tpu.loader.fullbatch import FullBatchLoader

    gen = np.random.default_rng(0)
    if data is None:
        data = gen.integers(0, 256, (n, 8, 8, 1), dtype=np.uint8)
        norm = {"normalization": "range",
                "normalization_kwargs": {"scale": 255.0, "shift": -0.5}}
    else:
        norm = {}
    labels = gen.integers(0, 10, len(data)).astype(np.int32)
    ld = FullBatchLoader(
        {"train": data}, {"train": labels}, minibatch_size=bs,
        device_resident=False, **norm,
    )
    wf = StandardWorkflow(
        ld, MLP,
        decision_config={"max_epochs": 10000},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
        epoch_dispatch="step",
        **kw,
    )
    wf.initialize(seed=7)
    return wf


def _reset_anomaly_gauges():
    """Zero the shared anomaly families so a prior test's detector
    can't leak an active flag into this one's exposition."""
    fams = get_registry().metrics()
    for name in (
        "znicz_train_anomalies_total",
        "znicz_train_anomaly_active",
        "znicz_train_last_loss",
        "znicz_train_last_grad_norm",
    ):
        if name in fams:
            fams[name].reset()


class TestPipelineAttribution:
    def _synthetic_registry(self):
        """30 steps of 0.1 s wall: 2.0 s prefetch-wait (producer busy
        fetching), 0.8 s dispatch, 0.2 s untimed."""
        reg = MetricsRegistry()
        wall = pipeline.step_wall_seconds(reg)
        for _ in range(30):
            wall.observe(0.1)
        wait = reg.histogram(pipeline.WAIT_METRIC)
        for _ in range(30):
            wait.observe(2.0 / 30)
        phase = reg.histogram(pipeline.PHASE_METRIC, labelnames=("phase",))
        phase.labels(phase="dispatch/train").observe(0.8)
        stage = pipeline.stage_seconds(reg)
        stage.labels(stage=pipeline.STAGE_FETCH).observe(1.8)
        stage.labels(stage=pipeline.STAGE_H2D).observe(0.2)
        return reg

    def test_fractions_sum_to_one_on_synthetic_trace(self):
        att = PipelineAttribution.from_registry(
            self._synthetic_registry()
        ).attribution()
        f = att["fractions"]
        assert abs(sum(f.values()) - 1.0) < 0.05
        assert att["type"] == "pipeline"
        assert att["steps"] == 30
        # 2.0 of 3.0 s waiting, producer 90% in fetch -> input-bound
        assert att["verdict"] == "input-bound"
        assert f["prefetch_wait"] == pytest.approx(0.6, abs=0.05)
        assert f["compute"] == pytest.approx(0.8 / 3.0, abs=0.05)
        # h2d carved out of the wait slice by the producer's h2d share
        assert f["h2d"] == pytest.approx(
            (2.0 / 3.0) * (0.2 / 2.0), abs=0.05
        )
        assert att["input_bound_frac"] == pytest.approx(
            f["prefetch_wait"] + f["h2d"]
        )
        assert att["confidence"] in ("low", "medium", "high")

    def test_prometheus_roundtrip_matches_registry(self):
        reg = self._synthetic_registry()
        from_reg = PipelineAttribution.from_registry(reg).attribution()
        from_prom = PipelineAttribution.from_prometheus(
            reg.prometheus_text()
        ).attribution()
        assert from_prom["fractions"] == from_reg["fractions"]
        assert from_prom["verdict"] == from_reg["verdict"]

    def test_snapshot_source_skips_self_describing_riders(self):
        reg = self._synthetic_registry()
        snap = reg.snapshot()
        # the bench attaches {"type": "slo"/"programs"/"pipeline"}
        # records next to the families; the parser must skip them
        snap["slo"] = {"type": "slo", "breached": False}
        snap["pipeline"] = {"type": "pipeline", "verdict": "input-bound"}
        att = PipelineAttribution.from_snapshot(snap).attribution()
        assert att["verdict"] == "input-bound"
        assert att["steps"] == 30

    def test_no_data_verdict(self):
        att = PipelineAttribution.from_registry(
            MetricsRegistry()
        ).attribution()
        assert att["verdict"] == "no-data"
        assert att["input_bound_frac"] == 0.0

    def test_slow_producer_fixture_is_input_bound(self):
        # the CI twin of the acceptance criterion: a deterministically
        # slow producer (loader.fetch delay) must be named input-bound
        wf = _stream_workflow()
        wf.run_epoch()  # compile + warmup
        pipeline.reset_window()
        with faults.injected("loader.fetch", delay=0.02):
            wf.run_epoch()
        att = PipelineAttribution.from_registry().attribution()
        assert att["verdict"] == "input-bound"
        assert abs(sum(att["fractions"].values()) - 1.0) < 0.05
        assert att["input_bound_frac"] > 0.5
        assert att["fractions"]["prefetch_wait"] > att["fractions"]["h2d"]

    def test_slow_h2d_fixture_is_h2d_bound(self):
        wf = _stream_workflow()
        wf.run_epoch()
        pipeline.reset_window()
        with faults.injected("loader.h2d", delay=0.02):
            wf.run_epoch()
        att = PipelineAttribution.from_registry().attribution()
        assert att["verdict"] == "h2d-bound"
        assert abs(sum(att["fractions"].values()) - 1.0) < 0.05
        assert att["fractions"]["h2d"] > att["fractions"]["prefetch_wait"]
        # the probe's bandwidth gauge reflects the injected slowness
        assert att["h2d_bytes_per_second"] is not None

    def test_prefetch_stage_split_and_queue_full_counter(self):
        from znicz_tpu.loader.prefetch import prefetch

        pipeline.reset_window()
        # depth 1 + slow consumer: the producer finds the queue full
        out = []
        for item in prefetch(iter(range(8)), depth=1):
            time.sleep(0.01)
            out.append(item)
        assert out == list(range(8))
        reg = get_registry()
        stage = reg.metrics()[pipeline.STAGE_METRIC]
        by = {
            k[0]: child for k, child in stage.children().items()
        }
        assert by[pipeline.STAGE_FETCH].count >= 8
        assert by[pipeline.STAGE_ENQUEUE].count >= 8
        # the producer stalled on a full queue, and that is DISTINCT
        # from a slow producer: enqueue carries the stall time
        assert reg.metrics()[pipeline.QUEUE_FULL_METRIC].value > 0
        assert by[pipeline.STAGE_ENQUEUE].sum > by[pipeline.STAGE_FETCH].sum

    def test_prefetch_transform_stage_and_results(self):
        from znicz_tpu.loader.prefetch import prefetch

        pipeline.reset_window()
        out = list(
            prefetch(iter(range(6)), depth=2, transform=lambda x: x * 2)
        )
        assert out == [0, 2, 4, 6, 8, 10]
        stage = get_registry().metrics()[pipeline.STAGE_METRIC]
        by = {k[0]: c for k, c in stage.children().items()}
        assert by[pipeline.STAGE_TRANSFORM].count == 6

    def test_h2d_probe_bandwidth_gauge(self):
        reg = MetricsRegistry()
        probe = pipeline.H2DProbe(reg)
        probe.observe(1_000_000, 0.1)  # 10 MB/s
        assert reg.metrics()[
            pipeline.H2D_BPS_METRIC
        ].value == pytest.approx(1e7, rel=0.01)
        assert reg.metrics()[pipeline.H2D_BYTES_METRIC].value == 1e6


class TestAnomalyDetector:
    def test_loss_spike_robust_z(self):
        reg = MetricsRegistry()
        det = StepAnomalyDetector(registry=reg, min_history=8)
        for i in range(20):
            out = det.observe_step(i, loss=1.0 + 0.01 * (i % 3))
            assert out == []
        raised = det.observe_step(20, loss=50.0)
        assert [a["type"] for a in raised] == [anomaly_mod.LOSS_SPIKE]
        assert raised[0]["zscore"] > det.z_threshold
        assert det.active
        rep = det.report()
        assert rep["counts"] == {anomaly_mod.LOSS_SPIKE: 1}
        # the flight-recorder snapshot carries the lead-in steps
        assert len(rep["ring"]) == 1
        assert rep["ring"][0]["snapshot"][-1]["step"] == 19

    def test_step_time_regression_and_active_decay(self):
        reg = MetricsRegistry()
        det = StepAnomalyDetector(
            registry=reg, min_history=8, active_window=5
        )
        for i in range(15):
            det.observe_step(i, loss=1.0, step_seconds=0.01)
        # one slow step is a blip, not a regression: no verdict yet
        assert det.observe_step(15, loss=1.0, step_seconds=0.5) == []
        assert det.observe_step(16, loss=1.0, step_seconds=0.5) == []
        raised = det.observe_step(17, loss=1.0, step_seconds=0.5)
        assert [a["type"] for a in raised] == [
            anomaly_mod.STEP_TIME_REGRESSION
        ]
        assert det.active
        for i in range(18, 24):  # active_window steps later: cleared
            det.observe_step(i, loss=1.0, step_seconds=0.01)
        assert not det.active
        assert reg.metrics()["znicz_train_anomaly_active"].value == 0.0

    def test_non_finite_grad_norm_typed(self):
        det = StepAnomalyDetector(registry=MetricsRegistry())
        raised = det.observe_step(
            0, loss=1.0, grad_norm=float("inf")
        )
        assert [a["type"] for a in raised] == [anomaly_mod.NON_FINITE_GRAD]

    def test_ring_is_bounded(self):
        det = StepAnomalyDetector(
            registry=MetricsRegistry(), ring_size=4
        )
        for i in range(9):
            det.observe_step(i, loss=float("nan"))
        rep = det.report()
        assert len(rep["ring"]) == 4
        assert rep["counts"][anomaly_mod.NON_FINITE_LOSS] == 9
        assert rep["ring"][-1]["step"] == 8
        json.dumps(rep)  # JSON-able end to end

    def test_nan_baseline_does_not_mute_detection(self):
        # a NaN loss must not poison the rolling median: later finite
        # spikes still detect
        det = StepAnomalyDetector(
            registry=MetricsRegistry(), min_history=8
        )
        det.observe_step(0, loss=float("nan"))
        for i in range(1, 15):
            det.observe_step(i, loss=1.0)
        raised = det.observe_step(15, loss=100.0)
        assert anomaly_mod.LOSS_SPIKE in [a["type"] for a in raised]


class TestNanFlightRecorder:
    def test_injected_nan_loss_rings_and_doctor_exits_1(
        self, tmp_path, capsys
    ):
        from znicz_tpu.services.web_status import StatusWriter

        _reset_anomaly_gauges()
        # poison a late batch so the detector has a healthy lead-in
        data = np.random.default_rng(3).normal(
            size=(256, 8, 8, 1)
        ).astype(np.float32)
        data[200:] = np.nan
        wf = _stream_workflow(data=data, bs=32)
        sw = StatusWriter(str(tmp_path))
        wf.services.append(sw)
        verdict = wf.run_epoch()
        assert verdict is not None
        rep = wf.anomaly.report()
        assert rep["active"]
        assert rep["counts"].get(anomaly_mod.NON_FINITE_LOSS, 0) >= 1
        # the loader shuffles, so the FIRST poisoned batch may land at
        # step 0 (empty lead-in) — the latest entry always has one
        entry = [
            e for e in rep["ring"]
            if e["type"] == anomaly_mod.NON_FINITE_LOSS
        ][-1]
        assert entry["snapshot"], "ring entry must carry the lead-in"
        # the flight recorder surfaced through status.json ...
        status = json.loads((tmp_path / "status.json").read_text())
        assert status["anomalies"]["active"]
        assert status["anomalies"]["counts"]
        assert status["pipeline"]["type"] == "pipeline"
        # ... and through /metrics -> znicz-doctor gates exit 1
        prom = tmp_path / "metrics.prom"
        assert prom.exists()
        assert doctor.main([str(prom)]) == 1
        out = capsys.readouterr().out
        assert "ACTIVE" in out
        assert doctor.main([str(prom), "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["anomalies"]["active"] is True
        assert payload["anomalies"]["counts"].get(
            anomaly_mod.NON_FINITE_LOSS, 0
        ) >= 1
        _reset_anomaly_gauges()


class TestZeroNewPrograms:
    def test_watch_piggyback_compiles_nothing_new(self):
        # the acceptance pin: the grad-norm/attribution instrumentation
        # adds ZERO compiled programs — nothing lands in the PR 11
        # device ledger / znicz_serve_compiles_total, and the train
        # step stays ONE jit cache entry with the watch output riding
        # the existing program
        from znicz_tpu.observability import device

        ledger_before = device.program_count()
        compiles = get_registry().counter(
            "znicz_serve_compiles_total",
            "distinct compiled engine programs by kind and bucket",
            ("kind", "bucket"),
        )
        compiles_before = sum(
            c.value for c in compiles.children().values()
        )
        compile_hist = get_registry().metrics().get(
            "znicz_compile_seconds"
        )
        compile_obs_before = (
            sum(c.count for c in compile_hist.children().values())
            if compile_hist is not None
            else 0
        )
        wf = _stream_workflow(n=128, bs=64)  # detector ON by default
        assert wf.anomaly is not None
        wf.run_epoch()
        wf.run_epoch()
        assert wf._train_step._cache_size() == 1
        off = _stream_workflow(n=128, bs=64, anomaly=False)
        assert off.anomaly is None
        off.run_epoch()
        assert off._train_step._cache_size() == 1
        assert device.program_count() == ledger_before
        assert (
            sum(c.value for c in compiles.children().values())
            == compiles_before
        )
        compile_hist = get_registry().metrics().get(
            "znicz_compile_seconds"
        )
        compile_obs_after = (
            sum(c.count for c in compile_hist.children().values())
            if compile_hist is not None
            else 0
        )
        assert compile_obs_after == compile_obs_before

    def test_scan_path_feeds_detector_without_extra_programs(self):
        # scanned dispatch: watches stack inside the ONE scan program
        # and drain at the epoch sync
        from znicz_tpu.loader.fullbatch import FullBatchLoader

        gen = np.random.default_rng(1)
        imgs = gen.integers(0, 256, (256, 8, 8, 1), dtype=np.uint8)
        labels = gen.integers(0, 10, 256).astype(np.int32)
        ld = FullBatchLoader(
            {"train": imgs}, {"train": labels}, minibatch_size=64,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_resident=True,
        )
        wf = StandardWorkflow(
            ld, MLP,
            decision_config={"max_epochs": 10000},
            default_hyper={"learning_rate": 0.1},
            epoch_dispatch="scan",
        )
        wf.initialize(seed=5)
        wf.run_epoch()
        assert wf._train_epoch_scan._cache_size() == 1
        rep = wf.anomaly.report()
        assert rep["last_step"] == 3  # 4 scan steps fed, 0-indexed
        assert rep["total"] == 0  # healthy run


class TestDoctorCLI:
    def test_smoke_on_real_epoch_metrics_prom(self, tmp_path, capsys):
        # the tier-1 CI smoke: a real short training epoch writes
        # metrics.prom; the doctor parses it, prints a verdict, exit 0
        from znicz_tpu.services.web_status import StatusWriter

        _reset_anomaly_gauges()
        pipeline.reset_window()
        wf = _stream_workflow(n=256, bs=32)
        sw = StatusWriter(str(tmp_path))
        wf.services.append(sw)
        wf.run_epoch()
        rc = doctor.main([str(tmp_path / "metrics.prom")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "anomalies:" in out
        assert "-bound" in out or "unattributed" in out
        rc = doctor.main([str(tmp_path / "metrics.prom"), "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["type"] == "pipeline"
        assert payload["verdict"] != "no-data"
        assert abs(sum(payload["fractions"].values()) - 1.0) < 0.05
        assert payload["steps"] >= 8

    def test_usage_errors_exit_2(self, tmp_path, capsys):
        assert doctor.main([]) == 2
        assert doctor.main(["a", "b"]) == 2
        assert doctor.main(["--instance"]) == 2
        assert doctor.main([str(tmp_path / "missing.prom")]) == 2
        bad = tmp_path / "bad.prom"
        bad.write_text("this is { not an exposition !!!\n")
        assert doctor.main([str(bad)]) == 2
        capsys.readouterr()

    def test_no_data_source_is_healthy(self, tmp_path, capsys):
        reg = MetricsRegistry()
        reg.counter("some_counter_total", "x").inc()
        p = tmp_path / "m.prom"
        p.write_text(reg.prometheus_text())
        assert doctor.main([str(p)]) == 0
        assert "no-data" in capsys.readouterr().out

    def test_instance_filter_scopes_fleet_exposition(self, tmp_path):
        # two instances in one exposition (the aggregator's merged
        # /metrics): --instance must attribute only the wanted one
        lines = []
        for inst, wall in (("a", 1.0), ("b", 9.0)):
            lines += [
                "znicz_train_step_wall_seconds_bucket"
                f'{{instance="{inst}",le="+Inf"}} 10',
                f'znicz_train_step_wall_seconds_sum{{instance="{inst}"}}'
                f" {wall}",
                "znicz_train_step_wall_seconds_count"
                f'{{instance="{inst}"}} 10',
            ]
        text = (
            "# TYPE znicz_train_step_wall_seconds histogram\n"
            + "\n".join(lines) + "\n"
        )
        att = PipelineAttribution.from_prometheus(
            text, instance="a"
        ).attribution()
        assert att["wall_seconds"] == pytest.approx(1.0)
        both = PipelineAttribution.from_prometheus(text).attribution()
        assert both["wall_seconds"] == pytest.approx(10.0)


class TestTickOccupancy:
    def test_engine_tick_occupancy_fractions(self):
        from znicz_tpu.core import prng
        from znicz_tpu.services.engine import DecodeEngine
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(27)
        params = init_lm_params(17, 32, 2, 4, max_seq=64)
        eng = DecodeEngine(
            params, n_heads=4, eos_id=14, batch_size=2, admit_every=4
        )
        # the registry family is process-wide — zero it so earlier
        # engine tests' ticks don't skew the count comparison below
        get_registry().metrics()["znicz_serve_tick_occupancy"].reset()
        gen = np.random.default_rng(3)
        for _ in range(3):
            eng.submit(gen.integers(0, 17, (6,)).astype(np.int32), 8)
        eng.run()
        occ = eng.stats()["tick_occupancy"]
        assert occ["ticks"] > 0
        assert occ["wall_s"] > 0
        assert set(occ["frac"]) == {"prefill", "decode", "spec_verify"}
        assert sum(occ["frac"].values()) <= 1.0 + 1e-6
        assert occ["frac"]["decode"] > 0
        assert occ["frac"]["spec_verify"] == 0.0  # dense: no spec
        # the registry twin exists with fraction-ladder buckets
        hist = get_registry().metrics()["znicz_serve_tick_occupancy"]
        by = {k[0]: c for k, c in hist.children().items()}
        assert by["decode"].count == occ["ticks"]
        assert all(0.0 <= c._uppers[0] <= 0.01 for c in by.values())

    def test_spec_verify_phase_counted(self):
        from znicz_tpu.core import prng
        from znicz_tpu.services.engine import PagedDecodeEngine
        from znicz_tpu.workflow.transformer import init_lm_params

        prng.seed_all(27)
        params = init_lm_params(17, 32, 2, 4, max_seq=128)
        eng = PagedDecodeEngine(
            params, n_heads=4, eos_id=16, batch_size=2,
            block_size=8, n_blocks=64, spec_k=4,
        )
        # repeat-heavy prompt: prompt-lookup drafts, verify ticks run
        prompt = np.tile(
            np.array([1, 2, 3, 4], np.int32), 6
        )
        eng.submit(prompt, 16)
        eng.run()
        occ = eng.stats()["tick_occupancy"]
        if eng.stats()["spec"]["verify_steps"] > 0:
            assert occ["frac"]["spec_verify"] > 0


class TestBenchDiffMarkers:
    def test_bound_frac_is_lower_better(self):
        assert metric_direction(
            "train_input_bound_frac", set(), set()
        ) == "lower"

    def test_bytes_per_second_is_higher_better(self):
        assert metric_direction(
            "train_h2d_bytes_per_second", set(), set()
        ) == "higher"


class TestResetWindowInteraction:
    def test_phase_timer_survives_warmup_reset(self):
        # reset_window() clears znicz_train_phase_seconds; a PhaseTimer
        # holding a pre-reset baseline must fall back to the fresh
        # series instead of reporting empty/negative windows
        # (status.json["timing"] reads summary())
        from znicz_tpu.observability import PhaseTimer

        timer = PhaseTimer(pipeline.PHASE_METRIC)
        with timer.phase("dispatch/train"):
            time.sleep(0.002)
        assert "dispatch/train" in timer.summary()
        pipeline.reset_window()
        with timer.phase("dispatch/train"):
            time.sleep(0.002)
        s = timer.summary()["dispatch/train"]
        assert s["count"] == 1
        assert s["total_s"] > 0

    def test_anomaly_off_watch_is_none_on_device(self):
        # anomaly=False must remove the watch output entirely (XLA can
        # then DCE the norm), not just skip the host read
        wf = _stream_workflow(n=128, bs=64, anomaly=False)
        mb = next(iter(wf.loader.batches("train")))
        import jax.numpy as jnp

        _, _, watch = wf._train_step(
            wf.state, jnp.asarray(mb.data), jnp.asarray(mb.labels),
            jnp.asarray(mb.mask), 1.0, wf._acc_init(), wf._ctx,
        )
        assert watch is None


class TestWatchVector:
    def test_stepwise_detector_sees_losses_and_grad_norms(self):
        wf = _stream_workflow(n=256, bs=32)
        wf.run_epoch()
        rep = wf.anomaly.report()
        assert rep["last_step"] == 7  # 8 train steps, 0-indexed
        # gauges carry finite last-step values
        assert math.isfinite(
            get_registry().metrics()["znicz_train_last_loss"].value
        )
        assert (
            get_registry().metrics()["znicz_train_last_grad_norm"].value
            > 0
        )
