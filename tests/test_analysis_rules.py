"""Per-rule unit tests for the znicz-check static analyzer.

Each rule gets positive (fires) and negative (stays quiet) cases on
small inline modules; plus pragma suppression and baseline round-trip
semantics.  Pure-AST — no jax tracing happens here.
"""

import textwrap

import pytest

from znicz_tpu.analysis import engine
from znicz_tpu.analysis.rules import RULES, get_rules
from znicz_tpu.analysis.rules.sharding_axes import (
    ShardingAxisRule,
    declared_axes,
)


def run(src, rule_id, path="pkg/mod.py"):
    src = textwrap.dedent(src)
    if rule_id == "ZNC003":
        rules = [ShardingAxisRule(axes={"data", "model", "pipe"})]
    else:
        rules = [RULES[rule_id]()]
    return engine.analyze_source(src, path, rules)


def ids(findings):
    return [f.rule for f in findings]


# -- ZNC001: traced branch ----------------------------------------------


class TestTracedBranch:
    def test_if_on_traced_arg_fires(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x
            """,
            "ZNC001",
        )
        assert ids(fs) == ["ZNC001"]
        assert "x" in fs[0].message

    def test_while_on_traced_arg_fires(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(x):
                while x > 0:
                    x = x - 1
                return x
            """,
            "ZNC001",
        )
        assert ids(fs) == ["ZNC001"]

    def test_static_argname_is_exempt(self):
        fs = run(
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("greedy",))
            def f(x, greedy):
                if greedy:
                    return x
                return -x
            """,
            "ZNC001",
        )
        assert fs == []

    def test_static_argnums_is_exempt(self):
        fs = run(
            """
            from functools import partial
            import jax

            @partial(jax.jit, static_argnums=(1,))
            def f(x, n):
                if n:
                    return x
                return -x
            """,
            "ZNC001",
        )
        assert fs == []

    def test_is_none_and_shape_checks_are_exempt(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(x, mask):
                if mask is None:
                    return x
                if x.ndim == 2:
                    return x + mask
                return x
            """,
            "ZNC001",
        )
        assert fs == []

    def test_scan_body_branching_on_carry_fires(self):
        fs = run(
            """
            import jax

            def outer(xs):
                def body(carry, x):
                    if carry > 0:
                        carry = carry + x
                    return carry, x
                return jax.lax.scan(body, 0.0, xs)
            """,
            "ZNC001",
        )
        assert ids(fs) == ["ZNC001"]

    def test_call_form_jit_fires(self):
        fs = run(
            """
            import jax

            def step(x):
                if x > 0:
                    return x
                return -x

            fast = jax.jit(step)
            """,
            "ZNC001",
        )
        assert ids(fs) == ["ZNC001"]

    def test_partial_bound_kwargs_are_static(self):
        """Names bound by partial() are trace-time constants —
        branching on them is fine (pipeline.py's shard_map body does
        exactly this with n_micro/n_stages)."""
        fs = run(
            """
            from functools import partial
            import jax

            def outer(mesh, spec, x):
                def local(xs, n_micro, n_stages):
                    if n_micro < n_stages:
                        raise AssertionError("bad config")
                    return xs
                return jax.shard_map(
                    partial(local, n_micro=4, n_stages=2),
                    mesh=mesh, in_specs=(spec,), out_specs=spec,
                )(x)
            """,
            "ZNC001",
        )
        assert fs == []

    def test_builtin_map_is_not_lax_map(self):
        """Python's map() over a side-effecting helper is host code."""
        fs = run(
            """
            import os

            def f(x):
                print(x)
                return os.path.basename(x)

            def collect(items):
                return list(map(f, items))
            """,
            "ZNC002",
        )
        assert fs == []

    def test_sibling_same_named_def_is_not_conflated(self):
        """A host-side helper that merely SHARES a name with a scan
        body in another function must not be marked traced."""
        fs = run(
            """
            import jax

            def trainer(xs):
                def body(c, x):
                    return c + x, x
                return jax.lax.scan(body, 0.0, xs)

            def reporter(rows):
                def body(row):
                    if row:
                        print(row)
                for r in rows:
                    body(r)
            """,
            "ZNC002",
        )
        assert fs == []

    def test_plain_function_is_quiet(self):
        fs = run(
            """
            def f(x):
                if x > 0:
                    return x
                return -x
            """,
            "ZNC001",
        )
        assert fs == []

    def test_closure_sees_enclosing_traced_params(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(x):
                def g():
                    if x > 0:
                        return x
                    return -x
                return g()
            """,
            "ZNC001",
        )
        assert ids(fs) == ["ZNC001"]


# -- ZNC002: host effects ------------------------------------------------


class TestHostEffects:
    def test_print_in_jit_fires(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(x):
                print(x)
                return x
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002"]

    def test_time_in_scan_body_fires(self):
        fs = run(
            """
            import time
            import jax

            def outer(xs):
                def body(c, x):
                    t = time.time()
                    return c + x, t
                return jax.lax.scan(body, 0.0, xs)
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002"]

    def test_numpy_alias_in_grad_fires(self):
        fs = run(
            """
            import numpy as np
            import jax

            def loss(w, x):
                return np.sum(w * x)

            g = jax.grad(loss)
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002"]
        assert "numpy.sum" in fs[0].message

    def test_jnp_is_quiet(self):
        fs = run(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def f(x):
                return jnp.sum(x)
            """,
            "ZNC002",
        )
        assert fs == []

    def test_host_code_print_is_quiet(self):
        fs = run(
            """
            def f(x):
                print(x)
                return x
            """,
            "ZNC002",
        )
        assert fs == []

    def test_device_get_and_block_until_ready_in_jit_fire(self):
        """Host syncs inside jitted code are ZNC002's jurisdiction
        (ZNC007 deliberately defers traced code to it)."""
        fs = run(
            """
            import jax

            @jax.jit
            def step(xs):
                for x in xs:
                    jax.device_get(x)
                    x.block_until_ready()
                return xs
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002", "ZNC002"]

    def test_compat_shard_map_body_is_traced(self):
        """The repo's own compat shim must count as a transform — the
        shard_map bodies are exactly the per-device code these rules
        exist to protect."""
        fs = run(
            """
            import time
            from znicz_tpu.core.compat import shard_map

            def outer(mesh, spec, x):
                def local(xs):
                    time.time()
                    return xs
                return shard_map(
                    local, mesh=mesh, in_specs=(spec,), out_specs=spec
                )(x)
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002"]

    def test_partial_wrapped_shard_map_body_is_traced(self):
        """``shard_map(partial(local, ...))`` — the repo's dominant way
        of handing configured bodies to transforms."""
        fs = run(
            """
            import time
            from functools import partial
            import jax

            def outer(mesh, spec, x):
                def local(xs, scale):
                    time.time()
                    return xs * scale
                return jax.shard_map(
                    partial(local, scale=2.0),
                    mesh=mesh, in_specs=(spec,), out_specs=spec,
                )(x)
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002"]

    def test_experimental_shard_map_spelling_is_traced(self):
        fs = run(
            """
            import time
            from jax.experimental.shard_map import shard_map

            def outer(mesh, spec, x):
                def local(xs):
                    time.time()
                    return xs
                return shard_map(
                    local, mesh=mesh, in_specs=(spec,), out_specs=spec
                )(x)
            """,
            "ZNC002",
        )
        assert ids(fs) == ["ZNC002"]


# -- ZNC003: sharding axes -----------------------------------------------


class TestShardingAxes:
    def test_unknown_axis_in_partition_spec_fires(self):
        fs = run(
            """
            from jax.sharding import PartitionSpec as P

            spec = P("batch", None)
            """,
            "ZNC003",
        )
        assert ids(fs) == ["ZNC003"]
        assert "batch" in fs[0].message

    def test_known_axes_are_quiet(self):
        fs = run(
            """
            from jax.sharding import PartitionSpec as P

            a = P("data", None)
            b = P(("data", "model"))
            c = P(None, "pipe")
            """,
            "ZNC003",
        )
        assert fs == []

    def test_unknown_axis_in_collective_kwarg_fires(self):
        fs = run(
            """
            import jax

            def f(x):
                return jax.lax.psum(x, axis_name="dp")
            """,
            "ZNC003",
        )
        assert ids(fs) == ["ZNC003"]

    def test_unknown_axis_in_positional_collective_arg_fires(self):
        """psum(x, "bacth") — the dominant positional convention."""
        fs = run(
            """
            import jax

            def f(x):
                return jax.lax.psum(x, "bacth")
            """,
            "ZNC003",
        )
        assert ids(fs) == ["ZNC003"]

    def test_non_jax_method_named_like_a_collective_is_quiet(self):
        """`client.all_gather("metrics")` is someone's own method, not a
        jax collective — its string args are not axis names."""
        fs = run(
            """
            def push(client, mesh_like):
                client.all_gather("metrics")
                client.psum("totals")
                mesh_like.Mesh(None, ("rows", "cols"))
            """,
            "ZNC003",
        )
        assert fs == []

    def test_known_positional_collective_axis_is_quiet(self):
        fs = run(
            """
            import jax

            def f(x):
                return jax.lax.psum(x, "data")
            """,
            "ZNC003",
        )
        assert fs == []

    def test_mesh_axis_names_checked(self):
        fs = run(
            """
            from jax.sharding import Mesh

            def build(grid):
                return Mesh(grid, ("rows", "cols"))
            """,
            "ZNC003",
        )
        assert sorted(f.message.split("'")[1] for f in fs) == [
            "cols",
            "rows",
        ]

    def test_declared_axes_parses_real_mesh_module(self):
        axes = declared_axes()
        assert {"data", "model", "pipe"} <= axes

    def test_axes_resolved_against_analyzed_root(self, tmp_path):
        """A different tree's mesh.py governs that tree's analysis —
        e.g. a worktree branch that legitimately adds an axis."""
        mesh_dir = tmp_path / "znicz_tpu" / "parallel"
        mesh_dir.mkdir(parents=True)
        (mesh_dir / "mesh.py").write_text('EXPERT_AXIS = "expert"\n')
        mod = tmp_path / "mod.py"
        mod.write_text(
            "from jax.sharding import PartitionSpec as P\n"
            'a = P("expert")\n'
            'b = P("bogus")\n'
        )
        fs = engine.analyze_paths(
            [str(mod)],
            root=str(tmp_path),
            rules=[ShardingAxisRule()],
        )
        assert [f.rule for f in fs] == ["ZNC003"]
        assert "bogus" in fs[0].message and "expert" in fs[0].message


# -- ZNC004: prng keys ---------------------------------------------------


class TestPrngKeys:
    def test_hardcoded_key_fires(self):
        fs = run(
            """
            import jax

            k = jax.random.key(0)
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_hardcoded_prngkey_fires(self):
        fs = run(
            """
            import jax

            k = jax.random.PRNGKey(42)
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_core_prng_is_sanctioned(self):
        fs = run(
            """
            import jax

            k = jax.random.key(0)
            """,
            "ZNC004",
            path="znicz_tpu/core/prng.py",
        )
        assert fs == []

    def test_key_reuse_fires_once_per_extra_use(self):
        fs = run(
            """
            import jax

            def f(key, shape):
                a = jax.random.normal(key, shape)
                b = jax.random.uniform(key, shape)
                return a + b
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]
        assert "key" in fs[0].message

    def test_split_keys_are_quiet(self):
        fs = run(
            """
            import jax

            def f(key, shape):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1, shape)
                b = jax.random.uniform(k2, shape)
                return a + b
            """,
            "ZNC004",
        )
        assert fs == []

    def test_rebound_key_is_skipped(self):
        fs = run(
            """
            import jax

            def f(key, shape):
                a = jax.random.normal(key, shape)
                key = jax.random.split(key, 1)[0]
                b = jax.random.uniform(key, shape)
                return a + b
            """,
            "ZNC004",
        )
        assert fs == []

    def test_sibling_closures_with_own_key_params_are_quiet(self):
        """Nested scopes must not be conflated: two closures each with
        their OWN `key` parameter is not reuse."""
        fs = run(
            """
            import jax

            def outer(shape):
                def f(key):
                    return jax.random.uniform(key, shape)

                def g(key):
                    return jax.random.normal(key, shape)

                return f, g
            """,
            "ZNC004",
        )
        assert fs == []

    def test_reuse_inside_nested_def_reported_exactly_once(self):
        fs = run(
            """
            import jax

            def outer(shape):
                def f(key):
                    a = jax.random.uniform(key, shape)
                    b = jax.random.normal(key, shape)
                    return a + b

                return f
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_branch_exclusive_consumption_is_quiet(self):
        """if/else arms are mutually exclusive — only one sampler ever
        consumes the key."""
        fs = run(
            """
            import jax

            def f(key, shape, gaussian):
                if gaussian:
                    x = jax.random.normal(key, shape)
                else:
                    x = jax.random.uniform(key, shape)
                return x
            """,
            "ZNC004",
        )
        assert fs == []

    def test_keyword_spelled_key_reuse_fires(self):
        fs = run(
            """
            import jax

            def f(key, shape):
                a = jax.random.normal(key=key, shape=shape)
                b = jax.random.uniform(key=key, shape=shape)
                return a + b
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_keyword_spelled_hardcoded_seed_fires(self):
        fs = run(
            """
            import jax

            k = jax.random.PRNGKey(seed=7)
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_lambda_key_reuse_fires(self):
        fs = run(
            """
            import jax

            sample = lambda k, s: (
                jax.random.normal(k, s) + jax.random.uniform(k, s)
            )
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_module_level_key_reuse_fires(self):
        fs = run(
            """
            import jax

            key = jax.random.split(SEED)[0]
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]

    def test_locally_bound_key_reuse_fires(self):
        """The defining assignment must not mask later reuse — the
        classic `key = ...; use; use` silent-correlation bug."""
        fs = run(
            """
            import jax

            def f(seed, shape):
                key = jax.random.fold_in(jax.random.split(seed)[0], 1)
                a = jax.random.normal(key, shape)
                b = jax.random.uniform(key, shape)
                return a + b
            """,
            "ZNC004",
        )
        assert ids(fs) == ["ZNC004"]


# -- ZNC005: donation ----------------------------------------------------


class TestDonation:
    def test_jit_call_without_donation_fires(self):
        fs = run(
            """
            import jax

            def step(state, x):
                return state, x

            fast = jax.jit(step)
            """,
            "ZNC005",
        )
        assert ids(fs) == ["ZNC005"]
        assert "state" in fs[0].message

    def test_decorated_without_donation_fires(self):
        fs = run(
            """
            import jax

            @jax.jit
            def step(state, x):
                return state, x
            """,
            "ZNC005",
        )
        assert ids(fs) == ["ZNC005"]

    def test_donate_argnums_is_quiet(self):
        fs = run(
            """
            import jax

            def step(state, x):
                return state, x

            fast = jax.jit(step, donate_argnums=(0,))
            """,
            "ZNC005",
        )
        assert fs == []

    def test_no_state_param_is_quiet(self):
        fs = run(
            """
            import jax

            @jax.jit
            def f(x, y):
                return x + y
            """,
            "ZNC005",
        )
        assert fs == []

    def test_trainstate_annotation_fires_despite_renamed_param(self):
        # a renamed state arg with a TrainState annotation still gets
        # the donation check (the name heuristic alone would miss it)
        fs = run(
            """
            import jax
            from znicz_tpu.nn.train_state import TrainState

            @jax.jit
            def step(ts: TrainState, x):
                return ts, x
            """,
            "ZNC005",
        )
        assert ids(fs) == ["ZNC005"]
        assert "ts" in fs[0].message

    def test_dotted_and_optional_annotations_fire(self):
        fs = run(
            """
            import jax
            from typing import Optional
            from znicz_tpu.nn import train_state

            @jax.jit
            def a(s0: train_state.TrainState, x):
                return s0, x

            @jax.jit
            def b(maybe: Optional[TrainState], x):
                return maybe, x
            """,
            "ZNC005",
        )
        assert ids(fs) == ["ZNC005", "ZNC005"]

    def test_string_forward_reference_annotation_fires(self):
        fs = run(
            """
            import jax

            @jax.jit
            def step(ts: "TrainState", x):
                return ts, x
            """,
            "ZNC005",
        )
        assert ids(fs) == ["ZNC005"]

    def test_lookalike_type_name_is_quiet(self):
        # word-boundary matching: TrainStateless is a different type
        fs = run(
            """
            import jax

            @jax.jit
            def step(ts: "TrainStateless", x):
                return ts, x
            """,
            "ZNC005",
        )
        assert fs == []

    def test_annotated_with_donation_is_quiet(self):
        fs = run(
            """
            import jax

            def step(ts: TrainState, x):
                return ts, x

            fast = jax.jit(step, donate_argnums=(0,))
            """,
            "ZNC005",
        )
        assert fs == []

    def test_annotated_static_param_is_quiet(self):
        fs = run(
            """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("ts",))
            def step(ts: TrainState, x):
                return ts, x
            """,
            "ZNC005",
        )
        assert fs == []


# -- ZNC006: mutable state -----------------------------------------------


class TestMutableState:
    def test_mutable_default_fires(self):
        fs = run(
            """
            def f(x, acc=[]):
                acc.append(x)
                return acc
            """,
            "ZNC006",
        )
        assert ids(fs) == ["ZNC006"]

    def test_none_default_is_quiet(self):
        fs = run(
            """
            def f(x, acc=None):
                return acc
            """,
            "ZNC006",
        )
        assert fs == []

    def test_empty_tuple_default_is_quiet(self):
        fs = run(
            """
            def f(x, shape=()):
                return shape
            """,
            "ZNC006",
        )
        assert fs == []

    def test_module_mutable_captured_by_jit_fires(self):
        fs = run(
            """
            import jax

            CACHE = {}

            @jax.jit
            def f(x):
                return x * CACHE["scale"]
            """,
            "ZNC006",
        )
        assert ids(fs) == ["ZNC006"]

    def test_module_mutable_in_host_code_is_quiet(self):
        fs = run(
            """
            CACHE = {}

            def f(x):
                return CACHE.get(x)
            """,
            "ZNC006",
        )
        assert fs == []

    def test_local_rebinding_of_module_name_is_quiet(self):
        """A name assigned inside the function is local THROUGHOUT it
        (python scoping) — no module-level capture happens."""
        fs = run(
            """
            import jax

            CACHE = []

            @jax.jit
            def f(x):
                CACHE = [x]
                return CACHE[0]
            """,
            "ZNC006",
        )
        assert fs == []

    def test_global_in_jit_fires(self):
        fs = run(
            """
            import jax

            counter = 0

            @jax.jit
            def f(x):
                global counter
                counter = counter + 1
                return x
            """,
            "ZNC006",
        )
        assert "ZNC006" in ids(fs)


# -- ZNC007: host sync in loop -------------------------------------------


class TestHostSync:
    def test_device_get_in_loop_fires(self):
        fs = run(
            """
            import jax

            def epoch(batches):
                out = []
                for b in batches:
                    out.append(jax.device_get(b))
                return out
            """,
            "ZNC007",
        )
        assert ids(fs) == ["ZNC007"]

    def test_block_until_ready_in_loop_fires(self):
        fs = run(
            """
            def epoch(xs):
                for x in xs:
                    x.block_until_ready()
            """,
            "ZNC007",
        )
        assert ids(fs) == ["ZNC007"]

    def test_time_time_in_while_fires(self):
        fs = run(
            """
            import time

            def run():
                while True:
                    t = time.time()
                    if t > 10:
                        break
            """,
            "ZNC007",
        )
        assert ids(fs) == ["ZNC007"]

    def test_outside_loop_is_quiet(self):
        fs = run(
            """
            import jax
            import time

            def finish(acc):
                t = time.time()
                return jax.device_get(acc), t
            """,
            "ZNC007",
        )
        assert fs == []

    def test_closure_defined_in_loop_is_quiet(self):
        fs = run(
            """
            import jax

            def make(xs):
                fns = []
                for x in xs:
                    def fetch():
                        return jax.device_get(x)
                    fns.append(fetch)
                return fns
            """,
            "ZNC007",
        )
        assert fs == []


# -- ZNC008: swallowed exceptions ----------------------------------------


class TestSwallowedExceptions:
    def test_bare_except_fires(self):
        fs = run(
            """
            def f():
                try:
                    return 1
                except:
                    return 0
            """,
            "ZNC008",
        )
        assert ids(fs) == ["ZNC008"]
        assert "bare" in fs[0].message

    def test_silent_pass_fires(self):
        fs = run(
            """
            def f():
                try:
                    return 1
                except Exception:
                    pass
            """,
            "ZNC008",
        )
        assert ids(fs) == ["ZNC008"]

    def test_logging_handler_is_quiet(self):
        fs = run(
            """
            import logging

            def f():
                try:
                    return 1
                except Exception:
                    logging.exception("boom")
                    return 0
            """,
            "ZNC008",
        )
        assert fs == []

    def test_return_fallback_is_quiet(self):
        """``return <fallback>`` is a documented degraded result, not a
        swallowed exception."""
        fs = run(
            """
            def f():
                try:
                    return compute()
                except OSError:
                    return []
            """,
            "ZNC008",
        )
        assert fs == []

    def test_bare_return_fires(self):
        fs = run(
            """
            def f():
                try:
                    work()
                except OSError:
                    return
            """,
            "ZNC008",
        )
        assert ids(fs) == ["ZNC008"]

    def test_reraise_is_quiet(self):
        fs = run(
            """
            def f():
                try:
                    return 1
                except Exception as e:
                    raise RuntimeError("ctx") from e
            """,
            "ZNC008",
        )
        assert fs == []


# -- ZNC009: wall-clock durations ----------------------------------------


class TestWallClockDuration:
    def test_direct_subtraction_fires(self):
        fs = run(
            """
            import time

            def f(t0):
                return time.time() - t0
            """,
            "ZNC009",
        )
        assert ids(fs) == ["ZNC009"]

    def test_reversed_direct_subtraction_fires(self):
        fs = run(
            """
            import time

            def remaining(deadline):
                return deadline - time.time()
            """,
            "ZNC009",
        )
        assert ids(fs) == ["ZNC009"]

    def test_variable_pair_fires(self):
        fs = run(
            """
            import time

            def f(work):
                t0 = time.time()
                work()
                t1 = time.time()
                return t1 - t0
            """,
            "ZNC009",
        )
        assert ids(fs) == ["ZNC009"]

    def test_attribute_pair_fires(self):
        fs = run(
            """
            import time

            class Watch:
                def start(self):
                    self._t0 = time.time()

                def lap(self):
                    self._t1 = time.time()
                    return self._t1 - self._t0
            """,
            "ZNC009",
        )
        assert ids(fs) == ["ZNC009"]

    def test_from_import_alias_fires(self):
        fs = run(
            """
            from time import time

            def f(t0):
                return time() - t0
            """,
            "ZNC009",
        )
        assert ids(fs) == ["ZNC009"]

    def test_timestamp_use_is_quiet(self):
        fs = run(
            """
            import time

            def stamp(record):
                record["created_at"] = time.time()
                return record
            """,
            "ZNC009",
        )
        assert fs == []

    def test_monotonic_and_perf_counter_quiet(self):
        fs = run(
            """
            import time

            def f(work):
                t0 = time.monotonic()
                p0 = time.perf_counter()
                work()
                return time.monotonic() - t0, time.perf_counter() - p0
            """,
            "ZNC009",
        )
        assert fs == []

    def test_unrelated_names_quiet(self):
        # a subtraction of two NON-wall names in a module that also
        # calls time.time() elsewhere must not fire
        fs = run(
            """
            import time

            NOW = time.time()

            def f(a, b):
                return a - b
            """,
            "ZNC009",
        )
        assert fs == []

    def test_pragma_exempts(self):
        fs = run(
            """
            import time

            def age(mtime):
                # cross-process file age IS an epoch difference
                return time.time() - mtime  # znicz-check: disable=ZNC009
            """,
            "ZNC009",
        )
        assert fs == []


# -- ZNC010: unbounded blocking in services/ ------------------------------


SERVICES_PATH = "znicz_tpu/services/mod.py"


class TestUnboundedBlocking:
    def test_queue_get_without_timeout_fires(self):
        fs = run(
            """
            import queue

            def pull(q):
                return q.get()
            """,
            "ZNC010",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC010"]
        assert "timeout" in fs[0].message

    def test_event_wait_and_thread_join_and_acquire_fire(self):
        fs = run(
            """
            def sync(evt, thread, lock):
                evt.wait()
                thread.join()
                lock.acquire()
            """,
            "ZNC010",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC010"] * 3

    def test_bounded_calls_are_quiet(self):
        fs = run(
            """
            def sync(q, evt, thread, lock, grace):
                q.get(timeout=1.0)
                q.get_nowait()
                evt.wait(timeout=grace)
                thread.join(grace)
                lock.acquire(timeout=0.5)
                lock.acquire(False)
                lock.acquire(blocking=False)
                q.get(block=False)
            """,
            "ZNC010",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_non_blocking_homonyms_are_quiet(self):
        # str.join / dict.get / sound-alike methods with args must not
        # be confused with synchronization primitives
        fs = run(
            """
            def fmt(parts, d, k):
                return ", ".join(parts) + str(d.get(k))
            """,
            "ZNC010",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_module_level_wait_is_quiet(self):
        fs = run(
            """
            import os

            def reap():
                return os.wait()
            """,
            "ZNC010",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_outside_services_is_quiet(self):
        fs = run(
            """
            def pull(q):
                return q.get()
            """,
            "ZNC010",
            path="znicz_tpu/loader/prefetch.py",
        )
        assert fs == []

    def test_cluster_scope_fires(self):
        # ISSUE 8: the serving tier grew znicz_tpu/cluster/ — the
        # router/registry threads strand CLIENTS when they hang, so
        # the no-unbounded-waits contract covers them too
        fs = run(
            """
            def pull(q, evt):
                evt.wait()
                return q.get()
            """,
            "ZNC010",
            path="znicz_tpu/cluster/router.py",
        )
        assert ids(fs) == ["ZNC010"] * 2

    def test_cluster_bounded_calls_are_quiet(self):
        fs = run(
            """
            def sync(evt, thread):
                evt.wait(timeout=1.0)
                thread.join(timeout=2.0)
            """,
            "ZNC010",
            path="znicz_tpu/cluster/registry.py",
        )
        assert fs == []

    def test_pragma_exempts(self):
        fs = run(
            """
            def pull(q):
                # the producer is in-process and cannot die silently
                return q.get()  # znicz-check: disable=ZNC010
            """,
            "ZNC010",
            path=SERVICES_PATH,
        )
        assert fs == []


# -- ZNC011: dynamic metric names -----------------------------------------


class TestDynamicMetricNames:
    def test_fstring_name_fires(self):
        fs = run(
            """
            from znicz_tpu import observability

            def make(kind):
                return observability.counter(f"znicz_{kind}_total")
            """,
            "ZNC011",
        )
        assert ids(fs) == ["ZNC011"]
        assert "label" in fs[0].message

    def test_concat_percent_and_format_fire(self):
        fs = run(
            """
            def make(reg, name, phase):
                a = reg.gauge("znicz_" + name)
                b = reg.histogram("znicz_%s_seconds" % phase)
                c = reg.counter("znicz_{}_total".format(name))
                return a, b, c
            """,
            "ZNC011",
        )
        assert ids(fs) == ["ZNC011"] * 3

    def test_bare_and_keyword_name_forms_fire(self):
        fs = run(
            """
            from znicz_tpu.observability import counter, gauge

            def make(kind):
                counter(f"znicz_{kind}_total")
                gauge(name="znicz_" + kind)
            """,
            "ZNC011",
        )
        assert ids(fs) == ["ZNC011"] * 2

    def test_static_names_and_variables_stay_quiet(self):
        # literal names, a pass-through variable (PhaseTimer's metric
        # param), labels carrying the varying value, and non-factory
        # homonyms must all stay quiet
        fs = run(
            """
            from collections import Counter

            def make(reg, metric, kind):
                a = reg.counter("znicz_serve_requests_total", "h",
                                ("kind",))
                a.labels(kind=kind).inc()
                b = reg.histogram(metric)  # variable: may be static
                c = Counter(f"not a {kind} metric")  # uppercase: not ours
                d = "x".format()  # format off a factory-free call
                return a, b, c, d
            """,
            "ZNC011",
        )
        assert fs == []

    def test_nested_concat_with_literal_fires(self):
        fs = run(
            """
            def make(reg, a, b):
                return reg.counter(a + b + "_total")
            """,
            "ZNC011",
        )
        assert ids(fs) == ["ZNC011"]

    def test_plain_fstring_without_interpolation_is_quiet(self):
        fs = run(
            """
            def make(reg):
                return reg.counter(f"znicz_static_total")
            """,
            "ZNC011",
        )
        assert fs == []

    def test_pragma_exempts(self):
        fs = run(
            """
            def make(reg, kind):
                # one-off migration shim, bounded set of kinds
                return reg.counter(f"znicz_{kind}_total")  # znicz-check: disable=ZNC011
            """,
            "ZNC011",
        )
        assert fs == []


# -- ZNC012: lock discipline ----------------------------------------------


class TestLockDiscipline:
    RACY = """
        import threading

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = []
                self._thread = threading.Thread(
                    target=self._loop, daemon=True
                )

            def submit(self, item):
                with self._lock:
                    self._pending.append(item)

            def _loop(self):
                while True:
                    expired = [x for x in list(self._pending) if x]
        """

    def test_bare_iterate_of_locked_container_fires(self):
        fs = run(self.RACY, "ZNC012", path=SERVICES_PATH)
        assert ids(fs) == ["ZNC012"]
        assert "_pending" in fs[0].message
        assert "thread:_loop" in fs[0].message

    def test_lock_correct_equivalent_is_quiet(self):
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pending = []
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def submit(self, item):
                    with self._lock:
                        self._pending.append(item)

                def _loop(self):
                    while True:
                        with self._lock:
                            expired = list(self._pending)
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_bare_write_to_lock_read_flag_fires(self):
        # the shipped shape: a flag READ under the lock on the client
        # path, STORED bare from two different threads
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._closed = False
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def submit(self):
                    with self._lock:
                        if self._closed:
                            raise RuntimeError("closed")

                def close(self):
                    self._closed = True

                def _loop(self):
                    self._closed = True
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC012", "ZNC012"]

    def test_plain_read_of_atomic_is_quiet(self):
        # reading a lock-guarded counter without the lock is stale,
        # not torn — the negative case the issue pins
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def _loop(self):
                    with self._lock:
                        self._n += 1

                def stats(self):
                    return {"n": self._n, "big": self._n > 10}
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_init_writes_are_quiet(self):
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []
                    self._items.append("seed")

                def add(self, x):
                    with self._lock:
                        self._items.append(x)
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_single_thread_root_is_quiet(self):
        # an attribute only the dedicated thread ever touches cannot
        # race, locked sometimes or not
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._scratch = []
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def _loop(self):
                    self._a()
                    self._b()

                def _a(self):
                    with self._lock:
                        self._scratch.append(1)

                def _b(self):
                    self._scratch.append(2)
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_lock_held_by_caller_convention_is_quiet(self):
        # a private method whose every call site holds the lock runs
        # under it (the repo's documented "lock held by the caller")
        fs = run(
            """
            import threading

            class Roster:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._replicas = {}
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def register(self, name):
                    with self._lock:
                        self._replicas[name] = 1
                        self._update_gauges()

                def _loop(self):
                    with self._lock:
                        self._update_gauges()

                def _update_gauges(self):
                    for name in list(self._replicas):
                        pass
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_immutable_config_iteration_is_quiet(self):
        # a tuple assigned only in __init__ is config, not shared
        # mutable state — iterating it bare cannot race
        fs = run(
            """
            import threading

            class Mon:
                def __init__(self, windows):
                    self._lock = threading.Lock()
                    self.windows = tuple(windows)
                    self._ring = []
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def _loop(self):
                    with self._lock:
                        self._ring.append(1)

                def snapshot(self):
                    return [w for w in self.windows]
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_class_without_lock_is_quiet(self):
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._items = []
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def add(self, x):
                    self._items.append(x)

                def _loop(self):
                    self._items.clear()
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_outside_serving_tier_is_quiet(self):
        fs = run(self.RACY, "ZNC012", path="znicz_tpu/loader/x.py")
        assert fs == []

    def test_observability_scope_fires(self):
        fs = run(
            self.RACY, "ZNC012", path="znicz_tpu/observability/x.py"
        )
        assert ids(fs) == ["ZNC012"]

    def test_pragma_exempts(self):
        fs = run(
            """
            import threading

            class Door:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._closed = False
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def submit(self):
                    with self._lock:
                        return self._closed

                def _loop(self):
                    # atomic bool store; stale reads are acceptable
                    self._closed = True  # znicz-check: disable=ZNC012
            """,
            "ZNC012",
            path=SERVICES_PATH,
        )
        assert fs == []


# -- ZNC013: thread exception sink -----------------------------------------


class TestThreadExceptionSink:
    def test_unguarded_method_target_fires(self):
        fs = run(
            """
            import threading

            class Door:
                def start(self):
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )
                    self._thread.start()

                def _loop(self):
                    while True:
                        self._sweep()
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC013"]
        assert "_loop" in fs[0].message

    def test_log_wrapped_loop_is_quiet(self):
        fs = run(
            """
            import logging
            import threading

            logger = logging.getLogger(__name__)

            class Door:
                def start(self):
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True
                    )

                def _loop(self):
                    while not self._stop.wait(timeout=2.0):
                        try:
                            self._sweep()
                        except Exception:
                            logger.warning("sweep failed", exc_info=True)
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_narrow_handler_still_fires(self):
        fs = run(
            """
            import logging
            import threading

            logger = logging.getLogger(__name__)

            class Door:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    try:
                        self._sweep()
                    except OSError:
                        logger.warning("io failed")
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC013"]

    def test_silent_broad_handler_still_fires(self):
        # `except Exception: pass` protects nothing (and ZNC008 flags
        # the swallow separately)
        fs = run(
            """
            import threading

            class Door:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    try:
                        self._sweep()
                    except Exception:
                        pass
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC013"]

    def test_typed_event_handler_is_the_sink(self):
        # the front door's shape: the broad handler delegates to the
        # typed-failure path; the rule does not demand infinite regress
        fs = run(
            """
            import threading

            class Door:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    while True:
                        try:
                            self._tick()
                        except Exception as exc:
                            self._engine_failure(exc)
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_module_level_target_fires(self):
        fs = run(
            """
            import threading

            def worker(q):
                while True:
                    handle(q.get(timeout=1.0))

            def start(q):
                threading.Thread(target=worker, args=(q,)).start()
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC013"]
        assert "worker" in fs[0].message

    def test_lambda_target_fires(self):
        fs = run(
            """
            import threading

            def start(server):
                threading.Thread(target=lambda: server.run()).start()
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC013"]

    def test_unresolvable_target_is_skipped(self):
        fs = run(
            """
            import threading

            def start(server):
                threading.Thread(target=server.shutdown).start()
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_outside_serving_tier_is_quiet(self):
        fs = run(
            """
            import threading

            def worker():
                risky()

            threading.Thread(target=worker).start()
            """,
            "ZNC013",
            path="znicz_tpu/loader/prefetch.py",
        )
        assert fs == []

    def test_reraising_handler_is_not_a_sink(self):
        """``raise RuntimeError(exc)`` still kills the thread — the
        exception-constructor call must not count as handling."""
        fs = run(
            """
            import threading

            class Door:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    try:
                        self._work()
                    except Exception as exc:
                        raise RuntimeError(exc)
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert ids(fs) == ["ZNC013"]

    def test_logging_then_reraising_handler_is_a_sink(self):
        # the death is at least a LOGGED event; the log call (outside
        # the raise) qualifies
        fs = run(
            """
            import logging
            import threading

            logger = logging.getLogger(__name__)

            class Door:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    try:
                        self._work()
                    except Exception as exc:
                        logger.exception("worker died")
                        raise
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert fs == []

    def test_pragma_exempts(self):
        fs = run(
            """
            import threading

            class Pusher:
                def start(self):
                    # push_now never raises (catches all internally)
                    t = threading.Thread(  # znicz-check: disable=ZNC013
                        target=self._loop,
                    )
                    t.start()

                def _loop(self):
                    while not self._stop.wait(timeout=1.0):
                        self.push_now()
            """,
            "ZNC013",
            path=SERVICES_PATH,
        )
        assert fs == []


# -- pragmas -------------------------------------------------------------


class TestPragmas:
    SRC = """
        def f():
            try:
                return 1
            except Exception:{pragma}
                pass
        """

    def test_inline_disable(self):
        src = self.SRC.format(
            pragma="  # znicz-check: disable=ZNC008"
        )
        assert run(src, "ZNC008") == []

    def test_inline_disable_all(self):
        src = self.SRC.format(pragma="  # znicz-check: disable=all")
        assert run(src, "ZNC008") == []

    def test_inline_disable_other_rule_still_fires(self):
        src = self.SRC.format(
            pragma="  # znicz-check: disable=ZNC001"
        )
        assert ids(run(src, "ZNC008")) == ["ZNC008"]

    def test_file_level_disable(self):
        src = (
            "# znicz-check: disable-file=ZNC008\n"
            + textwrap.dedent(self.SRC.format(pragma=""))
        )
        assert engine.analyze_source(
            src, "x.py", [RULES["ZNC008"]()]
        ) == []


# -- baseline ------------------------------------------------------------


class TestBaseline:
    SRC = """
        def f():
            try:
                return 1
            except Exception:
                pass
        """

    def findings(self):
        return run(self.SRC, "ZNC008")

    def test_round_trip(self, tmp_path):
        fs = self.findings()
        path = str(tmp_path / "baseline.json")
        engine.write_baseline(fs, path)
        baseline = engine.load_baseline(path)
        assert engine.new_findings(fs, baseline) == []

    def test_new_finding_not_suppressed(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        engine.write_baseline(self.findings(), path)
        src = textwrap.dedent(self.SRC) + textwrap.dedent(
            """
            def g():
                try:
                    return 2
                except ValueError:
                    pass
            """
        )
        fs = engine.analyze_source(
            src, "pkg/mod.py", [RULES["ZNC008"]()]
        )
        new = engine.new_findings(fs, engine.load_baseline(path))
        assert len(new) == 1
        assert new[0].symbol == "g"

    def test_fingerprint_survives_line_shift(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        engine.write_baseline(self.findings(), path)
        shifted = "# a new comment line\n\n" + textwrap.dedent(self.SRC)
        fs = engine.analyze_source(
            shifted, "pkg/mod.py", [RULES["ZNC008"]()]
        )
        assert engine.new_findings(fs, engine.load_baseline(path)) == []

    def test_stale_entries_reported(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        engine.write_baseline(self.findings(), path)
        stale = engine.stale_baseline_entries(
            [], engine.load_baseline(path)
        )
        assert sum(stale.values()) == 1

    def test_missing_baseline_is_empty(self, tmp_path):
        assert engine.load_baseline(str(tmp_path / "nope.json")) == {}


# -- engine odds and ends ------------------------------------------------


class TestEngine:
    def test_rule_catalog_has_eight_active_rules(self):
        assert len(RULES) >= 8
        assert len({cls.severity for cls in RULES.values()}) <= 2

    def test_get_rules_select_and_ignore(self):
        assert [r.id for r in get_rules(select=["ZNC001"])] == ["ZNC001"]
        assert "ZNC001" not in [
            r.id for r in get_rules(ignore=["ZNC001"])
        ]
        with pytest.raises(ValueError):
            get_rules(select=["ZNC999"])

    def test_write_baseline_refuses_partial_rule_set(self, tmp_path):
        """--write-baseline under --select would silently erase every
        other rule's grandfathered entries."""
        from znicz_tpu.analysis.__main__ import main

        with pytest.raises(SystemExit) as exc:
            main(
                [
                    "--write-baseline",
                    "--select",
                    "ZNC003",
                    "--baseline",
                    str(tmp_path / "b.json"),
                ]
            )
        assert exc.value.code == 2

    def test_write_baseline_refuses_path_subset(self, tmp_path):
        """A subset-path regen would erase other files' grandfathered
        entries."""
        from znicz_tpu.analysis.__main__ import main

        with pytest.raises(SystemExit) as exc:
            main(
                [
                    "--write-baseline",
                    "--baseline",
                    str(tmp_path / "b.json"),
                    "znicz_tpu/services",
                ]
            )
        assert exc.value.code == 2

    def test_syntax_error_reported_as_znc000(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("def f(:\n")
        fs = engine.analyze_paths([str(bad)], root=str(tmp_path))
        assert [f.rule for f in fs] == ["ZNC000"]

    def test_nonexistent_path_is_an_error_not_clean(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            engine.analyze_paths(
                [str(tmp_path / "no_such_dir")], root=str(tmp_path)
            )

    def test_findings_sorted_and_pathed_relative(self, tmp_path):
        mod = tmp_path / "m.py"
        mod.write_text(
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:\n"
            "        pass\n"
        )
        fs = engine.analyze_paths([str(mod)], root=str(tmp_path))
        assert fs[0].path == "m.py"


# -- project rules: ZNC014/ZNC015/ZNC016 ---------------------------------


def run_project(sources, rule_id):
    """Run ONE project rule over an in-memory multi-file project
    (``{rel_path: source}``), suppression applied — the harness for
    the dataflow/lock-order/blocking rules, which reason over the
    whole index instead of one module."""
    from znicz_tpu.analysis.project import (
        ProjectIndex,
        project_rule_findings,
    )

    idx = ProjectIndex("/proj")
    for rel, src in sources.items():
        idx.add_module(textwrap.dedent(src), rel)
    idx.link()
    rule = RULES[rule_id]()
    assert rule.project, f"{rule_id} is not a project rule"
    return project_rule_findings(idx, [rule]), idx


class TestRecompileHazard:
    def test_len_into_cache_key_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                programs = {}

                def admit(prompt):
                    key = ("admit", len(prompt))
                    programs[key] = 1
                """
            },
            "ZNC014",
        )
        assert ids(fs) == ["ZNC014"]
        assert "len(...)" in fs[0].message
        assert "programs" in fs[0].message

    def test_bucketed_key_stays_quiet(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                LADDER = (16, 32, 64)
                programs = {}

                def bucket_for(n, ladder):
                    for rung in ladder:
                        if n <= rung:
                            return rung
                    return ladder[-1]

                def admit(prompt):
                    key = ("admit", bucket_for(len(prompt), LADDER))
                    programs[key] = 1
                """
            },
            "ZNC014",
        )
        assert fs == []

    def test_rebinding_through_bucket_is_flow_sensitive(self):
        """``n = len(p); n = bucket_for(n, L)`` must be bounded at
        later uses — the last textual assignment before the use wins."""
        fs, _ = run_project(
            {
                "services/mod.py": """
                cache = {}

                def admit(p):
                    n = len(p)
                    n = bucket_for(n, (8, 16))
                    cache[n] = 1
                """
            },
            "ZNC014",
        )
        assert fs == []

    def test_ledger_call_key_fires(self):
        fs, _ = run_project(
            {
                "services/engine.py": """
                class Engine:
                    def admit(self, prompt):
                        self._timed_program(
                            ("admit", len(prompt)), run, prompt
                        )
                """
            },
            "ZNC014",
        )
        assert ids(fs) == ["ZNC014"]
        assert "_timed_program" in fs[0].message

    def test_wallclock_static_arg_fires(self):
        fs, _ = run_project(
            {
                "pkg/mod.py": """
                import jax
                import time

                def step(x, n):
                    return x * n

                fast = jax.jit(step, static_argnums=(1,))

                def run(x):
                    return fast(x, int(time.time()))
                """
            },
            "ZNC014",
        )
        assert ids(fs) == ["ZNC014"]
        assert "wall-clock" in fs[0].message
        assert "static argument 'n'" in fs[0].message

    def test_static_arg_resolved_cross_module(self):
        fs, _ = run_project(
            {
                "liba.py": """
                def step(x, width):
                    return x * width
                """,
                "libb.py": """
                import jax
                import liba

                fast = jax.jit(liba.step, static_argnames=("width",))

                def run(x, prompt):
                    return fast(x, width=len(prompt))
                """,
            },
            "ZNC014",
        )
        assert ids(fs) == ["ZNC014"]
        assert fs[0].path == "libb.py"

    def test_interprocedural_param_taint_fires(self):
        """A helper sized by its parameter fires when a call site
        passes ``len(...)`` — the origin names the call site."""
        fs, _ = run_project(
            {
                "services/mod.py": """
                import numpy as np

                def make_buffer(n):
                    return np.zeros((n, 4))

                def admit(prompt):
                    return make_buffer(len(prompt))
                """
            },
            "ZNC014",
        )
        assert ids(fs) == ["ZNC014"]
        assert "via call at services/mod.py" in fs[0].message

    def test_shape_ctor_outside_serving_tier_stays_quiet(self):
        """Loader-tier dataset-sized host buffers are one-time
        allocations, not per-request compile drivers."""
        fs, _ = run_project(
            {
                "loader/mod.py": """
                import numpy as np

                def materialize(items):
                    return np.zeros((len(items), 4))
                """
            },
            "ZNC014",
        )
        assert fs == []

    def test_traced_context_shapes_stay_quiet(self):
        """``jnp.zeros(...)`` INSIDE jitted code is trace
        polymorphism, not a host recompile driver."""
        fs, _ = run_project(
            {
                "services/mod.py": """
                import jax
                import jax.numpy as jnp

                @jax.jit
                def step(xs):
                    return jnp.zeros((len(xs), 4))
                """
            },
            "ZNC014",
        )
        assert fs == []

    def test_min_clamp_is_a_boundary(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                cache = {}

                def admit(prompt):
                    cache[min(len(prompt), 64)] = 1
                """
            },
            "ZNC014",
        )
        assert fs == []

    def test_loop_counter_key_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                cache = {}

                def admit(prompts):
                    for i, p in enumerate(prompts):
                        cache[("row", i)] = p
                """
            },
            "ZNC014",
        )
        assert ids(fs) == ["ZNC014"]
        assert "enumerate" in fs[0].message

    def test_unknown_provenance_stays_quiet(self):
        """Config plumbing (constructor params, fields with no
        stores) is UNKNOWN — never fired on."""
        fs, _ = run_project(
            {
                "services/mod.py": """
                class Engine:
                    def __init__(self, batch_size):
                        self.batch_size = batch_size
                        self._programs = {}

                    def admit(self):
                        self._programs[("chunk", self.batch_size)] = 1
                """
            },
            "ZNC014",
        )
        assert fs == []

    def test_pragma_suppresses(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                cache = {}

                def admit(prompt):
                    cache[len(prompt)] = 1  # znicz-check: disable=ZNC014
                """
            },
            "ZNC014",
        )
        assert fs == []


class TestLockOrder:
    CYCLE = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats_lock = threading.Lock()

            def tick(self):
                with self._lock:
                    with self._stats_lock:
                        pass

            def stats(self):
                with self._stats_lock:
                    with self._lock:
                        pass
        """

    def test_opposite_nesting_fires(self):
        fs, _ = run_project({"services/mod.py": self.CYCLE}, "ZNC015")
        assert ids(fs) == ["ZNC015"]
        assert "lock-order cycle" in fs[0].message
        assert "_lock" in fs[0].message and "_stats_lock" in fs[0].message

    def test_consistent_order_stays_quiet(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Engine:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._stats_lock = threading.Lock()

                    def tick(self):
                        with self._lock:
                            with self._stats_lock:
                                pass

                    def stats(self):
                        with self._lock:
                            with self._stats_lock:
                                pass
                """
            },
            "ZNC015",
        )
        assert fs == []

    def test_cycle_through_method_call(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Engine:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b_lock = threading.Lock()

                    def _grab_b(self):
                        with self._b_lock:
                            pass

                    def tick(self):
                        with self._a:
                            self._grab_b()

                    def other(self):
                        with self._b_lock:
                            with self._a:
                                pass
                """
            },
            "ZNC015",
        )
        assert ids(fs) == ["ZNC015"]
        assert "self._grab_b()" in fs[0].message

    def test_cross_class_cycle_via_typed_attr(self):
        """Router holds its lock and calls into the registry (which
        locks); a registry sweep hook calls back into the router —
        the classic cross-object deadlock."""
        fs, _ = run_project(
            {
                "cluster/router.py": """
                import threading
                from cluster.registry import Registry

                class Router:
                    def __init__(self):
                        self._rr_lock = threading.Lock()
                        self.registry = Registry(self)

                    def route(self):
                        with self._rr_lock:
                            self.registry.note()

                    def on_sweep(self):
                        with self._rr_lock:
                            pass
                """,
                "cluster/registry.py": """
                import threading

                class Registry:
                    def __init__(self, router):
                        self.router: "Router" = router
                        self._lock = threading.Lock()

                    def note(self):
                        with self._lock:
                            pass

                    def sweep(self):
                        with self._lock:
                            self.router.on_sweep()
                """,
                "cluster/__init__.py": "",
            },
            "ZNC015",
        )
        assert ids(fs) == ["ZNC015"]
        assert "Router._rr_lock" in fs[0].message
        assert "Registry._lock" in fs[0].message

    def test_self_reacquisition_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def _inner(self):
                        with self._lock:
                            pass

                    def close(self):
                        with self._lock:
                            self._inner()
                """
            },
            "ZNC015",
        )
        assert ids(fs) == ["ZNC015"]
        assert "self-deadlock" in fs[0].message

    def test_rlock_reacquisition_stays_quiet(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Door:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def _inner(self):
                        with self._lock:
                            pass

                    def close(self):
                        with self._lock:
                            self._inner()
                """
            },
            "ZNC015",
        )
        assert fs == []

    def test_out_of_scope_module_stays_quiet(self):
        fs, _ = run_project({"workflow/mod.py": self.CYCLE}, "ZNC015")
        assert fs == []

    def test_pragma_suppresses(self):
        # the finding anchors at the FIRST edge's acquisition site (in
        # sorted lock order) — a pragma on that line suppresses it
        fired, _ = run_project({"services/mod.py": self.CYCLE}, "ZNC015")
        anchor_line = fired[0].line
        lines = textwrap.dedent(self.CYCLE).splitlines()
        lines[anchor_line - 1] += "  # znicz-check: disable=ZNC015"
        fs, _ = run_project(
            {"services/mod.py": "\n".join(lines)}, "ZNC015"
        )
        assert fs == []


class TestBlockingUnderLock:
    def test_sleep_under_lock_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading
                import time

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def tick(self):
                        with self._lock:
                            time.sleep(0.05)
                """
            },
            "ZNC016",
        )
        assert ids(fs) == ["ZNC016"]
        assert "time.sleep()" in fs[0].message

    def test_sleep_outside_lock_stays_quiet(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading
                import time

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.n = 0

                    def tick(self):
                        time.sleep(0.05)
                        with self._lock:
                            self.n += 1
                """
            },
            "ZNC016",
        )
        assert fs == []

    def test_urlopen_through_helper_fires_with_chain(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading
                import urllib.request

                def push(url):
                    return urllib.request.urlopen(url, timeout=5)

                class Pusher:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.url = "http://x/push"

                    def flush(self):
                        with self._lock:
                            push(self.url)
                """
            },
            "ZNC016",
        )
        assert ids(fs) == ["ZNC016"]
        assert "urlopen" in fs[0].message
        assert "push()" in fs[0].message

    def test_queue_get_with_timeout_under_lock_fires(self):
        """A BOUNDED wait under a lock still stalls every peer for
        the bound — timeout does not excuse ZNC016 (unlike ZNC010)."""
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.q = make_queue()

                    def tick(self):
                        with self._lock:
                            return self.q.get(timeout=1.0)
                """
            },
            "ZNC016",
        )
        assert ids(fs) == ["ZNC016"]

    def test_dict_get_homonym_stays_quiet(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self.d = {}

                    def lookup(self, k):
                        with self._lock:
                            return self.d.get(k)
                """
            },
            "ZNC016",
        )
        assert fs == []

    def test_out_of_scope_stays_quiet(self):
        fs, _ = run_project(
            {
                "workflow/mod.py": """
                import threading
                import time

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def tick(self):
                        with self._lock:
                            time.sleep(0.05)
                """
            },
            "ZNC016",
        )
        assert fs == []

    def test_pragma_suppresses(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading
                import time

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def tick(self):
                        with self._lock:
                            time.sleep(0.01)  # znicz-check: disable=ZNC016
                """
            },
            "ZNC016",
        )
        assert fs == []


class TestExplainExamples:
    """The --explain registry metadata is EXECUTABLE documentation:
    every rule ships a firing example and a minimally-edited quiet
    twin, and this test runs both — the one source of truth cannot
    drift from the analyzer's behavior."""

    @pytest.mark.parametrize("rule_id", sorted(RULES))
    def test_example_fires_and_quiet_twin_is_quiet(self, rule_id):
        from znicz_tpu.analysis.project import (
            ProjectIndex,
            project_rule_findings,
        )

        cls = RULES[rule_id]
        assert cls.example_fire.strip(), f"{rule_id} has no example"
        assert cls.example_quiet.strip(), f"{rule_id} has no quiet twin"

        def run_example(src):
            idx = ProjectIndex("/example")
            for rel, s in cls.example_support_files.items():
                idx.add_module(textwrap.dedent(s), rel)
            idx.add_module(textwrap.dedent(src), cls.example_path)
            idx.link()
            rule = cls()
            if cls.project:
                out = project_rule_findings(idx, [rule])
            else:
                out = []
                for info in idx.modules.values():
                    for f in rule.check(info):
                        if not info.suppressed(f):
                            out.append(f)
                out = idx.relocate(out)
            return [f for f in out if f.rule == rule_id]

        assert run_example(cls.example_fire), (
            f"{rule_id}'s example_fire does not fire"
        )
        assert run_example(cls.example_quiet) == [], (
            f"{rule_id}'s example_quiet fires"
        )

    def test_explain_cli_prints_examples(self, capsys):
        from znicz_tpu.analysis.__main__ import main

        rc = main(["--explain", "ZNC014"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "ZNC014" in out
        assert "FIRES" in out and "QUIET" in out
        assert "bucket_for" in out

    def test_explain_unknown_rule_is_usage_error(self):
        from znicz_tpu.analysis.__main__ import main

        with pytest.raises(SystemExit) as exc:
            main(["--explain", "ZNC999"])
        assert exc.value.code == 2


class TestLockModelExceptHandlers:
    """Review regression: ExceptHandler (and match_case) bodies are
    neither stmt nor expr — a naive child partition routed them around
    the held-lock walk, blinding ZNC015/016 to exactly the error-path
    retry/backoff code where sleep-under-lock lives."""

    def test_blocking_inside_except_handler_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading
                import time

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def tick(self):
                        try:
                            work()
                        except Exception:
                            with self._lock:
                                time.sleep(0.05)
                """
            },
            "ZNC016",
        )
        assert ids(fs) == ["ZNC016"]

    def test_lock_order_inside_except_handler_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading

                class Door:
                    def __init__(self):
                        self._a_lock = threading.Lock()
                        self._b_lock = threading.Lock()

                    def tick(self):
                        with self._a_lock:
                            with self._b_lock:
                                pass

                    def recover(self):
                        try:
                            work()
                        except Exception:
                            with self._b_lock:
                                with self._a_lock:
                                    pass
                """
            },
            "ZNC015",
        )
        assert ids(fs) == ["ZNC015"]

    def test_blocking_inside_match_case_fires(self):
        fs, _ = run_project(
            {
                "services/mod.py": """
                import threading
                import time

                class Door:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def tick(self, kind):
                        with self._lock:
                            match kind:
                                case "slow":
                                    time.sleep(0.05)
                                case _:
                                    pass
                """
            },
            "ZNC016",
        )
        assert ids(fs) == ["ZNC016"]
