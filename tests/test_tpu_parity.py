"""Cross-backend golden tests: CPU vs the real TPU chip.

The literal rebuild of the reference's numpy-vs-OpenCL-vs-CUDA golden checks
(SURVEY.md §4): the same seeded computation must agree across backends.  The
suite itself runs on the virtual CPU mesh (conftest), so the TPU half runs in
a SUBPROCESS with a clean environment; skipped when no accelerator responds.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE = """
import jax
ds = jax.devices()
print("OK" if ds and ds[0].platform != "cpu" else "NO")
"""

_COMPUTE = """
import json
import jax, jax.numpy as jnp
import numpy as np
from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.workflow import StandardWorkflow

prng.seed_all(777)
loader = datasets.mnist(n_train=128, n_test=0, minibatch_size=64)
wf = StandardWorkflow(
    loader,
    [{"type": "all2all_tanh", "->": {"output_sample_shape": 16}},
     {"type": "softmax", "->": {"output_sample_shape": 10}}],
    decision_config={"max_epochs": 2},
    default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
)
wf.initialize(seed=777)
dec = wf.run()
out = {
    "losses": [e["train"]["loss"] for e in dec.history],
    "n_err": [e["train"]["n_err"] for e in dec.history],
    "w_sum": float(jnp.sum(wf.state.params[0]["weights"])),
}
print("RESULT:" + json.dumps(out))
"""


def _run_subprocess(code: str, *, force_cpu: bool) -> str:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    if force_cpu:
        # mirror conftest: config update AFTER import beats sitecustomize
        code = (
            "import jax\njax.config.update('jax_platforms', 'cpu')\n" + code
        )
    r = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        env=env,
        timeout=540,
        cwd=REPO,
    )
    if r.returncode != 0:
        raise RuntimeError(r.stderr[-1500:])
    return r.stdout


@pytest.fixture(scope="session")
def tpu_reachable():
    try:
        out = _run_subprocess(_PROBE, force_cpu=False)
    except (RuntimeError, subprocess.TimeoutExpired):
        pytest.skip("no accelerator backend reachable")
    if "OK" not in out:
        pytest.skip("no accelerator backend reachable")
    return True


def _extract(stdout: str) -> dict:
    for line in stdout.splitlines():
        if line.startswith("RESULT:"):
            return json.loads(line[len("RESULT:"):])
    raise AssertionError(f"no RESULT line in: {stdout[-500:]}")


class TestCrossBackendGolden:
    def test_seeded_training_matches_cpu(self, tpu_reachable):
        """Two epochs of seeded MNIST training must agree across backends:
        identical error counts, near-identical losses and weight sums
        (tolerance band per SURVEY.md §7 — fusion differences are real)."""
        cpu = _extract(_run_subprocess(_COMPUTE, force_cpu=True))
        tpu = _extract(_run_subprocess(_COMPUTE, force_cpu=False))
        assert cpu["n_err"] == tpu["n_err"]
        np.testing.assert_allclose(
            cpu["losses"], tpu["losses"], rtol=2e-2
        )
        np.testing.assert_allclose(
            cpu["w_sum"], tpu["w_sum"], rtol=2e-2
        )
