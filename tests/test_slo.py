"""SLO monitor: windowed percentiles, burn rates, breach/recovery, the
lifetime evaluation behind tools/znicz-slo, and the /slo + front-door
integration (fault-injected latency flips it to breach, then recovers).
"""

import json
import math

import pytest

from znicz_tpu.observability.registry import (
    MetricsRegistry,
    fraction_le,
    quantile_from_cumulative,
)
from znicz_tpu.observability import slo as slo_mod
from znicz_tpu.observability.slo import SLOMonitor, SLOTarget


def _reg():
    r = MetricsRegistry()
    r.histogram("znicz_serve_ttft_seconds", "ttft")
    r.histogram("znicz_serve_request_latency_seconds", "lat")
    r.counter("znicz_serve_requests_submitted_total", "req")
    r.counter(
        "znicz_serve_requests_retired_total", "ret", ("reason",)
    )
    r.counter("znicz_serve_rejected_total", "rej", ("reason",))
    r.counter("znicz_serve_deadline_exceeded_total", "dl")
    r.counter("znicz_serve_cancelled_total", "cx")
    return r


def _observe(r, metric, values, requests=None):
    h = r.metrics()[metric]
    for v in values:
        h.observe(v)
    n = len(values) if requests is None else requests
    r.counter("znicz_serve_requests_submitted_total", "req").inc(n)


TT = SLOTarget("ttft", "znicz_serve_ttft_seconds", 0.05, 0.9)


class TestMath:
    def test_fraction_le_interpolates_within_buckets(self):
        cum = [(0.1, 0.0), (1.0, 10.0), (math.inf, 10.0)]
        # all 10 samples are in (0.1, 1.0]; 0.55 is halfway through
        assert fraction_le(cum, 0.55) == pytest.approx(0.5)
        assert fraction_le(cum, 1.0) == pytest.approx(1.0)
        assert fraction_le(cum, 0.1) == pytest.approx(0.0)

    def test_fraction_le_empty_is_all_good(self):
        assert fraction_le([], 1.0) == 1.0
        assert fraction_le([(1.0, 0.0), (math.inf, 0.0)], 0.5) == 1.0

    def test_fraction_le_inf_bucket_counts_as_bad(self):
        cum = [(1.0, 5.0), (math.inf, 10.0)]
        # 5 samples past the last finite edge: provably-below only
        assert fraction_le(cum, 2.0) == pytest.approx(0.5)

    def test_quantile_from_cumulative_matches_registry(self):
        r = _reg()
        h = r.metrics()["znicz_serve_ttft_seconds"]
        for v in (0.001, 0.002, 0.003, 0.004):
            h.observe(v)
        child = h.children()[()]
        assert quantile_from_cumulative(
            child.cumulative(), 0.5
        ) == pytest.approx(child.quantile(0.5))

    def test_target_validation(self):
        with pytest.raises(ValueError):
            SLOTarget("x", "m", 1.0, objective=1.0)
        with pytest.raises(ValueError):
            SLOTarget("x", "m", 0.0)


class TestMonitorWindows:
    def test_windowed_deltas_see_only_the_window(self):
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(10.0, 100.0), registry=r,
            min_sample_gap_s=0.0,
        )
        mon.sample(now=0.0)  # pristine baseline
        _observe(r, "znicz_serve_ttft_seconds", [0.2] * 10)  # slow era
        mon.sample(now=5.0)
        _observe(r, "znicz_serve_ttft_seconds", [0.001] * 10)  # fast era
        mon.sample(now=95.0)
        snap = mon.snapshot(now=100.0)
        w = snap["targets"]["ttft"]["windows"]
        # short window: only the fast era
        assert w["10"]["n"] == 10.0
        assert w["10"]["bad_frac"] == 0.0
        # long window: both eras
        assert w["100"]["n"] == 20.0
        assert w["100"]["bad_frac"] == pytest.approx(0.5)

    def test_latest_burn_matches_the_full_snapshot(self):
        """The cheap per-tick reduction behind the burn-rate gauge
        must agree with snapshot()'s max windowed burn (same newest
        capture, no fresh registry walk)."""
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(10.0, 100.0), registry=r,
            min_sample_gap_s=0.0,
        )
        assert mon.latest_burn() == 0.0  # empty ring: no judgment
        mon.sample(now=0.0)
        _observe(r, "znicz_serve_ttft_seconds", [0.2] * 8 + [0.001] * 2)
        mon.sample(now=5.0)
        got = mon.latest_burn()
        snap = mon.snapshot(now=5.0)
        want = max(
            w["burn_rate"]
            for w in snap["targets"]["ttft"]["windows"].values()
            if w["n"] > 0
        )
        assert got == pytest.approx(want)
        assert got > 1.0  # 80% bad at a 90% objective: burning

    def test_short_uptime_reports_true_span(self):
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(3600.0,), registry=r,
            min_sample_gap_s=0.0,
        )
        mon.sample(now=0.0)
        _observe(r, "znicz_serve_ttft_seconds", [0.001])
        snap = mon.snapshot(now=30.0)
        assert snap["targets"]["ttft"]["windows"]["3600"][
            "span_s"
        ] == pytest.approx(30.0)

    def test_unsampled_monitor_does_not_fabricate_window_span(self):
        # a directly-constructed monitor whose snapshot() runs before
        # any sample() landed: lifetime counter totals must not be
        # reported as if they spanned exactly one window (a 2-hour-old
        # process would claim requests_per_s = lifetime/60); the span
        # is the monitor's true (tiny) age
        r = _reg()
        r.counter("znicz_serve_requests_submitted_total", "req").inc(
            36000
        )
        mon = SLOMonitor(targets=(TT,), windows_s=(60.0,), registry=r)
        snap = mon.snapshot()
        row = snap["rates"]["60"]
        assert row["requests"] == 36000.0
        assert row["span_s"] < 1.0  # true age, not a claimed 60s

    def test_breach_needs_every_window_burning_and_recovers(self):
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(10.0, 100.0), registry=r,
            min_sample_gap_s=0.0,
        )
        mon.sample(now=0.0)
        _observe(r, "znicz_serve_ttft_seconds", [0.2] * 20)
        snap = mon.snapshot(now=5.0)  # bad samples in BOTH windows
        assert snap["targets"]["ttft"]["breached"] is True
        assert snap["breached"] is True
        mon.sample(now=5.0)
        _observe(r, "znicz_serve_ttft_seconds", [0.001] * 20)
        snap = mon.snapshot(now=50.0)
        # short window clean -> breach clears even though the long
        # window still remembers the incident (multi-window AND)
        assert snap["targets"]["ttft"]["windows"]["10"]["burn_rate"] < 1.0
        assert snap["targets"]["ttft"]["windows"]["100"][
            "burn_rate"
        ] >= 1.0
        assert snap["targets"]["ttft"]["breached"] is False

    def test_no_traffic_is_not_a_breach(self):
        r = _reg()
        mon = SLOMonitor(targets=(TT,), registry=r, min_sample_gap_s=0.0)
        snap = mon.snapshot(now=0.0)
        assert snap["breached"] is False
        for ev in snap["targets"]["ttft"]["windows"].values():
            assert ev["n"] == 0.0 and ev["burn_rate"] == 0.0

    def test_rates_from_counter_deltas(self):
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(60.0,), registry=r,
            min_sample_gap_s=0.0,
        )
        mon.sample(now=0.0)
        r.counter("znicz_serve_requests_submitted_total", "req").inc(10)
        r.counter(
            "znicz_serve_requests_retired_total", "ret", ("reason",)
        ).labels(reason="error").inc(2)
        r.counter(
            "znicz_serve_requests_retired_total", "ret", ("reason",)
        ).labels(reason="eos").inc(8)  # not an error
        r.counter(
            "znicz_serve_rejected_total", "rej", ("reason",)
        ).labels(reason="queue_full").inc(5)
        r.counter("znicz_serve_deadline_exceeded_total", "dl").inc(1)
        row = mon.snapshot(now=30.0)["rates"]["60"]
        assert row["requests"] == 10.0
        assert row["errors"] == 2.0
        assert row["sheds"] == 5.0
        assert row["deadlines"] == 1.0
        assert row["error_rate"] == pytest.approx(3.0 / 15.0)
        assert row["shed_rate"] == pytest.approx(5.0 / 15.0)

    def test_error_rate_saturates_when_deaths_outnumber_submits(self):
        # a wedged engine tick: requests die by deadline in the
        # FRONT-DOOR pending queue, never reaching engine submit —
        # error_rate must saturate at 1.0, not report 5000%
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(60.0,), registry=r,
            min_sample_gap_s=0.0,
        )
        mon.sample(now=0.0)
        r.counter("znicz_serve_deadline_exceeded_total", "dl").inc(50)
        row = mon.snapshot(now=30.0)["rates"]["60"]
        assert row["requests"] == 0.0
        assert row["deadlines"] == 50.0
        assert row["error_rate"] == 1.0

    def test_maybe_sample_respects_gap(self):
        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), registry=r, min_sample_gap_s=5.0
        )
        assert mon.maybe_sample(now=0.0) is True
        assert mon.maybe_sample(now=3.0) is False
        assert mon.maybe_sample(now=6.0) is True

    def test_snapshot_is_json_able(self):
        r = _reg()
        mon = SLOMonitor(targets=(TT,), registry=r, min_sample_gap_s=0.0)
        _observe(r, "znicz_serve_ttft_seconds", [0.01, 0.2])
        mon.sample(now=0.0)
        json.dumps(mon.snapshot(now=1.0))

    def test_snapshot_concurrent_with_sample_is_safe(self):
        # /slo runs snapshot() on an HTTP worker thread while the
        # engine thread samples — iterating the live deque raised
        # "deque mutated during iteration" before the ring lock
        import threading

        r = _reg()
        mon = SLOMonitor(
            targets=(TT,), windows_s=(1e9,), registry=r,
            min_sample_gap_s=0.0,
        )
        for i in range(512):  # long ring -> long snapshot iteration
            mon.sample(now=float(i))
        errors = []
        stop = threading.Event()

        def sampler():
            t = 512.0
            while not stop.is_set():
                try:
                    mon.sample(now=t)
                except Exception as exc:  # pragma: no cover - fail path
                    errors.append(exc)
                    return
                t += 1.0

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        try:
            for _ in range(200):
                mon.snapshot(now=1e6)
        finally:
            stop.set()
            th.join(timeout=10.0)
        assert errors == []


class TestLifetimeAndCLI:
    def test_lifetime_snapshot_marks_breach(self):
        r = _reg()
        _observe(r, "znicz_serve_ttft_seconds", [0.2] * 9 + [0.001])
        snap = slo_mod.lifetime_snapshot(r, targets=(TT,))
        ev = snap["targets"]["ttft"]["windows"]["lifetime"]
        assert ev["n"] == 10.0
        assert snap["targets"]["ttft"]["breached"] is True
        assert snap["type"] == "slo"

    def test_evaluate_exposition_round_trip(self):
        r = _reg()
        _observe(r, "znicz_serve_ttft_seconds", [0.001] * 10)
        snap = slo_mod.evaluate_exposition(
            r.prometheus_text(), targets=(TT,)
        )
        ev = snap["targets"]["ttft"]["windows"]["lifetime"]
        assert ev["n"] == 10.0
        assert snap["breached"] is False
        with pytest.raises(ValueError):
            slo_mod.evaluate_exposition("garbage { exposition")

    def test_cli_exit_codes_and_table(self, tmp_path, capsys):
        r = _reg()
        _observe(r, "znicz_serve_ttft_seconds", [0.001] * 10)
        _observe(
            r, "znicz_serve_request_latency_seconds", [0.01] * 10,
            requests=0,
        )
        prom = tmp_path / "metrics.prom"
        prom.write_text(r.prometheus_text())
        assert slo_mod.main([str(prom)]) == 0
        out = capsys.readouterr().out
        assert "ttft" in out and "ok" in out
        # tighten the objective until the same file breaches
        assert (
            slo_mod.main([str(prom), "--ttft", "0.0001"]) == 1
        )
        assert "BREACH" in capsys.readouterr().out

    def test_cli_json_mode_and_usage_errors(self, tmp_path, capsys):
        r = _reg()
        prom = tmp_path / "metrics.prom"
        prom.write_text(r.prometheus_text())
        assert slo_mod.main([str(prom), "--json"]) == 0
        json.loads(capsys.readouterr().out)
        assert slo_mod.main([]) == 2
        assert slo_mod.main([str(prom), "--ttft"]) == 2
        assert slo_mod.main([str(tmp_path / "missing.prom")]) == 2

    def test_cli_frontdoor_flag_judges_client_clock_series(
        self, tmp_path, capsys
    ):
        # a queue-wait-dominated replica: engine-clock TTFT healthy,
        # client-clock (front-door) TTFT blown — only --frontdoor
        # lets the CI gate see what /slo on the replica judges
        r = MetricsRegistry()
        fams = {
            "znicz_serve_ttft_seconds": 0.001,
            "znicz_serve_request_latency_seconds": 0.01,
            "znicz_serve_frontdoor_ttft_seconds": 10.0,
            "znicz_serve_frontdoor_latency_seconds": 10.5,
        }
        for name, v in fams.items():
            h = r.histogram(name, name)
            for _ in range(10):
                h.observe(v)
        prom = tmp_path / "metrics.prom"
        prom.write_text(r.prometheus_text())
        assert slo_mod.main([str(prom)]) == 0  # engine clock: all ok
        capsys.readouterr()
        assert slo_mod.main([str(prom), "--frontdoor"]) == 1
        out = capsys.readouterr().out
        assert "BREACH" in out
        assert slo_mod.main([str(prom), "--frontdoor", "--json"]) == 1
        snap = json.loads(capsys.readouterr().out)
        assert (
            snap["targets"]["ttft"]["metric"]
            == "znicz_serve_frontdoor_ttft_seconds"
        )

    def test_cli_reads_aggregator_url(self, tmp_path):
        import threading

        from znicz_tpu.observability.aggregate import (
            build_aggregator_server,
        )

        r = _reg()
        _observe(r, "znicz_serve_ttft_seconds", [0.001] * 5)
        server = build_aggregator_server(port=0)
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            server.aggregator.push("a", r.snapshot())
            port = server.server_address[1]
            assert slo_mod.main([f"http://127.0.0.1:{port}"]) == 0
        finally:
            server.shutdown()
            server.server_close()
