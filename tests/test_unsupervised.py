"""Kohonen SOM and RBM workflow tests (the non-backprop paths)."""

import jax.numpy as jnp
import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.ops import kohonen as kh, rbm as rbm_op
from znicz_tpu.workflow import KohonenWorkflow, RBMWorkflow


def _loader(n=200, bs=50, **kw):
    return datasets.mnist(
        n_train=n, n_test=0, minibatch_size=bs, normalization="mean_disp", **kw
    )


class TestKohonenWorkflow:
    def test_quantization_error_decreases(self):
        prng.seed_all(42)
        wf = KohonenWorkflow(
            _loader(), sx=6, sy=6, total_epochs=15,
            lr0=0.8, lr1=0.05, sigma1=0.5,
        )
        wf.initialize(seed=42)
        dec = wf.run()
        first = dec.history[0]["train"]["loss"]
        last = dec.history[-1]["train"]["loss"]
        assert last < first * 0.7, (first, last)

    def test_masked_padding_rows_ignored(self):
        # 130 samples / bs 100 -> second batch 30 valid; must count 130
        prng.seed_all(1)
        wf = KohonenWorkflow(_loader(130, 100), sx=4, sy=4, total_epochs=2)
        wf.initialize(seed=1)
        dec = wf.run()
        assert dec.history[-1]["train"]["n_samples"] == 130.0

    def test_weights_map_shape(self):
        wf = KohonenWorkflow(_loader(50, 50), sx=5, sy=4, total_epochs=1)
        wf.initialize(seed=2)
        wf.run()
        assert wf.weights_map().shape == (4, 5, 784)

    def test_snapshot_resume(self, tmp_path):
        from znicz_tpu.workflow import Snapshotter

        prng.seed_all(9)
        wf = KohonenWorkflow(
            _loader(100, 50),
            sx=4,
            sy=4,
            total_epochs=3,
            snapshotter=Snapshotter(str(tmp_path), "k", compress=False),
        )
        wf.initialize(seed=9)
        wf.run()
        best = tmp_path / "k_best.pickle"
        assert best.exists()
        prng.seed_all(9)
        wf2 = KohonenWorkflow(_loader(100, 50), sx=4, sy=4, total_epochs=3)
        wf2.snapshotter = wf.snapshotter
        wf2.initialize(snapshot=str(best))
        np.testing.assert_array_equal(
            np.asarray(wf2.state.params["weights"]),
            np.asarray(wf.snapshotter.load(str(best))[0].params["weights"]),
        )


class TestRBMWorkflow:
    def _loader01(self, n=200, bs=50):
        ld = datasets.mnist(n_train=n, n_test=0, minibatch_size=bs)
        for split, arr in ld.data.items():
            a = arr - arr.min()
            ld.data[split] = a / max(a.max(), 1e-6)
        return ld

    def test_reconstruction_error_decreases(self):
        prng.seed_all(7)
        wf = RBMWorkflow(
            self._loader01(), n_hidden=64, learning_rate=0.5, max_epochs=10
        )
        wf.initialize(seed=7)
        dec = wf.run()
        first = dec.history[0]["train"]["loss"]
        last = dec.history[-1]["train"]["loss"]
        assert last < first, (first, last)

    def test_cd_step_mask_equivalence(self):
        # a padded batch with mask must produce the same update as the
        # unpadded batch
        prng.seed_all(3)
        params = rbm_op.init_params(12, 6)
        import jax

        v = jnp.asarray(prng.get("x").uniform((4, 12), 0.0, 1.0))
        rng = jax.random.key(0)
        new_a, err_a = rbm_op.cd_step(params, v, rng, learning_rate=0.1)
        v_pad = jnp.concatenate([v, v[:1], v[:1]])
        mask = jnp.array([1.0, 1.0, 1.0, 1.0, 0.0, 0.0])
        new_b, err_b = rbm_op.cd_step(
            params, v_pad, rng, learning_rate=0.1, mask=mask
        )
        # gibbs keys differ in shape (6 vs 4 rows) -> chains differ; compare
        # only the deterministic positive phase via cd_k=1 + same seed rows.
        # The robust invariant: masked stats never include padded rows, so
        # vbias update from positive phase matches.
        np.testing.assert_allclose(err_a, err_b, rtol=0.5)

    def test_kohonen_train_step_mask_exact(self):
        prng.seed_all(4)
        params = kh.init_params(3, 3, 8)
        coords = kh.grid_coords(3, 3)
        x = jnp.asarray(prng.get("x").normal((5, 8)))
        lr = jnp.float32(0.5)
        sigma = jnp.float32(1.0)
        new_a, _ = kh.train_step(
            params, x, coords, learning_rate=lr, sigma=sigma
        )
        x_pad = jnp.concatenate([x, x[:2] * 100.0])  # junk padding rows
        mask = jnp.array([1.0] * 5 + [0.0] * 2)
        new_b, _ = kh.train_step(
            params, x_pad, coords, learning_rate=lr, sigma=sigma, mask=mask
        )
        np.testing.assert_allclose(
            new_a["weights"], new_b["weights"], rtol=1e-5
        )
