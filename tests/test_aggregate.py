"""Fleet metrics aggregation: push/merge semantics, TTL expiry, the
HTTP push + scrape surface, and the MetricsPusher slave side.

The ISSUE 7 acceptance path: >= 2 registries pushing concurrently merge
into ONE parse-clean Prometheus exposition with correct counter sums
and bucket-wise histogram merges; stale instances TTL out.  Pure
host-side — no jax, no compiled programs."""

import json
import http.client
import threading

import pytest

from znicz_tpu.observability import parse_prometheus_text
from znicz_tpu.observability.aggregate import (
    MetricsAggregator,
    MetricsPusher,
    build_aggregator_server,
)
from znicz_tpu.observability.registry import MetricsRegistry
from znicz_tpu.utils import faults


def _registry(submitted, ttfts, pending=0.0, reasons=()):
    r = MetricsRegistry()
    r.counter("znicz_serve_requests_submitted_total", "req").inc(submitted)
    h = r.histogram("znicz_serve_ttft_seconds", "ttft")
    for t in ttfts:
        h.observe(t)
    r.gauge("znicz_serve_frontdoor_pending", "pend").set(pending)
    ret = r.counter(
        "znicz_serve_requests_retired_total", "ret", ("reason",)
    )
    for reason in reasons:
        ret.labels(reason=reason).inc()
    return r


class TestMerge:
    def test_counters_and_gauges_sum_across_instances(self):
        agg = MetricsAggregator()
        agg.push("a", _registry(3, [], pending=2).snapshot(), now=0.0)
        agg.push("b", _registry(5, [], pending=7).snapshot(), now=0.0)
        snap = agg.merged_snapshot(now=0.1)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 8.0
        )
        assert (
            snap["znicz_serve_frontdoor_pending"]["series"][0]["value"]
            == 9.0
        )
        assert (
            snap["znicz_aggregator_instances"]["series"][0]["value"]
            == 2.0
        )

    def test_labeled_series_merge_per_labelset(self):
        agg = MetricsAggregator()
        agg.push(
            "a",
            _registry(1, [], reasons=("eos", "eos", "budget")).snapshot(),
            now=0.0,
        )
        agg.push(
            "b", _registry(1, [], reasons=("eos",)).snapshot(), now=0.0
        )
        snap = agg.merged_snapshot(now=0.0)
        by_reason = {
            s["labels"]["reason"]: s["value"]
            for s in snap["znicz_serve_requests_retired_total"]["series"]
        }
        assert by_reason == {"eos": 3.0, "budget": 1.0}

    def test_histograms_merge_bucket_wise(self):
        a, b = [0.01, 0.02, 0.3], [0.02, 4.0]
        agg = MetricsAggregator()
        agg.push("a", _registry(0, a).snapshot(), now=0.0)
        agg.push("b", _registry(0, b).snapshot(), now=0.0)
        ser = agg.merged_snapshot(now=0.0)["znicz_serve_ttft_seconds"][
            "series"
        ][0]
        assert ser["count"] == 5.0
        assert ser["sum"] == pytest.approx(sum(a) + sum(b))
        # cumulative per-edge sums: everything <= 0.025 is 3 samples
        assert ser["buckets"]["0.025"] == 3.0
        assert ser["buckets"]["+Inf"] == 5.0
        assert ser["p50"] is not None

    def test_bench_style_slo_side_entry_is_skipped_not_rejected(self):
        # bench._metrics_snapshot() rides a self-describing
        # {"type": "slo", ...} entry next to the metric families; a
        # round-tripped push must keep every family and skip the side
        # entry, not 400 the whole snapshot
        snap = _registry(4, [0.01]).snapshot()
        snap["slo"] = {"type": "slo", "targets": [], "breach": False}
        agg = MetricsAggregator()
        agg.push("bench", snap, now=0.0)
        merged = agg.merged_snapshot(now=0.0)
        assert (
            merged["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 4.0
        )
        assert "slo" not in merged

    def test_federated_push_drops_upstream_self_series(self):
        # a tier-1 aggregator's merged /metrics federated into a tier-2
        # aggregator: the upstream znicz_aggregator_* self-series are
        # dropped at canon time — only the LOCAL aggregator speaks
        # those names (never summed-then-overwritten, never a conflict)
        tier1 = MetricsAggregator()
        tier1.push("a", _registry(3, [0.01]).snapshot(), now=0.0)
        tier1.push("b", _registry(4, []).snapshot(), now=0.0)
        tier1.push("a", _registry(3, [0.01]).snapshot(), now=0.0)
        tier2 = MetricsAggregator()
        tier2.push("tier1", text=tier1.prometheus_text(now=0.0), now=0.0)
        tier2.push("local", _registry(2, []).snapshot(), now=0.0)
        snap = tier2.merged_snapshot(now=0.0)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 9.0
        )
        # tier1 reported instances=2 pushes=3; tier2's own view wins
        assert (
            snap["znicz_aggregator_instances"]["series"][0]["value"] == 2.0
        )
        assert (
            snap["znicz_aggregator_pushes_total"]["series"][0]["value"]
            == 2.0
        )
        assert (
            snap["znicz_aggregator_merge_conflicts"]["series"][0]["value"]
            == 0.0
        )

    def test_json_and_prom_pushes_merge_identically(self):
        r1, r2 = _registry(2, [0.01]), _registry(3, [0.5])
        agg = MetricsAggregator()
        agg.push("json", r1.snapshot(), now=0.0)
        agg.push("prom", text=r2.prometheus_text(), now=0.0)
        snap = agg.merged_snapshot(now=0.0)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 5.0
        )
        assert snap["znicz_serve_ttft_seconds"]["series"][0]["count"] == 2.0

    def test_merged_exposition_parse_clean_round_trip(self):
        agg = MetricsAggregator()
        agg.push("a", _registry(3, [0.01, 0.4]).snapshot(), now=0.0)
        agg.push("b", _registry(4, [0.02]).snapshot(), now=0.0)
        text = agg.prometheus_text(now=0.0)
        parsed = parse_prometheus_text(text)  # histogram invariants too
        samples = {
            (n, tuple(sorted(lbl.items()))): v
            for n, lbl, v in parsed["samples"]
        }
        assert (
            samples[("znicz_serve_requests_submitted_total", ())] == 7.0
        )
        assert samples[("znicz_serve_ttft_seconds_count", ())] == 3.0
        assert parsed["types"]["znicz_serve_ttft_seconds"] == "histogram"

    def test_last_push_wins_per_instance(self):
        agg = MetricsAggregator()
        agg.push("a", _registry(3, []).snapshot(), now=0.0)
        agg.push("a", _registry(10, []).snapshot(), now=1.0)
        snap = agg.merged_snapshot(now=1.0)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 10.0
        )
        assert agg.instances(now=1.0)[0]["pushes"] == 2

    def test_kind_conflict_skips_not_corrupts(self):
        r = MetricsRegistry()
        r.counter("znicz_thing_total", "as counter").inc(5)
        r2 = MetricsRegistry()
        r2.gauge("znicz_thing_total", "as gauge").set(100)
        agg = MetricsAggregator()
        agg.push("a", r.snapshot(), now=0.0)
        agg.push("b", r2.snapshot(), now=0.0)
        snap = agg.merged_snapshot(now=0.0)
        assert snap["znicz_thing_total"]["series"][0]["value"] == 5.0
        assert (
            snap["znicz_aggregator_merge_conflicts"]["series"][0][
                "value"
            ]
            == 1.0
        )
        # a GAUGE of the current view: re-reading the same persistent
        # conflict must not inflate it (reads never mutate)
        for _ in range(3):
            again = agg.merged_snapshot(now=0.0)
            assert (
                again["znicz_aggregator_merge_conflicts"]["series"][0][
                    "value"
                ]
                == 1.0
            )
        assert (
            again["znicz_aggregator_merge_conflicts"]["type"] == "gauge"
        )
        parse_prometheus_text(agg.prometheus_text(now=0.0))

    def test_malformed_push_raises_and_applies_nothing(self):
        agg = MetricsAggregator()
        with pytest.raises(ValueError):
            agg.push("a", {"bad": "not a family"})
        with pytest.raises(ValueError):
            agg.push("a", text="not { prometheus")
        with pytest.raises(ValueError):
            agg.push("a")  # neither snapshot nor text
        with pytest.raises(ValueError):
            agg.push(
                "a", _registry(1, []).snapshot(), text="x"
            )  # both
        assert agg.instances() == []


class TestTTL:
    def test_stale_instance_expires_out_of_the_merge(self):
        agg = MetricsAggregator(default_ttl_s=5.0)
        agg.push("old", _registry(3, []).snapshot(), now=0.0)
        agg.push("live", _registry(4, []).snapshot(), now=8.0)
        snap = agg.merged_snapshot(now=9.0)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 4.0
        )
        assert [i["instance"] for i in agg.instances(now=9.0)] == ["live"]

    def test_per_push_ttl_overrides_default(self):
        agg = MetricsAggregator(default_ttl_s=1000.0)
        agg.push("short", _registry(1, []).snapshot(), ttl_s=2.0, now=0.0)
        agg.push("long", _registry(1, []).snapshot(), now=0.0)
        assert [i["instance"] for i in agg.instances(now=5.0)] == ["long"]

    def test_repush_revives_before_expiry_boundary(self):
        agg = MetricsAggregator(default_ttl_s=5.0)
        agg.push("a", _registry(1, []).snapshot(), now=0.0)
        agg.push("a", _registry(2, []).snapshot(), now=4.0)
        assert len(agg.instances(now=8.0)) == 1  # 8-4 < 5: still live

    def test_forget_drops_immediately(self):
        agg = MetricsAggregator()
        agg.push("a", _registry(1, []).snapshot(), now=0.0)
        assert agg.forget("a") is True
        assert agg.forget("a") is False
        assert agg.instances(now=0.0) == []


@pytest.fixture()
def agg_server():
    server = build_aggregator_server(port=0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        yield server
    finally:
        server.shutdown()
        server.server_close()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class TestHTTPSurface:
    def test_concurrent_pushers_merge_end_to_end(self, agg_server):
        # the acceptance path: two registries push CONCURRENTLY over
        # real HTTP; the merged scrape is parse-clean with exact sums
        port = agg_server.server_address[1]
        regs = {
            "replica-0": _registry(3, [0.01, 0.02]),
            "replica-1": _registry(9, [0.5]),
        }
        pushers = {
            name: MetricsPusher(
                f"http://127.0.0.1:{port}", instance=name,
                registry=reg, interval_s=60.0,
            )
            for name, reg in regs.items()
        }
        threads = [
            threading.Thread(target=p.push_now)
            for p in pushers.values()
            for _ in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        status, body = _get(port, "/metrics")
        assert status == 200
        parsed = parse_prometheus_text(body.decode())
        flat = {
            (n, tuple(sorted(lbl.items()))): v
            for n, lbl, v in parsed["samples"]
        }
        assert (
            flat[("znicz_serve_requests_submitted_total", ())] == 12.0
        )
        assert flat[("znicz_serve_ttft_seconds_count", ())] == 3.0
        status, body = _get(port, "/instances")
        roster = json.loads(body)
        assert roster["live"] == 2
        assert {i["instance"] for i in roster["instances"]} == set(regs)
        status, body = _get(port, "/metrics.json")
        assert status == 200
        snap = json.loads(body)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 12.0
        )

    def test_text_push_with_instance_query(self, agg_server):
        port = agg_server.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request(
                "POST", "/push?instance=prom-replica",
                body=_registry(6, []).prometheus_text(),
                headers={"Content-Type": "text/plain"},
            )
            assert conn.getresponse().status == 200
        finally:
            conn.close()
        _, body = _get(port, "/metrics.json")
        snap = json.loads(body)
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 6.0
        )

    def test_bad_pushes_answer_400(self, agg_server):
        port = agg_server.server_address[1]
        for body, headers in (
            (b"{}", {"Content-Type": "application/json"}),  # no instance
            (b"garbage {", {"Content-Type": "text/plain"}),  # no instance
            (
                json.dumps(
                    {"instance": "x", "snapshot": {"bad": 1}}
                ).encode(),
                {"Content-Type": "application/json"},
            ),
            # non-object JSON: a 400, not an AttributeError-dropped
            # connection
            (b"[1, 2, 3]", {"Content-Type": "application/json"}),
            (b'"str"', {"Content-Type": "application/json"}),
            # non-object series entries: same contract
            (
                json.dumps(
                    {
                        "instance": "x",
                        "snapshot": {
                            "f": {"type": "gauge", "series": [42]}
                        },
                    }
                ).encode(),
                {"Content-Type": "application/json"},
            ),
        ):
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=10
            )
            try:
                conn.request("POST", "/push", body=body, headers=headers)
                assert conn.getresponse().status == 400
            finally:
                conn.close()
        _, body = _get(port, "/instances")
        assert json.loads(body)["live"] == 0

    def test_unknown_paths_404_and_healthz_ok(self, agg_server):
        port = agg_server.server_address[1]
        assert _get(port, "/healthz")[0] == 200
        assert _get(port, "/nope")[0] == 404


class TestPusher:
    def test_push_failure_never_raises(self):
        # nothing listening on a fresh ephemeral port
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        p = MetricsPusher(
            f"http://127.0.0.1:{port}", instance="x",
            registry=_registry(1, []), timeout_s=0.5,
        )
        assert p.push_now() is False
        assert p.pushes_failed == 1

    def test_fault_point_is_injectable(self, agg_server):
        port = agg_server.server_address[1]
        p = MetricsPusher(
            f"http://127.0.0.1:{port}", instance="x",
            registry=_registry(1, []),
        )
        with faults.injected("pusher.push", times=1):
            assert p.push_now() is False  # injected failure, swallowed
        assert p.push_now() is True  # disarmed: lands
        assert p.pushes_ok == 1 and p.pushes_failed == 1

    def test_background_loop_and_final_flush(self, agg_server):
        port = agg_server.server_address[1]
        reg = _registry(2, [])
        p = MetricsPusher(
            f"http://127.0.0.1:{port}", instance="bg", registry=reg,
            interval_s=0.05,
        )
        p.start()
        deadline = 50
        while p.pushes_ok == 0 and deadline:
            import time as _t

            _t.sleep(0.05)
            deadline -= 1
        reg.counter("znicz_serve_requests_submitted_total", "req").inc(100)
        p.stop()  # final flush carries the bump
        snap = agg_server.aggregator.merged_snapshot()
        assert (
            snap["znicz_serve_requests_submitted_total"]["series"][0][
                "value"
            ]
            == 102.0
        )

    def test_rejects_non_http_urls(self):
        with pytest.raises(ValueError):
            MetricsPusher("ftp://somewhere/push")
        with pytest.raises(ValueError):
            MetricsPusher("http://", instance="x")


class TestStatusWriterWiring:
    def test_status_writer_pushes_training_registry(
        self, tmp_path, agg_server
    ):
        # training side of the fleet view: StatusWriter's epoch hook
        # lands the process registry in the aggregator synchronously
        from znicz_tpu.services.web_status import StatusWriter

        port = agg_server.server_address[1]
        w = StatusWriter(
            str(tmp_path),
            aggregator_url=f"http://127.0.0.1:{port}",
            instance="trainer",
            push_interval_s=60.0,
        )

        class _Dec:
            epoch = 1
            max_epochs = 1
            best_value = 0.0
            best_epoch = 0
            history = []

        class _WF:
            name = "wf"
            decision = _Dec()
            timer = None

        w.on_epoch(
            _WF(),
            {"improved": False, "stop": True, "summary": {}},
        )
        w.close()
        roster = agg_server.aggregator.instances()
        assert any(i["instance"] == "trainer" for i in roster)
