"""Multi-host bring-up proof: 2 real processes, localhost coordinator, CPU.

Exercises the reference's master/slave replacement end to end [SURVEY.md 3.4
``--listen``/``--master-address`` -> ``--coordinator``/``--num-processes``/
``--process-id``]: both processes rendezvous via ``jax.distributed``, build
ONE global mesh spanning both, and run a jitted cross-process reduction.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
sys.path.insert(0, sys.argv[3])

import jax
jax.config.update("jax_platforms", "cpu")  # beat any sitecustomize override

from znicz_tpu.parallel import multihost

info = multihost.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert info["process_count"] == 2, info
assert info["global_devices"] == 2, info

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# exactly-one-coordinator contract (reference: master does bookkeeping)
flags = multihost_utils.process_allgather(
    jnp.asarray([1.0 if multihost.is_coordinator() else 0.0])
)
assert float(np.sum(flags)) == 1.0, flags

# jitted cross-process reduction over the global mesh
mesh = Mesh(np.array(jax.devices()), ("data",))
local = jnp.ones((4,)) * (jax.process_index() + 1)
garr = multihost_utils.host_local_array_to_global_array(local, mesh, P("data"))
total = jax.jit(
    jnp.sum, out_shardings=NamedSharding(mesh, P())
)(garr)
assert float(total) == 12.0, float(total)  # 4*1 + 4*2
print(f"OK process={jax.process_index()}")
"""


TRAIN_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
sys.path.insert(0, sys.argv[4])

import jax
jax.config.update("jax_platforms", "cpu")

from znicz_tpu.parallel import multihost

info = multihost.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert info["global_devices"] == 2, info

import numpy as np
from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.parallel import DataParallel, make_mesh
from znicz_tpu.workflow import StandardWorkflow
from znicz_tpu.workflow.snapshotter import Snapshotter

snap_root = sys.argv[3]
prng.seed_all(99)
loader = datasets.mnist(n_train=256, n_test=64, minibatch_size=64)
wf = StandardWorkflow(
    loader,
    [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
        {"type": "softmax", "->": {"output_sample_shape": 10}},
    ],
    decision_config={"max_epochs": 3},
    default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
)
wf.parallel = DataParallel(make_mesh(2, 1))
# separate per-process dirs: proves only the coordinator ever writes
wf.snapshotter = Snapshotter(
    os.path.join(snap_root, f"proc{jax.process_index()}"), interval=1
)
wf.initialize(seed=99)
# the loader must be serving this process's half of each global minibatch
assert wf.loader.process_count == 2, wf.loader.process_count
dec = wf.run()
hist = [
    {
        "train_loss": e["train"]["loss"],
        "train_n_err": e["train"]["n_err"],
        "test_n_err": e["test"]["n_err"],
    }
    for e in dec.history
]
print("HIST" + str(jax.process_index()) + "=" + json.dumps(hist))
print(f"OK process={jax.process_index()}")
"""


SCAN_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
sys.path.insert(0, sys.argv[3])

import jax
jax.config.update("jax_platforms", "cpu")

from znicz_tpu.parallel import multihost

multihost.initialize(
    coordinator_address=sys.argv[1], num_processes=2,
    process_id=int(sys.argv[2]),
)

import numpy as np
from znicz_tpu.core import prng
from znicz_tpu.loader import FullBatchLoader
from znicz_tpu.parallel import DataParallel, make_mesh
from znicz_tpu.workflow import StandardWorkflow

gen = np.random.default_rng(0)
imgs = gen.integers(0, 256, (256, 64), dtype=np.uint8)
labels = gen.integers(0, 10, 256).astype(np.int32)
prng.seed_all(77)
loader = FullBatchLoader(
    {"train": imgs}, {"train": labels}, minibatch_size=64,
    normalization="range", normalization_kwargs={"scale": 255.0,
                                                 "shift": -0.5},
    device_resident=True,
)
wf = StandardWorkflow(
    loader,
    [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
        {"type": "softmax", "->": {"output_sample_shape": 10}},
    ],
    decision_config={"max_epochs": 3},
    default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
)
wf.parallel = DataParallel(make_mesh(2, 1))
wf.initialize(seed=77)
assert wf._use_epoch_scan(), "device-resident loader must take the scan path"
dec = wf.run()
hist = [e["train"]["loss"] for e in dec.history]
print("HIST" + str(jax.process_index()) + "=" + json.dumps(hist))
print(f"OK process={jax.process_index()}")
"""


TP_CONV_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
# 2 LOCAL devices per process -> 4 global: the mesh's model axis spans
# devices WITHIN a process, data axis spans processes
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, sys.argv[4])

import jax
jax.config.update("jax_platforms", "cpu")

from znicz_tpu.parallel import multihost

info = multihost.initialize(
    coordinator_address=sys.argv[1], num_processes=2,
    process_id=int(sys.argv[2]),
)
assert info["global_devices"] == 4, info

import numpy as np
from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.parallel import DataParallel, make_mesh
from znicz_tpu.workflow import StandardWorkflow
from znicz_tpu.workflow.snapshotter import Snapshotter

snap_dir = sys.argv[3]
prng.seed_all(55)
loader = datasets.mnist(n_train=128, n_test=0, minibatch_size=32, flat=False)
wf = StandardWorkflow(
    loader,
    [
        {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 5, "ky": 5}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "conv_relu", "->": {"n_kernels": 16, "kx": 5, "ky": 5}},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "softmax", "->": {"output_sample_shape": 10}},
    ],
    decision_config={"max_epochs": 3},
    default_hyper={"learning_rate": 0.05, "gradient_moment": 0.9},
)
wf.parallel = DataParallel(make_mesh(2, 2), tp=True)  # cnn_tp_rules auto
wf.snapshotter = Snapshotter(snap_dir, interval=1)
wf.initialize(seed=55)
# conv kernels really live sharded over model, ACROSS the two hosts
w0 = wf.state.params[0]["weights"]
assert not w0.is_fully_replicated, w0.sharding
assert not w0.is_fully_addressable  # spans both processes' devices
dec = wf.run()
hist = [e["train"]["loss"] for e in dec.history]
print("HIST" + str(jax.process_index()) + "=" + json.dumps(hist))
print(f"OK process={jax.process_index()}")
"""


POOL_SHARDED_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, sys.argv[3])

import jax
jax.config.update("jax_platforms", "cpu")

from znicz_tpu.parallel import multihost

info = multihost.initialize(
    coordinator_address=sys.argv[1], num_processes=2,
    process_id=int(sys.argv[2]),
)
assert info["global_devices"] == 4, info

import numpy as np
from znicz_tpu.core import prng
from znicz_tpu.loader import FullBatchLoader
from znicz_tpu.parallel import DataParallel, make_mesh
from znicz_tpu.workflow import StandardWorkflow

gen = np.random.default_rng(3)
imgs = gen.integers(0, 256, (128, 8, 8, 1), dtype=np.uint8)
labels = (imgs.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
prng.seed_all(67)
loader = FullBatchLoader(
    {"train": imgs}, {"train": labels}, minibatch_size=32,
    normalization="range",
    normalization_kwargs={"scale": 255.0, "shift": -0.5},
    device_resident=True, pool_sharded=True,
)
wf = StandardWorkflow(
    loader,
    [{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
     {"type": "softmax", "->": {"output_sample_shape": 2}}],
    decision_config={"max_epochs": 3},
    default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
)
wf.parallel = DataParallel(make_mesh(4, 1))
wf.initialize(seed=67)
# each PROCESS shipped only its 2 shards' rows; the global pool spans all 4
pool = wf._ctx["pool"]
assert pool.shape[0] == 128
assert not pool.is_fully_addressable
assert pool.addressable_shards[0].data.shape[0] == 32
dec = wf.run()
hist = [e["train"]["loss"] for e in dec.history]
print("HIST" + str(jax.process_index()) + "=" + json.dumps(hist))
print(f"OK process={jax.process_index()}")
"""


KILL_WORKER = r"""
import json, os, signal, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)
sys.path.insert(0, sys.argv[4])

import jax
jax.config.update("jax_platforms", "cpu")

from znicz_tpu.parallel import multihost

multihost.initialize(
    coordinator_address=sys.argv[1], num_processes=2,
    process_id=int(sys.argv[2]),
)

import numpy as np
from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.parallel import DataParallel, make_mesh
from znicz_tpu.workflow import StandardWorkflow
from znicz_tpu.workflow.snapshotter import Snapshotter

phase = sys.argv[5]  # "kill" or "resume"
snap_dir = sys.argv[3]
prng.seed_all(99)
loader = datasets.mnist(n_train=256, n_test=64, minibatch_size=64)
wf = StandardWorkflow(
    loader,
    [
        {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
        {"type": "softmax", "->": {"output_sample_shape": 10}},
    ],
    decision_config={"max_epochs": 5},
    default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
)
wf.parallel = DataParallel(make_mesh(2, 1))
# ONE shared snapshot dir: only the coordinator's writer flag is set
wf.snapshotter = Snapshotter(snap_dir, interval=1)
if phase == "kill":
    wf.initialize(seed=99)
    for done in range(1, 6):
        v = wf.run_epoch()
        if jax.process_index() == 1 and done == 3:
            # hard failure mid-job: epoch 2's snapshot is durable, epoch 3
            # is in flight on the peer — the reference's dying-slave case
            os.kill(os.getpid(), signal.SIGKILL)
        if v["stop"]:
            break
else:
    wf.initialize(
        snapshot=os.path.join(snap_dir, "workflow_epoch2.pickle.gz")
    )
    assert wf.decision.epoch == 3, wf.decision.epoch
    dec = wf.run()
    hist = [
        {"train_loss": e["train"]["loss"], "train_n_err": e["train"]["n_err"]}
        for e in dec.history
    ]
    print("HIST" + str(jax.process_index()) + "=" + json.dumps(hist))
print(f"OK process={jax.process_index()}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_localhost_rendezvous(tmp_path):
    addr = f"127.0.0.1:{_free_port()}"
    env = {
        k: v for k, v in os.environ.items() if k != "XLA_FLAGS"
    }
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, addr, str(pid), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{out}\n{err}"
    assert any("OK process=0" in o for _, o, _ in outs)
    assert any("OK process=1" in o for _, o, _ in outs)


def test_two_process_training_matches_single_process(tmp_path):
    """Multi-host DP *training* end to end [SURVEY.md 3.4: the reference's
    master/slave actually trained across processes — job loop, loader shard
    assignment, aggregation]: 2 processes, each feeding only its half of
    every global minibatch, must reproduce the single-process loss
    trajectory; only the coordinator writes snapshots."""
    import json

    import numpy as np

    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    snap_root = str(tmp_path)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", TRAIN_WORKER, addr, str(pid), snap_root, REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host training worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{out}\n{err}"

    hists = {}
    for _, out, _ in outs:
        for line in out.splitlines():
            if line.startswith("HIST"):
                pid, _, payload = line[4:].partition("=")
                hists[int(pid)] = json.loads(payload)
    assert set(hists) == {0, 1}
    # both processes observed the SAME global metrics (no per-process drift)
    assert hists[0] == hists[1]

    # single-process baseline, same seeds (DP == single-device is proven by
    # tests/test_parallel.py; here cross-PROCESS must match too)
    from znicz_tpu.core import prng
    from znicz_tpu.loader import datasets
    from znicz_tpu.workflow import StandardWorkflow

    prng.seed_all(99)
    loader = datasets.mnist(n_train=256, n_test=64, minibatch_size=64)
    wf = StandardWorkflow(
        loader,
        [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ],
        decision_config={"max_epochs": 3},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
    )
    wf.initialize(seed=99)
    dec = wf.run()
    assert len(dec.history) == len(hists[0])
    for es, ep in zip(dec.history, hists[0]):
        assert es["train"]["n_err"] == ep["train_n_err"]
        assert es["test"]["n_err"] == ep["test_n_err"]
        np.testing.assert_allclose(
            es["train"]["loss"], ep["train_loss"], rtol=1e-4
        )

    # coordinator-gated snapshots: proc0's dir has them, proc1's is empty
    wrote0 = os.listdir(tmp_path / "proc0")
    wrote1 = (
        os.listdir(tmp_path / "proc1")
        if os.path.isdir(tmp_path / "proc1")
        else []
    )
    assert any(f.startswith("workflow") for f in wrote0), wrote0
    assert wrote1 == [], wrote1


def test_two_process_tensor_parallel_conv_training(tmp_path):
    """Multi-host x TP x conv (VERDICT r3 weak #7): 2 processes x 2 local
    devices on a (data=2, model=2) mesh — conv kernels shard over model
    ACROSS hosts, exercising shard_state's numpy round-trip and the
    snapshotter's cross-host allgather under real multi-process training.
    Losses must match the single-process 4-device run."""
    import json

    import numpy as np

    addr = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    snap_dir = str(tmp_path / "snaps")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", TP_CONV_WORKER, addr, str(pid), snap_dir,
             REPO],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("tp conv worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{out}\n{err}"
    hists = {}
    for _, out, _ in outs:
        for line in out.splitlines():
            if line.startswith("HIST"):
                pid, _, payload = line[4:].partition("=")
                hists[int(pid)] = json.loads(payload)
    assert set(hists) == {0, 1}
    assert hists[0] == hists[1]
    # the coordinator's snapshot contains the ALLGATHERED full conv kernel
    from znicz_tpu.workflow.snapshotter import load_snapshot

    state, host = load_snapshot(
        os.path.join(snap_dir, "workflow_epoch2.pickle.gz")
    )
    assert np.asarray(state[0][0]["weights"]).shape == (5, 5, 1, 8)

    # single-process baseline on a 4-device (data=2, model=2) mesh
    import jax

    from znicz_tpu.core import prng
    from znicz_tpu.loader import datasets
    from znicz_tpu.parallel import DataParallel, make_mesh
    from znicz_tpu.workflow import StandardWorkflow

    prng.seed_all(55)
    loader = datasets.mnist(
        n_train=128, n_test=0, minibatch_size=32, flat=False
    )
    wf = StandardWorkflow(
        loader,
        [
            {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 5, "ky": 5}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
            {"type": "conv_relu", "->": {"n_kernels": 16, "kx": 5, "ky": 5}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ],
        decision_config={"max_epochs": 3},
        default_hyper={"learning_rate": 0.05, "gradient_moment": 0.9},
        parallel=DataParallel(
            make_mesh(2, 2, devices=jax.devices()[:4]), tp=True
        ),
    )
    wf.initialize(seed=55)
    base = [e["train"]["loss"] for e in wf.run().history]
    np.testing.assert_allclose(base, hists[0], rtol=1e-4)


def test_two_process_pool_sharded_training(tmp_path):
    """Multi-host x data-axis-sharded HBM pool: each process device_puts
    ONLY its shards' rows (the capacity contract that lets the pooled
    dataset exceed any one host/chip), assembled globally via
    make_array_from_process_local_data; losses must match the
    single-process 4-device pool-sharded run."""
    import json

    import numpy as np

    addr = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", POOL_SHARDED_WORKER, addr, str(pid), REPO],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("pool-sharded worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{out}\n{err}"
    hists = {}
    for _, out, _ in outs:
        for line in out.splitlines():
            if line.startswith("HIST"):
                pid, _, payload = line[4:].partition("=")
                hists[int(pid)] = json.loads(payload)
    assert set(hists) == {0, 1}
    assert hists[0] == hists[1]

    # single-process baseline: same config on a 4-device mesh
    import jax

    from znicz_tpu.core import prng
    from znicz_tpu.loader import FullBatchLoader
    from znicz_tpu.parallel import DataParallel, make_mesh
    from znicz_tpu.workflow import StandardWorkflow

    gen = np.random.default_rng(3)
    imgs = gen.integers(0, 256, (128, 8, 8, 1), dtype=np.uint8)
    labels = (imgs.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
    prng.seed_all(67)
    loader = FullBatchLoader(
        {"train": imgs}, {"train": labels}, minibatch_size=32,
        normalization="range",
        normalization_kwargs={"scale": 255.0, "shift": -0.5},
        device_resident=True, pool_sharded=True,
    )
    wf = StandardWorkflow(
        loader,
        [{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
         {"type": "softmax", "->": {"output_sample_shape": 2}}],
        decision_config={"max_epochs": 3},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
        parallel=DataParallel(make_mesh(4, 1, devices=jax.devices()[:4])),
    )
    wf.initialize(seed=67)
    base = [e["train"]["loss"] for e in wf.run().history]
    np.testing.assert_allclose(base, hists[0], rtol=1e-4)


def test_kill_and_resume_from_coordinator_snapshot(tmp_path):
    """Elastic failure recovery, demonstrated (VERDICT r3 missing #1): a
    2-process job loses one process to SIGKILL mid-training; both restart
    from the coordinator's latest durable snapshot and the final loss
    trajectory matches an uninterrupted run — the checkpoint-restart
    counterpart of the reference master's ``drop_slave`` re-queue
    [SURVEY.md 5.3]."""
    import json
    import signal
    import time as _time

    import numpy as np

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    snap_dir = str(tmp_path / "snaps")

    # ---- phase 1: train, SIGKILL process 1 after epoch 2's snapshot
    addr = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", KILL_WORKER, addr, str(pid), snap_dir,
             REPO, "kill"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    # the launcher-as-supervisor role: once a worker dies, tear the job
    # down (the surviving process is blocked in a collective)
    deadline = _time.time() + 300
    while _time.time() < deadline:
        if procs[1].poll() is not None:
            break
        _time.sleep(0.5)
    else:
        for p in procs:
            p.kill()
        pytest.fail("process 1 never died")
    assert procs[1].returncode == -signal.SIGKILL
    _time.sleep(2.0)  # let proc0 finish any in-flight snapshot write
    procs[0].kill()
    procs[0].communicate()
    procs[1].communicate()

    # durable state: the coordinator wrote periodic snapshots up to epoch 2
    snaps = sorted(os.listdir(snap_dir))
    assert "workflow_epoch2.pickle.gz" in snaps, snaps

    # ---- phase 2: both processes restart from the epoch-2 snapshot
    addr2 = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", KILL_WORKER, addr2, str(pid), snap_dir,
             REPO, "resume"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("resume worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"resume worker failed:\n{out}\n{err}"
    hists = {}
    for _, out, _ in outs:
        for line in out.splitlines():
            if line.startswith("HIST"):
                pid, _, payload = line[4:].partition("=")
                hists[int(pid)] = json.loads(payload)
    assert set(hists) == {0, 1}
    assert hists[0] == hists[1]
    # restored history (epochs 0-2) + resumed epochs (3-4) = full run
    assert len(hists[0]) == 5

    # ---- uninterrupted single-process baseline, same seeds
    from znicz_tpu.core import prng
    from znicz_tpu.loader import datasets
    from znicz_tpu.workflow import StandardWorkflow

    prng.seed_all(99)
    loader = datasets.mnist(n_train=256, n_test=64, minibatch_size=64)
    wf = StandardWorkflow(
        loader,
        [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ],
        decision_config={"max_epochs": 5},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
    )
    wf.initialize(seed=99)
    dec = wf.run()
    assert len(dec.history) == 5
    for es, ep in zip(dec.history, hists[0]):
        assert es["train"]["n_err"] == ep["train_n_err"]
        np.testing.assert_allclose(
            es["train"]["loss"], ep["train_loss"], rtol=1e-4
        )


def test_two_process_device_resident_scan_training(tmp_path):
    """Multi-host x device-resident x scanned dispatch: the HBM pool is
    replicated per process, each process stacks only ITS loader shard, and
    the whole-split lax.scan runs over global arrays — losses must match
    the single-process run of the identical config."""
    import json

    import numpy as np

    addr = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", SCAN_WORKER, addr, str(pid), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host scan worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{out}\n{err}"
    hists = {}
    for _, out, _ in outs:
        for line in out.splitlines():
            if line.startswith("HIST"):
                pid, _, payload = line[4:].partition("=")
                hists[int(pid)] = json.loads(payload)
    assert hists[0] == hists[1]

    # single-process baseline of the same config
    from znicz_tpu.core import prng
    from znicz_tpu.loader import FullBatchLoader
    from znicz_tpu.workflow import StandardWorkflow

    gen = np.random.default_rng(0)
    imgs = gen.integers(0, 256, (256, 64), dtype=np.uint8)
    labels = gen.integers(0, 10, 256).astype(np.int32)
    prng.seed_all(77)
    loader = FullBatchLoader(
        {"train": imgs}, {"train": labels}, minibatch_size=64,
        normalization="range",
        normalization_kwargs={"scale": 255.0, "shift": -0.5},
        device_resident=True,
    )
    wf = StandardWorkflow(
        loader,
        [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ],
        decision_config={"max_epochs": 3},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
    )
    wf.initialize(seed=77)
    base = [e["train"]["loss"] for e in wf.run().history]
    np.testing.assert_allclose(base, hists[0], rtol=1e-4)
