"""Multi-host bring-up proof: 2 real processes, localhost coordinator, CPU.

Exercises the reference's master/slave replacement end to end [SURVEY.md 3.4
``--listen``/``--master-address`` -> ``--coordinator``/``--num-processes``/
``--process-id``]: both processes rendezvous via ``jax.distributed``, build
ONE global mesh spanning both, and run a jitted cross-process reduction.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # 1 local device per process
sys.path.insert(0, sys.argv[3])

import jax
jax.config.update("jax_platforms", "cpu")  # beat any sitecustomize override

from znicz_tpu.parallel import multihost

info = multihost.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
assert info["process_count"] == 2, info
assert info["global_devices"] == 2, info

import numpy as np
import jax.numpy as jnp
from jax.experimental import multihost_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# exactly-one-coordinator contract (reference: master does bookkeeping)
flags = multihost_utils.process_allgather(
    jnp.asarray([1.0 if multihost.is_coordinator() else 0.0])
)
assert float(np.sum(flags)) == 1.0, flags

# jitted cross-process reduction over the global mesh
mesh = Mesh(np.array(jax.devices()), ("data",))
local = jnp.ones((4,)) * (jax.process_index() + 1)
garr = multihost_utils.host_local_array_to_global_array(local, mesh, P("data"))
total = jax.jit(
    jnp.sum, out_shardings=NamedSharding(mesh, P())
)(garr)
assert float(total) == 12.0, float(total)  # 4*1 + 4*2
print(f"OK process={jax.process_index()}")
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_localhost_rendezvous(tmp_path):
    addr = f"127.0.0.1:{_free_port()}"
    env = {
        k: v for k, v in os.environ.items() if k != "XLA_FLAGS"
    }
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, addr, str(pid), REPO],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"worker failed:\n{out}\n{err}"
    assert any("OK process=0" in o for _, o, _ in outs)
    assert any("OK process=1" in o for _, o, _ in outs)
