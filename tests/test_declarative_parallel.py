"""Declarative parallelism UX: mesh specs, moe/attention layer entries,
tensor-parallel transformer — the config-driven surface over the DP/TP/SP/EP
primitives (VERDICT round-1 item 2; reference UX parity target is
``znicz/standard_workflow.py``-level declarativeness [SURVEY.md 2.3])."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader import FullBatchLoader, datasets
from znicz_tpu.parallel import (
    DATA_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    DataParallel,
    make_mesh,
    mesh_from_spec,
    parse_mesh_spec,
)
from znicz_tpu.workflow import StandardWorkflow
from znicz_tpu.workflow.transformer import TransformerLMWorkflow, lm_tp_rules


class TestMeshSpec:
    def test_parse(self):
        assert parse_mesh_spec("data=4,model=2") == {"data": 4, "model": 2}
        assert parse_mesh_spec("data=2, model=2, pipe=2") == {
            "data": 2, "model": 2, "pipe": 2,
        }

    def test_parse_errors(self):
        with pytest.raises(ValueError):
            parse_mesh_spec("data=4,bogus=2")
        with pytest.raises(ValueError):
            parse_mesh_spec("data")
        with pytest.raises(ValueError):
            parse_mesh_spec("data=0")

    def test_mesh_from_spec(self):
        m = mesh_from_spec("data=4,model=2")
        assert m.shape[DATA_AXIS] == 4 and m.shape[MODEL_AXIS] == 2
        # unlisted data axis soaks up remaining devices
        m2 = mesh_from_spec("model=2")
        assert m2.shape[DATA_AXIS] == 4
        m3 = mesh_from_spec("data=2,model=2,pipe=2")
        assert m3.shape[PIPE_AXIS] == 2

    def test_cli_mesh_flag_builds_tp_dataparallel(self, tmp_path):
        from znicz_tpu.launcher import run_args

        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.models.wine import run  # noqa: F401\n"
        )
        saved = root.wine.to_dict()
        try:
            root.wine.decision.update({"max_epochs": 1})
            # wine: 178 samples, minibatch 10 not divisible by 2 -> fix size
            root.wine.loader.update({"minibatch_size": 16})
            launcher = run_args(
                [str(wf_py), "--mesh", "data=2,model=2", "--random-seed", "3"]
            )
        finally:
            root.wine.clear()
            root.wine.update(saved)
        dp = launcher.workflow.parallel
        assert isinstance(dp, DataParallel)
        assert dp.mesh.shape[DATA_AXIS] == 2
        assert dp.mesh.shape[MODEL_AXIS] == 2
        assert dp.tp


class TestMoELayerEntry:
    def test_moe_in_layer_list_trains(self):
        prng.seed_all(21)
        loader = datasets.mnist(n_train=256, n_test=64, minibatch_size=64)
        wf = StandardWorkflow(
            loader,
            [
                {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
                {"type": "moe",
                 "->": {"n_experts": 4, "n_hidden": 32, "top_k": 2}},
                {"type": "softmax", "->": {"output_sample_shape": 10}},
            ],
            decision_config={"max_epochs": 3},
            default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
        )
        wf.initialize(seed=21)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]
        assert dec.history[-1]["test"]["err_pct"] < 30.0
        assert "moe" in wf.model.layer_types

    def test_moe_flattens_conv_activations(self):
        prng.seed_all(22)
        from znicz_tpu.workflow import build

        model = build(
            [
                {"type": "conv_relu",
                 "->": {"n_kernels": 4, "kx": 3, "ky": 3}},
                {"type": "moe", "->": {"n_experts": 2, "n_hidden": 8}},
                {"type": "softmax", "->": {"output_sample_shape": 3}},
            ],
            (8, 8, 1),
        )
        import jax.numpy as jnp

        y = model.apply(model.params, jnp.zeros((2, 8, 8, 1)))
        assert y.shape == (2, 3)


class TestAttentionLayerEntry:
    def test_attention_block_trains_sequence_classifier(self):
        """[T, D] per-sample input through attention blocks + softmax head:
        class = which half of the sequence carries the bright token."""
        prng.seed_all(23)
        gen = np.random.default_rng(0)
        n, t, d = 256, 8, 16
        labels = gen.integers(0, 2, n).astype(np.int32)
        x = gen.normal(0, 0.1, (n, t, d)).astype(np.float32)
        for i in range(n):
            pos = labels[i] * (t // 2) + gen.integers(0, t // 2)
            x[i, pos, :] += 2.0
        loader = FullBatchLoader(
            {"train": x[:192], "test": x[192:]},
            {"train": labels[:192], "test": labels[192:]},
            minibatch_size=64,
        )
        wf = StandardWorkflow(
            loader,
            [
                {"type": "attention", "->": {"n_heads": 2, "causal": False}},
                {"type": "attention", "->": {"n_heads": 2, "causal": False}},
                {"type": "softmax", "->": {"output_sample_shape": 2}},
            ],
            decision_config={"max_epochs": 8},
            default_hyper={"learning_rate": 0.05, "gradient_moment": 0.9},
        )
        wf.initialize(seed=23)
        dec = wf.run()
        assert dec.history[-1]["train"]["loss"] < dec.history[0]["train"]["loss"]
        assert dec.history[-1]["test"]["err_pct"] < 25.0

    def test_attention_needs_sequence_input(self):
        from znicz_tpu.workflow import build

        with pytest.raises(ValueError, match="attention"):
            build([{"type": "attention", "->": {"n_heads": 2}}], (16,))


def _lm_history(tokens, *, parallel=None, tp=False, sp=False, mesh=None,
                epochs=2):
    prng.seed_all(31)
    ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
    wf = TransformerLMWorkflow(
        ld, vocab=16, d_model=32, n_layers=2, n_heads=4,
        max_epochs=epochs,
        sequence_parallel=sp,
        tensor_parallel=tp,
        mesh=mesh,
        parallel=parallel,
    )
    wf.initialize(seed=31)
    return wf, wf.run().history


class TestTransformerTP:
    @pytest.fixture(scope="class")
    def tokens(self):
        return np.asarray(
            np.random.default_rng(4).integers(0, 16, (32, 32)), np.int32
        )

    def test_tp_rules_cover_all_params(self):
        from jax.sharding import PartitionSpec as P

        assert lm_tp_rules("[1]['wq']", None) == P(None, MODEL_AXIS)
        assert lm_tp_rules("[1]['wo']", None) == P(MODEL_AXIS, None)
        assert lm_tp_rules("[1]['w_up']", None) == P(None, MODEL_AXIS)
        assert lm_tp_rules("[1]['w_down']", None) == P(MODEL_AXIS, None)
        assert lm_tp_rules("[1]['up_bias']", None) == P(MODEL_AXIS)
        assert lm_tp_rules("[2]['head']", None) == P(None, MODEL_AXIS)
        assert lm_tp_rules("[0]['embed']", None) == P()
        assert lm_tp_rules("[1]['ln1_scale']", None) == P()

    def test_tp_matches_single_device(self, tokens):
        _, base = _lm_history(tokens)
        mesh = make_mesh(2, 4)
        wf, tp_hist = _lm_history(
            tokens, parallel=DataParallel(mesh), tp=True
        )
        # params actually sharded over the model axis
        qkv = wf.state.params[1]["wq"]
        assert not qkv.is_fully_replicated
        for ea, eb in zip(base, tp_hist):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=2e-3
            )

    def test_tp_composes_with_sp(self, tokens):
        _, base = _lm_history(tokens)
        mesh = make_mesh(4, 2)
        _, both = _lm_history(
            tokens, parallel=DataParallel(mesh), tp=True, sp=True, mesh=mesh
        )
        for ea, eb in zip(base, both):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"], rtol=2e-3
            )

    def test_tp_requires_model_axis(self, tokens):
        with pytest.raises(ValueError, match="model axis"):
            _lm_history(
                tokens, parallel=DataParallel(make_mesh(8, 1)), tp=True
            )

    def test_tp_requires_divisible_heads(self, tokens):
        ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
        with pytest.raises(ValueError, match="divisible"):
            TransformerLMWorkflow(
                ld, vocab=16, d_model=30, n_layers=1, n_heads=3,
                tensor_parallel=True,
                parallel=DataParallel(make_mesh(4, 2)),
            )
