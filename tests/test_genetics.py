"""Genetic optimizer tests (veles --optimize parity)."""

import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.genetics import GeneticOptimizer, Tune, find_tunables


class TestTunables:
    def test_find_in_tree_and_layer_dicts(self):
        root.g.update({"a": Tune(0.1, 0.0, 1.0), "nested": {"b": Tune(2, 1, 5, "int")}})
        root.g.layers = [
            {"type": "all2all", "<-": {"learning_rate": Tune(0.01, 1e-4, 1.0)}}
        ]
        found = find_tunables(root.g)
        assert len(found) == 3
        keys = {k for _, k, _ in found}
        assert keys == {"a", "b", "learning_rate"}

    def test_clip_kinds(self):
        t = Tune(2, 1, 5, "int")
        assert t.clip(7.6) == 5 and t.clip(0.2) == 1 and t.clip(3.4) == 3
        f = Tune(0.1, 0.0, 1.0)
        assert f.clip(2.0) == 1.0


class TestGeneticOptimizer:
    def test_minimizes_quadratic(self):
        prng.seed_all(123)
        root.q.update({"x": Tune(5.0, -10.0, 10.0), "y": Tune(-5.0, -10.0, 10.0)})
        tunables = find_tunables(root.q)

        def evaluate(genome):
            x, y = genome
            return (x - 3.0) ** 2 + (y + 1.0) ** 2

        opt = GeneticOptimizer(
            evaluate, tunables, population_size=12, mutation_rate=0.4
        )
        result = opt.run(generations=15)
        assert result["best_fitness"] < 0.5
        x, y = result["best_genome"]
        assert abs(x - 3.0) < 1.0 and abs(y + 1.0) < 1.0
        # apply_genome writes back into the config tree
        opt.apply_genome(result["best_genome"])
        assert root.q.x == x and root.q.y == y

    def test_no_tunables_raises(self):
        with pytest.raises(ValueError, match="no Tune leaves"):
            GeneticOptimizer(lambda g: 0.0, [])

    def test_deterministic_under_seed(self):
        def run_once():
            prng.reset()
            prng.seed_all(7)
            tunables = [({}, "x", Tune(0.0, -5.0, 5.0))]
            opt = GeneticOptimizer(
                lambda g: g[0] ** 2, tunables, population_size=6
            )
            return opt.run(generations=5)["best_fitness"]

        assert run_once() == run_once()


class TestConcurrentOptimize:
    def test_batch_evaluation_is_generationwise_and_concurrent(self):
        # the GA hands the WHOLE uncached generation to evaluate_batch at
        # once — concurrency happens there (wall-clock scaling check)
        import time
        from concurrent.futures import ThreadPoolExecutor

        calls = []

        def eval_batch(genomes):
            calls.append(len(genomes))
            with ThreadPoolExecutor(4) as ex:
                return list(
                    ex.map(
                        lambda g: (time.sleep(0.2), g[0] ** 2)[1], genomes
                    )
                )

        prng.seed_all(5)
        tunables = [({}, "x", Tune(0.0, -5.0, 5.0))]
        opt = GeneticOptimizer(
            None, tunables, population_size=8, evaluate_batch=eval_batch
        )
        t0 = time.time()
        result = opt.run(generations=1)
        dt = time.time() - t0
        assert max(calls) >= 4  # generation-sized batches, not per-genome
        assert dt < 8 * 0.2 * 0.8, dt  # faster than sequential => concurrent
        assert np.isfinite(result["best_fitness"])

    @pytest.mark.slow
    def test_worker_processes_deterministic_and_worker_count_invariant(
        self, tmp_path
    ):
        # VERDICT r1 #5 gate: N-way concurrent --optimize, deterministic
        # given seeds — and identical for every worker count
        from znicz_tpu.genetics import optimize_workflow
        from znicz_tpu.launcher import Launcher, _load_module, make_parser

        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.core.config import root\n"
            "from znicz_tpu.genetics import Tune\n"
            "import znicz_tpu.models.wine as wine\n"
            "root.wine.update({'lr': Tune(0.3, 0.05, 0.5)})\n"
            "def run(load, main):\n"
            "    lr = root.wine.get('lr')\n"
            "    layers = [dict(l) for l in wine.DEFAULTS['layers']]\n"
            "    for l in layers:\n"
            "        l['<-'] = {**l['<-'], 'learning_rate': lr}\n"
            "    root.wine.layers = layers\n"
            "    load(wine.build_workflow)\n"
            "    main()\n"
        )
        args = make_parser().parse_args(
            [str(wf_py), "--random-seed", "11", "--stop-after", "2"]
        )

        def run_once(n_workers):
            prng.reset()
            prng.seed_all(11)
            from znicz_tpu.core.config import root as r
            from znicz_tpu.genetics import find_tunables

            # reload each run: the previous search's apply_genome left the
            # best VALUE where the Tune leaf was (that is its contract)
            module = _load_module(str(wf_py), "wf_concurrent_test_mod")
            return optimize_workflow(
                module,
                Launcher(args),
                generations=1,
                tunables=find_tunables(r),
                n_workers=n_workers,
                population_size=3,
            )

        r2 = run_once(2)
        r1 = run_once(1)
        assert np.isfinite(r2["best_fitness"])
        assert r2["best_fitness"] == r1["best_fitness"]
        assert r2["best_genome"] == r1["best_genome"]


class TestSharedAcceleratorWarning:
    def test_warns_when_workers_exceed_chips(self, monkeypatch):
        import warnings

        import jax

        from znicz_tpu.core import subproc

        jax.devices()  # the parent-side check only fires on an
        # already-initialized backend (it must never initialize one)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(jax, "device_count", lambda: 1)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            subproc.warn_if_shared_accelerator(4, None)
        assert any("contend" in str(x.message) for x in w)
        # device='cpu' is the documented recipe: no warning
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            subproc.warn_if_shared_accelerator(4, "cpu")
            subproc.warn_if_shared_accelerator(1, None)
        assert not w

    def test_worker_side_check_fires_from_payload_tag(
        self, monkeypatch, capsys
    ):
        # the in-worker twin covers the CLI path where the parent never
        # initializes a backend (only one payload carries the tag)
        import jax

        from znicz_tpu.core import subproc

        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(jax, "device_count", lambda: 1)
        subproc._worker_warn_shared_chip({"warn_n_workers": 4})
        assert "contend" in capsys.readouterr().err
        subproc._worker_warn_shared_chip({})  # untagged: silent
        subproc._worker_warn_shared_chip(
            {"warn_n_workers": 4, "device": "cpu"}
        )
        assert capsys.readouterr().err == ""


class TestOptimizeCLI:
    def test_optimize_flag_end_to_end(self, tmp_path):
        from znicz_tpu.launcher import run_args

        wf_py = tmp_path / "wf.py"
        wf_py.write_text(
            "from znicz_tpu.core.config import root\n"
            "from znicz_tpu.genetics import Tune\n"
            "from znicz_tpu.models.wine import build_workflow\n"
            "root.wine.layers = None  # use DEFAULTS, then tune lr below\n"
            "import znicz_tpu.models.wine as wine\n"
            "root.wine.update({'lr': Tune(0.3, 0.05, 0.5)})\n"
            "def run(load, main):\n"
            "    lr = root.wine.get('lr')\n"
            "    layers = [dict(l) for l in wine.DEFAULTS['layers']]\n"
            "    for l in layers:\n"
            "        l['<-'] = {**l['<-'], 'learning_rate': lr}\n"
            "    root.wine.layers = layers\n"
            "    load(wine.build_workflow)\n"
            "    main()\n"
        )
        out = tmp_path / "best.znicz"
        launcher = run_args(
            [
                str(wf_py),
                "--random-seed", "11",
                "--stop-after", "2",
                "--optimize", "2",
                "--export", str(out),
            ]
        )
        assert launcher.result is not None
        assert np.isfinite(launcher.result["best_fitness"])
        assert len(launcher.result["history"]) == 2
        # export happens once, AFTER the search, with the best config applied
        assert out.read_bytes()[:8] == b"ZNICZT01"
