"""Continuous-batching decode engine: tier-1 smoke + goldens.

The engine (znicz_tpu/services/engine.py) must be a TRANSPARENT
batching layer: every completion's tokens equal the single-request
``generate()`` output for that prompt (up to EOS), whatever mix of
prompt lengths, budgets, slot reuse and admission order the queue held —
and the whole stream must stay recompile-free: exactly one admit
program per (prompt bucket, sampling structure) and ONE chunked decode
program, verified against both the engine's program ledger and the
process-wide jit caches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.services.engine import DecodeEngine
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 14
HEADS = 4


def _params(seed=27, max_seq=64):
    prng.seed_all(seed)
    return init_lm_params(17, 32, 2, HEADS, max_seq=max_seq)


def _reference(params, prompt, budget):
    """Single-request greedy generate(), trimmed at (and including) the
    first EOS — what the engine promises each request, batching aside."""
    out = np.asarray(
        G.generate(
            params, jnp.asarray(prompt)[None], n_heads=HEADS,
            max_new_tokens=budget, eos_id=EOS,
        )
    )[0]
    new = out[len(prompt):]
    hit = np.where(new == EOS)[0]
    if len(hit):
        new = new[: hit[0] + 1]
    return np.concatenate([prompt, new])


class TestEngineSmoke:
    def test_two_mixed_length_requests(self):
        # the tier-1 smoke: tiny LM, two mixed-length requests through
        # the engine, outputs golden against per-request generate()
        params = _params()
        gen = np.random.default_rng(3)
        pa = gen.integers(0, 17, (5,)).astype(np.int32)
        pb = gen.integers(0, 17, (12,)).astype(np.int32)
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2, admit_every=4
        )
        ia, ib = eng.submit(pa, 6), eng.submit(pb, 5)
        comps = eng.run()
        assert len(comps) == 2 and eng.pending == 0 and eng.active == 0
        np.testing.assert_array_equal(
            eng.completions[ia].tokens, _reference(params, pa, 6)
        )
        np.testing.assert_array_equal(
            eng.completions[ib].tokens, _reference(params, pb, 5)
        )
        # serving metrics ride profiling: latency + tokens/s per request
        c = eng.completions[ia]
        assert c.latency_s > 0 and c.tokens_per_sec > 0
        assert eng.latency.summary()["count"] == 2
        assert set(eng.stats()["phases"]) >= {"admit", "decode"}

    def test_slot_reuse_more_requests_than_slots(self):
        # 5 ragged requests through 2 slots: retirements must re-admit
        # from the queue mid-stream and every output stay golden
        params = _params()
        gen = np.random.default_rng(7)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32)
            for n in (5, 12, 3, 9, 17)
        ]
        budgets = [6, 4, 8, 5, 7]
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2, admit_every=3
        )
        ids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        assert eng.pending == 5
        comps = eng.run()
        assert len(comps) == 5
        for p, b, rid in zip(prompts, budgets, ids):
            np.testing.assert_array_equal(
                eng.completions[rid].tokens, _reference(params, p, b)
            )
        assert eng.stats()["generated_tokens"] == sum(
            c.n_new for c in comps
        )

    def test_one_compile_per_bucket_and_structure(self):
        # the ISSUE acceptance criterion: exactly one compile per
        # (bucket, sampling-structure) pair — same-bucket requests later
        # in the stream add NOTHING, cross-checked against the
        # process-wide jit caches, which a second engine of the same
        # geometry must leave untouched
        params = _params()
        gen = np.random.default_rng(5)
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2, admit_every=4
        )
        for length in (5, 9, 30, 7):  # buckets 16, 16, 32, 16
            eng.submit(gen.integers(0, 17, (length,)).astype(np.int32), 4)
        eng.run()
        st = eng.compile_stats()
        structure = (True, 0, False)  # greedy, no top_k, no nucleus
        assert st["programs"] == {
            ("admit", 16, structure): 1,
            ("admit", 32, structure): 1,
            ("chunk", 4, 2, structure): 1,
        }
        assert st["n_programs"] == 3
        n_admit, n_chunk = st["admit_jit_entries"], st["chunk_jit_entries"]
        eng2 = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2, admit_every=4
        )
        eng2.submit(gen.integers(0, 17, (11,)).astype(np.int32), 5)
        eng2.run()
        st2 = eng2.compile_stats()
        assert st2["admit_jit_entries"] == n_admit
        assert st2["chunk_jit_entries"] == n_chunk

    def test_sampling_mode_deterministic_and_in_vocab(self):
        # same rng + same submission order -> identical streams; tokens
        # stay in-vocab under temperature sampling
        params = _params()
        gen = np.random.default_rng(11)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32) for n in (4, 10, 6)
        ]

        def serve():
            eng = DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, batch_size=2,
                admit_every=3, temperature=0.9, rng=jax.random.key(8),
            )
            ids = [eng.submit(p, 5) for p in prompts]
            eng.run()
            return [eng.completions[i].tokens for i in ids]

        a, b = serve(), serve()
        for ta, tb, p in zip(a, b, prompts):
            np.testing.assert_array_equal(ta, tb)
            new = ta[len(p):]
            assert (new >= 0).all() and (new < 17).all()
            assert 1 <= len(new) <= 5

    def test_budget_one_and_immediate_eos_retire_at_admit(self):
        params = _params()
        gen = np.random.default_rng(13)
        p = gen.integers(0, 17, (6,)).astype(np.int32)
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2
        )
        rid = eng.submit(p, 1)
        (comp,) = eng.run()
        assert comp.id == rid and comp.n_new == 1
        assert comp.finish_reason in ("eos", "budget")
        np.testing.assert_array_equal(comp.tokens, _reference(params, p, 1))

    def test_instant_retirement_does_not_idle_the_slot(self):
        # budget-1 requests retire AT admission; the slot must keep
        # pulling from the queue in the same pass instead of decoding a
        # chunk at reduced capacity
        params = _params()
        gen = np.random.default_rng(17)
        prompts = [
            gen.integers(0, 17, (n,)).astype(np.int32)
            for n in (4, 6, 8, 5, 7)
        ]
        budgets = [1, 1, 1, 6, 5]  # three instant retirements up front
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2, admit_every=4
        )
        ids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
        comps = eng.run()
        assert len(comps) == 5
        for p, b, rid in zip(prompts, budgets, ids):
            np.testing.assert_array_equal(
                eng.completions[rid].tokens, _reference(params, p, b)
            )

    def test_submit_validation(self):
        params = _params()
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2
        )
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.asarray([], np.int32), 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.asarray([1, 2], np.int32), 0)
        # the error names the backend whose capacity actually ran out
        with pytest.raises(ValueError, match="dense KV buffer"):
            eng.submit(np.arange(5, dtype=np.int32), 60)  # 16 + 60 > 64
        with pytest.raises(ValueError, match="eos_id"):
            DecodeEngine(params, n_heads=HEADS, eos_id=99)

    def test_prefix_cache_requires_the_paged_backend(self):
        # the dense layout has no shareable blocks: asking for the
        # prefix cache must fail loudly, never be silently ignored
        params = _params()
        with pytest.raises(
            ValueError, match="prefix cache requires the paged backend"
        ):
            DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, prefix_cache=True
            )
        # explicit off (and the default) stay accepted
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, prefix_cache=False
        )
        assert eng.kv_backend == "dense"
