"""bench.py section harness: schema, isolation, selection, retry.

Tier-1 (no TPU): the bench driver parses ONE JSON object per line, so
the section runner must emit exactly that — a ``{"metric": ...}``
record per succeeding section and an ``{"error": ..., "section": ...}``
record for a failing one, with every OTHER section's records intact
(BENCH_r05 lost a whole round to one init flake).  Sections here are
monkeypatched fast fakes; the real measurement bodies never run.
"""

import json

import pytest

import bench


def _collect(sections, only=None, budget_s=0):
    # budget_s=0 disables the per-section wall budget by default so the
    # schema tests stay timing-free; the timeout tests pass their own
    lines = []
    failed = bench.run_sections(
        sections=sections,
        only=only,
        emit_record=lambda rec: lines.append(json.dumps(rec)),
        budget_s=budget_s,
    )
    return lines, failed


def _ok_section(name, value):
    def fn(ctx):
        ctx[name] = value
        return [{"metric": name, "value": value, "unit": "u"}]

    return (name, fn)


def _boom_section(name, exc=RuntimeError):
    def fn(ctx):
        raise exc(f"{name} exploded")

    return (name, fn)


class TestSectionIsolation:
    def test_every_line_is_one_parseable_json_record(self):
        lines, failed = _collect(
            [_ok_section("a_rate", 1.5), _ok_section("b_rate", 2.5)]
        )
        assert failed == []
        assert len(lines) == 2
        for line in lines:
            rec = json.loads(line)  # one object per line, parseable
            assert "\n" not in line
            assert "metric" in rec and "value" in rec

    def test_one_failing_section_cannot_zero_the_run(self):
        # the BENCH_r05 regression shape: a mid-run failure must emit
        # its own error record and leave neighbors' records intact
        lines, failed = _collect(
            [
                _ok_section("before_rate", 1.0),
                _boom_section("flaky", RuntimeError),
                _ok_section("after_rate", 2.0),
            ]
        )
        assert failed == ["flaky"]
        recs = [json.loads(x) for x in lines]
        assert [r.get("metric") for r in recs] == [
            "before_rate", None, "after_rate",
        ]
        err = recs[1]
        assert err["error"] == "RuntimeError"
        assert err["section"] == "flaky"
        assert "exploded" in err["detail"]

    def test_only_prefix_selects_sections(self):
        sections = [
            _ok_section("lm_serve_rate", 1.0),
            _ok_section("lm_serve_paged_rate", 2.0),
            _ok_section("alexnet_rate", 3.0),
        ]
        lines, failed = _collect(sections, only="lm_serve")
        assert failed == []
        got = {json.loads(x)["metric"] for x in lines}
        assert got == {"lm_serve_rate", "lm_serve_paged_rate"}

    def test_registered_sections_cover_the_headline_metrics(self):
        names = [name for name, _ in bench._SECTIONS]
        assert names == sorted(set(names), key=names.index)  # unique
        for expected in (
            "alexnet_step", "lm_train", "lm_serve", "lm_serve_paged",
            "lm_serve_prefix", "lm_serve_frontdoor",
        ):
            assert expected in names


class TestSectionBudget:
    def test_hung_section_times_out_and_round_continues(self):
        # the PR 5 leftover named in ROADMAP: a section that never
        # returns must emit its own timeout record and yield to the
        # next section instead of stalling the round forever
        import threading

        def hung(ctx):
            threading.Event().wait(timeout=30)  # "forever" at test scale
            return [{"metric": "never", "value": 0, "unit": "u"}]

        lines, failed = _collect(
            [
                _ok_section("before_rate", 1.0),
                ("stuck", hung),
                _ok_section("after_rate", 2.0),
            ],
            budget_s=0.3,
        )
        assert failed == ["stuck"]
        recs = [json.loads(x) for x in lines]
        assert [r.get("metric") for r in recs] == [
            "before_rate", None, "after_rate",
        ]
        assert recs[1] == {
            "error": "timeout", "section": "stuck", "budget_s": 0.3,
        }

    def test_fast_sections_are_untouched_by_the_budget(self):
        lines, failed = _collect(
            [_ok_section("quick_rate", 1.0)], budget_s=30.0
        )
        assert failed == []
        assert json.loads(lines[0])["metric"] == "quick_rate"


class TestBackendRetry:
    def test_init_backend_retries_then_succeeds(self):
        calls = []

        def probe():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError(
                    "Unable to initialize backend 'axon': UNAVAILABLE"
                )
            return ["dev0"]

        assert bench._init_backend(retries=3, delay=0.0, probe=probe) == [
            "dev0"
        ]
        assert len(calls) == 3

    def test_init_backend_gives_up_after_bounded_attempts(self):
        calls = []

        def probe():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE")

        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            bench._init_backend(retries=3, delay=0.0, probe=probe)
        assert len(calls) == 3
