"""bench.py section harness: schema, isolation, selection, retry —
plus the znicz-bench-diff regression gate over round files.

Tier-1 (no TPU): the bench driver parses ONE JSON object per line, so
the section runner must emit exactly that — a ``{"metric": ...}``
record per succeeding section and an ``{"error": ..., "section": ...}``
record for a failing one, with every OTHER section's records intact
(BENCH_r05 lost a whole round to one init flake).  Sections here are
monkeypatched fast fakes; the real measurement bodies never run.

``znicz-bench-diff`` (the bench trajectory's machine-readable gate)
is smoke-tested here in the same tier so a schema drift in either the
round files or the tool fails CI, not the next release round.
"""

import json

import pytest

import bench
from znicz_tpu.utils import bench_diff


def _collect(sections, only=None, budget_s=0):
    # budget_s=0 disables the per-section wall budget by default so the
    # schema tests stay timing-free; the timeout tests pass their own
    lines = []
    failed = bench.run_sections(
        sections=sections,
        only=only,
        emit_record=lambda rec: lines.append(json.dumps(rec)),
        budget_s=budget_s,
    )
    return lines, failed


def _ok_section(name, value):
    def fn(ctx):
        ctx[name] = value
        return [{"metric": name, "value": value, "unit": "u"}]

    return (name, fn)


def _boom_section(name, exc=RuntimeError):
    def fn(ctx):
        raise exc(f"{name} exploded")

    return (name, fn)


class TestSectionIsolation:
    def test_every_line_is_one_parseable_json_record(self):
        lines, failed = _collect(
            [_ok_section("a_rate", 1.5), _ok_section("b_rate", 2.5)]
        )
        assert failed == []
        assert len(lines) == 2
        for line in lines:
            rec = json.loads(line)  # one object per line, parseable
            assert "\n" not in line
            assert "metric" in rec and "value" in rec

    def test_one_failing_section_cannot_zero_the_run(self):
        # the BENCH_r05 regression shape: a mid-run failure must emit
        # its own error record and leave neighbors' records intact
        lines, failed = _collect(
            [
                _ok_section("before_rate", 1.0),
                _boom_section("flaky", RuntimeError),
                _ok_section("after_rate", 2.0),
            ]
        )
        assert failed == ["flaky"]
        recs = [json.loads(x) for x in lines]
        assert [r.get("metric") for r in recs] == [
            "before_rate", None, "after_rate",
        ]
        err = recs[1]
        assert err["error"] == "RuntimeError"
        assert err["section"] == "flaky"
        assert "exploded" in err["detail"]

    def test_only_prefix_selects_sections(self):
        sections = [
            _ok_section("lm_serve_rate", 1.0),
            _ok_section("lm_serve_paged_rate", 2.0),
            _ok_section("alexnet_rate", 3.0),
        ]
        lines, failed = _collect(sections, only="lm_serve")
        assert failed == []
        got = {json.loads(x)["metric"] for x in lines}
        assert got == {"lm_serve_rate", "lm_serve_paged_rate"}

    def test_registered_sections_cover_the_headline_metrics(self):
        names = [name for name, _ in bench._SECTIONS]
        assert names == sorted(set(names), key=names.index)  # unique
        for expected in (
            "alexnet_step", "lm_train", "lm_serve", "lm_serve_paged",
            "lm_serve_prefix", "lm_serve_frontdoor",
        ):
            assert expected in names


class TestSectionBudget:
    def test_hung_section_times_out_and_round_continues(self):
        # the PR 5 leftover named in ROADMAP: a section that never
        # returns must emit its own timeout record and yield to the
        # next section instead of stalling the round forever
        import threading

        def hung(ctx):
            threading.Event().wait(timeout=30)  # "forever" at test scale
            return [{"metric": "never", "value": 0, "unit": "u"}]

        lines, failed = _collect(
            [
                _ok_section("before_rate", 1.0),
                ("stuck", hung),
                _ok_section("after_rate", 2.0),
            ],
            budget_s=0.3,
        )
        assert failed == ["stuck"]
        recs = [json.loads(x) for x in lines]
        assert [r.get("metric") for r in recs] == [
            "before_rate", None, "after_rate",
        ]
        assert recs[1] == {
            "error": "timeout", "section": "stuck", "budget_s": 0.3,
        }

    def test_fast_sections_are_untouched_by_the_budget(self):
        lines, failed = _collect(
            [_ok_section("quick_rate", 1.0)], budget_s=30.0
        )
        assert failed == []
        assert json.loads(lines[0])["metric"] == "quick_rate"


def _round_file(tmp_path, name, metrics, driver=True):
    """One bench round on disk, in either accepted shape."""
    path = tmp_path / name
    if driver:
        path.write_text(json.dumps({"rc": 0, "parsed": metrics}))
    else:
        lines = [
            json.dumps({"metric": k, "value": v, "unit": "u"})
            for k, v in metrics.items()
        ]
        path.write_text("\n".join(lines) + "\n")
    return str(path)


class TestBenchDiff:
    def test_clean_diff_exits_zero(self, tmp_path, capsys):
        old = _round_file(
            tmp_path, "old.json", {"lm_serve_tokens_per_sec": 100.0}
        )
        new = _round_file(
            tmp_path, "new.json", {"lm_serve_tokens_per_sec": 99.0}
        )
        assert bench_diff.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "0 regression(s)" in out

    def test_throughput_drop_is_a_regression(self, tmp_path):
        old = _round_file(
            tmp_path, "old.json", {"lm_serve_tokens_per_sec": 100.0}
        )
        new = _round_file(
            tmp_path, "new.json", {"lm_serve_tokens_per_sec": 80.0}
        )
        assert bench_diff.main([old, new, "--threshold", "0.1"]) == 1
        # a looser threshold tolerates the same move
        assert bench_diff.main([old, new, "--threshold", "0.25"]) == 0

    def test_latency_shaped_metrics_regress_upward(self, tmp_path):
        old = _round_file(
            tmp_path, "old.json",
            {"lm_serve_frontdoor_ttft_p99_ms": 10.0, "step_ms": 5.0},
        )
        new = _round_file(
            tmp_path, "new.json",
            {"lm_serve_frontdoor_ttft_p99_ms": 15.0, "step_ms": 5.1},
        )
        # ttft +50% regresses; step_ms +2% is inside the threshold
        assert bench_diff.main([old, new]) == 1
        assert bench_diff.main(
            [old, new, "--only", "step_ms"]
        ) == 0

    def test_lower_better_from_zero_regresses(self, tmp_path):
        old = _round_file(
            tmp_path, "old.json", {"lm_serve_paged_compiles": 0.0}
        )
        new = _round_file(
            tmp_path, "new.json", {"lm_serve_paged_compiles": 2.0}
        )
        assert bench_diff.main([old, new]) == 1

    def test_ndjson_rounds_and_missing_metrics_tolerated(
        self, tmp_path, capsys
    ):
        old = _round_file(
            tmp_path, "old.json",
            {"a_rate_per_sec": 1.0, "only_old_per_sec": 3.0},
            driver=False,
        )
        new = _round_file(
            tmp_path, "new.json",
            {"a_rate_per_sec": 1.05, "only_new_per_sec": 9.0},
            driver=False,
        )
        assert bench_diff.main([old, new]) == 0
        out = capsys.readouterr().out
        assert "present in only one round" in out

    def test_error_records_skipped_in_ndjson(self, tmp_path):
        path = tmp_path / "mixed.json"
        path.write_text(
            json.dumps({"metric": "x_per_sec", "value": 2.0}) + "\n"
            + json.dumps({"error": "RuntimeError", "section": "s"})
            + "\n"
        )
        assert bench_diff.load_metrics(str(path)) == {"x_per_sec": 2.0}

    def test_direction_overrides(self, tmp_path):
        old = _round_file(tmp_path, "old.json", {"oddly_named": 10.0})
        new = _round_file(tmp_path, "new.json", {"oddly_named": 20.0})
        # default: higher-better, a rise is fine
        assert bench_diff.main([old, new]) == 0
        assert bench_diff.main(
            [old, new, "--lower", "oddly_named"]
        ) == 1

    def test_spec_metrics_are_higher_better(self, tmp_path):
        # ISSUE 12 satellite: the new speculative-serving metrics are
        # throughput-shaped — a DROP in acceptance rate or the
        # vs-baseline ratio is the regression, a rise never is
        for name in (
            "lm_serve_spec_acceptance_rate",
            "lm_serve_spec_vs_baseline",
        ):
            assert bench_diff.metric_direction(name, set(), set()) == (
                "higher"
            )
            old = _round_file(tmp_path, "old.json", {name: 1.0})
            new = _round_file(tmp_path, "new.json", {name: 0.5})
            assert bench_diff.main([old, new]) == 1  # drop regresses
            assert bench_diff.main([new, old]) == 0  # rise is fine
        # the marker beats embedded lower-better substrings ("_ms"
        # etc. never hijack an acceptance-rate family name)
        assert bench_diff.metric_direction(
            "spec_ttft_acceptance_rate", set(), set()
        ) == "higher"
        # while the spec COMPILE count stays lower-better
        assert bench_diff.metric_direction(
            "lm_serve_spec_compiles", set(), set()
        ) == "lower"

    def test_json_output_shape(self, tmp_path, capsys):
        old = _round_file(tmp_path, "old.json", {"r_per_sec": 1.0})
        new = _round_file(tmp_path, "new.json", {"r_per_sec": 0.5})
        assert bench_diff.main([old, new, "--json"]) == 1
        body = json.loads(capsys.readouterr().out)
        assert body["regressions"] == 1
        assert body["rows"][0]["metric"] == "r_per_sec"
        assert body["rows"][0]["regressed"] is True

    def test_usage_and_parse_errors_exit_two(self, tmp_path, capsys):
        assert bench_diff.main([]) == 2
        assert bench_diff.main(["one.json"]) == 2
        assert bench_diff.main(["a", "b", "--threshold"]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("not json at all")
        ok = _round_file(tmp_path, "ok.json", {"m_per_sec": 1.0})
        assert bench_diff.main([str(bad), ok]) == 2
        capsys.readouterr()  # drain stderr/stdout

    def test_fully_failed_round_fails_the_gate(self, tmp_path, capsys):
        """A round that crashed entirely (driver rc!=0, no parsed
        metrics — the committed BENCH_r05 shape) must NOT pass as
        '0 compared, 0 regressions': the gate exits 2."""
        failed = tmp_path / "failed.json"
        failed.write_text(
            json.dumps({"rc": 1, "cmd": "python bench.py",
                        "tail": "Traceback ...", "parsed": None})
        )
        ok = _round_file(tmp_path, "ok.json", {"m_per_sec": 1.0})
        assert bench_diff.main([ok, str(failed)]) == 2
        assert "no numeric metrics" in capsys.readouterr().err
        # all-error NDJSON is the same story
        errs = tmp_path / "errs.json"
        errs.write_text(
            json.dumps({"error": "RuntimeError", "section": "s"}) + "\n"
        )
        assert bench_diff.main([ok, str(errs)]) == 2
        capsys.readouterr()

    def test_program_headline_is_top_level_and_diffable(self, tmp_path):
        """The compile-ledger headline must ride as TOP-LEVEL numeric
        fields of the summary record (nested under metrics_snapshot it
        would be invisible to the flatten), and a compile-count rise
        must regress under the name heuristic."""
        headline = bench._program_headline()
        assert set(headline) >= {
            "programs_compiled", "programs_compile_seconds"
        }
        old = _round_file(
            tmp_path, "old.json",
            {"bench_sections_failed": 0, "programs_compiled": 3.0},
        )
        new = _round_file(
            tmp_path, "new.json",
            {"bench_sections_failed": 0, "programs_compiled": 5.0},
        )
        assert bench_diff.main([old, new]) == 1  # compiles grew: gate

    def test_committed_round_files_still_load(self):
        """The real BENCH_*.json trajectory must stay parseable — the
        tool is only a gate if it can read the artifacts the driver
        actually writes."""
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rounds = sorted(
            f for f in os.listdir(root)
            if f.startswith("BENCH_r") and f.endswith(".json")
        )
        assert rounds, "no committed bench rounds found"
        loaded = 0
        for name in rounds:
            try:
                metrics = bench_diff.load_metrics(
                    os.path.join(root, name)
                )
            except ValueError:
                continue  # an all-error round carries no metrics
            loaded += 1
            assert all(
                isinstance(v, float) for v in metrics.values()
            )
        assert loaded >= 2  # enough history for a real diff


class TestBackendRetry:
    def test_init_backend_retries_then_succeeds(self):
        calls = []

        def probe():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError(
                    "Unable to initialize backend 'axon': UNAVAILABLE"
                )
            return ["dev0"]

        assert bench._init_backend(retries=3, delay=0.0, probe=probe) == [
            "dev0"
        ]
        assert len(calls) == 3

    def test_init_backend_gives_up_after_bounded_attempts(self):
        calls = []

        def probe():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE")

        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            bench._init_backend(retries=3, delay=0.0, probe=probe)
        assert len(calls) == 3
