"""Workflow engine tests: model builder, training loop, snapshot/resume.

Mirrors the reference's functional-test style (SURVEY.md §4): run a sample
workflow for a few epochs with a fixed PRNG seed, assert convergence within a
tolerance band, then snapshot, reload, continue and assert the continued run
matches the uninterrupted one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.core import prng
from znicz_tpu.loader import datasets
from znicz_tpu.workflow import StandardWorkflow, Workflow, build
from znicz_tpu.workflow.snapshotter import Snapshotter


MLP_LAYERS = [
    {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
    {"type": "softmax", "->": {"output_sample_shape": 10}},
]


class TestEpochScanDispatch:
    def test_scan_matches_stepwise(self):
        # ONE lax.scan dispatch per split (device-resident loaders) must
        # reproduce the per-batch dispatch path exactly
        from znicz_tpu.loader.fullbatch import FullBatchLoader

        gen = np.random.default_rng(0)
        images = gen.integers(0, 256, (96, 8, 8, 1), dtype=np.uint8)
        labels = (images.mean(axis=(1, 2, 3)) > 127).astype(np.int32)

        def build_wf(dispatch):
            prng.seed_all(21)
            loader = FullBatchLoader(
                {"train": images, "test": images[:32]},
                {"train": labels, "test": labels[:32]},
                minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=True,
            )
            wf = StandardWorkflow(
                loader,
                [
                    {"type": "all2all_tanh",
                     "->": {"output_sample_shape": 8}},
                    {"type": "softmax", "->": {"output_sample_shape": 2}},
                ],
                decision_config={"max_epochs": 3},
                default_hyper={"learning_rate": 0.1,
                               "gradient_moment": 0.9},
                epoch_dispatch=dispatch,
            )
            wf.initialize(seed=21)
            return wf

        # build AND run each workflow under a freshly seeded registry —
        # the loader shuffle stream is global, so interleaving two runs
        # would hand them different permutations
        wf_scan = build_wf("auto")
        assert wf_scan._use_epoch_scan()  # device-resident -> scan path
        a = wf_scan.run().history
        wf_step = build_wf("step")
        assert not wf_step._use_epoch_scan()
        b = wf_step.run().history
        for ea, eb in zip(a, b):
            for split in ea:
                np.testing.assert_allclose(
                    ea[split]["loss"], eb[split]["loss"],
                    rtol=1e-5, atol=1e-7,
                )
                assert ea[split]["n_err"] == eb[split]["n_err"]
        # params identical too (same math, same order)
        np.testing.assert_allclose(
            np.asarray(wf_scan.state.params[0]["weights"]),
            np.asarray(wf_step.state.params[0]["weights"]),
            rtol=1e-6, atol=1e-7,
        )

    def test_scan_under_data_parallel_matches_stepwise(self):
        # stacked payloads shard on the batch dim: scan+DP == step+DP
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.parallel import DataParallel, make_mesh

        gen = np.random.default_rng(2)
        images = gen.integers(0, 256, (96, 8, 8, 1), dtype=np.uint8)
        labels = (images.mean(axis=(1, 2, 3)) > 127).astype(np.int32)

        def build_and_run(dispatch):
            prng.seed_all(23)
            loader = FullBatchLoader(
                {"train": images}, {"train": labels},
                minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=True,
            )
            wf = StandardWorkflow(
                loader,
                [{"type": "all2all_tanh",
                  "->": {"output_sample_shape": 8}},
                 {"type": "softmax", "->": {"output_sample_shape": 2}}],
                decision_config={"max_epochs": 2},
                default_hyper={"learning_rate": 0.1,
                               "gradient_moment": 0.9},
                epoch_dispatch=dispatch,
                parallel=DataParallel(make_mesh(8, 1)),
            )
            wf.initialize(seed=23)
            if dispatch == "auto":
                assert wf._use_epoch_scan()
            return wf.run().history

        a = build_and_run("auto")
        b = build_and_run("step")
        for ea, eb in zip(a, b):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"],
                rtol=1e-5, atol=1e-7,
            )
            assert ea["train"]["n_err"] == eb["train"]["n_err"]


class TestDeferredEpochSync:
    """epoch_sync='deferred': the metric fetch of epoch N overlaps epoch
    N+1's dispatch.  History and stopping must be IDENTICAL to sync mode —
    only the reporting lags."""

    def _build(self, epoch_sync, *, max_epochs=4, fail_iterations=100,
               seed=81):
        from znicz_tpu.loader.fullbatch import FullBatchLoader

        prng.seed_all(seed)
        gen = np.random.default_rng(19)
        images = gen.integers(0, 256, (96, 8, 8, 1), dtype=np.uint8)
        labels = (images.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
        loader = FullBatchLoader(
            {"train": images}, {"train": labels}, minibatch_size=32,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
            device_resident=True,
        )
        wf = StandardWorkflow(
            loader,
            [{"type": "all2all_tanh", "->": {"output_sample_shape": 8}},
             {"type": "softmax", "->": {"output_sample_shape": 2}}],
            decision_config={"max_epochs": max_epochs,
                             "fail_iterations": fail_iterations},
            default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
            epoch_sync=epoch_sync,
        )
        wf.initialize(seed=seed)
        return wf

    def test_matches_sync_history_and_stop(self):
        a = self._build("sync").run()
        b = self._build("deferred").run()
        assert len(a.history) == len(b.history) == 4  # exact stop
        for ea, eb in zip(a.history, b.history):
            np.testing.assert_allclose(
                ea["train"]["loss"], eb["train"]["loss"],
                rtol=1e-6, atol=1e-8,
            )

    def test_patience_stop_is_exact(self):
        # fail_iterations-driven stop: deferred must not run extra epochs
        da = self._build(
            "sync", max_epochs=50, fail_iterations=2, seed=83
        ).run()
        db = self._build(
            "deferred", max_epochs=50, fail_iterations=2, seed=83
        ).run()
        assert len(da.history) == len(db.history)
        assert da.best_epoch == db.best_epoch

    def test_run_epoch_lags_one_verdict(self):
        wf = self._build("deferred")
        assert wf.run_epoch() is None  # epoch 0 dispatched, nothing done
        v0 = wf.run_epoch()  # epoch 1 dispatched, epoch 0 reported
        assert v0 is not None and not v0["stop"]
        assert wf.decision.epoch == 1
        final = wf.sync_epoch()  # flush epoch 1
        assert final is not None
        assert wf.sync_epoch() is None  # idempotent

    def test_best_snapshots_compose_exactly(self, tmp_path):
        # deferred + save_best: improvement is only known one epoch late,
        # so best saves write from the retained one-epoch state buffer —
        # the written files must be BYTE-identical to a sync-mode run's
        # (state, loader/prng host state, decision bookkeeping, all of it)
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.workflow.snapshotter import Snapshotter

        def run(epoch_sync, out_dir):
            prng.seed_all(85)
            gen = np.random.default_rng(21)
            images = gen.integers(0, 256, (96, 8, 8, 1), dtype=np.uint8)
            labels = (images.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
            loader = FullBatchLoader(
                {"train": images}, {"train": labels}, minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=True,
            )
            wf = StandardWorkflow(
                loader,
                [{"type": "all2all_tanh",
                  "->": {"output_sample_shape": 8}},
                 {"type": "softmax", "->": {"output_sample_shape": 2}}],
                decision_config={"max_epochs": 5},
                default_hyper={"learning_rate": 0.1,
                               "gradient_moment": 0.9},
                epoch_sync=epoch_sync,
            )
            # compress=False: gzip headers embed an mtime, which would
            # defeat the byte-for-byte comparison
            wf.snapshotter = Snapshotter(
                str(out_dir), compress=False, interval=2
            )
            wf.initialize(seed=85)
            wf.run()

        run("sync", tmp_path / "sync")
        run("deferred", tmp_path / "deferred")
        for tag in ("best", "epoch1", "epoch3"):
            s = (tmp_path / "sync" / f"workflow_{tag}.pickle").read_bytes()
            d = (
                tmp_path / "deferred" / f"workflow_{tag}.pickle"
            ).read_bytes()
            assert s == d, f"{tag} snapshot differs between sync/deferred"

    def test_interval_snapshots_compose_exactly(self, tmp_path):
        # interval epochs flush BEFORE the next dispatch, so the snapshot
        # captures exactly the state a sync-mode run would have written
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.workflow.snapshotter import (
            Snapshotter,
            load_snapshot,
        )

        def run(epoch_sync, out_dir):
            prng.seed_all(85)
            gen = np.random.default_rng(21)
            images = gen.integers(0, 256, (96, 8, 8, 1), dtype=np.uint8)
            labels = (images.mean(axis=(1, 2, 3)) > 127).astype(np.int32)
            loader = FullBatchLoader(
                {"train": images}, {"train": labels}, minibatch_size=32,
                normalization="range",
                normalization_kwargs={"scale": 255.0, "shift": -0.5},
                device_resident=True,
            )
            wf = StandardWorkflow(
                loader,
                [{"type": "all2all_tanh",
                  "->": {"output_sample_shape": 8}},
                 {"type": "softmax", "->": {"output_sample_shape": 2}}],
                decision_config={"max_epochs": 5},
                default_hyper={"learning_rate": 0.1,
                               "gradient_moment": 0.9},
                epoch_sync=epoch_sync,
            )
            wf.snapshotter = Snapshotter(
                str(out_dir), interval=2, save_best=False
            )
            wf.initialize(seed=85)
            wf.run()

        run("sync", tmp_path / "sync")
        run("deferred", tmp_path / "deferred")
        for tag in ("epoch1", "epoch3"):
            s_state, _ = load_snapshot(
                str(tmp_path / "sync" / f"workflow_{tag}.pickle.gz")
            )
            d_state, _ = load_snapshot(
                str(tmp_path / "deferred" / f"workflow_{tag}.pickle.gz")
            )
            np.testing.assert_allclose(
                s_state[0][0]["weights"], d_state[0][0]["weights"],
                rtol=1e-6, atol=1e-8,
            )


class TestModelBuilder:
    def test_mlp_shapes(self):
        m = build(MLP_LAYERS, (784,))
        assert m.params[0]["weights"].shape == (784, 32)
        assert m.params[1]["weights"].shape == (32, 10)
        assert m.output_shape == (10,)
        assert m.returns_logits
        y = m.apply(m.params, jnp.zeros((4, 784)))
        assert y.shape == (4, 10)

    def test_conv_stack_shapes(self):
        layers = [
            {"type": "conv_relu", "->": {"n_kernels": 8, "kx": 5, "ky": 5}},
            {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
            {"type": "norm"},
            {"type": "dropout", "->": {"dropout_ratio": 0.3}},
            {"type": "softmax", "->": {"output_sample_shape": 10}},
        ]
        m = build(layers, (28, 28, 1))
        y = m.apply(m.params, jnp.zeros((2, 28, 28, 1)))
        assert y.shape == (2, 10)
        # conv 28->24, pool ->12: FC input is 12*12*8
        assert m.params[-1]["weights"].shape == (12 * 12 * 8, 10)

    def test_per_layer_gd_config(self):
        layers = [
            {
                "type": "all2all_tanh",
                "->": {"output_sample_shape": 4},
                "<-": {"learning_rate": 0.5, "gradient_moment": 0.9},
            },
            {"type": "softmax", "->": {"output_sample_shape": 2}},
        ]
        m = build(layers, (8,))
        assert m.hyper[0].learning_rate == 0.5
        assert m.hyper[0].gradient_moment == 0.9
        assert m.hyper[1].learning_rate == 0.01  # default

    def test_dropout_needs_rng_in_train(self):
        m = build(
            [{"type": "dropout", "->": {"dropout_ratio": 0.5}}], (16,)
        )
        x = jnp.ones((2, 16))
        with pytest.raises(ValueError):
            m.apply(m.params, x, train=True)
        y = m.apply(m.params, x, train=True, rng=jax.random.key(0))
        assert float(jnp.min(y)) == 0.0  # something dropped
        np.testing.assert_allclose(m.apply(m.params, x, train=False), x)

    def test_unknown_layer_type(self):
        with pytest.raises(ValueError, match="unknown layer type"):
            build([{"type": "transformer"}], (8,))

    def test_predict_softmax_probs(self):
        m = build(MLP_LAYERS, (784,))
        p = m.predict(m.params, jnp.zeros((3, 784)))
        np.testing.assert_allclose(jnp.sum(p, axis=1), 1.0, rtol=1e-5)

    def test_deterministic_init(self):
        prng.seed_all(11)
        a = build(MLP_LAYERS, (784,))
        prng.seed_all(11)
        b = build(MLP_LAYERS, (784,))
        np.testing.assert_array_equal(
            a.params[0]["weights"], b.params[0]["weights"]
        )


def _mnist_workflow(tmp_path=None, max_epochs=4, **kw):
    loader = datasets.mnist(
        n_train=256, n_test=64, validation_ratio=0.25, minibatch_size=64
    )
    return StandardWorkflow(
        loader,
        MLP_LAYERS,
        decision_config={"max_epochs": max_epochs},
        default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
        snapshot_dir=str(tmp_path) if tmp_path else None,
        **kw,
    )


class TestTraining:
    def test_mnist_mlp_converges(self):
        prng.seed_all(1234)
        wf = _mnist_workflow()
        wf.initialize(seed=1234)
        dec = wf.run()
        final = dec.history[-1]
        # tolerance-band acceptance per SURVEY.md §7 "Hard parts"
        assert final["train"]["err_pct"] < 5.0
        assert final["valid"]["err_pct"] < 10.0
        assert dec.epoch == 4

    def test_masked_last_batch(self):
        # 100 train samples / bs 64 -> second batch half padded; training
        # must still work and count exactly 100 samples per epoch
        loader = datasets.mnist(n_train=100, n_test=10, minibatch_size=64)
        wf = StandardWorkflow(
            loader,
            MLP_LAYERS,
            decision_config={"max_epochs": 1},
            default_hyper={"learning_rate": 0.05},
        )
        wf.initialize(seed=7)
        dec = wf.run()
        assert dec.history[-1]["train"]["n_samples"] == 100.0

    def test_autoencoder_mse_path(self):
        loader = datasets.mnist(
            n_train=128, n_test=0, minibatch_size=16, normalization="mean_disp"
        )
        layers = [
            {"type": "all2all_tanh", "->": {"output_sample_shape": 32}},
            {"type": "all2all", "->": {"output_sample_shape": 784}},
        ]
        wf = StandardWorkflow(
            loader,
            layers,
            decision_config={"max_epochs": 10},
            default_hyper={"learning_rate": 0.1, "gradient_moment": 0.9},
        )
        assert wf.loss_function == "mse" and wf.target == "input"
        wf.initialize(seed=3)
        dec = wf.run()
        assert (
            dec.history[-1]["train"]["loss"]
            < dec.history[0]["train"]["loss"] * 0.8
        )

    def test_lr_policy_applied(self):
        wf = _mnist_workflow(
            max_epochs=1, lr_policy={"name": "exp", "gamma": 0.5}
        )
        wf.initialize(seed=1)
        wf.run()  # just exercises the scaled-lr code path
        assert int(wf.state.step) == 3  # 192 train / 64


class TestEvaluate:
    def test_confusion_matrix_sums_over_batches(self):
        prng.seed_all(8)
        wf = _mnist_workflow(max_epochs=2)
        wf.initialize(seed=8)
        wf.run()
        result = wf.evaluate("test", confusion=True)
        conf = result["confusion"]
        assert conf.shape == (10, 10)
        assert conf.sum() == result["n_samples"] == 64
        # diagonal dominance after training on separable synthetic data
        assert np.trace(conf) == result["n_samples"] - result["n_err"]

    def test_timer_ledger_populated(self):
        prng.seed_all(8)
        wf = _mnist_workflow(max_epochs=1)
        wf.initialize(seed=8)
        wf.run()
        s = wf.timer.summary()
        assert "dispatch/train" in s and "metrics_sync" in s
        assert s["dispatch/train"]["count"] == 3  # 192 train / 64


class TestSnapshotResume:
    def test_resume_matches_uninterrupted(self, tmp_path):
        # uninterrupted run: 6 epochs
        prng.seed_all(77)
        wf_a = _mnist_workflow(tmp_path / "a", max_epochs=6)
        wf_a.initialize(seed=77)
        dec_a = wf_a.run()

        # interrupted: 3 epochs, snapshot every epoch, then resume 3 more
        prng.seed_all(77)
        wf_b = _mnist_workflow(
            tmp_path / "b",
            max_epochs=3,
            snapshot_config={"interval": 1, "compress": False},
        )
        wf_b.initialize(seed=77)
        wf_b.run()
        snap = tmp_path / "b" / "StandardWorkflow_epoch2.pickle"
        assert snap.exists()

        # dataset construction must see the same seed (synthetic data stands
        # in for on-disk files); stream positions are then restored from the
        # snapshot inside initialize()
        prng.seed_all(77)
        wf_c = _mnist_workflow(tmp_path / "c", max_epochs=6)
        wf_c.initialize(snapshot=str(snap))
        assert wf_c.decision.epoch == 3
        dec_c = wf_c.run()

        # continued trajectory must match the uninterrupted run exactly:
        # same shuffles (prng restore), same params (state restore)
        for ea, ec in zip(dec_a.history[3:], dec_c.history[3:]):
            assert ea["train"]["n_err"] == ec["train"]["n_err"]
            np.testing.assert_allclose(
                ea["train"]["loss"], ec["train"]["loss"], rtol=1e-5
            )

    def test_best_snapshot_written_on_improvement(self, tmp_path):
        wf = _mnist_workflow(tmp_path, max_epochs=2)
        wf.initialize(seed=5)
        wf.run()
        assert (tmp_path / "StandardWorkflow_best.pickle.gz").exists()

    def test_snapshot_keep_limit(self, tmp_path):
        snap = Snapshotter(str(tmp_path), "t", interval=1, keep=2, compress=False)
        from znicz_tpu.nn.train_state import TrainState

        st = TrainState.create([{"w": jnp.ones(2)}], jax.random.key(0))
        for e in range(5):
            snap.maybe_save(st, {}, epoch=e, improved=False)
        files = sorted(
            p.name for p in tmp_path.iterdir()
            if p.name.endswith(".pickle")
        )
        assert files == ["t_epoch3.pickle", "t_epoch4.pickle"]
        # pruning removes the integrity sidecar along with its snapshot
        sidecars = sorted(
            p.name for p in tmp_path.iterdir()
            if p.name.endswith(".sha256")
        )
        assert sidecars == [
            "t_epoch3.pickle.sha256", "t_epoch4.pickle.sha256"
        ]

    def test_snapshot_keep_limit_survives_restart(self, tmp_path):
        from znicz_tpu.nn.train_state import TrainState

        st = TrainState.create([{"w": jnp.ones(2)}], jax.random.key(0))
        snap = Snapshotter(str(tmp_path), "t", interval=1, keep=2, compress=False)
        for e in range(3):
            snap.maybe_save(st, {}, epoch=e, improved=False)
        # new process: retention must count snapshots the old process wrote
        snap2 = Snapshotter(str(tmp_path), "t", interval=1, keep=2, compress=False)
        for e in range(3, 5):
            snap2.maybe_save(st, {}, epoch=e, improved=False)
        files = sorted(
            p.name for p in tmp_path.iterdir()
            if p.name.endswith(".pickle")
        )
        assert files == ["t_epoch3.pickle", "t_epoch4.pickle"]

    def test_state_roundtrip_preserves_key(self, tmp_path):
        from znicz_tpu.nn.train_state import TrainState

        snap = Snapshotter(str(tmp_path), "k", compress=True)
        st = TrainState.create([{"w": jnp.arange(4.0)}], jax.random.key(42))
        path = snap.save(st, {"decision": {"epoch": 1}}, tag="x")
        loaded, host = snap.load(path)
        loaded = TrainState(*loaded)
        assert host["decision"]["epoch"] == 1
        np.testing.assert_array_equal(loaded.params[0]["w"], st.params[0]["w"])
        # key must be usable
        jax.random.uniform(loaded.key)
