"""Test harness: simulate an 8-device mesh on CPU.

Mirrors SURVEY.md section 4's rebuild strategy: all sharding/collective logic
is unit-testable without TPUs via xla_force_host_platform_device_count.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# The axon sitecustomize force-registers the TPU backend and sets
# jax_platforms="axon,cpu" in every process, overriding the env var above —
# override it back AFTER import so tests run on the virtual 8-device CPU
# mesh.  ZNICZ_TEST_TPU=1 keeps the real chip instead (for the TPU-gated
# timing assertions in test_pallas.py; most golden tests still pass there,
# but the virtual-mesh parallelism tests need the 8-device CPU setup).
if os.environ.get("ZNICZ_TEST_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

# Golden tests compare XLA ops against naive numpy: use full fp32 matmuls.
# Production code keeps JAX's fast default (bf16-on-MXU) — see bench.py.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _fresh_prng():
    """Reseed the named-generator registry per test for reproducibility."""
    from znicz_tpu.core import prng

    prng.reset()
    prng.seed_all(1234)
    yield
    prng.reset()


@pytest.fixture(autouse=True)
def _fresh_config():
    from znicz_tpu.core.config import root

    saved = root.to_dict()
    yield
    root.clear()
    root.update(saved)
