"""Pipeline parallelism tests (GPipe-style stage pipeline on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from znicz_tpu.parallel.pipeline import (
    pipeline_apply,
    shard_stacked_params,
    stack_stage_params,
)


def _pipe_mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("pipe",))


def _stage_params(n_stages=4, width=16, seed=0):
    keys = jax.random.split(jax.random.key(seed), n_stages)
    return [
        {
            "w": jax.random.normal(k, (width, width)) * (1.0 / np.sqrt(width)),
            "b": jnp.zeros((width,)),
        }
        for k in keys
    ]


def _apply_one(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _sequential(per_stage, x):
    for p in per_stage:
        x = _apply_one(p, x)
    return x


class TestPipelineApply:
    @pytest.mark.parametrize("n_micro", [1, 2, 4])
    def test_matches_sequential(self, n_micro):
        mesh = _pipe_mesh(4)
        per_stage = _stage_params(4)
        stacked = stack_stage_params(per_stage)
        x = jax.random.normal(jax.random.key(1), (8, 16))
        ref = _sequential(per_stage, x)
        out = pipeline_apply(
            stacked, x, apply_one=_apply_one, mesh=mesh,
            n_microbatches=n_micro,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_eight_stages(self):
        mesh = _pipe_mesh(8)
        per_stage = _stage_params(8, width=8, seed=3)
        stacked = stack_stage_params(per_stage)
        x = jax.random.normal(jax.random.key(2), (4, 8))
        ref = _sequential(per_stage, x)
        out = pipeline_apply(
            stacked, x, apply_one=_apply_one, mesh=mesh, n_microbatches=2
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_gradients_match_sequential(self):
        mesh = _pipe_mesh(4)
        per_stage = _stage_params(4, width=8, seed=5)
        stacked = stack_stage_params(per_stage)
        x = jax.random.normal(jax.random.key(3), (4, 8))

        def pipe_loss(sp):
            return jnp.sum(
                jnp.square(
                    pipeline_apply(
                        sp, x, apply_one=_apply_one, mesh=mesh,
                        n_microbatches=2,
                    )
                )
            )

        def seq_loss(sp):
            per = [
                jax.tree_util.tree_map(lambda l: l[i], sp) for i in range(4)
            ]
            return jnp.sum(jnp.square(_sequential(per, x)))

        g_pipe = jax.grad(pipe_loss)(stacked)
        g_seq = jax.grad(seq_loss)(stacked)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_pipe),
            jax.tree_util.tree_leaves(g_seq),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )

    def test_trains_a_pipelined_tower(self):
        # end-to-end: regression through a pipelined 4-stage tower improves
        mesh = _pipe_mesh(4)
        per_stage = _stage_params(4, width=8, seed=7)
        stacked = shard_stacked_params(stack_stage_params(per_stage), mesh)
        x = jax.random.normal(jax.random.key(4), (16, 8))
        target = jnp.sin(x)

        @jax.jit
        def step(sp):
            def loss(sp):
                out = pipeline_apply(
                    sp, x, apply_one=_apply_one, mesh=mesh, n_microbatches=4
                )
                return jnp.mean(jnp.square(out - target))

            val, g = jax.value_and_grad(loss)(sp)
            sp = jax.tree_util.tree_map(lambda p, gp: p - 0.5 * gp, sp, g)
            return sp, val

        losses = []
        for _ in range(30):
            stacked, val = step(stacked)
            losses.append(float(val))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_microbatch_storage_is_sharded_per_device(self, monkeypatch):
        # VERDICT r1 weak #4 gate: each device's input store is the padded
        # chunk ceil(M/S) of microbatches, NOT the replicated full batch
        from znicz_tpu.parallel import pipeline as pipe_mod

        mesh = _pipe_mesh(4)
        per_stage = _stage_params(4, width=8, seed=11)
        stacked = stack_stage_params(per_stage)
        x = jax.random.normal(jax.random.key(6), (16, 8))
        seen = {}
        orig = pipe_mod._local_pipeline

        def spy(params, xl, **kw):
            seen["store_shape"] = xl.shape
            return orig(params, xl, **kw)

        monkeypatch.setattr(pipe_mod, "_local_pipeline", spy)
        out = pipe_mod.pipeline_apply(
            stacked, x, apply_one=_apply_one, mesh=mesh, n_microbatches=8
        )
        # 8 microbatches over 4 stages -> 2 per device (batch 16 -> mb 2)
        assert seen["store_shape"] == (2, 2, 8)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_sequential(per_stage, x)),
            rtol=1e-5, atol=1e-6,
        )

    def test_embed_tower_head_matches_sequential(self):
        # real-model decomposition: different widths outside the tower
        from znicz_tpu.parallel.pipeline import pipelined_model_apply

        mesh = _pipe_mesh(4)
        k = jax.random.split(jax.random.key(7), 6)
        params = {
            "embed": {"w": jax.random.normal(k[0], (5, 8)) * 0.4},
            "stages": stack_stage_params(_stage_params(4, width=8, seed=8)),
            "head": {"w": jax.random.normal(k[1], (8, 3)) * 0.4},
        }
        x = jax.random.normal(k[2], (8, 5))

        def embed_fn(p, x):
            return jnp.tanh(x @ p["w"])

        def head_fn(p, x):
            return x @ p["w"]

        def run(p):
            return pipelined_model_apply(
                p, x, embed_fn=embed_fn, stage_fn=_apply_one,
                head_fn=head_fn, mesh=mesh, n_microbatches=4,
            )

        per = [
            jax.tree_util.tree_map(lambda l: l[i], params["stages"])
            for i in range(4)
        ]
        ref = head_fn(
            params["head"], _sequential(per, embed_fn(params["embed"], x))
        )
        np.testing.assert_allclose(
            np.asarray(run(params)), np.asarray(ref), rtol=1e-5, atol=1e-6
        )
        # gradients flow end-to-end through embed -> tower -> head
        g = jax.grad(lambda p: jnp.sum(jnp.square(run(p))))(params)
        assert all(
            np.isfinite(np.asarray(leaf)).all()
            for leaf in jax.tree_util.tree_leaves(g)
        )
        assert float(jnp.sum(jnp.abs(g["embed"]["w"]))) > 0

    def test_bubble_fraction(self):
        from znicz_tpu.parallel.pipeline import bubble_fraction

        assert bubble_fraction(4, 8) == 3 / 11
        assert bubble_fraction(1, 4) == 0.0
        # padding counts: 2 microbatches on 4 stages schedule like 4
        assert bubble_fraction(4, 2) == 3 / 7
        # more microbatches -> smaller bubble
        assert bubble_fraction(4, 32) < bubble_fraction(4, 8)

    def test_stage_count_mismatch_error(self):
        mesh = _pipe_mesh(4)
        stacked = stack_stage_params(_stage_params(3, width=8))
        x = jnp.zeros((4, 8))
        with pytest.raises(ValueError, match="stage dim"):
            pipeline_apply(
                stacked, x, apply_one=_apply_one, mesh=mesh, n_microbatches=2
            )

    def test_batch_divisibility_error(self):
        mesh = _pipe_mesh(4)
        stacked = stack_stage_params(_stage_params(4, width=8))
        x = jnp.zeros((5, 8))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(
                stacked, x, apply_one=_apply_one, mesh=mesh, n_microbatches=2
            )
