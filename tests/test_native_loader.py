"""Native batch assembler vs numpy fallback (loader hot path)."""

import numpy as np
import pytest

from znicz_tpu.loader import native


@pytest.fixture(scope="session")
def native_available():
    if not native.available():
        pytest.skip("native batch assembler unavailable (no g++?)")
    return True


class TestGatherRows:
    def test_matches_numpy(self, native_available):
        rng = np.random.default_rng(0)
        data = rng.random((50, 17), np.float32)
        idx = rng.integers(0, 50, 23)
        np.testing.assert_array_equal(
            native.gather_rows(data, idx), data[idx]
        )

    def test_multidim_shapes(self, native_available):
        rng = np.random.default_rng(1)
        data = rng.random((20, 4, 5, 3), np.float32).astype(np.float32)
        idx = np.array([3, 1, 19, 0])
        out = native.gather_rows(data, idx)
        assert out.shape == (4, 4, 5, 3)
        np.testing.assert_array_equal(out, data[idx])

    def test_non_f32_falls_back(self):
        data = np.arange(12, dtype=np.float64).reshape(4, 3)
        idx = np.array([2, 0])
        np.testing.assert_array_equal(
            native.gather_rows(data, idx), data[idx]
        )

    def test_u8_normalize(self, native_available):
        rng = np.random.default_rng(2)
        data = rng.integers(0, 256, (30, 11)).astype(np.uint8)
        idx = rng.integers(0, 30, 8)
        out = native.gather_rows_u8(data, idx, scale=255.0, shift=-0.5)
        # native uses x * (1/scale): one-ulp difference vs division
        np.testing.assert_allclose(
            out, data[idx].astype(np.float32) / 255.0 - 0.5, atol=1e-6
        )

    def test_fullbatch_f32_path_is_plain_numpy(self):
        # the f32 path deliberately does NOT use the native lib (no win);
        # this guards the plain-indexing behavior
        from znicz_tpu.loader import FullBatchLoader

        x = np.arange(40, dtype=np.float32).reshape(10, 4)
        ld = FullBatchLoader(
            {"train": x}, minibatch_size=4, shuffle=False
        )
        assert not ld._lazy_u8
        mb = next(iter(ld.batches("train")))
        np.testing.assert_array_equal(mb.data, x[:4])

    def test_out_of_range_indices_raise(self):
        # validated on BOTH paths (native and numpy fallback): no silent
        # negative-index wrapping anywhere
        data = np.zeros((4, 3), np.float32)
        with pytest.raises(IndexError):
            native.gather_rows(data, np.array([4]))
        with pytest.raises(IndexError):
            native.gather_rows(data, np.array([-1]))
        u8 = np.zeros((4, 3), np.uint8)
        with pytest.raises(IndexError):
            native.gather_rows_u8(u8, np.array([9]))
        # fallback dtype (f64) also validates
        with pytest.raises(IndexError):
            native.gather_rows(data.astype(np.float64), np.array([-1]))

    def test_fullbatch_lazy_u8_path(self):
        # u8 data + range normalization: dataset stays u8 in memory and
        # minibatches come out converted — the fused native pipeline
        from znicz_tpu.loader import FullBatchLoader

        rng = np.random.default_rng(3)
        x = rng.integers(0, 256, (12, 2, 2, 1)).astype(np.uint8)
        ld = FullBatchLoader(
            {"train": x},
            minibatch_size=4,
            shuffle=False,
            normalization="range",
            normalization_kwargs={"scale": 255.0, "shift": -0.5},
        )
        assert ld._lazy_u8
        assert ld.data["train"].dtype == np.uint8  # stays u8 at rest
        mb = next(iter(ld.batches("train")))
        assert mb.data.dtype == np.float32
        np.testing.assert_allclose(
            mb.data, x[:4].astype(np.float32) / 255.0 - 0.5, atol=1e-6
        )

    def test_evaluation_batches_do_not_touch_shuffle_stream(self):
        # regression: batches(shuffle=False) must not draw from the PRNG
        from znicz_tpu.core import prng
        from znicz_tpu.loader import FullBatchLoader

        prng.seed_all(5)
        x = np.zeros((20, 2), np.float32)
        ld = FullBatchLoader({"train": x}, minibatch_size=5)
        list(ld.batches("train"))  # one shuffled epoch
        state_before = prng.get(ld.rand_name).state_dict()
        list(ld.batches("train", shuffle=False))  # read-only pass
        state_after = prng.get(ld.rand_name).state_dict()
        np.testing.assert_array_equal(
            state_before["key"], state_after["key"]
        )
