"""Pallas kernels vs their jnp reference twins.

The rebuild of the reference's numpy-vs-OpenCL-vs-CUDA golden tests
(SURVEY.md §4): every Pallas kernel must match the pure-jnp implementation,
including gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np

from znicz_tpu.ops import kohonen as kh, normalization
from znicz_tpu.ops.pallas import kohonen as pallas_kh


class TestPallasLRN:
    def _x(self, shape=(2, 7, 7, 96), seed=0):
        return jax.random.normal(jax.random.key(seed), shape, jnp.float32)

    def test_forward_matches_xla(self):
        x = self._x()
        y_ref = normalization.lrn(x, impl="xla")
        y_pal = normalization.lrn(x, impl="pallas")
        np.testing.assert_allclose(y_pal, y_ref, rtol=1e-5, atol=1e-6)

    def test_forward_nondefault_params(self):
        x = self._x((4, 3, 3, 64), seed=1)
        kw = dict(alpha=2e-4, beta=0.5, k=1.0, n=3)
        np.testing.assert_allclose(
            normalization.lrn(x, impl="pallas", **kw),
            normalization.lrn(x, impl="xla", **kw),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_gradient_matches_xla(self):
        x = self._x((2, 5, 5, 32), seed=2)

        def loss(impl):
            return lambda x: jnp.sum(
                jnp.sin(normalization.lrn(x, impl=impl))
            )

        g_ref = jax.grad(loss("xla"))(x)
        g_pal = jax.grad(loss("pallas"))(x)
        np.testing.assert_allclose(g_pal, g_ref, rtol=1e-4, atol=1e-5)

    def test_gradient_even_window(self):
        # even n: the backward window is the TRANSPOSED extent of forward
        x = self._x((2, 4, 4, 32), seed=6)
        kw = dict(alpha=1e-3, beta=0.6, k=1.5, n=4)

        def loss(impl):
            return lambda x: jnp.sum(
                jnp.cos(normalization.lrn(x, impl=impl, **kw))
            )

        g_ref = jax.grad(loss("xla"))(x)
        g_pal = jax.grad(loss("pallas"))(x)
        np.testing.assert_allclose(g_pal, g_ref, rtol=1e-4, atol=1e-5)

    def test_rows_not_multiple_of_tile(self):
        # 2*3*3 = 18 rows << ROW_TILE: exercises the padded last block
        x = self._x((2, 3, 3, 128), seed=3)
        np.testing.assert_allclose(
            normalization.lrn(x, impl="pallas"),
            normalization.lrn(x, impl="xla"),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_under_jit_and_bf16(self):
        x = self._x((2, 4, 4, 96)).astype(jnp.bfloat16)
        f = jax.jit(lambda x: normalization.lrn(x, impl="pallas"))
        y = f(x)
        assert y.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            y.astype(jnp.float32),
            normalization.lrn(
                x.astype(jnp.float32), impl="xla"
            ),
            rtol=2e-2,
            atol=2e-2,
        )


class TestPallasKohonen:
    def _setup(self, b=100, sx=6, sy=6, f=784, seed=0):
        k1, k2 = jax.random.split(jax.random.key(seed))
        params = {
            "weights": jax.random.normal(k1, (sx * sy, f), jnp.float32) * 0.1
        }
        x = jax.random.normal(k2, (b, f), jnp.float32)
        coords = kh.grid_coords(sx, sy)
        return params, x, coords

    def test_matches_jnp_twin(self):
        params, x, coords = self._setup()
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.5, sigma=1.5
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.5, sigma=1.5
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )

    def test_mask_and_multi_tile(self):
        # batch > BATCH_TILE exercises scratch accumulation across grid steps
        params, x, coords = self._setup(b=600, f=256, seed=3)
        mask = (jnp.arange(600) < 500).astype(jnp.float32)
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.3, sigma=2.0, mask=mask
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.3, sigma=2.0, mask=mask
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )

    def test_padded_batch(self):
        # b not a multiple of BATCH_TILE -> host-side zero-mask padding
        params, x, coords = self._setup(b=300, f=64, seed=5)
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.2, sigma=1.0
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.2, sigma=1.0
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )
