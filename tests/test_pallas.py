"""Pallas kernels vs their jnp reference twins.

The rebuild of the reference's numpy-vs-OpenCL-vs-CUDA golden tests
(SURVEY.md §4): every Pallas kernel must match the pure-jnp implementation,
including gradients.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.ops import kohonen as kh, normalization, rbm as rbm_op
from znicz_tpu.ops.pallas import kohonen as pallas_kh, rbm as pallas_rbm

ON_TPU = jax.default_backend() in ("tpu", "axon")


def _device_ms_per_iter(fn, x, n_inner=300, reps=4):
    """Device time of fn chained n_inner times inside one fori_loop; the
    3n-vs-n difference cancels the relay's fixed sync cost and min-over-reps
    is robust to its additive noise (bench.py methodology)."""
    from jax import lax

    def many(mult):
        @jax.jit
        def f(x):
            return lax.fori_loop(0, mult * n_inner, lambda _, a: fn(a), x)

        return f

    m1, m3 = many(1), many(3)

    def t(m):
        t0 = time.time()
        float(jnp.sum(m(x))[None][0])  # value fetch = reliable relay sync
        return time.time() - t0

    t(m1), t(m3)  # compile + warm
    t1 = min(t(m1) for _ in range(reps))
    t3 = min(t(m3) for _ in range(reps))
    return (t3 - t1) / (2 * n_inner) * 1000


def _params_ms_per_iter(fn, params, n_inner=100, reps=4):
    """Same protocol as _device_ms_per_iter for fn: pytree -> pytree."""
    from jax import lax

    def many(mult):
        @jax.jit
        def f(p):
            return lax.fori_loop(
                0, mult * n_inner, lambda _, a: fn(a), p
            )

        return f

    m1, m3 = many(1), many(3)

    def t(m):
        t0 = time.time()
        out = m(params)
        total = sum(
            jnp.sum(leaf) for leaf in jax.tree_util.tree_leaves(out)
        )
        float(total[None][0])
        return time.time() - t0

    t(m1), t(m3)
    t1 = min(t(m1) for _ in range(reps))
    t3 = min(t(m3) for _ in range(reps))
    return (t3 - t1) / (2 * n_inner) * 1000


class TestPallasLRN:
    def _x(self, shape=(2, 7, 7, 96), seed=0):
        return jax.random.normal(jax.random.key(seed), shape, jnp.float32)

    def test_forward_matches_xla(self):
        x = self._x()
        y_ref = normalization.lrn(x, impl="xla")
        y_pal = normalization.lrn(x, impl="pallas")
        np.testing.assert_allclose(y_pal, y_ref, rtol=1e-5, atol=1e-6)

    def test_forward_nondefault_params(self):
        x = self._x((4, 3, 3, 64), seed=1)
        kw = dict(alpha=2e-4, beta=0.5, k=1.0, n=3)
        np.testing.assert_allclose(
            normalization.lrn(x, impl="pallas", **kw),
            normalization.lrn(x, impl="xla", **kw),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_gradient_matches_xla(self):
        x = self._x((2, 5, 5, 32), seed=2)

        def loss(impl):
            return lambda x: jnp.sum(
                jnp.sin(normalization.lrn(x, impl=impl))
            )

        g_ref = jax.grad(loss("xla"))(x)
        g_pal = jax.grad(loss("pallas"))(x)
        np.testing.assert_allclose(g_pal, g_ref, rtol=1e-4, atol=1e-5)

    def test_gradient_even_window(self):
        # even n: the backward window is the TRANSPOSED extent of forward
        x = self._x((2, 4, 4, 32), seed=6)
        kw = dict(alpha=1e-3, beta=0.6, k=1.5, n=4)

        def loss(impl):
            return lambda x: jnp.sum(
                jnp.cos(normalization.lrn(x, impl=impl, **kw))
            )

        g_ref = jax.grad(loss("xla"))(x)
        g_pal = jax.grad(loss("pallas"))(x)
        np.testing.assert_allclose(g_pal, g_ref, rtol=1e-4, atol=1e-5)

    def test_rows_not_multiple_of_tile(self):
        # 2*3*3 = 18 rows << ROW_TILE: exercises the padded last block
        x = self._x((2, 3, 3, 128), seed=3)
        np.testing.assert_allclose(
            normalization.lrn(x, impl="pallas"),
            normalization.lrn(x, impl="xla"),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_under_jit_and_bf16(self):
        x = self._x((2, 4, 4, 96)).astype(jnp.bfloat16)
        f = jax.jit(lambda x: normalization.lrn(x, impl="pallas"))
        y = f(x)
        assert y.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            y.astype(jnp.float32),
            normalization.lrn(
                x.astype(jnp.float32), impl="xla"
            ),
            rtol=2e-2,
            atol=2e-2,
        )


class TestPallasFlashAttention:
    """Blockwise attention vs the jnp twin, gradients included."""

    def _qkv(self, b=2, t=48, h=2, d=16, seed=0, dtype=jnp.float32):
        ks = jax.random.split(jax.random.key(seed), 3)
        return tuple(
            jax.random.normal(kk, (b, t, h, d), dtype) for kk in ks
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_twin(self, causal):
        from znicz_tpu.ops import attention as att
        from znicz_tpu.ops.pallas import attention as patt

        q, k, v = self._qkv()
        ref = att.dot_product_attention(q, k, v, causal=causal)
        out = patt.flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_forward_unaligned_length(self):
        # T=37 does not divide the 16-blocks: zero-pad + index masking
        from znicz_tpu.ops import attention as att
        from znicz_tpu.ops.pallas import attention as patt

        q, k, v = self._qkv(t=37, seed=3)
        ref = att.dot_product_attention(q, k, v, causal=True)
        out = patt.flash_attention(
            q, k, v, causal=True, block_q=16, block_k=16
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_twin(self, causal):
        from znicz_tpu.ops import attention as att
        from znicz_tpu.ops.pallas import attention as patt

        q, k, v = self._qkv(t=32, seed=5)

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                jnp.sin(fn(q, k, v, causal=causal))
            )

        g_ref = jax.grad(loss(att.dot_product_attention), argnums=(0, 1, 2))(
            q, k, v
        )
        g_pal = jax.grad(
            loss(
                partial_flash := (
                    lambda q, k, v, causal: patt.flash_attention(
                        q, k, v, causal=causal, block_q=16, block_k=16
                    )
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_pal, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_gradient_unaligned_causal(self):
        from znicz_tpu.ops import attention as att
        from znicz_tpu.ops.pallas import attention as patt

        q, k, v = self._qkv(t=23, seed=7)

        def loss(fn):
            return lambda q, k, v: jnp.mean(
                jnp.square(fn(q, k, v, causal=True))
            )

        g_ref = jax.grad(loss(att.dot_product_attention), argnums=(0, 1, 2))(
            q, k, v
        )
        g_pal = jax.grad(
            loss(
                lambda q, k, v, causal=True: patt.flash_attention(
                    q, k, v, causal=causal, block_q=16, block_k=16
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_pal, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_in_mha_block(self):
        from znicz_tpu.ops import attention as att
        from znicz_tpu.ops.pallas import attention as patt

        from znicz_tpu.core import prng

        prng.seed_all(4)
        params = att.init_mha_params(32, 4)
        x = jax.random.normal(jax.random.key(9), (2, 24, 32))
        ref = att.mha(params, x, n_heads=4, causal=True)
        out = att.mha(
            params, x, n_heads=4, causal=True,
            attention_fn=lambda q, k, v, causal: patt.flash_attention(
                q, k, v, causal=causal, block_q=8, block_k=8
            ),
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5
        )


class TestPallasRBM:
    """Fused CD-k kernel vs the jnp twin.

    The samplers use different RNGs (hardware bits vs threefry), so golden
    equality is pinned in the SATURATED regime — biases at +/-20 drive
    every sigmoid to 0/1 and sampling becomes RNG-independent — and the
    stochastic regime is covered statistically."""

    def _saturated_params(self, v=128, h=64):
        return {
            "weights": jnp.zeros((v, h), jnp.float32),
            "vbias": jnp.full((v,), -20.0),
            "hbias": jnp.full((h,), 20.0),
        }

    def test_oversized_problem_rejected_up_front(self):
        # no silent Mosaic compile failure: the VMEM budget is checked
        # before any kernel is built
        params = {
            "weights": jnp.zeros((2048, 2048), jnp.float32),
            "vbias": jnp.zeros((2048,)),
            "hbias": jnp.zeros((2048,)),
        }
        v0 = jnp.zeros((1024, 2048))
        with pytest.raises(ValueError, match="VMEM budget"):
            pallas_rbm.cd_step(params, v0, 0, learning_rate=0.1)

    def test_saturated_matches_twin_exactly(self):
        params = self._saturated_params()
        v0 = (
            jax.random.uniform(jax.random.key(0), (32, 128)) > 0.5
        ).astype(jnp.float32)
        mask = (jnp.arange(32) < 30).astype(jnp.float32)
        ref, ref_err = rbm_op.cd_step(
            params, v0, jax.random.key(1),
            learning_rate=0.2, cd_k=2, mask=mask,
        )
        fused, err = pallas_rbm.cd_step(
            params, v0, 5, learning_rate=0.2, cd_k=2, mask=mask
        )
        np.testing.assert_allclose(float(err), float(ref_err), rtol=1e-5)
        for name in ("weights", "vbias", "hbias"):
            np.testing.assert_allclose(
                np.asarray(fused[name]), np.asarray(ref[name]),
                rtol=1e-4, atol=1e-6,
            )

    def test_deterministic_given_seed(self):
        from znicz_tpu.core import prng

        prng.seed_all(3)
        params = rbm_op.init_params(128, 64)
        v0 = (
            jax.random.uniform(jax.random.key(2), (32, 128)) > 0.5
        ).astype(jnp.float32)
        a, ea = pallas_rbm.cd_step(params, v0, 7, learning_rate=0.1)
        b, eb = pallas_rbm.cd_step(params, v0, 7, learning_rate=0.1)
        assert float(ea) == float(eb)
        np.testing.assert_array_equal(
            np.asarray(a["weights"]), np.asarray(b["weights"])
        )
        _, ec = pallas_rbm.cd_step(params, v0, 8, learning_rate=0.1)
        assert float(ec) != float(ea)  # seed actually drives the chain

    def test_training_reduces_reconstruction_error(self):
        # stochastic regime: CD-1 on bar patterns must learn them
        from znicz_tpu.core import prng

        prng.seed_all(11)
        params = rbm_op.init_params(64, 32, weights_stddev=0.05)
        rows = jax.random.randint(jax.random.key(3), (64,), 0, 8)
        v0 = jnp.repeat(
            jax.nn.one_hot(rows, 8, dtype=jnp.float32), 8, axis=1
        )  # 8 bar patterns over 64 pixels
        errs = []
        for step in range(60):
            params, err = pallas_rbm.cd_step(
                params, v0, step, learning_rate=0.5
            )
            errs.append(float(err))
        assert np.mean(errs[-10:]) < 0.6 * np.mean(errs[:10]), (
            errs[:3], errs[-3:],
        )

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
    )
    def test_data_parallel_saturated_matches_full_batch(self):
        # the psum partitioning rule, checked exactly in the regime where
        # sampling is RNG-independent (per-shard seeds then cannot differ)
        from znicz_tpu.parallel import make_mesh

        params = self._saturated_params(v=64, h=32)
        v0 = (
            jax.random.uniform(jax.random.key(4), (48, 64)) > 0.5
        ).astype(jnp.float32)
        mask = (jnp.arange(48) < 40).astype(jnp.float32)
        ref, ref_err = pallas_rbm.cd_step(
            params, v0, 9, learning_rate=0.3, mask=mask
        )
        dp, dp_err = pallas_rbm.cd_step(
            params, v0, 9, learning_rate=0.3, mask=mask,
            mesh=make_mesh(8, 1),
        )
        np.testing.assert_allclose(float(dp_err), float(ref_err), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(dp["weights"]), np.asarray(ref["weights"]),
            rtol=1e-4, atol=1e-6,
        )


@pytest.mark.skipif(not ON_TPU, reason="TPU timing assertions need a chip")
class TestPallasFlashTimingTPU:
    def test_causal_flash_beats_twin_at_long_context(self):
        from znicz_tpu.ops import attention as att
        from znicz_tpu.ops.pallas import attention as patt

        ks = jax.random.split(jax.random.key(0), 3)
        q, k, v = (
            jax.random.normal(kk, (2, 2048, 4, 64), jnp.float32)
            for kk in ks
        )

        def grad_of(fn):
            return jax.grad(
                lambda q: jnp.sum(fn(q, k, v, causal=True))
            )

        def chainable(fn):
            g = grad_of(fn)
            return lambda x: g(x)

        t_twin = _device_ms_per_iter(
            chainable(att.dot_product_attention), q, n_inner=50
        )
        t_flash = _device_ms_per_iter(
            chainable(patt.flash_attention), q, n_inner=50
        )
        # measured 2.7x (v5e, T=2048); 1.2 margin absorbs relay noise
        assert t_flash * 1.2 < t_twin, (t_flash, t_twin)

    def test_ring_flash_inner_beats_dense_inner(self):
        # SP long context at kernel speed: the ring's per-shard block is the
        # flash kernel.  One chip = one ring shard, which is exactly the
        # per-device work a real N-chip ring would run (T_local = T/N).
        from functools import partial

        from znicz_tpu.parallel import make_mesh
        from znicz_tpu.parallel.ring_attention import ring_attention

        mesh = make_mesh(1, 1)
        ks = jax.random.split(jax.random.key(1), 3)
        q, k, v = (
            jax.random.normal(kk, (1, 4096, 4, 64), jnp.float32)
            for kk in ks
        )

        def chainable(inner):
            fn = partial(ring_attention, mesh=mesh, causal=True, inner=inner)
            g = jax.grad(lambda q: jnp.sum(fn(q, k, v)))
            return lambda x: g(x)

        t_dense = _device_ms_per_iter(chainable("dense"), q, n_inner=20)
        t_flash = _device_ms_per_iter(chainable("flash"), q, n_inner=20)
        assert t_flash * 1.2 < t_dense, (t_flash, t_dense)


@pytest.mark.skipif(not ON_TPU, reason="hardware PRNG needs a chip")
class TestPallasHardwareRNGTPU:
    def test_uniforms_are_unbiased_and_nonnegative(self):
        # prng_random_bits is int32: an arithmetic >>8 would leave half
        # the draws negative (u < p then fires with prob 0.5 + p/2)
        from functools import partial

        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def kernel(seed_ref, out_ref):
            pltpu.prng_seed(seed_ref[0, 0])
            out_ref[:] = pallas_rbm._uniform(out_ref.shape)

        u = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((256, 256), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(jnp.asarray([[7]], jnp.int32))
        u = np.asarray(u)
        assert u.min() >= 0.0 and u.max() < 1.0, (u.min(), u.max())
        assert abs(u.mean() - 0.5) < 0.01, u.mean()


@pytest.mark.skipif(not ON_TPU, reason="TPU timing assertions need a chip")
class TestPallasRBMTimingTPU:
    def test_fused_cd_beats_twin(self):
        # MNIST-RBM shapes; the win comes from hardware RNG vs threefry
        # and the VMEM-resident chain
        from znicz_tpu.core import prng

        prng.seed_all(5)
        params = rbm_op.init_params(784, 256)
        v0 = (
            jax.random.uniform(jax.random.key(5), (256, 784)) > 0.5
        ).astype(jnp.float32)

        def fused(p):
            return pallas_rbm.cd_step(p, v0, 3, learning_rate=0.1)[0]

        def twin(p):
            return rbm_op.cd_step(
                p, v0, jax.random.key(3), learning_rate=0.1
            )[0]

        def chain(fn):
            return lambda p: fn(p)

        # the margin is small relative to relay timing noise: allow one
        # re-measurement before declaring a regression
        for _ in range(2):
            t_fused = _params_ms_per_iter(chain(fused), params)
            t_twin = _params_ms_per_iter(chain(twin), params)
            if t_fused < t_twin * 1.1:
                break
        assert t_fused < t_twin * 1.1, (t_fused, t_twin)


@pytest.mark.skipif(not ON_TPU, reason="TPU timing assertions need a chip")
class TestPallasLRNTimingTPU:
    """VERDICT r1 weak #1: the kernel must win a measured benchmark.

    It wins the TRAIN-op pair (fwd+bwd — what normalization.cl/.cu's
    forward+backward pair is for): the fused backward recomputes s in VMEM
    and runs both windowed sums as MXU band matmuls.  Forward-only stays
    with XLA's single fusion (see ops/normalization.py docstring)."""

    def test_train_pair_beats_xla(self):
        x = jax.random.normal(
            jax.random.key(0), (256, 27, 27, 96), jnp.float32
        )

        def grad_of(impl):
            return jax.grad(
                lambda x: jnp.sum(normalization.lrn(x, impl=impl))
            )

        t_pal = _device_ms_per_iter(grad_of("pallas"), x)
        t_xla = _device_ms_per_iter(grad_of("xla"), x)
        # measured 0.63 vs 1.02 ms (v5e); 1.1 margin absorbs relay noise
        assert t_pal < t_xla * 1.1, (t_pal, t_xla)


class TestPallasKohonen:
    def _setup(self, b=100, sx=6, sy=6, f=784, seed=0):
        k1, k2 = jax.random.split(jax.random.key(seed))
        params = {
            "weights": jax.random.normal(k1, (sx * sy, f), jnp.float32) * 0.1
        }
        x = jax.random.normal(k2, (b, f), jnp.float32)
        coords = kh.grid_coords(sx, sy)
        return params, x, coords

    def test_matches_jnp_twin(self):
        params, x, coords = self._setup()
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.5, sigma=1.5
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.5, sigma=1.5
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )

    def test_mask_and_multi_tile(self):
        # batch > BATCH_TILE exercises scratch accumulation across grid steps
        params, x, coords = self._setup(b=600, f=256, seed=3)
        mask = (jnp.arange(600) < 500).astype(jnp.float32)
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.3, sigma=2.0, mask=mask
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.3, sigma=2.0, mask=mask
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
    )
    def test_data_parallel_matches_full_batch(self):
        # partitioning rule (VERDICT r1 weak #2): sharded-batch fused
        # kernel psums its (num, den) partials == full-batch jnp twin
        from znicz_tpu.parallel import make_mesh

        params, x, coords = self._setup(b=64, sx=4, sy=4, f=32, seed=7)
        mask = (jnp.arange(64) < 50).astype(jnp.float32)
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.4, sigma=1.2, mask=mask
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.4, sigma=1.2, mask=mask,
            mesh=make_mesh(8, 1),
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )

    def test_padded_batch(self):
        # b not a multiple of BATCH_TILE -> host-side zero-mask padding
        params, x, coords = self._setup(b=300, f=64, seed=5)
        ref, _ = kh.train_step(
            params, x, coords, learning_rate=0.2, sigma=1.0
        )
        fused = pallas_kh.train_step(
            params, x, coords, learning_rate=0.2, sigma=1.0
        )
        np.testing.assert_allclose(
            fused["weights"], ref["weights"], rtol=1e-4, atol=1e-5
        )
