"""Device/compile telemetry: the ledger behind /debug/programs.

The pins (docs/OBSERVABILITY.md "Device & compile telemetry"): every
TRUE first compile of a serving program lands exactly one ledger entry
with nonzero compile wall time — so the engine-sourced ledger count
moves in lockstep with the engine's own program ledger AND
``znicz_serve_compiles_total`` (the repo's zero-new-compiled-programs
invariant now has a wall-clock/FLOPs/bytes record per program); a
second engine with the same geometry adds nothing; the KV pool's byte
gauges mirror the block gauges; and the ``/debug/programs`` +
``POST /debug/profile`` surfaces answer live.
"""

import http.client
import json
import os
import threading

import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.core import prng
from znicz_tpu.observability import device
from znicz_tpu.services import PagedDecodeEngine, ServingFrontDoor
from znicz_tpu.services import serve as serve_mod
from znicz_tpu.services.engine import DecodeEngine
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 11
HEADS = 3
T_MAX = 48


@pytest.fixture(scope="module")
def params():
    # a geometry UNIQUE to this module: its first compiles must happen
    # here, whatever ran earlier in the process
    prng.seed_all(91)
    return init_lm_params(19, 24, 2, HEADS, max_seq=T_MAX)


def _compiles_total() -> float:
    m = obs.counter(
        "znicz_serve_compiles_total",
        "distinct compiled engine programs by kind and bucket",
        ("kind", "bucket"),
    )
    return sum(child.value for child in m.children().values())


class TestProgramLedger:
    def test_engine_first_compiles_land_in_the_ledger(self, params):
        ledger0 = device.program_count(source="engine")
        counter0 = _compiles_total()
        eng = PagedDecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            block_size=8, max_seq=T_MAX, admit_every=4,
        )
        gen = np.random.default_rng(7)
        eng.submit(gen.integers(0, 19, (11,)).astype(np.int32), 10)
        eng.submit(gen.integers(0, 19, (4,)).astype(np.int32), 6)
        eng.run()
        d_ledger = device.program_count(source="engine") - ledger0
        d_counter = _compiles_total() - counter0
        n_engine = eng.compile_stats()["n_programs"]
        # the acceptance identity: device ledger == engine ledger ==
        # znicz_serve_compiles_total, entry for entry
        assert d_ledger == d_counter == n_engine
        fresh = device.programs(source="engine")[-d_ledger:]
        for entry in fresh:
            assert entry["compile_s"] > 0.0, entry
            assert entry["kind"] in ("prefill", "paged_chunk", "cow")
        # cost analysis works on this backend: FLOPs recorded
        assert any(entry["flops"] for entry in fresh)

    def test_same_geometry_second_engine_adds_nothing(self, params):
        eng = PagedDecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            block_size=8, max_seq=T_MAX, admit_every=4,
        )
        ledger0 = device.program_count()
        counter0 = _compiles_total()
        gen = np.random.default_rng(9)
        eng.submit(gen.integers(0, 19, (11,)).astype(np.int32), 10)
        eng.run()
        assert device.program_count() == ledger0
        assert _compiles_total() == counter0

    def test_dense_engine_records_admit_and_chunk(self, params):
        ledger0 = device.program_count(source="engine")
        eng = DecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            max_seq=T_MAX, admit_every=4,
        )
        gen = np.random.default_rng(11)
        eng.submit(gen.integers(0, 19, (9,)).astype(np.int32), 8)
        eng.run()
        delta = device.program_count(source="engine") - ledger0
        assert delta >= 2
        fresh = device.programs(source="engine")[-delta:]
        kinds = {entry["kind"] for entry in fresh}
        assert {"admit", "chunk"} <= kinds

    def test_serve_cache_compile_records_cost_and_memory(self, params):
        before = device.program_count(source="serve_cache")
        gen = np.random.default_rng(13)
        prompt = gen.integers(0, 19, (1, 7)).astype(np.int32)
        G.generate_serve(
            params, prompt, n_heads=HEADS, max_new_tokens=5, eos_id=EOS
        )
        progs = device.programs(source="serve_cache")
        assert len(progs) == before + 1
        entry = progs[-1]
        assert entry["compile_s"] > 0.0
        assert entry["flops"] and entry["flops"] > 0
        # the AOT path has the Compiled in hand: memory analysis too
        assert entry["memory"] is not None
        assert entry["memory"]["argument_size_in_bytes"] > 0
        # a second identical call is a cache hit: no new entry
        G.generate_serve(
            params, prompt, n_heads=HEADS, max_new_tokens=5, eos_id=EOS
        )
        assert device.program_count(source="serve_cache") == before + 1

    def test_ledger_snapshot_shape(self):
        snap = device.ledger_snapshot()
        assert snap["count"] == len(snap["programs"])
        assert snap["engine_count"] <= snap["count"]
        assert sum(snap["by_kind"].values()) == snap["count"]
        assert snap["compile_seconds_total"] > 0.0
        assert isinstance(snap["device_memory"], list)


class TestGracefulHelpers:
    def test_cost_and_memory_helpers_never_raise(self):
        class Boom:
            def cost_analysis(self):
                raise RuntimeError("no api")

        assert device.stage_cost(Boom()) is None
        assert device.stage_cost(object()) is None
        assert device.compiled_memory(object()) is None
        assert device.lowered_cost(lambda x: x, (1,), {}) is None

    def test_stage_cost_normalizes_list_and_dict(self):
        class DictStage:
            def cost_analysis(self):
                return {"flops": 10.0, "bytes accessed": 20.0}

        class ListStage:
            def cost_analysis(self):
                return [{"flops": 5.0}]

        assert device.stage_cost(DictStage()) == {
            "flops": 10.0, "bytes_accessed": 20.0
        }
        assert device.stage_cost(ListStage())["flops"] == 5.0

    def test_device_memory_never_raises(self):
        out = device.device_memory()
        assert isinstance(out, list)
        for row in out:
            assert "device" in row and "stats" in row


class TestKvPoolBytes:
    def test_byte_gauges_mirror_the_block_gauges(self, params):
        eng = PagedDecodeEngine(
            params, n_heads=HEADS, eos_id=EOS, batch_size=2,
            block_size=8, n_blocks=9, max_seq=T_MAX, admit_every=4,
        )
        assert eng.block_bytes > 0
        st = eng.stats()
        assert st["block_bytes"] == eng.block_bytes
        assert st["pool_bytes"] == eng.usable_blocks * eng.block_bytes
        blocks = obs.gauge(
            "znicz_serve_kv_pool_blocks", "", ("state",)
        )
        by = obs.gauge("znicz_serve_kv_pool_bytes", "", ("state",))
        for state in ("free", "used", "cached"):
            assert (
                by.labels(state=state).value
                == blocks.labels(state=state).value * eng.block_bytes
            )
        gen = np.random.default_rng(17)
        eng.submit(gen.integers(0, 19, (11,)).astype(np.int32), 8)
        eng.run()
        for state in ("free", "used", "cached"):
            assert (
                by.labels(state=state).value
                == blocks.labels(state=state).value * eng.block_bytes
            )


class TestHttpSurfaces:
    @pytest.fixture
    def server(self, params):
        door = ServingFrontDoor(
            lambda: PagedDecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, batch_size=2,
                block_size=8, max_seq=T_MAX, admit_every=4,
            ),
            max_pending=4,
        )
        srv = serve_mod.build_server(directory=".", port=0, frontdoor=door)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield srv
        srv.shutdown()
        srv.server_close()
        door.close(grace_s=10.0)

    def _req(self, port, method, path):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            conn.request(method, path)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()

    def test_debug_programs_matches_the_ledger(self, server):
        port = server.server_address[1]
        status, body = self._req(port, "GET", "/debug/programs")
        assert status == 200
        assert body["count"] == device.program_count()
        assert body["engine_count"] == device.program_count("engine")
        # every compiled serving program ledgered with NONZERO compile
        # time — the acceptance wording, verbatim
        assert body["count"] > 0
        for entry in body["programs"]:
            assert entry["compile_s"] > 0.0
        assert body["engine_count"] == int(_compiles_total())

    def test_profile_endpoint_smoke(self, server):
        port = server.server_address[1]
        status, body = self._req(
            port, "POST", "/debug/profile?seconds=0.05"
        )
        assert status == 200, body
        assert body["ok"] is True
        assert os.path.isdir(body["log_dir"])
        # jax wrote an actual capture into the directory
        walked = [
            os.path.join(r, f)
            for r, _, fs in os.walk(body["log_dir"]) for f in fs
        ]
        assert walked, "empty profile capture"

    def test_profile_endpoint_bad_seconds_400(self, server):
        port = server.server_address[1]
        for bad in ("nope", "nan", "inf", "-inf"):
            status, body = self._req(
                port, "POST", f"/debug/profile?seconds={bad}"
            )
            assert status == 400 and body["error"] == "bad_request", (
                bad, status, body,
            )

    def test_profile_drains_body_keepalive_survives(self, server):
        """A POST body on /debug/profile must be drained: HTTP/1.1
        keep-alive reuses the socket, and leftover body bytes would be
        parsed as the next request's start line."""
        port = server.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        try:
            body = json.dumps({"client": "sends-a-body"})
            conn.request(
                "POST", "/debug/profile?seconds=0.05", body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            first = json.loads(resp.read())
            assert resp.status == 200, first
            # SAME connection: the next request must parse cleanly
            conn.request("GET", "/debug/programs")
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read())["count"] >= 0
        finally:
            conn.close()

    def test_profile_busy_409(self, server):
        port = server.server_address[1]
        with device._PROFILE_LOCK:
            status, body = self._req(
                port, "POST", "/debug/profile?seconds=0.05"
            )
        assert status == 409 and body["error"] == "profile_busy"

    def test_capture_profile_clamps_duration(self):
        assert device.PROFILE_MAX_SECONDS <= 60.0
        with pytest.raises(RuntimeError):
            with device._PROFILE_LOCK:
                device.capture_profile(0.01)
