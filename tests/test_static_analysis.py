"""Tier-1 gate: the package must stay clean under znicz-check.

Any NEW analyzer finding (relative to tools/znicz_check_baseline.json)
fails this test, which makes JAX-hygiene regressions — tracer-leaking
branches, host effects in jitted bodies, misspelled mesh axes, PRNG
reuse, swallowed exceptions — a test failure instead of a silent TPU
incident.  The workflow for a legitimate exception is an inline
``# znicz-check: disable=RULE`` pragma with a reason, or (for
pre-existing debt only) regenerating the baseline; see
docs/STATIC_ANALYSIS.md.
"""

import os

import znicz_tpu
from znicz_tpu.analysis import (
    analyze_paths,
    load_baseline,
    new_findings,
)
from znicz_tpu.analysis.engine import stale_baseline_entries

PKG_DIR = os.path.dirname(os.path.abspath(znicz_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)
BASELINE = os.path.join(REPO_ROOT, "tools", "znicz_check_baseline.json")


def _current_findings():
    return analyze_paths([PKG_DIR], root=REPO_ROOT)


def test_package_has_no_new_findings():
    findings = _current_findings()
    baseline = load_baseline(BASELINE)
    new = new_findings(findings, baseline)
    assert not new, (
        "znicz-check found NEW finding(s) — fix them, pragma-exempt "
        "with a reason, or (pre-existing debt only) regenerate the "
        "baseline:\n" + "\n".join(f.format() for f in new)
    )


def test_baseline_is_not_stale():
    """Burned-down debt must leave the ledger: a baseline entry that no
    longer fires means someone fixed it — shrink the file so it can't
    mask a future regression at the same fingerprint."""
    findings = _current_findings()
    baseline = load_baseline(BASELINE)
    stale = stale_baseline_entries(findings, baseline)
    assert not stale, (
        "baseline entries no longer fire; regenerate with "
        "'python -m znicz_tpu.analysis --write-baseline': "
        + ", ".join(sorted(stale))
    )


def test_committed_baseline_stays_small():
    """The baseline is a debt ledger, not a dumping ground."""
    baseline = load_baseline(BASELINE)
    assert sum(baseline.values()) <= 10, (
        "the suppression baseline is growing — burn findings down or "
        "pragma-exempt them with reasons instead of baselining"
    )
