"""Tier-1 gate: the package must stay clean under znicz-check.

Any NEW analyzer finding (relative to tools/znicz_check_baseline.json)
fails this test, which makes JAX-hygiene regressions — tracer-leaking
branches, host effects in jitted bodies, misspelled mesh axes, PRNG
reuse, swallowed exceptions, serving-tier lock-discipline races —
a test failure instead of a silent TPU (or paging) incident.

The gate runs the PROJECT-WIDE analysis (one index over the whole
package: cross-module transform applications and call-chain helper
marking included), asserts the index itself builds clean, and caps the
analyzer's runtime so the gate stays cheap enough to run on every
commit.  The workflow for a legitimate exception is an inline
``# znicz-check: disable=RULE`` pragma with a reason, or (for
pre-existing debt only) regenerating the baseline; see
docs/STATIC_ANALYSIS.md.
"""

import json
import os
import textwrap
import time

import pytest

import znicz_tpu
from znicz_tpu.analysis import (
    RULES,
    analyze_project,
    load_baseline,
    new_findings,
)
from znicz_tpu.analysis.engine import stale_baseline_entries
from znicz_tpu.analysis.project import ProjectIndex

PKG_DIR = os.path.dirname(os.path.abspath(znicz_tpu.__file__))
REPO_ROOT = os.path.dirname(PKG_DIR)
BASELINE = os.path.join(REPO_ROOT, "tools", "znicz_check_baseline.json")

# one shared project run per test session: the gate asserts several
# properties of the SAME analysis, and the runtime cap below is the
# budget for exactly one build
_CACHE = {}


def _project():
    if "result" not in _CACHE:
        t0 = time.monotonic()
        findings, index = analyze_project([PKG_DIR], root=REPO_ROOT)
        _CACHE["result"] = (
            findings, index, time.monotonic() - t0
        )
    return _CACHE["result"]


def test_package_has_no_new_findings():
    findings, _, _ = _project()
    baseline = load_baseline(BASELINE)
    new = new_findings(findings, baseline)
    assert not new, (
        "znicz-check found NEW finding(s) — fix them, pragma-exempt "
        "with a reason, or (pre-existing debt only) regenerate the "
        "baseline:\n" + "\n".join(f.format() for f in new)
    )


def test_baseline_is_not_stale():
    """Burned-down debt must leave the ledger: a baseline entry that no
    longer fires means someone fixed it — shrink the file so it can't
    mask a future regression at the same fingerprint."""
    findings, _, _ = _project()
    baseline = load_baseline(BASELINE)
    stale = stale_baseline_entries(findings, baseline)
    assert not stale, (
        "baseline entries no longer fire; regenerate with "
        "'python -m znicz_tpu.analysis --write-baseline': "
        + ", ".join(sorted(stale))
    )


def test_committed_baseline_stays_small():
    """The baseline is a debt ledger, not a dumping ground."""
    baseline = load_baseline(BASELINE)
    assert sum(baseline.values()) <= 10, (
        "the suppression baseline is growing — burn findings down or "
        "pragma-exempt them with reasons instead of baselining"
    )


def test_project_index_builds_clean_and_fast():
    """The whole-package index must parse every module (ZNC000-free),
    resolve a plausible symbol table, and finish inside the CI
    budget — a quadratic blowup in the call-graph pass would otherwise
    quietly turn every tier-1 run into minutes of analyzer time."""
    _, index, wall_s = _project()
    assert not index.syntax_findings, [
        f.format() for f in index.syntax_findings
    ]
    assert len(index.modules) >= 100  # the package, not a subset
    assert index.defs  # symbol table populated
    assert wall_s < 60.0, f"analyzer took {wall_s:.1f}s (budget 60s)"


def test_project_pass_sees_known_cross_module_facts():
    """Pin two facts the project pass discovered about THIS repo, so a
    refactor that silently breaks resolution fails loudly: the
    transformer workflow shard_maps the pallas flash-attention body
    across modules, and the serving engine's jit of the generate
    helpers chain-marks them."""
    _, index, _ = _project()
    targets = {a["target"] for a in index.applications}
    assert any("flash_attention" in t for t in targets), targets
    helpers = {c["helper"] for c in index.chains()}
    assert any("generate" in h for h in helpers), helpers


def test_thread_safety_rules_are_registered():
    assert "ZNC012" in RULES and "ZNC013" in RULES
    assert RULES["ZNC012"].severity in ("error", "warning")
    assert RULES["ZNC013"].severity in ("error", "warning")


def test_changed_files_gate_is_clean_on_the_live_repo():
    """Tier-1 runs the real ``znicz-check --changed`` path over this
    repo: the project index stays clean on exactly the files touched
    vs HEAD (an uncommitted working tree exercises the filter for
    real; a committed one proves the path end-to-end with an empty
    set).  Either way the gate is exit 0 — a finding in a touched
    file fails CI here before it lands."""
    from znicz_tpu.analysis.__main__ import main

    rc = main(["--root", REPO_ROOT, "--changed", "HEAD", PKG_DIR])
    assert rc == 0


# -- cross-module traced-context detection (the acceptance fixture) -------


def _write(tmp_path, name, src):
    (tmp_path / name).write_text(textwrap.dedent(src))


def _run_project(tmp_path, select=("ZNC001", "ZNC002")):
    rules = [RULES[r]() for r in select]
    return analyze_project(
        [str(tmp_path)], root=str(tmp_path), rules=rules
    )


class TestCrossModuleTransforms:
    STEP = """
        def step(x):
            if x > 0:
                return x
            return -x
        """

    def test_jit_in_other_module_marks_the_def(self, tmp_path):
        """Module A defines ``step`` with a traced-branch hazard;
        module B applies ``jax.jit(step)`` — ZNC001 must fire (and
        must NOT without the application): the acceptance pin."""
        _write(tmp_path, "liba.py", self.STEP)
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            fast = jax.jit(liba.step)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert [f.rule for f in findings] == ["ZNC001"]
        assert findings[0].path == "liba.py"
        assert findings[0].symbol == "step"

    def test_no_application_no_finding(self, tmp_path):
        _write(tmp_path, "liba.py", self.STEP)
        _write(tmp_path, "libb.py", "import liba\n")
        findings, _ = _run_project(tmp_path)
        assert findings == []

    def test_from_import_spelling_resolves(self, tmp_path):
        _write(tmp_path, "liba.py", self.STEP)
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            from liba import step

            fast = jax.jit(step)
            """,
        )
        findings, index = _run_project(tmp_path)
        assert [f.rule for f in findings] == ["ZNC001"]
        assert index.applications and (
            index.applications[0]["target"] == "liba.step"
        )

    def test_cross_module_static_argnames_honored(self, tmp_path):
        _write(
            tmp_path,
            "liba.py",
            """
            def step(x, greedy):
                if greedy:
                    return x
                return -x
            """,
        )
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            fast = jax.jit(liba.step, static_argnames=("greedy",))
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert findings == []

    def test_cross_module_lax_scan_body(self, tmp_path):
        _write(
            tmp_path,
            "bodies.py",
            """
            import time

            def body(c, x):
                t = time.time()
                return c + x, t
            """,
        )
        _write(
            tmp_path,
            "driver.py",
            """
            import jax
            import bodies

            def run(xs):
                return jax.lax.scan(bodies.body, 0.0, xs)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert [f.rule for f in findings] == ["ZNC002"]
        assert findings[0].path == "bodies.py"

    def test_package_dotted_modules_resolve(self, tmp_path):
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        _write(tmp_path, "pkg/ops.py", self.STEP)
        _write(
            tmp_path,
            "main.py",
            """
            import jax
            from pkg import ops

            fast = jax.jit(ops.step)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert [f.rule for f in findings] == ["ZNC001"]
        assert findings[0].path == "pkg/ops.py"


class TestChainReportedHelpers:
    def test_traced_only_helper_reported_at_entry_with_chain(
        self, tmp_path
    ):
        """A helper whose only call sites sit in traced code is
        analyzed as traced; the finding lands at the traced ENTRY with
        the chain in the message (that's where the fix applies)."""
        _write(
            tmp_path,
            "liba.py",
            """
            def helper(y):
                if y > 0:
                    return y
                return -y
            """,
        )
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            @jax.jit
            def outer(x):
                return liba.helper(x)
            """,
        )
        findings, index = _run_project(tmp_path)
        assert [f.rule for f in findings] == ["ZNC001"]
        f = findings[0]
        assert f.path == "libb.py" and f.symbol == "outer"
        assert "liba.helper" in f.message
        assert "libb.outer -> liba.helper" in f.message
        assert index.chains()[0]["helper"] == "liba.helper"

    def test_helper_also_called_from_host_stays_quiet(self, tmp_path):
        """One host call site proves a concrete-Python contract: the
        helper must not be marked (the conservative side of the
        approximation)."""
        _write(
            tmp_path,
            "liba.py",
            """
            def helper(y):
                if y > 0:
                    return y
                return -y
            """,
        )
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            @jax.jit
            def outer(x):
                return liba.helper(x)

            def host(z):
                return liba.helper(z)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert findings == []

    def test_call_site_literals_stay_static(self, tmp_path):
        """Parameters a traced call site binds to literals are static
        — ``helper(x, training=False)`` must not flag
        ``if training:``."""
        _write(
            tmp_path,
            "liba.py",
            """
            def helper(y, training):
                if training:
                    return y * 2
                return y
            """,
        )
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            @jax.jit
            def outer(x):
                return liba.helper(x, training=False)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert findings == []

    def test_shadowing_parameter_is_not_the_module_def(self, tmp_path):
        """``outer(x, helper)`` calling its PARAMETER must not be
        attributed to an unrelated module-level def of the same name
        and chain-marked off it (review regression)."""
        _write(
            tmp_path,
            "liba.py",
            """
            import time
            import jax

            def helper(y):
                return time.time() + y

            @jax.jit
            def outer(x, helper):
                return helper(x)
            """,
        )
        findings, index = _run_project(tmp_path)
        assert findings == []
        assert index.chains() == []

    def test_shadowed_transform_target_is_not_resolved(self, tmp_path):
        """``jax.jit(step)`` where ``step`` is the enclosing function's
        parameter must not mark the module-level ``step``."""
        _write(
            tmp_path,
            "liba.py",
            """
            import jax

            def step(x):
                if x > 0:
                    return x
                return -x

            def compile_it(step):
                return jax.jit(step)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert findings == []

    def test_pragma_on_the_helper_line_suppresses(self, tmp_path):
        _write(
            tmp_path,
            "liba.py",
            """
            def helper(y):
                if y > 0:  # znicz-check: disable=ZNC001
                    return y
                return -y
            """,
        )
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            @jax.jit
            def outer(x):
                return liba.helper(x)
            """,
        )
        findings, _ = _run_project(tmp_path)
        assert findings == []


# -- CLI surfaces ---------------------------------------------------------


class TestCliSurfaces:
    def _main(self, argv):
        from znicz_tpu.analysis.__main__ import main

        return main(argv)

    def test_sarif_output_shape(self, tmp_path, capsys):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:\n"
            "        pass\n"
        )
        rc = self._main(
            [
                str(bad),
                "--root",
                str(tmp_path),
                "--no-baseline",
                "--format",
                "sarif",
            ]
        )
        assert rc == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert run["tool"]["driver"]["name"] == "znicz-check"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert rule_ids == {"ZNC008"}
        result = run["results"][0]
        assert result["ruleId"] == "ZNC008"
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "bad.py"
        assert loc["region"]["startLine"] == 4
        assert "zniczCheck/v1" in result["partialFingerprints"]
        # SRCROOT must resolve to the analysis root so base-honoring
        # viewers (VS Code SARIF, sarif-multitool) open the real file
        base = run["originalUriBaseIds"]["SRCROOT"]["uri"]
        assert base.startswith("file://") and base.endswith("/")
        assert str(tmp_path) in base

    def test_sarif_clean_run_is_exit_zero(self, tmp_path, capsys):
        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x\n")
        rc = self._main(
            [
                str(good),
                "--root",
                str(tmp_path),
                "--no-baseline",
                "--format",
                "sarif",
            ]
        )
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["runs"][0]["results"] == []

    def test_changed_rejects_bogus_ref(self):
        import pytest

        with pytest.raises(SystemExit) as exc:
            self._main(["--changed", "definitely-not-a-ref"])
        assert exc.value.code == 2

    def test_changed_reports_subset_but_indexes_whole_repo(
        self, tmp_path, capsys, monkeypatch
    ):
        """--changed filters the REPORT to touched files while the
        index still spans everything — the cross-module finding for a
        changed applier module lands in the (unchanged) definer, so it
        must survive the filter only when its anchor file changed."""
        import subprocess

        _write(
            tmp_path,
            "liba.py",
            """
            def step(x):
                if x > 0:
                    return x
                return -x
            """,
        )
        _write(
            tmp_path,
            "libb.py",
            """
            import jax
            import liba

            fast = jax.jit(liba.step)
            """,
        )
        subprocess.run(
            ["git", "init", "-q"], cwd=tmp_path, check=True
        )
        subprocess.run(
            ["git", "add", "-A"], cwd=tmp_path, check=True
        )
        subprocess.run(
            [
                "git", "-c", "user.email=t@t", "-c", "user.name=t",
                "commit", "-qm", "seed",
            ],
            cwd=tmp_path,
            check=True,
        )
        # touch only libb (the APPLIER): the ZNC001 finding anchors in
        # liba, which did not change — the filtered report is empty,
        # but a full report still carries it
        (tmp_path / "libb.py").write_text(
            (tmp_path / "libb.py").read_text() + "\n# touched\n"
        )
        rc = self._main(
            [
                str(tmp_path),
                "--root",
                str(tmp_path),
                "--no-baseline",
                "--changed",
                "HEAD",
                "--format",
                "json",
            ]
        )
        assert rc == 0
        assert json.loads(capsys.readouterr().out) == []
        # now touch liba too: the finding's anchor is in the changed
        # set and must be reported
        (tmp_path / "liba.py").write_text(
            (tmp_path / "liba.py").read_text() + "\n# touched\n"
        )
        rc = self._main(
            [
                str(tmp_path),
                "--root",
                str(tmp_path),
                "--no-baseline",
                "--changed",
                "HEAD",
                "--format",
                "json",
            ]
        )
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in report] == ["ZNC001"]
        assert report[0]["path"] == "liba.py"

    def test_changed_rebases_git_paths_onto_root(
        self, tmp_path, capsys
    ):
        """git diff prints toplevel-relative paths; finding paths are
        --root-relative.  With --root a SUBDIRECTORY of the git
        toplevel the two frames differ — the filter must still match
        (review regression: it silently reported 0 findings)."""
        import subprocess

        pkg = tmp_path / "pkg"
        pkg.mkdir()
        subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
        (pkg / "mod.py").write_text("def f(x):\n    return x\n")
        subprocess.run(["git", "add", "-A"], cwd=tmp_path, check=True)
        subprocess.run(
            [
                "git", "-c", "user.email=t@t", "-c", "user.name=t",
                "commit", "-qm", "seed",
            ],
            cwd=tmp_path,
            check=True,
        )
        (pkg / "mod.py").write_text(
            "def f():\n"
            "    try:\n"
            "        return 1\n"
            "    except Exception:\n"
            "        pass\n"
        )
        rc = self._main(
            [
                str(pkg),
                "--root",
                str(pkg),  # root != git toplevel
                "--no-baseline",
                "--changed",
                "HEAD",
                "--format",
                "json",
            ]
        )
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        assert [f["rule"] for f in report] == ["ZNC008"]
        assert report[0]["path"] == "mod.py"  # root-relative

    def test_write_baseline_refuses_changed_subset(self, tmp_path):
        import pytest

        with pytest.raises(SystemExit) as exc:
            self._main(
                [
                    "--write-baseline",
                    "--changed",
                    "HEAD",
                    "--baseline",
                    str(tmp_path / "b.json"),
                ]
            )
        assert exc.value.code == 2

    def test_wall_time_in_summary(self, tmp_path, capsys):
        good = tmp_path / "good.py"
        good.write_text("def f(x):\n    return x\n")
        rc = self._main(
            [str(good), "--root", str(tmp_path), "--no-baseline"]
        )
        assert rc == 0
        err = capsys.readouterr().err
        assert "s]" in err and "finding" in err


# -- PR 15: dataflow rules, incremental cache, baseline versioning --------


def test_dataflow_and_concurrency_rules_are_registered():
    for rid in ("ZNC014", "ZNC015", "ZNC016"):
        assert rid in RULES
        assert RULES[rid].project, f"{rid} must be a project rule"
        assert RULES[rid].severity in ("error", "warning")


def test_every_registered_rule_has_a_docs_row():
    """Docs-drift lint: a rule without a catalog row in
    docs/STATIC_ANALYSIS.md is undocumented debt (PR 9 almost shipped
    ZNC013 without one)."""
    docs = os.path.join(REPO_ROOT, "docs", "STATIC_ANALYSIS.md")
    with open(docs, encoding="utf-8") as f:
        text = f.read()
    missing = [
        rid for rid in sorted(RULES) if f"| {rid} " not in text
    ]
    assert not missing, (
        f"rules missing a docs/STATIC_ANALYSIS.md catalog row: {missing}"
    )


def test_every_rule_ships_explain_examples():
    """--explain is registry-driven; an example-less rule would print
    an empty entry (the examples themselves are executed per-rule in
    test_analysis_rules.py)."""
    for rid, cls in sorted(RULES.items()):
        assert cls.example_fire.strip(), f"{rid} has no example_fire"
        assert cls.example_quiet.strip(), f"{rid} has no example_quiet"


class TestIncrementalCache:
    def test_cold_equals_warm_on_the_real_package_and_warm_is_fast(
        self, tmp_path
    ):
        """The tier-1 cache contract: a cold cached run and a warm one
        return IDENTICAL findings over this repo, and the warm run
        completes well inside the 5s --changed budget."""
        from znicz_tpu.analysis.cache import analyze_project_cached

        cache = tmp_path / "cache.json"
        cold, index, stats_cold = analyze_project_cached(
            [PKG_DIR], root=REPO_ROOT, cache_path=str(cache)
        )
        assert stats_cold["mode"] == "cold"
        assert index is not None
        t0 = time.monotonic()
        warm, index2, stats_warm = analyze_project_cached(
            [PKG_DIR], root=REPO_ROOT, cache_path=str(cache)
        )
        warm_s = time.monotonic() - t0
        assert stats_warm["mode"] == "warm"
        assert stats_warm["analyzed"] == 0
        assert index2 is None  # nothing was parsed
        assert warm == cold
        assert warm_s < 5.0, f"warm cached run took {warm_s:.2f}s"

    def test_edit_one_file_reanalyzes_only_it(self, tmp_path):
        """Edit one file -> only its findings recompute; cross-module
        results (a ZNC001 anchored in the UNCHANGED definer) ride the
        cache unchanged."""
        from znicz_tpu.analysis.cache import analyze_project_cached

        proj = tmp_path / "proj"
        proj.mkdir()
        (proj / "liba.py").write_text(
            "def step(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        (proj / "libb.py").write_text(
            "import jax\nimport liba\n\nfast = jax.jit(liba.step)\n"
        )
        cache = tmp_path / "cache.json"
        cold, _, stats = analyze_project_cached(
            [str(proj)], root=str(proj), cache_path=str(cache)
        )
        assert stats["mode"] == "cold"
        assert [f.rule for f in cold] == ["ZNC001"]
        assert cold[0].path == "liba.py"

        # touch only libb (the APPLIER): liba's findings are reused
        (proj / "libb.py").write_text(
            "import jax\nimport liba\n\n"
            "fast = jax.jit(liba.step)\n# touched\n"
        )
        warm, _, stats = analyze_project_cached(
            [str(proj)], root=str(proj), cache_path=str(cache)
        )
        assert stats["mode"] == "partial"
        assert stats["analyzed"] == 1 and stats["reused"] == 1
        assert [(f.rule, f.path, f.line) for f in warm] == [
            (f.rule, f.path, f.line) for f in cold
        ]

    def test_marks_digest_invalidates_on_cross_module_change(
        self, tmp_path
    ):
        """Removing the jit application in libb must ALSO invalidate
        (unchanged) liba — its traced marks changed even though its
        bytes did not.  This is the staleness bug the digest exists to
        prevent."""
        from znicz_tpu.analysis.cache import analyze_project_cached

        proj = tmp_path / "proj"
        proj.mkdir()
        (proj / "liba.py").write_text(
            "def step(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
        )
        (proj / "libb.py").write_text(
            "import jax\nimport liba\n\nfast = jax.jit(liba.step)\n"
        )
        cache = tmp_path / "cache.json"
        cold, _, _ = analyze_project_cached(
            [str(proj)], root=str(proj), cache_path=str(cache)
        )
        assert [f.rule for f in cold] == ["ZNC001"]
        (proj / "libb.py").write_text("import liba\n")
        warm, _, stats = analyze_project_cached(
            [str(proj)], root=str(proj), cache_path=str(cache)
        )
        assert warm == []  # liba re-analyzed unmarked, finding gone
        assert stats["analyzed"] == 2  # libb (hash) AND liba (digest)

    def test_corrupt_cache_degrades_to_cold(self, tmp_path):
        from znicz_tpu.analysis.cache import analyze_project_cached

        proj = tmp_path / "proj"
        proj.mkdir()
        (proj / "m.py").write_text("def f(x):\n    return x\n")
        cache = tmp_path / "cache.json"
        cache.write_text("{not json")
        findings, index, stats = analyze_project_cached(
            [str(proj)], root=str(proj), cache_path=str(cache)
        )
        assert findings == [] and stats["mode"] == "cold"
        assert index is not None

    def test_cli_uses_cache_and_reports_it(self, tmp_path, capsys):
        from znicz_tpu.analysis.__main__ import main

        proj = tmp_path / "proj"
        proj.mkdir()
        (proj / "m.py").write_text("def f(x):\n    return x\n")
        argv = [str(proj), "--root", str(proj), "--no-baseline"]
        assert main(argv) == 0
        err = capsys.readouterr().err
        assert "cache cold" in err
        assert main(argv) == 0
        err = capsys.readouterr().err
        assert "cache warm" in err
        # the cache landed at the documented default location
        assert (proj / "tools" / "znicz_check_cache.json").exists()

    def test_select_subset_bypasses_the_cache(self, tmp_path, capsys):
        from znicz_tpu.analysis.__main__ import main

        proj = tmp_path / "proj"
        proj.mkdir()
        (proj / "m.py").write_text("def f(x):\n    return x\n")
        argv = [
            str(proj), "--root", str(proj), "--no-baseline",
            "--select", "ZNC008",
        ]
        assert main(argv) == 0
        err = capsys.readouterr().err
        assert "cache" not in err


class TestBaselineVersioning:
    def test_write_baseline_records_analyzer_stamp(self, tmp_path):
        from znicz_tpu.analysis.engine import (
            ANALYZER_VERSION,
            baseline_meta,
            stale_baseline_meta,
            write_baseline,
        )

        path = str(tmp_path / "b.json")
        write_baseline([], path)
        meta = baseline_meta(path)
        assert meta["version"] == ANALYZER_VERSION
        assert meta["rules"] == sorted(RULES)
        assert stale_baseline_meta(path) is None

    def test_unstamped_baseline_is_stale(self, tmp_path):
        from znicz_tpu.analysis.engine import stale_baseline_meta

        path = tmp_path / "b.json"
        path.write_text('{"version": 1, "findings": {}}\n')
        note = stale_baseline_meta(str(path))
        assert note is not None and "--write-baseline" in note

    def test_baseline_missing_new_rules_is_stale_and_names_them(
        self, tmp_path
    ):
        from znicz_tpu.analysis.engine import stale_baseline_meta

        path = tmp_path / "b.json"
        rules = [r for r in sorted(RULES) if r != "ZNC016"]
        path.write_text(
            json.dumps(
                {
                    "version": 1,
                    "analyzer": {"version": "2.0", "rules": rules},
                    "findings": {},
                }
            )
        )
        note = stale_baseline_meta(str(path))
        assert note is not None and "ZNC016" in note

    def test_cli_warns_on_stale_baseline(self, tmp_path, capsys):
        from znicz_tpu.analysis.__main__ import main

        proj = tmp_path / "proj"
        proj.mkdir()
        (proj / "m.py").write_text("def f(x):\n    return x\n")
        stale = tmp_path / "b.json"
        stale.write_text('{"version": 1, "findings": {}}\n')
        rc = main(
            [
                str(proj), "--root", str(proj),
                "--baseline", str(stale),
            ]
        )
        assert rc == 0
        assert "warning:" in capsys.readouterr().err

    def test_committed_baseline_is_not_stale(self):
        """Adding a rule without regenerating the committed baseline
        fails HERE, not as a silent suppression gap."""
        from znicz_tpu.analysis.engine import stale_baseline_meta

        assert stale_baseline_meta(BASELINE) is None


class TestProjectRuleAcceptanceFixtures:
    """Seeded fire + minimally-edited quiet twins for ZNC014/015/016
    through the REAL analyze_project entry point (file-based, like
    PR 9's cross-module acceptance pair) — proving the project rules
    ride the full pipeline: suppression, sorting, --changed filtering."""

    RECOMPILE_FIRE = """
        programs = {}

        def admit(prompt):
            programs[("admit", len(prompt))] = 1
        """
    RECOMPILE_QUIET = """
        programs = {}

        def admit(prompt):
            programs[("admit", bucket_for(len(prompt), (16, 32)))] = 1
        """
    DEADLOCK_FIRE = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._stats_lock = threading.Lock()

            def tick(self):
                with self._lock:
                    with self._stats_lock:
                        pass

            def stats(self):
                with self._stats_lock:
                    with self._lock:
                        pass
        """
    DEADLOCK_QUIET = DEADLOCK_FIRE.replace(
        "with self._stats_lock:\n                    with self._lock:",
        "with self._lock:\n                    with self._stats_lock:",
    )
    BLOCKING_FIRE = """
        import threading
        import time

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def tick(self):
                with self._lock:
                    time.sleep(0.01)
                    self.n += 1
        """
    BLOCKING_QUIET = """
        import threading
        import time

        class Door:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def tick(self):
                time.sleep(0.01)
                with self._lock:
                    self.n += 1
        """

    def _services(self, tmp_path, src):
        (tmp_path / "services").mkdir(exist_ok=True)
        _write(tmp_path, "services/mod.py", src)
        return analyze_project(
            [str(tmp_path)], root=str(tmp_path)
        )[0]

    @pytest.mark.parametrize(
        "fire,quiet,rule",
        [
            ("RECOMPILE_FIRE", "RECOMPILE_QUIET", "ZNC014"),
            ("DEADLOCK_FIRE", "DEADLOCK_QUIET", "ZNC015"),
            ("BLOCKING_FIRE", "BLOCKING_QUIET", "ZNC016"),
        ],
    )
    def test_fire_and_quiet_twin(self, tmp_path, fire, quiet, rule):
        findings = self._services(tmp_path, getattr(self, fire))
        assert [f.rule for f in findings] == [rule]
        assert findings[0].path == "services/mod.py"
        findings = self._services(tmp_path, getattr(self, quiet))
        assert findings == []

    def test_report_paths_filters_project_findings(self, tmp_path):
        """--changed semantics: a ZNC015 finding survives the filter
        only when its ANCHOR file is in the changed set."""
        (tmp_path / "services").mkdir()
        _write(tmp_path, "services/mod.py", self.DEADLOCK_FIRE)
        _write(tmp_path, "other.py", "X = 1\n")
        kept, _ = analyze_project(
            [str(tmp_path)],
            root=str(tmp_path),
            report_paths={"services/mod.py"},
        )
        assert [f.rule for f in kept] == ["ZNC015"]
        dropped, _ = analyze_project(
            [str(tmp_path)],
            root=str(tmp_path),
            report_paths={"other.py"},
        )
        assert dropped == []
