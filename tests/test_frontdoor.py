"""ServingFrontDoor: streaming, deadlines, cancel, shed, watchdog.

The front door's contract (docs/SERVING.md "The front door"): every
accepted request resolves to exactly one typed completion — eos/budget
from the engine, cancelled / deadline_exceeded / error / shed from the
robustness layer — with its stream terminated and, on the paged
backend, its blocks reclaimed (free == pool after every scenario).  No
failure path is theoretical here: each is forced deterministically via
the :mod:`znicz_tpu.utils.faults` injection points and asserted
against non-faulted ``generate()`` goldens for the survivors, plus the
zero-new-compiled-programs invariant across watchdog restarts.
"""

import time

import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.core import prng
from znicz_tpu.services import (
    DecodeEngine,
    EngineClosedError,
    PagedDecodeEngine,
    RejectedError,
    RequestTooLargeError,
    ServingFrontDoor,
)
from znicz_tpu.utils import faults
from znicz_tpu.workflow import generate as G
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 14
HEADS = 4
T_MAX = 64
BS = 8


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    prng.seed_all(27)
    return init_lm_params(17, 32, 2, HEADS, max_seq=T_MAX)


@pytest.fixture(scope="module", autouse=True)
def _warm(params):
    """Compile the engine programs ONCE before any timing-sensitive
    test: the first-compile seconds must not eat a deadline budget."""
    eng = _engine_factory(params)()
    gen = np.random.default_rng(3)
    for n in (5, 12):
        eng.submit(gen.integers(0, 17, (n,)).astype(np.int32), 12)
    eng.run()


def _engine_factory(params, **kw):
    kw.setdefault("n_heads", HEADS)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("max_seq", T_MAX)
    kw.setdefault("admit_every", 4)

    def factory():
        return PagedDecodeEngine(params, **kw)

    return factory


def _reference(params, prompt, budget, eos=EOS):
    out = np.asarray(
        G.generate(
            params, jnp.asarray(prompt)[None], n_heads=HEADS,
            max_new_tokens=budget, eos_id=eos,
        )
    )[0]
    new = out[len(prompt):]
    hit = np.where(new == eos)[0]
    if len(hit):
        new = new[: hit[0] + 1]
    return np.concatenate([prompt, new])


def _prompts(n, seed=7):
    gen = np.random.default_rng(seed)
    return [
        gen.integers(0, 17, (k,)).astype(np.int32)
        for k in (5, 12, 3, 9, 17)[:n]
    ]


def _long_prompt(params, budget=40, seed=21):
    """A prompt whose greedy generation does NOT hit EOS within
    ``budget`` — the deterministic victim for cancel/deadline/crash
    tests (a natural EOS mid-test would win the race)."""
    gen = np.random.default_rng(seed)
    for _ in range(200):
        p = gen.integers(0, 17, (6,)).astype(np.int32)
        ref = _reference(params, p, budget)
        if len(ref) - len(p) == budget and ref[-1] != EOS:
            return p
    raise AssertionError("no EOS-free prompt found in 200 draws")


def _pool_swept(door):
    st = door.engine.stats()
    return st["pool_blocks_free"] == st["pool_blocks"]


def _wait_until(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {what}")


def _labeled_sum(name):
    m = obs.get_registry().metrics().get(name)
    if m is None:
        return 0.0
    return sum(c.value for c in m.children().values())


def _hist_count(name):
    m = obs.get_registry().metrics().get(name)
    if m is None:
        return 0
    return sum(c.count for c in m.children().values())


def _retired_errors():
    m = obs.get_registry().metrics().get(
        "znicz_serve_requests_retired_total"
    )
    if m is None:
        return 0.0
    return sum(
        c.value for key, c in m.children().items()
        if key and key[0] == "error"
    )


def _paged_compiles_total():
    m = obs.get_registry().metrics().get("znicz_serve_compiles_total")
    if m is None:
        return 0.0
    return sum(
        c.value for key, c in m.children().items()
        if key[0] in ("prefill", "paged_chunk", "cow")
    )


class TestStreaming:
    def test_tokens_stream_and_goldens_match_generate(self, params):
        prompts = _prompts(3)
        budgets = [6, 4, 8]
        with ServingFrontDoor(_engine_factory(params)) as door:
            handles = [
                door.submit(p, b) for p, b in zip(prompts, budgets)
            ]
            streamed = [list(h.tokens(timeout=30.0)) for h in handles]
            for h, p, b, toks in zip(handles, prompts, budgets, streamed):
                comp = h.result(timeout=30.0)
                assert comp.finish_reason in ("eos", "budget")
                assert comp.trace_id == h.id
                np.testing.assert_array_equal(
                    comp.tokens, _reference(params, p, b)
                )
                # the stream is the completion's tail, token for token
                assert toks == list(comp.tokens[len(p):])
            assert len({h.id for h in handles}) == 3  # distinct trace ids
            assert _pool_swept(door)
            st = door.stats()
            assert st["submitted"] == 3 and st["completed"] == 3

    def test_dense_backend_works_too(self, params):
        def factory():
            return DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, batch_size=2,
                max_seq=T_MAX, admit_every=4,
            )

        prompts = _prompts(2)
        with ServingFrontDoor(factory) as door:
            handles = [door.submit(p, 5) for p in prompts]
            for h, p in zip(handles, prompts):
                comp = h.result(timeout=30.0)
                np.testing.assert_array_equal(
                    comp.tokens, _reference(params, p, 5)
                )

    def test_handle_result_timeout_raises(self, params):
        with ServingFrontDoor(
            _engine_factory(params), engine_queue_limit=0
        ) as door:
            h = door.submit(_prompts(1)[0], 4)  # parked: nothing pumps
            with pytest.raises(TimeoutError):
                h.result(timeout=0.05)
            with pytest.raises(TimeoutError):
                next(h.tokens(timeout=0.05))


class TestAdmission:
    def test_validation_rejects_before_enqueue(self, params):
        with ServingFrontDoor(_engine_factory(params)) as door:
            with pytest.raises(ValueError, match="empty prompt"):
                door.submit([], 4)
            with pytest.raises(RequestTooLargeError, match="paged"):
                door.submit([1, 2, 3], 10_000)
            # malformed prompt/deadline surface as ValueError at the
            # caller — a str deadline must never reach the engine
            # thread, where the per-tick expiry compare would wedge it
            with pytest.raises(ValueError, match="malformed prompt"):
                door.submit(None, 4)
            with pytest.raises(ValueError, match="malformed prompt"):
                door.submit([[1, 2], [3]], 4)
            with pytest.raises(ValueError, match="malformed deadline"):
                door.submit([1, 2], 4, deadline_s="soon")
            with pytest.raises(ValueError, match="deadline_s >= 0"):
                door.submit([1, 2], 4, deadline_s=-1.0)
            # typed subclass keeps legacy except ValueError working
            assert issubclass(RequestTooLargeError, ValueError)
            assert door.stats()["submitted"] == 0  # nothing enqueued

    def test_queue_full_sheds_with_retry_after(self, params):
        before = _labeled_sum("znicz_serve_rejected_total")
        with ServingFrontDoor(
            _engine_factory(params), max_pending=2, engine_queue_limit=0
        ) as door:
            p = _prompts(1)[0]
            door.submit(p, 4)
            door.submit(p, 4)
            with pytest.raises(RejectedError) as exc:
                door.submit(p, 4)
            assert exc.value.reason == "queue_full"
            assert exc.value.retry_after_s > 0
            assert door.stats()["rejected"] == {"queue_full": 1}
        assert _labeled_sum("znicz_serve_rejected_total") > before

    def test_pool_pressure_watermark_sheds(self, params):
        with ServingFrontDoor(
            _engine_factory(params),
            engine_queue_limit=0,
            shed_pool_frac=2.0,  # every pool state is "under pressure"
        ) as door:
            p = _prompts(1)[0]
            door.submit(p, 4)  # no backlog yet: accepted
            with pytest.raises(RejectedError) as exc:
                door.submit(p, 4)
            assert exc.value.reason == "pool_pressure"


class TestCancellation:
    def test_cancel_before_admission(self, params):
        with ServingFrontDoor(
            _engine_factory(params), engine_queue_limit=0
        ) as door:
            h = door.submit(_prompts(1)[0], 8)
            assert h.cancel() is True
            comp = h.result(timeout=10.0)
            assert comp.finish_reason == "cancelled"
            assert comp.n_new == 0
            assert list(h.tokens(timeout=5.0)) == []  # stream terminated

    def test_cancel_during_decode_reclaims_blocks(self, params):
        pa = _long_prompt(params)  # EOS-free for the full 40 budget
        pb = _prompts(2)[1]
        # slow ticks: the 40-token victim needs >= 10 ticks x 50 ms,
        # so the cancel deterministically lands mid-decode
        faults.inject("frontdoor.slow_tick", delay=0.05)
        with ServingFrontDoor(_engine_factory(params)) as door:
            ha = door.submit(pa, 40)  # long-running victim
            hb = door.submit(pb, 5)  # unaffected neighbor
            it = ha.tokens(timeout=30.0)
            next(it)  # decoding for sure
            assert ha.cancel() is True
            comp = ha.result(timeout=30.0)
            faults.clear()
            assert comp.finish_reason == "cancelled"
            assert 1 <= comp.n_new < 40
            # the neighbor sharing the pool stays golden
            np.testing.assert_array_equal(
                hb.result(timeout=30.0).tokens, _reference(params, pb, 5)
            )
            _wait_until(
                lambda: _pool_swept(door), what="block reclamation"
            )
            assert door.stats()["cancelled"] == 1

    def test_cancel_after_completion_is_noop(self, params):
        with ServingFrontDoor(_engine_factory(params)) as door:
            h = door.submit(_prompts(1)[0], 3)
            h.result(timeout=30.0)
            assert h.cancel() is False
            assert door.stats()["cancelled"] == 0


class TestDeadlines:
    def test_deadline_expires_while_queued(self, params):
        with ServingFrontDoor(
            _engine_factory(params), engine_queue_limit=0
        ) as door:
            h = door.submit(_prompts(1)[0], 8, deadline_s=0.01)
            comp = h.result(timeout=10.0)
            assert comp.finish_reason == "deadline_exceeded"
            assert comp.n_new == 0

    def test_deadline_expires_mid_decode(self, params):
        # slow ticks make expiry deterministic: a 40-token budget needs
        # ~10 ticks x >=50 ms >> the 250 ms deadline, and the first
        # tick (admission + first chunk) lands well inside it
        faults.inject("frontdoor.slow_tick", delay=0.05)
        with ServingFrontDoor(_engine_factory(params)) as door:
            h = door.submit(_long_prompt(params), 40, deadline_s=0.25)
            comp = h.result(timeout=30.0)
            faults.clear()
            assert comp.finish_reason == "deadline_exceeded"
            assert 1 <= comp.n_new < 40  # expired MID-decode
            _wait_until(
                lambda: _pool_swept(door), what="block reclamation"
            )
            assert door.stats()["deadline_exceeded"] == 1

    def test_default_deadline_applies(self, params):
        with ServingFrontDoor(
            _engine_factory(params),
            engine_queue_limit=0,
            default_deadline_s=0.01,
        ) as door:
            comp = door.submit(_prompts(1)[0], 8).result(timeout=10.0)
            assert comp.finish_reason == "deadline_exceeded"


class TestWatchdog:
    def test_engine_crash_fails_inflight_readmits_queued(self, params):
        # batch_size=1: A occupies the slot, B sits in the ENGINE
        # queue, C waits at the front door.  A decode-step crash must
        # fail ONLY A (typed error), rebuild the engine, re-admit B and
        # leave C untouched — both then golden-match generate() — and
        # recompile NOTHING (the jit caches survive the restart).
        pa = _long_prompt(params, budget=30)
        pb, pc = _prompts(3)[1:]
        factory = _engine_factory(params, batch_size=1, admit_every=2)
        # pre-compile this factory's whole program ladder (prefill +
        # every x2 window rung pa can reach — the paged_chunk key is
        # per (admit_every, batch_size), so the module _warm doesn't
        # cover it): the zero-new-compiles pin below must measure only
        # restart-caused compiles, not a rung the stream itself happens
        # to touch for the first time after the snapshot (a race on how
        # far A has decoded when the crash lands)
        warm = factory()
        warm.submit(pa, 30)
        warm.run()
        # slow ticks: A's 30-token budget spans >= 15 ticks x 50 ms, so
        # the crash deterministically lands while A is still decoding
        faults.inject("frontdoor.slow_tick", delay=0.05)
        with ServingFrontDoor(factory, engine_queue_limit=1) as door:
            ha = door.submit(pa, 30)
            next(ha.tokens(timeout=30.0))  # A is decoding
            hb = door.submit(pb, 5)
            hc = door.submit(pc, 5)
            _wait_until(
                lambda: door.watchdog_state()["inflight"] == 2,
                what="B pumped into the engine queue",
            )
            engine_before = door.engine
            compiles_before = _paged_compiles_total()
            lat_before = _hist_count(
                "znicz_serve_frontdoor_latency_seconds"
            )
            err_before = _retired_errors()
            faults.inject(
                "engine.decode_step", exc=RuntimeError("boom"), times=1
            )
            ca = ha.result(timeout=30.0)
            faults.clear("frontdoor.slow_tick")
            assert ca.finish_reason == "error"
            assert "boom" in ca.error
            # the dead engine's REAL per-request accounting rides the
            # error completion: A was mid-decode when the engine
            # crashed, so its breakdown must say so — not the
            # never-reached-the-engine fallback's 100% queue wait
            assert ca.timings["decode_s"] > 0
            assert ca.timings["prefill_s"] > 0
            for h, p in ((hb, pb), (hc, pc)):
                comp = h.result(timeout=60.0)
                assert comp.finish_reason in ("eos", "budget")
                np.testing.assert_array_equal(
                    comp.tokens, _reference(params, p, 5)
                )
            st = door.stats()
            assert st["watchdog_restarts"] == 1
            assert door.engine is not engine_before
            # crash-failed A is NOT a latency measurement (its 'time to
            # crash' would dilute the SLO histogram mid-incident); only
            # B and C land in the client-clock latency series.  A IS an
            # error: retired{reason=error} must tick so /slo error_rate
            # sees the incident
            assert (
                _hist_count("znicz_serve_frontdoor_latency_seconds")
                - lat_before
                == 2
            )
            assert _retired_errors() - err_before == 1.0
            # watchdog restarts ride the warm jit caches: zero new
            # compiled programs, pinned via znicz_serve_compiles_total
            assert _paged_compiles_total() == compiles_before
            assert _pool_swept(door)

    def test_allocator_failure_is_survivable(self, params):
        with ServingFrontDoor(_engine_factory(params)) as door:
            faults.inject(
                "pool.alloc", exc=RuntimeError("alloc boom"), times=1
            )
            comp = door.submit(_prompts(1)[0], 4).result(timeout=30.0)
            assert comp.finish_reason == "error"
            assert "alloc boom" in comp.error
            assert door.stats()["watchdog_restarts"] == 1
            # the rebuilt engine serves normally
            p = _prompts(2)[1]
            comp2 = door.submit(p, 5).result(timeout=30.0)
            np.testing.assert_array_equal(
                comp2.tokens, _reference(params, p, 5)
            )
            assert _pool_swept(door)

    def test_pool_exhaustion_expires_typed_then_recovers(self, params):
        # persistent simulated exhaustion: allocation always reports
        # the pool dry, so the request livelocks bind -> starve ->
        # self-preempt until its DEADLINE retires it (typed, no hang,
        # no leak); once pressure clears the door serves again
        with ServingFrontDoor(_engine_factory(params)) as door:
            faults.inject("pool.pressure", flag=True)
            comp = door.submit(
                _prompts(1)[0], 4, deadline_s=0.3
            ).result(timeout=30.0)
            assert comp.finish_reason == "deadline_exceeded"
            faults.clear()
            p = _prompts(2)[1]
            comp2 = door.submit(p, 5).result(timeout=30.0)
            np.testing.assert_array_equal(
                comp2.tokens, _reference(params, p, 5)
            )
            assert _pool_swept(door)
            assert door.stats()["watchdog_restarts"] == 0  # no crash

    def test_stall_detection_flips_health(self, params):
        with ServingFrontDoor(
            _engine_factory(params), stall_after_s=0.1
        ) as door:
            assert door.healthy()
            faults.inject("frontdoor.slow_tick", delay=0.6, times=1)
            _wait_until(
                lambda: door.watchdog_state()["state"] == "stalled",
                timeout=5.0,
                what="stall detection",
            )
            assert not door.healthy()
            _wait_until(
                lambda: door.watchdog_state()["state"] == "running",
                timeout=5.0,
                what="stall recovery",
            )


class TestShutdown:
    def test_close_drains_then_sheds_with_typed_completions(self, params):
        door = ServingFrontDoor(
            _engine_factory(params), engine_queue_limit=0
        )
        h1 = door.submit(_prompts(1)[0], 4)
        h2 = door.submit(_prompts(1)[0], 4)
        door.close(grace_s=0.1)  # parked work cannot drain: shed
        for h in (h1, h2):
            comp = h.result(timeout=5.0)
            assert comp.finish_reason == "shed"
            assert list(h.tokens(timeout=2.0)) == []
        assert door.stats()["shed"] == 2
        with pytest.raises(EngineClosedError):
            door.submit(_prompts(1)[0], 4)
        assert door.watchdog_state()["state"] == "closed"

    def test_close_is_idempotent_and_drains_live_work(self, params):
        door = ServingFrontDoor(_engine_factory(params))
        p = _prompts(1)[0]
        h = door.submit(p, 5)
        door.close(grace_s=30.0)
        comp = h.result(timeout=5.0)
        np.testing.assert_array_equal(
            comp.tokens, _reference(params, p, 5)
        )
        door.close()  # second close is a no-op


class TestCompileBudget:
    def test_frontdoor_adds_zero_compiled_programs(self, params):
        # twin streams: once through a bare engine, once through the
        # front door — the registry's first-compile ledger must not
        # move for the front-door run (it reuses the same prefill /
        # decode-chunk programs; deadline/cancel/watchdog machinery is
        # host-side only)
        prompts, budgets = _prompts(3), [6, 4, 8]
        eng = _engine_factory(params)()
        for p, b in zip(prompts, budgets):
            eng.submit(p, b)
        eng.run()
        before = _paged_compiles_total()
        with ServingFrontDoor(_engine_factory(params)) as door:
            handles = [
                door.submit(p, b) for p, b in zip(prompts, budgets)
            ]
            for h in handles:
                h.result(timeout=30.0)
            ledger = door.engine.compile_stats()["programs"]
        assert _paged_compiles_total() == before
        assert {k[0] for k in ledger} <= {"prefill", "paged_chunk", "cow"}


_TIMING_KEYS = {
    "queue_s", "prefill_s", "decode_s", "preemptions", "cached_tokens",
    "spec_drafted", "spec_accepted",
}


class TestRequestTimings:
    def test_every_completion_carries_the_breakdown(self, params):
        prompts, budgets = _prompts(3), [6, 4, 8]
        with ServingFrontDoor(_engine_factory(params)) as door:
            handles = [
                door.submit(p, b) for p, b in zip(prompts, budgets)
            ]
            for h in handles:
                comp = h.result(timeout=30.0)
                assert comp.timings is not None
                assert set(comp.timings) == _TIMING_KEYS
                assert comp.timings["queue_s"] >= 0.0
                # an admitted request did real prefill + decode work
                assert comp.timings["prefill_s"] > 0.0
                assert comp.timings["decode_s"] > 0.0
            recent = door.recent_requests()
        assert len(recent) == 3
        assert recent[0]["timings"] is not None  # newest first
        assert {r["trace_id"] for r in recent} == {h.id for h in handles}

    def test_prefill_dominated_vs_queue_dominated_golden(self, params):
        # the acceptance golden: "why was this request slow" must have
        # two distinguishable answers.  (1) one long prompt, budget 1:
        # all prefill, no queue wait.  (2) a request parked behind a
        # busy single-slot engine: all queue wait, one chunk of prefill.
        long_p = np.arange(48, dtype=np.int32) % 16 + 1
        eng = _engine_factory(params, batch_size=1)()
        rid = eng.submit(long_p, 1)
        eng.run()
        t = eng.completions[rid].timings
        assert t["prefill_s"] > t["queue_s"]
        assert t["decode_s"] == 0.0  # retired at admission

        eng = _engine_factory(params, batch_size=1)()
        first = eng.submit(_long_prompt(params), 40)
        second = eng.submit(_prompts(1)[0], 2)
        eng.run()
        t2 = eng.completions[second].timings
        # the second request sat queued through the first's whole
        # 40-token decode: waiting dwarfs its own prefill
        assert t2["queue_s"] > t2["prefill_s"]
        assert t2["queue_s"] > eng.completions[first].timings["queue_s"]

    def test_preemption_and_cache_counts_land_in_timings(self, params):
        # pool pressure: 2 slots, a pool too small for both -> the
        # younger is preempted and recomputed; its breakdown says so
        factory = _engine_factory(
            params, batch_size=2, n_blocks=2 * (40 // BS) - 1,
            prefix_cache=False,
        )
        eng = factory()
        a = eng.submit(_long_prompt(params), 28)
        b = eng.submit(_long_prompt(params, seed=22), 28)
        eng.run()
        timings = [eng.completions[r].timings for r in (a, b)]
        assert sum(t["preemptions"] for t in timings) >= 1
        # prefix reuse: same prompt twice -> the second's cached_tokens
        eng2 = _engine_factory(params)()
        p = np.arange(2 * BS, dtype=np.int32) % 16 + 1
        r1 = eng2.submit(p, 3)
        eng2.run()
        r2 = eng2.submit(p, 3)
        eng2.run()
        assert eng2.completions[r1].timings["cached_tokens"] == 0
        assert eng2.completions[r2].timings["cached_tokens"] > 0

    def test_queued_termination_is_pure_queue_time(self, params):
        with ServingFrontDoor(
            _engine_factory(params), engine_queue_limit=0
        ) as door:
            h = door.submit(_prompts(1)[0], 4)  # parked forever
            time.sleep(0.05)
            h.cancel()
            comp = h.result(timeout=30.0)
        assert comp.finish_reason == "cancelled"
        assert comp.timings["queue_s"] >= 0.05
        assert comp.timings["prefill_s"] == 0.0
        assert comp.timings["decode_s"] == 0.0

    def test_trace_id_reaches_engine_spans_and_instants(self, params):
        tracer = obs.get_tracer()
        tracer.start()
        try:
            with ServingFrontDoor(_engine_factory(params)) as door:
                h = door.submit(_prompts(1)[0], 4)
                h.result(timeout=30.0)
                tid = h.id
        finally:
            events = tracer.stop()
        admits = [
            e for e in events
            if e["name"] == "serve/admit"
            and e.get("args", {}).get("trace") == tid
        ]
        assert len(admits) == 1
        lifecycle = {
            e["name"] for e in events
            if e.get("args", {}).get("trace") == tid
        }
        assert "serve/queued" in lifecycle
        assert "serve/retired" in lifecycle

    def test_debug_ring_is_bounded(self, params):
        with ServingFrontDoor(
            _engine_factory(params), debug_requests=2
        ) as door:
            handles = [door.submit(_prompts(1)[0], 2) for _ in range(4)]
            for h in handles:
                h.result(timeout=30.0)
            recent = door.recent_requests()
        assert len(recent) == 2
        assert recent[0]["trace_id"] == handles[-1].id  # newest first


class TestSLOEndpointBehavior:
    def test_slo_breach_under_injected_latency_then_recovery(
        self, params
    ):
        # the acceptance path: fault-injected slow ticks push TTFT over
        # a tight threshold -> burn rate breaches in EVERY window; after
        # the fault clears, fast requests wash the short window clean ->
        # breach clears (multi-window AND), p99s visibly recover
        from znicz_tpu.observability.slo import SLOTarget

        reg = obs.get_registry()
        with ServingFrontDoor(
            _engine_factory(params),
            slo_targets=(
                SLOTarget(
                    "ttft", "znicz_serve_frontdoor_ttft_seconds",
                    0.15, 0.9,
                ),
            ),
            slo_windows_s=(0.6, 120.0),
            slo_sample_gap_s=0.0,
        ) as door:
            mon = door._slo
            mon.sample()  # pristine baseline before any traffic
            with faults.injected("frontdoor.slow_tick", delay=0.3):
                for _ in range(3):
                    door.submit(_prompts(1)[0], 2).result(timeout=30.0)
            snap = door.slo_snapshot()
            assert snap["targets"]["ttft"]["breached"] is True
            assert snap["breached"] is True
            slow_p99 = snap["targets"]["ttft"]["windows"]["120"]["p99_s"]
            assert slow_p99 is not None and slow_p99 > 0.15
            # recovery: fault cleared, let the short window age out the
            # slow samples, then run fast traffic
            mon.sample()
            time.sleep(0.7)
            for _ in range(6):
                door.submit(_prompts(1)[0], 2).result(timeout=30.0)
            snap = door.slo_snapshot()
            short = snap["targets"]["ttft"]["windows"]["0.6"]
            assert short["n"] >= 6
            assert short["burn_rate"] < 1.0
            assert snap["targets"]["ttft"]["breached"] is False

    def test_observability_paths_add_zero_compiled_programs(self, params):
        # the host-side observability machinery (slo snapshot, debug
        # ring, aggregator push/merge of the live registry) must not
        # touch the compile ledger or the jit caches
        from znicz_tpu.observability.aggregate import MetricsAggregator

        prompts, budgets = _prompts(3), [6, 4, 8]
        with ServingFrontDoor(_engine_factory(params)) as door:
            for p, b in zip(prompts, budgets):
                door.submit(p, b).result(timeout=30.0)
            eng = door.engine
            ledger_before = dict(eng.compile_stats()["programs"])
            jit_before = {
                k: v for k, v in eng.compile_stats().items()
                if k.endswith("_jit_entries")
            }
            compiles_before = _paged_compiles_total()
            for _ in range(3):
                door.slo_snapshot()
                door.recent_requests()
            agg = MetricsAggregator()
            agg.push("self", obs.get_registry().snapshot())
            agg.push("twin", text=obs.get_registry().prometheus_text())
            agg.merged_snapshot()
            agg.prometheus_text()
            door.submit(prompts[0], budgets[0]).result(timeout=30.0)
            stats = eng.compile_stats()
        assert stats["programs"] == ledger_before
        assert {
            k: v for k, v in stats.items()
            if k.endswith("_jit_entries")
        } == jit_before
        assert _paged_compiles_total() == compiles_before


class TestFaultsHarness:
    def test_times_bounds_fires(self):
        faults.inject("x.y", exc=RuntimeError("q"), times=2)
        for _ in range(2):
            with pytest.raises(RuntimeError):
                faults.fire("x.y")
        assert faults.fire("x.y") is False  # auto-disarmed

    def test_flag_and_delay_points(self):
        faults.inject("p.q", flag=True)
        assert faults.fire("p.q") is True
        faults.clear("p.q")
        assert faults.fire("p.q") is False
        t0 = time.monotonic()
        faults.inject("s.t", delay=0.05, times=1)
        assert faults.fire("s.t") is True
        assert time.monotonic() - t0 >= 0.05

    def test_injected_scope_clears_even_on_raise(self):
        with pytest.raises(faults.FaultInjected):
            with faults.injected("a.b", times=5):
                faults.fire("a.b")
        assert faults.fire("a.b") is False

    def test_env_spec_parses_and_rejects_garbage(self):
        faults._parse_env("m.n:times=1:delay=0.0,o.p:flag")
        assert faults.armed("m.n") and faults.fire("o.p") is True
        faults.clear()
        with pytest.raises(ValueError, match="unknown field"):
            faults._parse_env("q.r:bogus=1")


class TestSpeculativeFrontDoor:
    """ISSUE 12 plumb-through: a spec_k factory serves through the
    front door on the same tick loop — streams stay golden, the
    completion timings carry the spec tallies, and a watchdog restart
    rebuilds a SPECULATING engine from the factory."""

    def test_spec_engine_streams_golden_with_timings(self, params):
        with ServingFrontDoor(
            _engine_factory(params, spec_k=7), max_pending=8
        ) as door:
            prompts = _prompts(3)
            handles = [door.submit(p, 16) for p in prompts]
            for h, p in zip(handles, prompts):
                toks = list(h.tokens(timeout=30.0))
                comp = h.result(timeout=5.0)
                ref = _reference(params, p, 16)
                assert np.array_equal(comp.tokens, ref)
                assert toks == list(ref[len(p):])
                assert "spec_drafted" in comp.timings
                assert "spec_accepted" in comp.timings
            st = door.stats()["engine"]["spec"]
            assert st["enabled"] and st["k"] == 7
            assert st["drafted"] == st["accepted"] + st["rejected"]

    def test_watchdog_restart_preserves_spec_config(self, params):
        with ServingFrontDoor(
            _engine_factory(params, spec_k=7), max_pending=8
        ) as door:
            p = _long_prompt(params)
            with faults.injected(
                "engine.decode_step", exc=RuntimeError("chip fell over"),
                times=1,
            ):
                h = door.submit(p, 40)
                comp = h.result(timeout=30.0)
            assert comp.finish_reason == "error"
            _wait_until(
                lambda: door.engine is not None
                and door.engine.spec_k == 7,
                what="rebuilt spec engine",
            )
            # the rebuilt engine speculates and stays golden
            h2 = door.submit(p, 12)
            assert np.array_equal(
                h2.result(timeout=30.0).tokens,
                _reference(params, p, 12),
            )
            assert door.stats()["watchdog_restarts"] == 1

    def test_dense_factory_with_spec_fails_construction(self, params):
        from znicz_tpu.services import SpeculationUnsupportedError

        def bad_factory():
            return DecodeEngine(
                params, n_heads=HEADS, eos_id=EOS, spec_k=4
            )

        with pytest.raises(SpeculationUnsupportedError):
            ServingFrontDoor(bad_factory, max_pending=4)
