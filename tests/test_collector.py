"""Fleet tracing: one merged timeline from router hop to engine chunk.

The collector contract (docs/OBSERVABILITY.md "Distributed tracing"):
replicas and the router push bounded span batches to a TraceCollector
(aggregator-shaped: instance-tagged, TTL-expired), and ``GET /trace``
answers ONE Perfetto-loadable Chrome trace with pid=instance and every
instance rebased onto a shared wall-clock epoch — so filtering a single
client-visible trace id shows the request's whole life: the router's
route/retry instants, BOTH replicas' queue/prefill/decode spans across
a mid-stream failover, preemptions included.  Trace-context propagation
makes the filter possible: the router mints the id, forwards it via
``X-Znicz-Trace-Id``, and the replica adopts it instead of minting its
own.
"""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from znicz_tpu import observability as obs
from znicz_tpu.cluster import ServingRouter, build_router_server
from znicz_tpu.core import prng
from znicz_tpu.observability.collector import (
    TraceCollector,
    TracePusher,
    build_collector_server,
)
from znicz_tpu.observability.tracing import Tracer
from znicz_tpu.services import PagedDecodeEngine, ServingFrontDoor
from znicz_tpu.services import serve as serve_mod
from znicz_tpu.utils import faults
from znicz_tpu.workflow.transformer import init_lm_params

EOS = 14
HEADS = 4
T_MAX = 64
BS = 8


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def params():
    prng.seed_all(27)
    return init_lm_params(17, 32, 2, HEADS, max_seq=T_MAX)


def _engine_kwargs(**kw):
    kw.setdefault("n_heads", HEADS)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("batch_size", 2)
    kw.setdefault("block_size", BS)
    kw.setdefault("max_seq", T_MAX)
    kw.setdefault("admit_every", 4)
    return kw


@pytest.fixture(scope="module", autouse=True)
def _warm(params):
    """Compile every program the fleet scenario runs BEFORE any traced
    request, so the zero-new-compiled-programs pin below measures the
    tracing layer, not a cold jit cache."""
    eng = PagedDecodeEngine(params, **_engine_kwargs())
    gen = np.random.default_rng(3)
    eng.submit(gen.integers(0, 17, (21,)).astype(np.int32), 30)
    eng.submit(gen.integers(0, 17, (5,)).astype(np.int32), 8)
    eng.run()


def _wait_until(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _ev(name, ts=0.0, ph="X", **args):
    ev = {"name": name, "ph": ph, "ts": ts, "pid": 1, "tid": 1}
    if args:
        ev["args"] = args
    return ev


# -- unit: the tracer's fleet hooks -----------------------------------------


class TestTracerFleetHooks:
    def test_default_instance_tag_stamped_explicit_wins(self):
        t = Tracer()
        t.start()
        t.set_instance("rep-9")
        with t.span("x"):
            pass
        t.instant("y", instance="other")
        events = t.stop()
        by_name = {e["name"]: e for e in events}
        assert by_name["x"]["args"]["instance"] == "rep-9"
        assert by_name["y"]["args"]["instance"] == "other"

    def test_sink_receives_events_and_is_bounded(self):
        t = Tracer()
        t.start()
        q = t.add_sink(maxlen=3)
        for i in range(5):
            t.instant("e", i=i)
        assert len(q) == 3  # oldest dropped, bounded
        assert [e["args"]["i"] for e in q] == [2, 3, 4]
        t.remove_sink(q)
        t.instant("after")
        assert len(q) == 3  # detached: no longer fed
        t.stop()

    def test_ensure_recording_starts_once(self):
        t = Tracer()
        assert t.ensure_recording() is True
        assert t.recording
        assert t.ensure_recording() is False  # already on
        t.stop()


# -- unit: the collector ----------------------------------------------------


class TestTraceCollector:
    def test_merged_pids_metadata_and_instances(self):
        col = TraceCollector()
        col.push("rep-a", [_ev("serve/admit", 10.0)], now=0.0)
        col.push("rep-b", [_ev("serve/admit", 20.0)], now=0.0)
        merged = col.merged_trace(now=1.0)
        meta = [e for e in merged["traceEvents"] if e["ph"] == "M"]
        names = {e["args"]["name"]: e["pid"] for e in meta}
        assert set(names) == {"rep-a", "rep-b"}
        assert len(set(names.values())) == 2  # distinct pids
        spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
        assert {e["pid"] for e in spans} == set(names.values())
        assert merged["instances"] == ["rep-a", "rep-b"]

    def test_event_instance_tag_splits_one_envelope(self):
        # an in-process fleet pushes through ONE pusher; the per-event
        # instance args still split the merged view into tracks
        col = TraceCollector()
        col.push(
            "proc",
            [
                _ev("a", instance="rep-0"),
                _ev("b", instance="rep-1"),
                _ev("c"),  # untagged: envelope instance
            ],
            now=0.0,
        )
        merged = col.merged_trace(now=0.5)
        assert merged["instances"] == ["proc", "rep-0", "rep-1"]

    def test_epoch_rebase_onto_shared_timeline(self):
        col = TraceCollector()
        col.push("a", [_ev("x", ts=5.0)], epoch_us=1_000_000.0, now=0.0)
        col.push("b", [_ev("y", ts=5.0)], epoch_us=2_500_000.0, now=0.0)
        spans = {
            e["name"]: e
            for e in col.merged_trace(now=0.5)["traceEvents"]
            if e["ph"] == "X"
        }
        assert spans["x"]["ts"] == 5.0  # earliest epoch is the base
        assert spans["y"]["ts"] == 1_500_005.0

    def test_ttl_expiry_drops_instance(self):
        col = TraceCollector()
        col.push("short", [_ev("x")], ttl_s=1.0, now=0.0)
        col.push("long", [_ev("y")], ttl_s=100.0, now=0.0)
        assert len(col.instances(now=0.5)) == 2
        inst = col.instances(now=5.0)
        assert [i["instance"] for i in inst] == ["long"]
        names = [
            e["name"]
            for e in col.merged_trace(now=5.0)["traceEvents"]
            if e["ph"] == "X"
        ]
        assert names == ["y"]

    def test_trace_id_filter_matches_all_arg_conventions(self):
        col = TraceCollector()
        col.push(
            "a",
            [
                _ev("serve/queued", trace="T1"),
                _ev("frontdoor/submit", id="T1"),
                _ev("serve/decode", traces="T1,T2"),
                _ev("serve/decode", traces="T21,T3"),  # no substring hit
                _ev("other", trace="T9"),
            ],
            now=0.0,
        )
        got = [
            e["name"]
            for e in col.merged_trace("T1", now=0.5)["traceEvents"]
            if e["ph"] != "M"
        ]
        assert got == [
            "serve/queued", "frontdoor/submit", "serve/decode"
        ]

    def test_filter_keeps_collision_suffixed_ids(self):
        """The front door adopts a duplicate inbound id as
        ``<id>-r<n>``; filtering by the client's original id must
        still surface that request's lifecycle (and not over-match
        ids that merely share a prefix)."""
        col = TraceCollector()
        col.push(
            "a",
            [
                _ev("serve/queued", trace="T1"),
                _ev("serve/queued", trace="T1-r0003"),
                _ev("serve/decode", traces="T1-r0003,Z9"),
                _ev("serve/queued", trace="T12"),  # prefix, no -r
                # a DIFFERENT client-chosen id sharing the "-r" prefix
                # (only all-digit suffixes are the collision spelling)
                _ev("serve/queued", trace="T1-run2"),
            ],
            now=0.0,
        )
        got = [
            (e["name"], (e.get("args") or {}))
            for e in col.merged_trace("T1", now=0.5)["traceEvents"]
            if e["ph"] != "M"
        ]
        assert len(got) == 3
        assert all(
            args.get("trace") not in ("T12", "T1-run2")
            for _, args in got
        )

    def test_instances_report_age_and_window_drops(self):
        col = TraceCollector(max_events_per_instance=4)
        col.push("a", [_ev("e", i) for i in range(6)], now=1.0)
        row = col.instances(now=3.5)[0]
        assert row["age_s"] == 2.5  # last-push age, the satellite pin
        assert row["events"] == 4 and row["dropped"] == 2

    def test_bad_pushes_raise_value_error(self):
        col = TraceCollector()
        with pytest.raises(ValueError):
            col.push("", [_ev("x")])
        with pytest.raises(ValueError):
            col.push("a", {"not": "a list"})
        with pytest.raises(ValueError):
            col.push("a", [_ev("x"), "not-a-dict"])
        with pytest.raises(ValueError):
            col.push("a", [], ttl_s=0.0)
        assert col.instances() == []  # nothing partially applied


# -- the HTTP surface -------------------------------------------------------


@pytest.fixture
def collector_srv():
    srv = build_collector_server(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _http(port, method, path, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(
            method, path,
            body=json.dumps(body) if body is not None else None,
            headers={"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


class TestCollectorHTTP:
    def test_push_trace_instances_healthz(self, collector_srv):
        port = collector_srv.server_address[1]
        status, body = _http(
            port, "POST", "/push",
            {"instance": "i1", "events": [_ev("x", trace="T")],
             "epoch_us": 0.0},
        )
        assert status == 200 and body["accepted"] == 1
        status, merged = _http(port, "GET", "/trace")
        assert status == 200
        assert any(
            e["name"] == "x" for e in merged["traceEvents"]
        )
        status, merged = _http(port, "GET", "/trace?trace_id=T")
        assert [
            e["name"] for e in merged["traceEvents"] if e["ph"] != "M"
        ] == ["x"]
        status, inst = _http(port, "GET", "/instances")
        assert status == 200 and inst["live"] == 1
        conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=10
        )
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
        conn.close()

    def test_bad_push_400_unknown_404(self, collector_srv):
        port = collector_srv.server_address[1]
        status, body = _http(port, "POST", "/push", {"events": []})
        assert status == 400 and body["error"] == "bad_push"
        status, _ = _http(
            port, "POST", "/push", {"instance": "i", "events": "nope"}
        )
        assert status == 400
        status, _ = _http(port, "GET", "/nope")
        assert status == 404
        status, _ = _http(port, "POST", "/nope", {})
        assert status == 404


# -- the pusher -------------------------------------------------------------


class TestTracePusher:
    def test_end_to_end_push_and_final_flush(self, collector_srv):
        port = collector_srv.server_address[1]
        t = Tracer()
        t.start()
        t.set_instance("push-1")
        pusher = TracePusher(
            f"http://127.0.0.1:{port}", instance="push-1", tracer=t,
            interval_s=30.0,  # the test drives pushes itself
        )
        with t.span("serve/admit", trace="T7"):
            pass
        assert pusher.push_now() is True
        merged = collector_srv.collector.merged_trace()
        assert any(
            e["name"] == "serve/admit" for e in merged["traceEvents"]
        )
        # events queued after the last manual push flush on stop()
        pusher.start()
        t.instant("late", trace="T7")
        pusher.stop()
        assert any(
            e["name"] == "late"
            for e in collector_srv.collector.merged_trace()["traceEvents"]
        )
        assert q_detached(t, pusher)
        t.stop()

    def test_never_raises_dead_collector_and_fault(self):
        t = Tracer()
        t.start()
        pusher = TracePusher(
            "http://127.0.0.1:9", instance="p", tracer=t,  # dead port
        )
        t.instant("x")
        assert pusher.push_now() is False
        assert pusher.pushes_failed == 1
        faults.inject("trace_pusher.push")
        pusher2 = TracePusher(
            "http://127.0.0.1:9", instance="p2", tracer=t
        )
        assert pusher2.push_now() is False  # fault path, still no raise
        t.stop()

    def test_bad_url_rejected(self):
        with pytest.raises(ValueError):
            TracePusher("ftp://nope")


def q_detached(tracer, pusher) -> bool:
    with tracer._lock:
        return pusher._queue not in tracer._sinks


class TestSharedPusher:
    def test_attachments_share_one_pusher_no_duplicate_spans(
        self, collector_srv
    ):
        """An in-process colocation (two doors + a router on one
        tracer) must NOT push every span once per component — attach
        returns the same pusher, and the last detach stops it."""
        from znicz_tpu.observability.collector import (
            attach_pusher,
            detach_pusher,
        )

        url = f"http://127.0.0.1:{collector_srv.server_address[1]}"
        t = Tracer()
        t.start()
        p1 = attach_pusher(url, instance="rep-0", tracer=t,
                           interval_s=30.0)
        p2 = attach_pusher(url, instance="rep-1", tracer=t)
        try:
            assert p1 is p2  # shared, not a second sink
            t.instant("once", trace="S1")
            p1.push_now()
            merged = collector_srv.collector.merged_trace("S1")
            spans = [
                e for e in merged["traceEvents"] if e["ph"] != "M"
            ]
            assert len(spans) == 1  # ONE copy, not one per attachment
            detach_pusher(p1)
            assert not q_detached(t, p1)  # rep-1 still attached
        finally:
            detach_pusher(p2)
        assert q_detached(t, p1)  # last detach stopped + unhooked
        t.stop()

    def test_later_attachment_tightens_the_cadence(self, collector_srv):
        from znicz_tpu.observability.collector import (
            attach_pusher,
            detach_pusher,
        )

        url = f"http://127.0.0.1:{collector_srv.server_address[1]}"
        t = Tracer()
        t.start()
        p1 = attach_pusher(url, tracer=t, interval_s=2.0)
        ttl0 = p1.ttl_s
        p2 = attach_pusher(url, tracer=t, interval_s=0.25)
        try:
            # the shared pusher runs at the FASTEST requested cadence
            assert p1 is p2 and p1.interval_s == 0.25
            assert p1.ttl_s == pytest.approx(ttl0 * 0.25 / 2.0)
            # a slower later attachment does not loosen it back
            p3 = attach_pusher(url, tracer=t, interval_s=5.0)
            assert p3.interval_s == 0.25
            detach_pusher(p3)
        finally:
            detach_pusher(p1)
            detach_pusher(p2)
            t.stop()

    def test_doors_sharing_a_collector_share_the_pusher(
        self, params, collector_srv
    ):
        url = f"http://127.0.0.1:{collector_srv.server_address[1]}"
        doors = [
            ServingFrontDoor(
                lambda: PagedDecodeEngine(params, **_engine_kwargs()),
                max_pending=4,
                instance=f"share-{i}",
                collector_url=url,
            )
            for i in range(2)
        ]
        try:
            assert doors[0]._trace_pusher is doors[1]._trace_pusher
        finally:
            for door in doors:
                door.close(grace_s=10.0)
        tracer = obs.get_tracer()
        if tracer.recording:
            tracer.stop()

    def test_bad_collector_url_fails_fast_without_leaking(self, params):
        """A malformed collector_url must abort the constructor with
        no background pusher thread left behind (the metrics pusher
        was previously started first and leaked)."""
        before = {
            th.name
            for th in threading.enumerate()
            if th.name.startswith("znicz-pusher")
            or th.name.startswith("znicz-trace-pusher")
        }
        with pytest.raises(ValueError):
            ServingFrontDoor(
                lambda: PagedDecodeEngine(params, **_engine_kwargs()),
                max_pending=4,
                instance="leaky",
                aggregator_url="http://127.0.0.1:9",
                collector_url="not-a-url",
            )
        after = {
            th.name
            for th in threading.enumerate()
            if th.name.startswith("znicz-pusher")
            or th.name.startswith("znicz-trace-pusher")
        }
        assert after == before
        tracer = obs.get_tracer()
        if tracer.recording:  # ensure_recording ran before the raise
            tracer.stop()


# -- trace-context propagation over HTTP ------------------------------------


class TestTraceIdPropagation:
    @pytest.fixture
    def replica(self, params):
        door = ServingFrontDoor(
            lambda: PagedDecodeEngine(params, **_engine_kwargs()),
            max_pending=8,
            instance="rep-solo",
        )
        srv = serve_mod.build_server(directory=".", port=0, frontdoor=door)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield door, srv
        srv.shutdown()
        srv.server_close()
        door.close(grace_s=10.0)

    def _post(self, port, prompt, trace_id=None):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            headers = {"Content-Type": "application/json"}
            if trace_id:
                headers["X-Znicz-Trace-Id"] = trace_id
            conn.request(
                "POST", "/generate",
                body=json.dumps(
                    {"prompt": [int(x) for x in prompt],
                     "max_new_tokens": 6}
                ),
                headers=headers,
            )
            resp = conn.getresponse()
            out = {
                "status": resp.status,
                "trace_header": resp.getheader("X-Znicz-Trace-Id"),
                "done": None,
            }
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if rec.get("done"):
                    out["done"] = rec
            return out
        finally:
            conn.close()

    def test_inbound_header_becomes_the_request_id(self, replica):
        door, srv = replica
        gen = np.random.default_rng(5)
        prompt = gen.integers(0, 17, (9,)).astype(np.int32)
        r = self._post(
            srv.server_address[1], prompt, trace_id="client-abc-001"
        )
        assert r["status"] == 200
        assert r["trace_header"] == "client-abc-001"
        assert r["done"]["trace_id"] == "client-abc-001"

    def test_without_header_the_door_mints(self, replica):
        door, srv = replica
        gen = np.random.default_rng(6)
        prompt = gen.integers(0, 17, (5,)).astype(np.int32)
        r = self._post(srv.server_address[1], prompt)
        assert r["status"] == 200
        assert r["trace_header"].startswith("znicz-")

    def test_live_collision_keeps_the_id_as_prefix(self, replica):
        door, _ = replica
        with door._lock:
            door._by_id["dup-1"] = object()  # membership is all it reads
            tid = door._mint_id("dup-1")
        with door._lock:
            door._by_id.pop("dup-1")
        assert tid.startswith("dup-1-r")


# -- the acceptance scenario ------------------------------------------------


class _TracedFleet:
    """Two named replicas behind a router, spans flowing to a real
    collector through ONE pusher on the process tracer (the in-process
    twin of per-process pushers; per-event instance tags split the
    merged view)."""

    def __init__(self, params):
        self.doors, self.srvs = [], []
        for i in range(2):
            door = ServingFrontDoor(
                lambda: PagedDecodeEngine(params, **_engine_kwargs()),
                max_pending=8,
                instance=f"rep-{i}",
            )
            srv = serve_mod.build_server(
                directory=".", port=0, frontdoor=door
            )
            threading.Thread(target=srv.serve_forever, daemon=True).start()
            self.doors.append(door)
            self.srvs.append(srv)
        self.router = ServingRouter(
            block_size=BS, heartbeat_interval_s=60.0
        )
        for i, srv in enumerate(self.srvs):
            self.router.register(
                f"rep-{i}",
                f"http://127.0.0.1:{srv.server_address[1]}",
            )
        self.rsrv = build_router_server(self.router, port=0)
        threading.Thread(
            target=self.rsrv.serve_forever, daemon=True
        ).start()
        self.port = self.rsrv.server_address[1]

    def post(self, prompt, max_new=12):
        conn = http.client.HTTPConnection(
            "127.0.0.1", self.port, timeout=60
        )
        try:
            conn.request(
                "POST", "/generate",
                body=json.dumps(
                    {"prompt": [int(t) for t in prompt],
                     "max_new_tokens": max_new}
                ),
            )
            resp = conn.getresponse()
            out = {
                "status": resp.status,
                "trace_header": resp.getheader("X-Znicz-Trace-Id"),
                "tokens": [],
                "done": None,
            }
            while True:
                line = resp.readline()
                if not line:
                    break
                rec = json.loads(line)
                if "token" in rec:
                    out["tokens"].append(rec["token"])
                elif rec.get("done"):
                    out["done"] = rec
            return out
        finally:
            conn.close()

    def close(self):
        for srv in self.srvs:
            srv.shutdown()
            srv.server_close()
        self.rsrv.shutdown()
        self.rsrv.server_close()
        for door in self.doors:
            door.close(grace_s=10.0)
        self.router.close()


class TestMergedFleetTimeline:
    def test_one_trace_id_shows_the_full_cross_replica_life(
        self, params, collector_srv
    ):
        """THE acceptance scenario: a request replayed through the
        cluster proxy with an injected mid-stream replica crash yields
        ONE merged Chrome trace in which the client-visible trace id
        filters to the router's route/retry instants AND both replicas'
        queue/prefill/decode spans on a shared timeline — and the
        tracing layer itself compiled nothing."""
        from znicz_tpu.observability import device

        tracer = obs.get_tracer()
        if tracer.recording:
            tracer.stop()
        tracer.start()
        fleet = _TracedFleet(params)
        pusher = TracePusher(
            f"http://127.0.0.1:{collector_srv.server_address[1]}",
            instance="proc",
            tracer=tracer,
            interval_s=30.0,  # pushed by hand below
        )
        try:
            programs_before = device.program_count()
            gen = np.random.default_rng(37)
            prompt = gen.integers(0, 17, (2 * BS + 3,)).astype(np.int32)
            # 2 token records pass, then the router's upstream read
            # dies: a mid-stream replica crash from the router's view
            faults.inject("router.stream", after=2, times=1)
            r = fleet.post(prompt)
            assert r["status"] == 200
            assert r["done"]["router"]["retries"] == 1
            tid = r["trace_header"]
            assert tid and tid == r["done"]["trace_id"]
            assert tid.startswith("znicz-router-")  # router-minted

            col = collector_srv.collector

            def filtered():
                pusher.push_now()
                merged = col.merged_trace(tid)
                return [
                    e for e in merged["traceEvents"] if e["ph"] != "M"
                ]

            def instances_of(events):
                return {
                    (e.get("args") or {}).get("instance")
                    for e in events
                }

            # the cancelled first replica retires on its next tick —
            # wait until BOTH replicas' spans carry the id
            _wait_until(
                lambda: {"rep-0", "rep-1"} <= instances_of(filtered()),
                what="both replicas' spans under one trace id",
            )
            events = filtered()
            names = [e["name"] for e in events]
            # the router hop: initial route + post-crash retry + reroute
            assert names.count("router/route") == 2
            assert names.count("router/retry") == 1
            assert "router/done" in names
            # replica lifecycle under the SAME id, on both instances
            for rep in ("rep-0", "rep-1"):
                rep_names = {
                    e["name"] for e in events
                    if (e.get("args") or {}).get("instance") == rep
                }
                assert "frontdoor/submit" in rep_names
                assert "serve/queued" in rep_names
                assert "serve/admit" in rep_names, rep_names
                assert "serve/decode" in rep_names
            # one shared timeline: every event timestamped, and the
            # merged view splits into ≥3 pids (router + two replicas)
            assert all(isinstance(e.get("ts"), float) for e in events)
            merged = col.merged_trace(tid)
            meta = {
                e["args"]["name"]
                for e in merged["traceEvents"] if e["ph"] == "M"
            }
            assert {"rep-0", "rep-1"} <= meta
            assert any("router" in m for m in meta)
            # the tracing layer added ZERO compiled programs
            assert device.program_count() == programs_before
        finally:
            pusher.stop()
            fleet.close()
            faults.clear()
            if tracer.recording:
                tracer.stop()
