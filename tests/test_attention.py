"""Attention + ring-attention sequence parallelism tests (8-device mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from znicz_tpu.ops import attention
from znicz_tpu.parallel import make_mesh
from znicz_tpu.parallel.ring_attention import ring_attention


def _qkv(b=2, t=32, h=4, d=16, seed=0):
    keys = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in keys)


class TestDotProductAttention:
    def test_softmax_rows_sum_to_one_effect(self):
        q, k, v = _qkv()
        ones = jnp.ones_like(v)
        out = attention.dot_product_attention(q, k, ones)
        np.testing.assert_allclose(out, 1.0, rtol=1e-5)

    def test_causal_first_token_attends_self_only(self):
        q, k, v = _qkv()
        out = attention.dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            out[:, 0], v[:, 0], rtol=1e-5, atol=1e-6
        )

    def test_mha_shapes(self):
        from znicz_tpu.core import prng

        prng.seed_all(3)
        params = attention.init_mha_params(32, 4)
        x = jax.random.normal(jax.random.key(1), (2, 10, 32))
        y = attention.mha(params, x, n_heads=4)
        assert y.shape == (2, 10, 32)


class TestPagedAttention:
    """Block-table attention (docs/SERVING.md paged KV): gathered-window
    numerics must equal a dense masked softmax over the same keys,
    whatever (shuffled) block assignment the table holds."""

    def _paged_setup(self, b=2, t=32, h=2, d=8, bs=8, seed=0):
        rng = np.random.default_rng(seed)
        m = t // bs
        k = rng.normal(size=(b, t, h, d)).astype(np.float32)
        v = rng.normal(size=(b, t, h, d)).astype(np.float32)
        # scatter each row's contiguous K/V into a shared pool under a
        # SHUFFLED block assignment (block 0 reserved null, as served)
        n_blocks = 1 + b * m
        table = (
            rng.permutation(np.arange(1, n_blocks))
            .reshape(b, m)
            .astype(np.int32)
        )
        k_pool = np.zeros((n_blocks, bs, h, d), np.float32)
        v_pool = np.zeros((n_blocks, bs, h, d), np.float32)
        for row in range(b):
            for j in range(m):
                k_pool[table[row, j]] = k[row, j * bs:(j + 1) * bs]
                v_pool[table[row, j]] = v[row, j * bs:(j + 1) * bs]
        return k, v, k_pool, v_pool, table

    @staticmethod
    def _dense_ref(q, k, v, q_pos, start):
        """Masked stable softmax per row, numpy — the paged contract."""
        b, tq, h, d = q.shape
        out = np.zeros_like(q)
        for row in range(b):
            for qi in range(tq):
                p = q_pos[row, qi]
                lo = min(start[row], p)
                s = np.einsum(
                    "hd,khd->hk", q[row, qi], k[row]
                ) / np.sqrt(d)
                mask = np.zeros(k.shape[1], bool)
                mask[lo: p + 1] = True
                s = np.where(mask[None, :], s, -np.inf)
                e = np.exp(s - s.max(axis=-1, keepdims=True))
                w = e / e.sum(axis=-1, keepdims=True)
                out[row, qi] = np.einsum("hk,khd->hd", w, v[row])
        return out

    def test_decode_step_matches_dense_masked_softmax(self):
        bs = 8
        k, v, k_pool, v_pool, table = self._paged_setup(bs=bs)
        rng = np.random.default_rng(1)
        q = rng.normal(size=(2, 1, 2, 8)).astype(np.float32)
        pos = np.asarray([[13], [29]], np.int32)
        start = np.asarray([3, 0], np.int32)
        out = attention.paged_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(pos), block_size=bs,
            start=jnp.asarray(start),
        )
        ref = self._dense_ref(q, k, v, pos, start)
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=2e-5, atol=2e-6
        )

    def test_prefill_chunk_queries_match(self):
        # a whole chunk of queries at consecutive positions (the
        # chunked-prefill shape), pad-region queries included: their
        # window collapses to the self position and stays finite
        bs = 8
        k, v, k_pool, v_pool, table = self._paged_setup(bs=bs)
        rng = np.random.default_rng(2)
        q = rng.normal(size=(2, bs, 2, 8)).astype(np.float32)
        q_pos = np.broadcast_to(np.arange(bs), (2, bs)).astype(np.int32)
        start = np.asarray([5, 0], np.int32)  # row 0: pad queries 0..4
        out = attention.paged_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(q_pos), block_size=bs,
            start=jnp.asarray(start),
        )
        ref = self._dense_ref(q, k, v, q_pos, start)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=2e-5, atol=2e-6
        )

    def test_stale_blocks_cannot_leak(self):
        # poison every pool block the tables do NOT cover a row's valid
        # window with: garbage past pos / outside the table must not
        # change the output (masking is by index, never by value)
        bs = 8
        k, v, k_pool, v_pool, table = self._paged_setup(bs=bs)
        rng = np.random.default_rng(3)
        q = rng.normal(size=(2, 1, 2, 8)).astype(np.float32)
        pos = np.asarray([[10], [3]], np.int32)
        start = np.asarray([2, 0], np.int32)
        clean = attention.paged_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(pos), block_size=bs,
            start=jnp.asarray(start),
        )
        kp, vp = k_pool.copy(), v_pool.copy()
        for row in range(2):
            p = int(pos[row, 0])
            jb, slot = p // bs, p % bs
            kp[table[row, jb], slot + 1:] = 1e9  # rest of the live block
            vp[table[row, jb], slot + 1:] = 1e9
            for j in range(jb + 1, table.shape[1]):  # blocks past pos
                kp[table[row, j]] = 1e9
                vp[table[row, j]] = 1e9
        kp[0] = 1e9  # the null block
        vp[0] = 1e9
        poisoned = attention.paged_attention(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(table), jnp.asarray(pos), block_size=bs,
            start=jnp.asarray(start),
        )
        np.testing.assert_allclose(
            np.asarray(clean), np.asarray(poisoned), rtol=1e-6
        )

    def test_many_tables_one_block_aliasing(self):
        # prefix sharing maps ONE physical block into MANY tables: each
        # row's output must equal the dense reference over the content
        # its own table resolves to — the gather must not care that a
        # block id repeats across rows
        bs = 8
        rng = np.random.default_rng(5)
        b, t, h, d = 3, 24, 2, 8
        m = t // bs
        shared = rng.normal(size=(bs, h, d)).astype(np.float32)
        shared_v = rng.normal(size=(bs, h, d)).astype(np.float32)
        k = rng.normal(size=(b, t, h, d)).astype(np.float32)
        v = rng.normal(size=(b, t, h, d)).astype(np.float32)
        # every row's FIRST block is the shared prefix content
        k[:, :bs] = shared
        v[:, :bs] = shared_v
        # pool: block 1 = the one shared block; per-row private tails
        n_blocks = 2 + b * (m - 1)
        k_pool = np.zeros((n_blocks, bs, h, d), np.float32)
        v_pool = np.zeros((n_blocks, bs, h, d), np.float32)
        k_pool[1], v_pool[1] = shared, shared_v
        table = np.zeros((b, m), np.int32)
        table[:, 0] = 1  # ALIASED: all three tables point at block 1
        nxt = 2
        for row in range(b):
            for j in range(1, m):
                table[row, j] = nxt
                k_pool[nxt] = k[row, j * bs:(j + 1) * bs]
                v_pool[nxt] = v[row, j * bs:(j + 1) * bs]
                nxt += 1
        rngq = np.random.default_rng(6)
        q = rngq.normal(size=(b, 1, h, d)).astype(np.float32)
        # rows at DIFFERENT depths through the same shared block: row 0
        # still inside it, rows 1/2 past it
        pos = np.asarray([[5], [13], [21]], np.int32)
        start = np.zeros((b,), np.int32)
        out = attention.paged_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(pos), block_size=bs,
            start=jnp.asarray(start),
        )
        ref = self._dense_ref(q, k, v, pos, start)
        np.testing.assert_allclose(
            np.asarray(out), ref, rtol=2e-5, atol=2e-6
        )

    def test_aliased_block_validity_is_per_row(self):
        # poison-grade check for aliasing: positions of the SHARED
        # block past a shallow row's pos are real live content for a
        # deeper row.  Perturbing them must leave the shallow row's
        # output bit-identical (masked by index) while changing the
        # deeper row's (it genuinely attends them).
        bs = 8
        rng = np.random.default_rng(7)
        h, d = 2, 8
        shared_k = rng.normal(size=(bs, h, d)).astype(np.float32)
        shared_v = rng.normal(size=(bs, h, d)).astype(np.float32)
        k_pool = np.zeros((3, bs, h, d), np.float32)
        v_pool = np.zeros((3, bs, h, d), np.float32)
        k_pool[1], v_pool[1] = shared_k, shared_v
        k_pool[2] = rng.normal(size=(bs, h, d)).astype(np.float32)
        v_pool[2] = rng.normal(size=(bs, h, d)).astype(np.float32)
        table = np.asarray([[1, 0], [1, 2]], np.int32)
        q = rng.normal(size=(2, 1, h, d)).astype(np.float32)
        pos = np.asarray([[3], [11]], np.int32)  # row 0 shallow, row 1 deep

        def run(kp, vp):
            return np.asarray(
                attention.paged_attention(
                    jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                    jnp.asarray(table), jnp.asarray(pos), block_size=bs,
                )
            )

        clean = run(k_pool, v_pool)
        kp, vp = k_pool.copy(), v_pool.copy()
        kp[1, 5:] += 3.0  # rewrite shared-block positions 5..7
        vp[1, 5:] += 3.0
        pert = run(kp, vp)
        np.testing.assert_array_equal(clean[0], pert[0])  # masked out
        assert np.abs(clean[1] - pert[1]).max() > 1e-6  # really attended

    def test_pallas_stub_delegates_to_reference(self):
        from znicz_tpu.ops.pallas import paged_attention as pp

        bs = 8
        _, _, k_pool, v_pool, table = self._paged_setup(bs=bs)
        rng = np.random.default_rng(4)
        q = rng.normal(size=(2, 1, 2, 8)).astype(np.float32)
        pos = np.asarray([[9], [17]], np.int32)
        ref = attention.paged_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(pos), block_size=bs,
        )
        out = pp.paged_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(table), jnp.asarray(pos), block_size=bs,
        )
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
        assert pp.PALLAS_PAGED_IMPLEMENTED is False


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_single_device(self, causal):
        mesh = make_mesh(8, 1)
        q, k, v = _qkv(b=2, t=64, h=4, d=16, seed=7)
        ref = attention.dot_product_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    def test_long_sequence_grad_flows(self):
        mesh = make_mesh(8, 1)
        q, k, v = _qkv(b=1, t=128, h=2, d=8, seed=9)

        def loss(q, k, v):
            return jnp.sum(
                jnp.square(ring_attention(q, k, v, mesh=mesh, causal=True))
            )

        g = jax.grad(loss)(q, k, v)
        ref_g = jax.grad(
            lambda q, k, v: jnp.sum(
                jnp.square(
                    attention.dot_product_attention(q, k, v, causal=True)
                )
            )
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(ref_g), rtol=1e-4, atol=1e-5
        )

    def test_under_jit_with_sharded_inputs(self):
        mesh = make_mesh(8, 1)
        from jax.sharding import NamedSharding, PartitionSpec as P

        q, k, v = _qkv(b=2, t=64, h=4, d=16, seed=11)
        sharding = NamedSharding(mesh, P(None, "data", None, None))
        qs, ks, vs = (jax.device_put(a, sharding) for a in (q, k, v))
        f = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=True)
        )
        out = f(qs, ks, vs)
        ref = attention.dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )


class TestFlashLse:
    def test_lse_matches_dense_logsumexp(self):
        from znicz_tpu.ops.pallas.attention import flash_attention_lse

        q, k, v = _qkv(b=1, t=48, h=2, d=16, seed=3)
        out, lse = flash_attention_lse(q, k, v, causal=True)
        ref = attention.dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )
        # reference logsumexp over the causal score rows
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        t = q.shape[1]
        mask = np.tril(np.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        ref_lse = jax.scipy.special.logsumexp(s, axis=-1)  # [B, H, T]
        np.testing.assert_allclose(
            np.asarray(lse),
            np.asarray(ref_lse.transpose(0, 2, 1)),
            rtol=1e-5, atol=1e-5,
        )

    def test_lse_gradient_flows(self):
        """The lse OUTPUT must carry gradient (ring combination uses it)."""
        from znicz_tpu.ops.pallas.attention import flash_attention_lse

        q, k, v = _qkv(b=1, t=32, h=2, d=8, seed=5)

        def loss(q, k, v):
            out, lse = flash_attention_lse(q, k, v, causal=True)
            return jnp.sum(jnp.square(out)) + jnp.sum(jnp.square(lse))

        def ref_loss(q, k, v):
            scale = 1.0 / np.sqrt(q.shape[-1])
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
            t = q.shape[1]
            mask = np.tril(np.ones((t, t), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
            lse = jax.scipy.special.logsumexp(s, axis=-1)
            return jnp.sum(jnp.square(out)) + jnp.sum(jnp.square(lse))

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        rg = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, rg):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )


class TestRingFlashInner:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_inner(self, causal):
        mesh = make_mesh(8, 1)
        q, k, v = _qkv(b=2, t=64, h=4, d=16, seed=13)
        ref = ring_attention(q, k, v, mesh=mesh, causal=causal)
        out = ring_attention(
            q, k, v, mesh=mesh, causal=causal, inner="flash"
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    def test_grads_match_single_device(self):
        mesh = make_mesh(8, 1)
        q, k, v = _qkv(b=1, t=64, h=2, d=8, seed=17)

        def loss(fn):
            return lambda q, k, v: jnp.sum(jnp.square(fn(q, k, v)))

        g = jax.grad(
            loss(
                lambda q, k, v: ring_attention(
                    q, k, v, mesh=mesh, causal=True, inner="flash"
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        rg = jax.grad(
            loss(
                lambda q, k, v: attention.dot_product_attention(
                    q, k, v, causal=True
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g, rg):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )

    def test_bf16_inputs_causal(self):
        # the causal lax.switch branches must agree on dtype (skip branch
        # emits f32 zeros) — regression for a trace-time TypeError
        mesh = make_mesh(8, 1)
        q, k, v = (
            x.astype(jnp.bfloat16)
            for x in _qkv(b=1, t=64, h=2, d=8, seed=19)
        )
        out = ring_attention(q, k, v, mesh=mesh, causal=True, inner="flash")
        assert out.dtype == jnp.bfloat16
        ref = ring_attention(q, k, v, mesh=mesh, causal=True, inner="dense")
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32),
            rtol=5e-2, atol=5e-2,
        )

    def test_bad_inner_rejected(self):
        mesh = make_mesh(8, 1)
        q, k, v = _qkv(b=1, t=16, h=1, d=8)
        with pytest.raises(ValueError, match="inner"):
            ring_attention(q, k, v, mesh=mesh, inner="blockwise")


class TestBf16FlashKernel:
    def test_bf16_flash_matches_f32_twin(self):
        # the kernel keeps input dtype on the MXU; bf16 q/k/v must still
        # reproduce the f32 jnp twin within bf16 mantissa tolerance
        from znicz_tpu.ops.pallas.attention import flash_attention

        q, k, v = _qkv(b=2, t=128, h=2, d=32, seed=7)
        ref = attention.dot_product_attention(q, k, v, causal=True)
        out = flash_attention(
            q.astype(jnp.bfloat16),
            k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
            causal=True,
        )
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref),
            rtol=3e-2, atol=3e-2,
        )

    def test_bf16_flash_grads_close_to_f32(self):
        from znicz_tpu.ops.pallas.attention import flash_attention

        q, k, v = _qkv(b=1, t=64, h=2, d=16, seed=9)

        def loss(fn, qkv):
            return jnp.sum(
                jnp.square(fn(*qkv, causal=True).astype(jnp.float32))
            )

        g_ref = jax.grad(
            lambda t: loss(attention.dot_product_attention, t)
        )((q, k, v))
        g_bf = jax.grad(lambda t: loss(flash_attention, t))(
            tuple(x.astype(jnp.bfloat16) for x in (q, k, v))
        )
        for a, b in zip(g_ref, g_bf):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b, np.float32),
                rtol=6e-2, atol=6e-2,
            )


class TestAttentionDtypeKnob:
    def test_bf16_attention_trains_close_to_f32(self):
        from znicz_tpu.core import prng
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.workflow.transformer import TransformerLMWorkflow

        tokens = np.random.default_rng(3).integers(
            0, 16, (32, 64)
        ).astype(np.int32)

        def run(dtype):
            prng.seed_all(61)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=2, attention="flash", attention_dtype=dtype,
            )
            wf.initialize(seed=61)
            return [h["train"]["loss"] for h in wf.run().history]

        f32 = run("f32")
        bf16 = run("bf16")
        np.testing.assert_allclose(f32, bf16, rtol=2e-2)

    def test_invalid_attention_dtype_rejected(self):
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.workflow.transformer import TransformerLMWorkflow

        tokens = np.zeros((8, 16), np.int32)
        ld = FullBatchLoader({"train": tokens}, minibatch_size=4)
        with pytest.raises(ValueError, match="attention_dtype"):
            TransformerLMWorkflow(
                ld, vocab=4, attention_dtype="fp8"
            )

    def test_bf16_attention_composes_with_sequence_parallel(self):
        # attention_dtype wraps the ring-attention path too: bf16 q/k/v
        # through the ring (flash inner) must train close to the f32 run
        from znicz_tpu.core import prng
        from znicz_tpu.loader.fullbatch import FullBatchLoader
        from znicz_tpu.parallel import DataParallel, make_mesh
        from znicz_tpu.workflow.transformer import TransformerLMWorkflow

        tokens = np.random.default_rng(5).integers(
            0, 16, (32, 64)
        ).astype(np.int32)
        mesh = make_mesh(8, 1)

        def run(dtype):
            prng.seed_all(67)
            ld = FullBatchLoader({"train": tokens.copy()}, minibatch_size=16)
            wf = TransformerLMWorkflow(
                ld, vocab=16, d_model=32, n_layers=2, n_heads=2,
                max_epochs=2, sequence_parallel=True, mesh=mesh,
                parallel=DataParallel(mesh), attention_dtype=dtype,
                # force the flash inner (auto resolves dense on the CPU
                # test backend) so bf16 x SP x flash is really exercised
                attention="flash",
            )
            wf.initialize(seed=67)
            return [h["train"]["loss"] for h in wf.run().history]

        f32 = run("f32")
        bf16 = run("bf16")
        np.testing.assert_allclose(f32, bf16, rtol=2e-2)
