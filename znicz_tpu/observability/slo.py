"""SLO evaluation: rolling percentiles and multi-window burn rates.

The front door (PR 6) produces TTFT and request-latency histograms and
typed error/shed counters; what the REMAINING SLO-aware-scheduling rung
(ROADMAP) needs is the judgment on top: "over the last minute / five
minutes / hour, what were p50/p95/p99, what fraction of requests blew
the target, and how fast is the error budget burning?"  This module is
that judgment, host-side and registry-fed:

* :class:`SLOTarget` — one declared objective: "``objective`` of
  requests must finish the ``metric`` histogram under ``threshold_s``"
  (e.g. 99% of TTFTs under 1 s).
* :class:`SLOMonitor` — keeps a bounded ring of timestamped registry
  captures; :meth:`snapshot` evaluates each target over each rolling
  window from CUMULATIVE-BUCKET DELTAS (the same interpolation rule the
  registry's own quantiles use), plus request/error/shed rates from
  counter deltas.  ``burn_rate = bad_fraction / (1 - objective)`` —
  1.0 means the error budget spends exactly as fast as it accrues; a
  target is **breached** when every window with data burns at or above
  ``breach_burn_rate`` (the classic multi-window AND: a transient spike
  trips only the short window, a recovered incident clears it, a real
  sustained burn trips both).
* :func:`evaluate_exposition` / :func:`lifetime_snapshot` — the
  windowless twins over a single Prometheus exposition or live
  registry (process-lifetime deltas from zero): what ``tools/znicz-slo``
  and the bench attach, and what CI gates on.

Exposed at ``GET /slo`` (:mod:`znicz_tpu.services.serve`) and as
:meth:`~znicz_tpu.services.frontdoor.ServingFrontDoor.slo_snapshot`.
Pure stdlib — importing this module must never pull in jax.
"""

from __future__ import annotations

import dataclasses
import json
import math
import sys
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from znicz_tpu.observability.registry import (
    MetricsRegistry,
    fraction_le,
    get_registry,
    parse_prometheus_text,
    quantile_from_cumulative,
)


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One latency objective over a registry histogram."""

    name: str  # e.g. "ttft"
    metric: str  # histogram family, e.g. znicz_serve_ttft_seconds
    threshold_s: float  # a request is "good" when under this
    objective: float = 0.99  # fraction of requests that must be good

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"{self.name}: want 0 < objective < 1; got "
                f"{self.objective}"
            )
        if self.threshold_s <= 0:
            raise ValueError(
                f"{self.name}: want threshold_s > 0; got "
                f"{self.threshold_s}"
            )


DEFAULT_TARGETS: Tuple[SLOTarget, ...] = (
    SLOTarget("ttft", "znicz_serve_ttft_seconds", 1.0, 0.99),
    SLOTarget(
        "latency", "znicz_serve_request_latency_seconds", 5.0, 0.99
    ),
)

# the front door's CLIENT-clock twins (submit -> first token /
# completion delivery, front-door queueing and tick cadence included).
# The engine-clock defaults above start at ENGINE submit and cannot see
# a deep pending queue — a replica gate should judge these instead
# (znicz-slo --frontdoor; ServingFrontDoor.slo_snapshot() already does).
FRONTDOOR_TARGETS: Tuple[SLOTarget, ...] = (
    SLOTarget("ttft", "znicz_serve_frontdoor_ttft_seconds", 1.0, 0.99),
    SLOTarget(
        "latency", "znicz_serve_frontdoor_latency_seconds", 5.0, 0.99
    ),
)

DEFAULT_WINDOWS_S: Tuple[float, ...] = (60.0, 300.0, 3600.0)

# counters the rates view reads (label-summed deltas per window)
_RATE_COUNTERS = {
    "requests": "znicz_serve_requests_submitted_total",
    "errors": ("znicz_serve_requests_retired_total", ("error",)),
    "sheds": "znicz_serve_rejected_total",
    "deadlines": "znicz_serve_deadline_exceeded_total",
    "cancels": "znicz_serve_cancelled_total",
}


def _capture(
    registry: MetricsRegistry, metrics: Sequence[str]
) -> dict:
    """One point-in-time state: per-histogram cumulative pairs summed
    across label-sets, and the watched counters (``reason``-filtered
    where declared)."""
    fams = registry.metrics()
    hists: Dict[str, dict] = {}
    for name in metrics:
        m = fams.get(name)
        if m is None or m.kind != "histogram":
            continue
        merged: Dict[float, float] = {}
        count, total = 0.0, 0.0
        for child in m.children().values():
            for upper, acc in child.cumulative():
                merged[upper] = merged.get(upper, 0.0) + acc
            count += child.count
            total += child.sum
        hists[name] = {
            "cum": sorted(merged.items()), "count": count, "sum": total
        }
    counters: Dict[str, float] = {}
    for key, spec in _RATE_COUNTERS.items():
        name, reasons = (
            spec if isinstance(spec, tuple) else (spec, None)
        )
        m = fams.get(name)
        if m is None or m.kind != "counter":
            counters[key] = 0.0
            continue
        v = 0.0
        for labels, child in m.children().items():
            if reasons is not None and not any(
                lv in reasons for lv in labels
            ):
                continue
            v += child.value
        counters[key] = v
    return {"hists": hists, "counters": counters}


def _delta_cum(cur: dict, base: Optional[dict]) -> List[Tuple[float, float]]:
    """current-minus-baseline cumulative pairs (baseline None = zero).
    Registries share one process-fixed ladder, so the edges line up;
    a mid-flight ladder change just clamps negatives to zero."""
    if base is None:
        return list(cur["cum"])
    base_map = dict(base["cum"])
    return [
        (upper, max(acc - base_map.get(upper, 0.0), 0.0))
        for upper, acc in cur["cum"]
    ]


def _eval_target(
    target: SLOTarget,
    cum: List[Tuple[float, float]],
    *,
    span_s: Optional[float],
) -> dict:
    n = cum[-1][1] if cum else 0.0
    good = fraction_le(cum, target.threshold_s) if n else 1.0
    bad = max(1.0 - good, 0.0)
    burn = bad / max(1.0 - target.objective, 1e-9)
    out = {
        "n": n,
        "p50_s": quantile_from_cumulative(cum, 0.5),
        "p95_s": quantile_from_cumulative(cum, 0.95),
        "p99_s": quantile_from_cumulative(cum, 0.99),
        "bad_frac": round(bad, 6),
        "burn_rate": round(burn, 4),
    }
    if span_s is not None:
        out["span_s"] = round(span_s, 3)
    return out


def _window_key(w: float) -> str:
    return str(int(w)) if float(w).is_integer() else str(w)


class SLOMonitor:
    """Rolling-window SLO evaluation over one registry.

    :meth:`sample` appends a timestamped capture to a bounded ring
    (call it on a cadence — the front door's engine thread does, every
    ``min_sample_gap_s``); :meth:`snapshot` takes a fresh capture and
    evaluates every target over every window against the ring.  A
    window with no baseline old enough uses the OLDEST capture and
    reports its true ``span_s`` — short uptimes degrade honestly
    instead of inventing history."""

    def __init__(
        self,
        *,
        targets: Sequence[SLOTarget] = DEFAULT_TARGETS,
        windows_s: Sequence[float] = DEFAULT_WINDOWS_S,
        registry: Optional[MetricsRegistry] = None,
        min_sample_gap_s: float = 5.0,
        breach_burn_rate: float = 1.0,
        max_samples: int = 4096,
    ):
        if not targets:
            raise ValueError("want at least one SLOTarget")
        if not windows_s or any(w <= 0 for w in windows_s):
            raise ValueError(f"want positive windows; got {windows_s}")
        self.targets = tuple(targets)
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self.breach_burn_rate = float(breach_burn_rate)
        self.min_sample_gap_s = float(min_sample_gap_s)
        self._registry = registry if registry is not None else get_registry()
        self._metrics = tuple(
            dict.fromkeys(t.metric for t in self.targets)
        )
        self._ring: Deque[Tuple[float, dict]] = deque(maxlen=max_samples)
        self._last_sample = -math.inf
        # construction instant: the honest span for a snapshot taken
        # before any sample() landed (an empty ring must not report
        # lifetime counter totals as if they spanned exactly one window)
        self._t0 = time.monotonic()
        # sample() runs on the engine thread, snapshot() on HTTP worker
        # threads — the ring needs one lock or iteration can see a
        # mid-append deque ("deque mutated during iteration")
        self._ring_lock = threading.Lock()

    def sample(self, now: Optional[float] = None) -> None:
        """Record one capture (and prune the ring past the longest
        window — plus slack so the oldest baseline stays available)."""
        t = time.monotonic() if now is None else now
        state = _capture(self._registry, self._metrics)
        with self._ring_lock:
            self._record(t, state)

    def _record(self, t: float, state: dict) -> None:
        self._ring.append((t, state))
        self._last_sample = t
        horizon = t - 1.25 * self.windows_s[-1]
        while len(self._ring) > 2 and self._ring[1][0] <= horizon:
            self._ring.popleft()

    def maybe_sample(self, now: Optional[float] = None) -> bool:
        """Tick-rate-friendly :meth:`sample`: records only when
        ``min_sample_gap_s`` has passed since the last one."""
        t = time.monotonic() if now is None else now
        with self._ring_lock:
            if t - self._last_sample < self.min_sample_gap_s:
                return False
        # capture outside the lock (it walks the whole registry), then
        # re-check: a concurrent sampler winning the race just means
        # one redundant-but-valid capture lands in the ring
        state = _capture(self._registry, self._metrics)
        with self._ring_lock:
            if t - self._last_sample < self.min_sample_gap_s:
                return False
            self._record(t, state)
        return True

    def latest_burn(self) -> float:
        """Max burn rate across targets and windows WITH data,
        evaluated from the ring's newest capture — no fresh registry
        walk, no rates/percentile computation.  The cheap per-tick
        reduction behind the ``znicz_serve_slo_burn_rate`` gauge (the
        front door calls this right after :meth:`maybe_sample`
        recorded, so the newest capture is current); :meth:`snapshot`
        stays the full judgment."""
        with self._ring_lock:
            ring = list(self._ring)
        if not ring:
            return 0.0
        t_new, current = ring[-1]
        burn = 0.0
        for target in self.targets:
            cur_h = current["hists"].get(target.metric)
            if cur_h is None:
                continue
            for w in self.windows_s:
                _, base = self._baseline(ring, t_new - w)
                cum = _delta_cum(
                    cur_h,
                    base["hists"].get(target.metric)
                    if base is not None
                    else None,
                )
                ev = _eval_target(target, cum, span_s=None)
                if ev["n"] > 0:
                    burn = max(burn, ev["burn_rate"])
        return round(burn, 4)

    @staticmethod
    def _baseline(
        ring: Sequence[Tuple[float, dict]], t_want: float
    ) -> Tuple[float, Optional[dict]]:
        """Newest capture at or before ``t_want``; oldest available
        when the ring does not reach back that far; (t_want, None)
        when the ring is empty (zero baseline)."""
        chosen: Optional[Tuple[float, dict]] = None
        for t, state in ring:
            if t <= t_want:
                chosen = (t, state)
            else:
                break
        if chosen is None:
            chosen = ring[0] if ring else None
        if chosen is None:
            return t_want, None
        return chosen

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Evaluate every target over every rolling window.  JSON-able;
        the ``/slo`` endpoint body.  Safe against a concurrent
        :meth:`sample`: evaluates one consistent copy of the ring."""
        t = time.monotonic() if now is None else now
        with self._ring_lock:
            ring = list(self._ring)
        current = _capture(self._registry, self._metrics)
        targets_out: dict = {}
        any_breach = False
        for target in self.targets:
            windows: dict = {}
            burns: List[float] = []
            for w in self.windows_s:
                bt, base = self._baseline(ring, t - w)
                span = (
                    t - bt if base is not None
                    else max(t - self._t0, 1e-9)
                )
                cur_h = current["hists"].get(target.metric)
                cum = (
                    _delta_cum(
                        cur_h,
                        base["hists"].get(target.metric)
                        if base is not None
                        else None,
                    )
                    if cur_h is not None
                    else []
                )
                ev = _eval_target(target, cum, span_s=span)
                windows[_window_key(w)] = ev
                if ev["n"] > 0:
                    burns.append(ev["burn_rate"])
            breached = bool(burns) and all(
                b >= self.breach_burn_rate for b in burns
            )
            any_breach = any_breach or breached
            targets_out[target.name] = {
                "metric": target.metric,
                "threshold_s": target.threshold_s,
                "objective": target.objective,
                "windows": windows,
                "breached": breached,
            }
        rates: dict = {}
        for w in self.windows_s:
            bt, base = self._baseline(ring, t - w)
            span = max(
                t - bt if base is not None else t - self._t0, 1e-9
            )
            row: dict = {"span_s": round(span, 3)}
            for key in _RATE_COUNTERS:
                cur_v = current["counters"].get(key, 0.0)
                base_v = (
                    base["counters"].get(key, 0.0)
                    if base is not None
                    else 0.0
                )
                row[key] = max(cur_v - base_v, 0.0)
            # "requests" counts ENGINE submits, but errors/deadlines
            # also claim requests that died in the front-door pending
            # queue before ever reaching engine submit (a wedged tick
            # holds them exactly there) — floor the denominator at the
            # fatality count so the rate saturates at 1.0 instead of
            # reporting a nonsensical >100% mid-incident
            fatal = row["errors"] + row["deadlines"]
            denom = max(row["requests"] + row["sheds"], fatal, 1.0)
            row["requests_per_s"] = round(row["requests"] / span, 4)
            row["error_rate"] = round(fatal / denom, 6)
            row["shed_rate"] = round(row["sheds"] / denom, 6)
            rates[_window_key(w)] = row
        return {
            "generated_unix": time.time(),  # timestamp, not a duration
            "breach_burn_rate": self.breach_burn_rate,
            "targets": targets_out,
            "rates": rates,
            "breached": any_breach,
        }


# -- windowless evaluation (prom files, aggregator scrapes, bench) ----------


def _eval_state(
    state: dict,
    targets: Sequence[SLOTarget],
    *,
    breach_burn_rate: float = 1.0,
) -> dict:
    targets_out: dict = {}
    any_breach = False
    for target in targets:
        h = state["hists"].get(target.metric)
        cum = list(h["cum"]) if h is not None else []
        ev = _eval_target(target, cum, span_s=None)
        breached = ev["n"] > 0 and ev["burn_rate"] >= breach_burn_rate
        any_breach = any_breach or breached
        targets_out[target.name] = {
            "metric": target.metric,
            "threshold_s": target.threshold_s,
            "objective": target.objective,
            "windows": {"lifetime": ev},
            "breached": breached,
        }
    counters = state["counters"]
    # same pending-queue-fatality floor as SLOMonitor.snapshot(): the
    # rate must stay a fraction even when deaths outnumber engine
    # submits
    fatal = counters["errors"] + counters["deadlines"]
    denom = max(counters["requests"] + counters["sheds"], fatal, 1.0)
    rates = {
        "lifetime": {
            **{k: counters.get(k, 0.0) for k in _RATE_COUNTERS},
            "error_rate": round(fatal / denom, 6),
            "shed_rate": round(counters["sheds"] / denom, 6),
        }
    }
    return {
        "type": "slo",  # self-describing inside a metrics_snapshot
        "generated_unix": time.time(),
        "breach_burn_rate": breach_burn_rate,
        "targets": targets_out,
        "rates": rates,
        "breached": any_breach,
    }


def lifetime_snapshot(
    registry: Optional[MetricsRegistry] = None,
    targets: Sequence[SLOTarget] = DEFAULT_TARGETS,
    *,
    breach_burn_rate: float = 1.0,
) -> dict:
    """Process-lifetime SLO view of a live registry (deltas from zero).
    What the bench attaches to every ``metrics_snapshot``."""
    reg = registry if registry is not None else get_registry()
    metrics = tuple(dict.fromkeys(t.metric for t in targets))
    return _eval_state(
        _capture(reg, metrics), targets,
        breach_burn_rate=breach_burn_rate,
    )


def evaluate_exposition(
    text: str,
    targets: Sequence[SLOTarget] = DEFAULT_TARGETS,
    *,
    breach_burn_rate: float = 1.0,
) -> dict:
    """SLO view of one Prometheus text exposition — a ``metrics.prom``
    file or an aggregator's merged ``/metrics`` body.  Raises
    ``ValueError`` on a malformed exposition."""
    parsed = parse_prometheus_text(text)
    wanted = {t.metric for t in targets}
    hists: Dict[str, dict] = {}
    by_series: Dict[str, Dict[float, float]] = {}
    counts: Dict[str, float] = {}
    sums: Dict[str, float] = {}
    for name, labels, value in parsed["samples"]:
        for metric in wanted:
            if name == f"{metric}_bucket" and "le" in labels:
                acc = by_series.setdefault(metric, {})
                le = float(labels["le"])
                acc[le] = acc.get(le, 0.0) + value
            elif name == f"{metric}_count":
                counts[metric] = counts.get(metric, 0.0) + value
            elif name == f"{metric}_sum":
                sums[metric] = sums.get(metric, 0.0) + value
    for metric, acc in by_series.items():
        hists[metric] = {
            "cum": sorted(acc.items()),
            "count": counts.get(metric, 0.0),
            "sum": sums.get(metric, 0.0),
        }
    counters: Dict[str, float] = {}
    for key, spec in _RATE_COUNTERS.items():
        cname, reasons = (
            spec if isinstance(spec, tuple) else (spec, None)
        )
        v = 0.0
        for name, labels, value in parsed["samples"]:
            if name != cname:
                continue
            if reasons is not None and not any(
                lv in reasons for lv in labels.values()
            ):
                continue
            v += value
        counters[key] = v
    return _eval_state(
        {"hists": hists, "counters": counters}, targets,
        breach_burn_rate=breach_burn_rate,
    )


# -- the znicz-slo CLI ------------------------------------------------------


def _fmt_ms(v: Optional[float]) -> str:
    return "-" if v is None else f"{1000.0 * v:.1f}"


def _render_table(snap: dict) -> str:
    rows = [
        (
            "target", "window", "n", "p50 ms", "p95 ms", "p99 ms",
            "bad %", "burn", "status",
        )
    ]
    for name, t in snap["targets"].items():
        for wname, ev in t["windows"].items():
            rows.append(
                (
                    f"{name}<{t['threshold_s']}s@{t['objective']:.0%}",
                    wname,
                    str(int(ev["n"])),
                    _fmt_ms(ev["p50_s"]),
                    _fmt_ms(ev["p95_s"]),
                    _fmt_ms(ev["p99_s"]),
                    f"{100.0 * ev['bad_frac']:.2f}",
                    f"{ev['burn_rate']:.2f}",
                    "BREACH" if t["breached"] else "ok",
                )
            )
    widths = [
        max(len(r[i]) for r in rows) for i in range(len(rows[0]))
    ]
    lines = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in rows
    ]
    for wname, r in snap["rates"].items():
        lines.append(
            f"[{wname}] requests={int(r['requests'])} "
            f"errors={int(r['errors'])} sheds={int(r['sheds'])} "
            f"deadlines={int(r['deadlines'])} "
            f"error_rate={r['error_rate']:.4f} "
            f"shed_rate={r['shed_rate']:.4f}"
        )
    return "\n".join(lines)


def _read_source(src: str, timeout_s: float = 10.0) -> str:
    """A metrics source: a local ``metrics.prom`` path, or an http URL
    (an aggregator or serve endpoint; a bare ``http://host:port`` gets
    ``/metrics`` appended)."""
    if src.startswith("http://") or src.startswith("https://"):
        import urllib.parse
        import urllib.request

        parsed = urllib.parse.urlsplit(src)
        if parsed.path in ("", "/"):
            src = src.rstrip("/") + "/metrics"
        with urllib.request.urlopen(src, timeout=timeout_s) as resp:
            return resp.read().decode("utf-8")
    with open(src) as f:
        return f.read()


def main(argv=None) -> int:
    """``znicz-slo <metrics.prom|url> [--frontdoor] [--ttft S]
    [--latency S] [--objective F] [--burn-threshold F] [--json]`` —
    print the SLO table for one exposition; exit 1 when any target's
    burn rate breaches (the CI/bench gate), 2 on usage/read errors.
    ``--frontdoor`` judges the client-clock
    ``znicz_serve_frontdoor_*`` histograms (what ``/slo`` on a serving
    replica judges — a deep pending queue is invisible to the
    engine-clock defaults)."""
    args = list(sys.argv[1:] if argv is None else argv)
    opts = {
        "--ttft": 1.0, "--latency": 5.0, "--objective": 0.99,
        "--burn-threshold": 1.0,
    }
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    frontdoor = "--frontdoor" in args
    if frontdoor:
        args.remove("--frontdoor")
    positional: List[str] = []
    i = 0
    while i < len(args):
        if args[i] in opts:
            if i + 1 >= len(args):
                print(f"{args[i]} needs a value", file=sys.stderr)
                return 2
            try:
                opts[args[i]] = float(args[i + 1])
            except ValueError:
                print(
                    f"{args[i]}: not a number: {args[i + 1]!r}",
                    file=sys.stderr,
                )
                return 2
            i += 2
        else:
            positional.append(args[i])
            i += 1
    if len(positional) != 1:
        print(
            "usage: znicz-slo <metrics.prom | http://host:port[/metrics]>"
            " [--frontdoor] [--ttft S] [--latency S] [--objective F]"
            " [--burn-threshold F] [--json]",
            file=sys.stderr,
        )
        return 2
    metrics = FRONTDOOR_TARGETS if frontdoor else DEFAULT_TARGETS
    try:
        # inside the try: an out-of-range --objective/--ttft must be
        # the usage exit (2), never a traceback or a fake breach (1)
        targets = (
            SLOTarget(
                "ttft", metrics[0].metric,
                opts["--ttft"], opts["--objective"],
            ),
            SLOTarget(
                "latency", metrics[1].metric,
                opts["--latency"], opts["--objective"],
            ),
        )
        text = _read_source(positional[0])
        snap = evaluate_exposition(
            text, targets, breach_burn_rate=opts["--burn-threshold"]
        )
    except (OSError, ValueError) as exc:
        print(f"znicz-slo: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(snap, indent=2) if as_json else _render_table(snap))
    return 1 if snap["breached"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
