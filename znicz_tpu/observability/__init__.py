"""Unified telemetry: metrics registry, span tracer, export surfaces.

One process-wide substrate replacing the per-subsystem ledgers that
had accumulated by PR 2 (engine LatencyStats + compile dict, the
generate serve-cache counters, StatusWriter's timing dict):

* **Registry** (:mod:`registry`) — labeled counters / gauges /
  histograms with a fixed bucket ladder; Prometheus text exposition
  (``/metrics`` on ``python -m znicz_tpu.services.serve``,
  ``metrics.prom`` beside ``status.json``) and JSON snapshots
  (``status.json``, bench records).
* **Tracer** (:mod:`tracing`) — nested host spans emitted as Chrome
  trace-event JSONL (open in https://ui.perfetto.dev), wrapping
  ``jax.profiler.TraceAnnotation`` so host spans line up with device
  captures.
* **PhaseTimer** (:mod:`phases`) — StepTimer-compatible phase timing
  that feeds both.

Convenience module-level ``counter``/``gauge``/``histogram`` operate on
the default registry; see docs/OBSERVABILITY.md for the metric catalog.
Pure stdlib at import time — jax is only touched lazily by the tracer.
"""

from znicz_tpu.observability.phases import PhaseTimer  # noqa: F401
from znicz_tpu.observability.registry import (  # noqa: F401
    DEFAULT_TIME_BUCKETS,
    Metric,
    MetricsRegistry,
    get_registry,
    parse_prometheus_text,
)
from znicz_tpu.observability.tracing import (  # noqa: F401
    Tracer,
    get_tracer,
    instant,
    span,
)


def counter(name: str, help: str = "", labelnames=()) -> Metric:
    """Get-or-create a counter on the default registry."""
    return get_registry().counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Metric:
    """Get-or-create a gauge on the default registry."""
    return get_registry().gauge(name, help, labelnames)


def histogram(
    name: str, help: str = "", labelnames=(), buckets=DEFAULT_TIME_BUCKETS
) -> Metric:
    """Get-or-create a histogram on the default registry."""
    return get_registry().histogram(name, help, labelnames, buckets)


def prometheus_text() -> str:
    """Prometheus text exposition of the default registry."""
    return get_registry().prometheus_text()


def snapshot() -> dict:
    """JSON-able snapshot of the default registry."""
    return get_registry().snapshot()
