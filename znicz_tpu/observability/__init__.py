"""Unified telemetry: metrics registry, span tracer, export surfaces.

One process-wide substrate replacing the per-subsystem ledgers that
had accumulated by PR 2 (engine LatencyStats + compile dict, the
generate serve-cache counters, StatusWriter's timing dict):

* **Registry** (:mod:`registry`) — labeled counters / gauges /
  histograms with a fixed bucket ladder; Prometheus text exposition
  (``/metrics`` on ``python -m znicz_tpu.services.serve``,
  ``metrics.prom`` beside ``status.json``) and JSON snapshots
  (``status.json``, bench records).
* **Tracer** (:mod:`tracing`) — nested host spans emitted as Chrome
  trace-event JSONL (open in https://ui.perfetto.dev), wrapping
  ``jax.profiler.TraceAnnotation`` so host spans line up with device
  captures.
* **PhaseTimer** (:mod:`phases`) — StepTimer-compatible phase timing
  that feeds both.

* **Fleet aggregation** (:mod:`aggregate`) — a MetricsAggregator
  service replicas push registry snapshots to (instance-tagged,
  TTL-expired, bucket-wise histogram merge) plus the MetricsPusher
  background thread feeding it.
* **Fleet tracing** (:mod:`collector`) — the tracing twin: a
  TraceCollector spans push to (TracePusher), merged into ONE
  Perfetto-loadable timeline at ``GET /trace`` with pid=instance.
* **Device/compile telemetry** (:mod:`device`) — the program ledger
  behind ``/debug/programs`` (compile wall time, cost analysis,
  executable memory per true first compile) and on-demand
  ``jax.profiler`` captures.
* **SLO monitoring** (:mod:`slo`) — rolling-window p50/p95/p99 and
  multi-window burn rates over declared targets (``/slo``,
  ``tools/znicz-slo``).
* **Pipeline attribution** (:mod:`pipeline`) — per-stage input-pipeline
  timings (fetch / host_transform / h2d / enqueue), the live H2D
  bandwidth gauge, and the step-wall decomposition behind
  ``tools/znicz-doctor``.
* **Step anomaly flight recorder** (:mod:`anomaly`) — typed per-step
  verdicts (non-finite loss/grad, loss spikes, step-time regressions)
  with a bounded ring of last-K-steps snapshots, surfaced through
  ``status.json`` / ``/metrics`` / the aggregator.

Convenience module-level ``counter``/``gauge``/``histogram`` operate on
the default registry; see docs/OBSERVABILITY.md for the metric catalog.
Pure stdlib at import time — jax is only touched lazily by the tracer.
"""

from znicz_tpu.observability.aggregate import (  # noqa: F401
    MetricsAggregator,
    MetricsPusher,
    build_aggregator_server,
)
from znicz_tpu.observability.collector import (  # noqa: F401
    TraceCollector,
    TracePusher,
    build_collector_server,
)
from znicz_tpu.observability import device  # noqa: F401
from znicz_tpu.observability.anomaly import (  # noqa: F401
    StepAnomalyDetector,
)
from znicz_tpu.observability.phases import PhaseTimer  # noqa: F401
from znicz_tpu.observability.pipeline import (  # noqa: F401
    H2DProbe,
    PipelineAttribution,
)
from znicz_tpu.observability.registry import (  # noqa: F401
    DEFAULT_FRACTION_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Metric,
    MetricsRegistry,
    fraction_le,
    get_registry,
    parse_prometheus_text,
    quantile_from_cumulative,
)
from znicz_tpu.observability.slo import (  # noqa: F401
    DEFAULT_TARGETS,
    SLOMonitor,
    SLOTarget,
)
from znicz_tpu.observability.tracing import (  # noqa: F401
    Tracer,
    get_tracer,
    instant,
    span,
)


def counter(name: str, help: str = "", labelnames=()) -> Metric:
    """Get-or-create a counter on the default registry."""
    return get_registry().counter(name, help, labelnames)


def gauge(name: str, help: str = "", labelnames=()) -> Metric:
    """Get-or-create a gauge on the default registry."""
    return get_registry().gauge(name, help, labelnames)


def histogram(
    name: str, help: str = "", labelnames=(), buckets=DEFAULT_TIME_BUCKETS
) -> Metric:
    """Get-or-create a histogram on the default registry."""
    return get_registry().histogram(name, help, labelnames, buckets)


def prometheus_text() -> str:
    """Prometheus text exposition of the default registry."""
    return get_registry().prometheus_text()


def snapshot() -> dict:
    """JSON-able snapshot of the default registry."""
    return get_registry().snapshot()
